"""Benchmark driver: end-to-end engine throughput on the BASELINE.json configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

The baseline denominator is the reference's published production throughput
claim — 20B events/day ~= 300k events/s on a JVM cluster
(reference: README.md:33-34; see BASELINE.md). Workloads follow
BASELINE.json "configs"; configs not yet implemented are skipped and the
headline value is the geometric mean of the implemented ones.
"""

from __future__ import annotations

import argparse
import json
import math
import time

import numpy as np

REFERENCE_EVENTS_PER_SEC = 300_000.0


def _make_stock_data(n: int, seed: int = 7):
    rng = np.random.default_rng(seed)
    symbols = np.array(["WSO2", "IBM", "GOOG", "MSFT", "ORCL", "AAPL", "AMZN", "NVDA"])
    return {
        "ts": np.arange(n, dtype=np.int64) + 1_700_000_000_000,
        "symbol": rng.integers(1, 9, size=n).astype(np.int32),  # pre-interned ids
        "price": rng.uniform(0.0, 100.0, size=n).astype(np.float32),
        "volume": rng.integers(1, 1000, size=n).astype(np.int64),
        "names": symbols,
    }


def _prime_interner(mgr, names):
    for s in names:
        mgr.interner.intern(str(s))


def _run_workload(ql, query_stream, data, n_events, batch_size, warmup_batches=3):
    """Throughput of one SiddhiQL app: events/sec through the full engine
    (ingest pack -> device step chain -> downstream junction)."""
    import jax

    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    # interner ids 1..8 = the 8 symbols, matching the pre-interned columns
    _prime_interner(mgr, data["names"])
    rt.start()
    h = rt.get_input_handler(query_stream)

    cols = {k: v for k, v in data.items() if k not in ("ts", "names")}
    warm_n = batch_size * warmup_batches
    h.send_columns(data["ts"][:warm_n], {k: v[:warm_n] for k, v in cols.items()})
    _block_on_states(rt)

    t0 = time.perf_counter()
    sent = 0
    while sent < n_events:  # data arrays are sized >= n_events by main()
        end = min(sent + batch_size * 64, n_events)
        h.send_columns(data["ts"][sent:end], {k: v[sent:end] for k, v in cols.items()})
        sent = end
    _block_on_states(rt)
    dt = time.perf_counter() - t0
    rt.shutdown()
    mgr.shutdown()
    return sent / dt


def _block_on_states(rt):
    import jax

    for qr in rt.queries.values():
        if qr.state is not None:
            jax.block_until_ready(qr.state)


WORKLOADS = {
    # BASELINE.json config 1: SiddhiQL quickstart — filter + length-window avg
    "filter_window_avg": (
        """
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream[price > 50]#window.length(50)
        select symbol, avg(price) as ap
        insert into Out;
        """,
        "StockStream",
        1.0,   # events multiplier
        None,  # batch override
    ),
    # BASELINE.json config 2: tumbling window group-by aggregation
    "tumbling_groupby": (
        """
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream#window.lengthBatch(1024)
        select symbol, sum(volume) as total, avg(price) as ap
        group by symbol
        insert into Out;
        """,
        "StockStream",
        1.0,
        None,
    ),
    # BASELINE.json config 3: two-sided sliding-window join (self-join form)
    "sliding_join": (
        """
        @app:joinCapacity(size='8192')
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream#window.length(100) as a join StockStream#window.length(100) as b
        on a.volume == b.volume
        select a.symbol as s1, b.symbol as s2
        insert into Out;
        """,
        "StockStream",
        0.25,
        8192,
    ),
    # BASELINE.json config 4: pattern `every A -> B within` (2-state NFA,
    # vectorized token-matrix fast path)
    "pattern_2state": (
        """
        @app:patternCapacity(size='4096')
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from every a1=StockStream[price > 95] -> a2=StockStream[price < 5]
        within 1 sec
        select a1.symbol as s1, a2.symbol as s2
        insert into Out;
        """,
        "StockStream",
        1.0,
        None,
    ),
    # BASELINE.json config 5: DEBS-style count sequence with a kleene bound
    "count_sequence": (
        """
        @app:patternCapacity(size='128')
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from every a1=StockStream[price > 90]<2:4> -> a2=StockStream[price < 10]
        select a2.symbol as s2
        insert into Out;
        """,
        "StockStream",
        0.02,
        1024,
    ),
}


def _table_scaling(rows_list=(100_000, 1_000_000), batch=8192, batches=12):
    """Events/s of a stream query probing+updating a table at capacity N
    (VERDICT r1 item 9: evidence for the exhaustive-scan-vs-index decision;
    reference analog: table/holder/IndexEventHolder primary-key fast path)."""
    import numpy as np

    from siddhi_tpu import SiddhiManager

    out = {}
    for n_rows in rows_list:
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(f"""
        @app:batch(size='{batch}')
        define stream Loader (k long, v long);
        define stream S (k long, v long);
        @capacity(size='{n_rows}')
        define table T (k long, v long);
        @info(name='load') from Loader insert into T;
        @info(name='upd')
        from S select k, v update T on T.k == k;
        """)
        rt.start()
        lk = np.arange(n_rows, dtype=np.int64)
        rt.get_input_handler("Loader").send_columns(
            np.arange(n_rows, dtype=np.int64),
            {"k": lk, "v": lk},
        )
        rng = np.random.default_rng(3)
        ks = rng.integers(0, n_rows, size=batch * batches).astype(np.int64)
        vs = np.arange(batch * batches, dtype=np.int64)
        h = rt.get_input_handler("S")
        h.send_columns(np.arange(batch, dtype=np.int64), {"k": ks[:batch], "v": vs[:batch]})
        _block_on_states(rt)
        t0 = time.perf_counter()
        h.send_columns(np.arange(batch * batches, dtype=np.int64), {"k": ks, "v": vs})
        _block_on_states(rt)
        dt = time.perf_counter() - t0
        rt.shutdown()
        mgr.shutdown()
        label = f"{n_rows // 1000}k" if n_rows < 1_000_000 else f"{n_rows // 1_000_000}m"
        out[f"table_update_{label}"] = round(batch * batches / dt, 1)
    return out


def _p99_detect_latency_ms(data, batch=256, batches=60):
    """p99 detection latency: wall time from the START of a micro-batch send
    to the query callback having DELIVERED that batch's matches (ingest pack
    -> NFA step -> device readback -> host decode -> callback). The callback
    drain is the single device synchronization per batch — the floor is one
    tunnel flush (~70-110 ms behind the axon relay; sub-ms on local chips),
    which the send path never pays twice (pack and dispatch are async)."""
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(f"""@app:batch(size='{batch}')
    @app:patternCapacity(size='256')
    define stream StockStream (symbol string, price float, volume long);
    @info(name='q')
    from every a1=StockStream[price > 95] -> a2=StockStream[price < 5]
    within 1 sec
    select a1.symbol as s1, a2.symbol as s2
    insert into Out;
    """)
    _prime_interner(mgr, data["names"])
    fired = [0.0]
    rt.add_callback("q", lambda ts, i, r: fired.__setitem__(0, time.perf_counter()))
    rt.start()
    h = rt.get_input_handler("StockStream")
    cols = {k: v for k, v in data.items() if k not in ("ts", "names")}

    lat = []
    for i in range(batches + 5):
        lo, hi = i * batch, (i + 1) * batch
        fired[0] = 0.0
        t0 = time.perf_counter()
        h.send_columns(data["ts"][lo:hi], {k: v[lo:hi] for k, v in cols.items()})
        t1 = fired[0] if fired[0] > 0.0 else time.perf_counter()
        if i >= 5:  # skip compile warmup
            lat.append((t1 - t0) * 1000)
    rt.shutdown()
    mgr.shutdown()
    lat.sort()
    return lat[max(0, math.ceil(len(lat) * 0.99) - 1)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", type=int, default=500_000)
    ap.add_argument("--batch", type=int, default=32768)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    n = args.events
    # size the data for the largest per-workload run (events + warmup)
    needed = n
    for _ql, _s, mult, batch_override in WORKLOADS.values():
        batch = batch_override or args.batch
        needed = max(needed, max(int(n * mult), batch * 4) + batch * 3)
    data = _make_stock_data(needed)
    per = {}
    for name, (ql, stream, mult, batch_override) in WORKLOADS.items():
        batch = batch_override or args.batch
        events = max(int(n * mult), batch * 4)
        ql = f"@app:batch(size='{batch}')\n" + ql
        per[name] = _run_workload(ql, stream, data, events, batch)
        if args.verbose:
            print(f"# {name}: {per[name]:,.0f} events/s")

    p99 = _p99_detect_latency_ms(data)
    if args.verbose:
        print(f"# p99 pattern detection latency (256-row micro-batch): {p99:.1f} ms")

    scaling = _table_scaling()
    if args.verbose:
        print(f"# table scaling: {scaling}")

    geomean = math.exp(sum(math.log(v) for v in per.values()) / len(per))
    detail = {k: round(v, 1) for k, v in per.items()}
    detail["p99_detect_ms"] = round(p99, 2)
    detail.update(scaling)
    print(
        json.dumps(
            {
                "metric": "engine_throughput_geomean",
                "value": round(geomean, 1),
                "unit": "events/s",
                "vs_baseline": round(geomean / REFERENCE_EVENTS_PER_SEC, 3),
                "detail": detail,
            }
        )
    )


if __name__ == "__main__":
    main()
