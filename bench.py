"""Benchmark driver: end-to-end engine throughput on the BASELINE.json configs.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

Methodology (round 3 — honest completion-rate timing):
- On tunneled PJRT backends the relay acks async work speculatively until the
  first device->host transfer, so `block_until_ready` alone can report an
  ENQUEUE rate, not a completion rate. Every timed region here therefore ends
  with a "truth sync": a tiny scalar derived from the final query state is
  read back to the host, which forces real completion of the whole dependent
  chain before the clock stops.
- That first read also permanently flips such relays into a synchronous
  ~100 ms completion cycle ("transfer-degraded mode"), so EACH LEG RUNS IN
  ITS OWN SUBPROCESS; legs cannot poison each other and per-leg numbers are
  reproducible in isolation (`python bench.py --leg filter_window_avg`).
- `timebudget` (in detail) publishes a PER-LEG budget of the fused-ingest
  program itself: wire bytes/event, host encode rate, effective per-chunk
  h2d cost, device rate, the predicted bound, and the leg's binding wall —
  plus the shared sync floor (the p99 denominator), bulk h2d bandwidth,
  and a pipelined-vs-serial A/B of the real engine send path
  (`*_overlap_meas` vs `*_overlap_pred`, see core/pipeline.py).

The baseline denominator is the reference's published production throughput
claim — 20B events/day ~= 300k events/s on a JVM cluster
(reference: README.md:33-34; see BASELINE.md).
"""

from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import time

import numpy as np

REFERENCE_EVENTS_PER_SEC = 300_000.0

# keep the engine's periodic aux drain from injecting a mid-run transfer
os.environ.setdefault("SIDDHI_TPU_AUX_DRAIN_S", "0")


def _make_stock_data(n: int, seed: int = 7):
    rng = np.random.default_rng(seed)
    symbols = np.array(["WSO2", "IBM", "GOOG", "MSFT", "ORCL", "AAPL", "AMZN", "NVDA"])
    return {
        "ts": np.arange(n, dtype=np.int64) + 1_700_000_000_000,
        "symbol": rng.integers(1, 9, size=n).astype(np.int32),  # pre-interned ids
        "price": rng.uniform(0.0, 100.0, size=n).astype(np.float32),
        "volume": rng.integers(1, 1000, size=n).astype(np.int64),
        "names": symbols,
    }


def _prime_interner(mgr, names):
    for s in names:
        mgr.interner.intern(str(s))


def _truth_sync(rt):
    """Force REAL completion of all queued work: read back one tiny scalar
    depending on ONE state leaf of EVERY stateful holder (query, table,
    window, aggregation) — projection-only queries have empty query state,
    and sampling globally could skip a holder whose work is still pending."""
    import jax
    import jax.numpy as jnp

    leaves = []
    holders = list(rt.queries.values()) + (
        list(rt.tables.values())
        + list(getattr(rt, "named_windows", {}).values())
        + list(getattr(rt, "aggregations", {}).values())
    )
    for h in holders:
        st = getattr(h, "state", None)
        if st is None:
            continue
        for leaf in jax.tree_util.tree_leaves(st):
            if hasattr(leaf, "dtype"):
                leaves.append(leaf)
                break
    if not leaves:
        return 0.0
    acc = sum(jnp.sum(x.ravel()[:1]).astype(jnp.float32) for x in leaves)
    return float(np.asarray(acc))


def _snapshot_status(rt):
    """Steady-state engine shape at the end of a leg (runtime.snapshot_status
    per the observability layer), stashed into the detail blob. Guarded: a
    snapshot failure must never fail a leg. Statistics-armed legs also
    persist the plan-vs-actual calibration blob + the roofline split so
    tools/calib_report.py can diff two runs' prediction errors."""
    try:
        status = rt.snapshot_status()
    except Exception:
        return None
    try:
        rep = rt.calibration_report()
        if rep is not None:
            status["calibration"] = rep
        sm = rt.statistics_manager
        if sm is not None:
            status["roofline"] = sm.roofline()
    except Exception:
        pass
    return status


_LAST_STATUS: list = [None]  # snapshot of the most recent _run_workload leg


def _run_workload(ql, query_stream, data, n_events, batch_size, callback=None):
    """TRUE throughput of one SiddhiQL app: events/sec through the full
    engine (host pack -> h2d -> fused/step dispatch), timed to completion
    via a truth sync. With `callback`, delivered throughput: the callback is
    registered on query 'q' and every output row is materialized on host
    before the clock stops (the reference's number includes delivery —
    QueryCallback.java:52-105)."""
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    _prime_interner(mgr, data["names"])
    if callback is not None:
        rt.add_callback("q", callback)
    rt.start()
    h = rt.get_input_handler(query_stream)

    cols = {k: v for k, v in data.items() if k not in ("ts", "names")}
    # delivered mode sends everything in ONE call: fewer, larger fused chunks
    # amortize the relay's ~fixed per-transfer cost
    stride = n_events if callback is not None else batch_size * 64
    # warm with the SAME send size as the timed loop so the engaged program
    # (per-batch or fused, at the same chunking) compiles before the clock
    warm_n = min(stride, n_events)
    h.send_columns(data["ts"][:warm_n], {k: v[:warm_n] for k, v in cols.items()})
    _truth_sync(rt)  # compile + flip the relay into truth mode before timing
    t0 = time.perf_counter()
    sent = 0
    while sent < n_events:  # data arrays are sized >= n_events by main()
        end = min(sent + stride, n_events)
        h.send_columns(data["ts"][sent:end], {k: v[sent:end] for k, v in cols.items()})
        sent = end
    _truth_sync(rt)
    dt = time.perf_counter() - t0
    _LAST_STATUS[0] = _snapshot_status(rt)
    rt.shutdown()
    mgr.shutdown()
    return sent / dt


WORKLOADS = {
    # BASELINE.json config 1: SiddhiQL quickstart — filter + length-window avg
    "filter_window_avg": (
        """
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream[price > 50]#window.length(50)
        select symbol, avg(price) as ap
        insert into Out;
        """,
        "StockStream",
        2.0,   # events multiplier
        None,  # batch override
    ),
    # BASELINE.json config 2: tumbling window group-by aggregation
    "tumbling_groupby": (
        """
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream#window.lengthBatch(1024)
        select symbol, sum(volume) as total, avg(price) as ap
        group by symbol
        insert into Out;
        """,
        "StockStream",
        2.0,
        None,
    ),
    # BASELINE.json config 3: two-sided sliding-window join (self-join form)
    "sliding_join": (
        """
        @app:joinCapacity(size='8192')
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream#window.length(100) as a join StockStream#window.length(100) as b
        on a.volume == b.volume
        select a.symbol as s1, b.symbol as s2
        insert into Out;
        """,
        "StockStream",
        1.0,
        8192,
    ),
    # BASELINE.json config 4: pattern `every A -> B within` (2-state NFA,
    # vectorized token-matrix fast path)
    "pattern_2state": (
        """
        @app:patternCapacity(size='4096')
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from every a1=StockStream[price > 95] -> a2=StockStream[price < 5]
        within 1 sec
        select a1.symbol as s1, a2.symbol as s2
        insert into Out;
        """,
        "StockStream",
        1.0,
        None,
    ),
    # BASELINE.json config 5: DEBS-style count sequence with a kleene bound.
    # patternCapacity/patternChunk are ENGINE BUFFER knobs, not workload
    # semantics: the reference's pending lists are unbounded, and at this
    # data rate (10% match rate, min-count 2 -> ~410 armed generations per
    # 8192-row chunk < 512 lanes) the outputs are identical to any larger
    # sizing (overflow would be flagged + warned). The r5 kernel's wall is
    # gather/scatter ELEMENT traffic (~1 elem/cycle on the TPU scalar core),
    # so small token table + big chunk is the fast shape: 13.3 Mev/s device
    # vs r4's 1.6 at T=4096=chunk.
    "count_sequence": (
        """
        @app:patternCapacity(size='512')
        @app:patternChunk(size='8192')
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from every a1=StockStream[price > 90]<2:4> -> a2=StockStream[price < 10]
        select a2.symbol as s2
        insert into Out;
        """,
        "StockStream",
        0.5,
        None,  # same batch as the sibling legs (VERDICT r2 item 2)
    ),
}


def _leg_throughput(name: str, n: int, batch: int) -> float:
    delivered = name.endswith("_delivered")
    ql, stream, mult, batch_override = WORKLOADS[
        name[: -len("_delivered")] if delivered else name
    ]
    batch = batch_override or batch
    events = max(int(n * mult), batch * 4)
    ql = f"@app:batch(size='{batch}')\n" + ql
    callback = None
    if delivered:
        # bigger fused chunks amortize the relay's ~fixed per-transfer cost
        # (the relay serializes device comms, so drain/compute overlap buys
        # less than fewer, larger transfers do)
        ql = "@app:ingestChunk(size='128')\n" + ql
        sink = [0]

        def callback(ts, ins, removed):
            # every delivered row is already a decoded host Event here
            sink[0] += len(ins or ()) + len(removed or ())

    needed = events + batch * 4
    data = _make_stock_data(needed)
    return _run_workload(ql, stream, data, events, batch, callback=callback)


def _leg_table_scaling(rows_list=(100_000, 1_000_000), batches=128) -> dict:
    """Events/s of a stream query probing+updating a table at capacity N.
    batch-1024 legs are the reproducible evidence for the exhaustive-scan-vs-
    index decision (VERDICT r1 item 9 / r2 weak #3); batch-8192 legs are the
    throughput-shaped extras. Reference analog: table/holder/IndexEventHolder
    primary-key fast path."""
    from siddhi_tpu import SiddhiManager

    out = {}
    for batch, pk, label_sfx in (
        (1024, False, "_b1024"),
        (8192, False, ""),
        (8192, True, "_pk"),  # @PrimaryKey -> O(B log C) sorted probe path
    ):
        for n_rows in rows_list:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(f"""
            @app:batch(size='{batch}')
            define stream Loader (k long, v long);
            define stream S (k long, v long);
            {"@PrimaryKey('k')" if pk else ""}
            @capacity(size='{n_rows}')
            define table T (k long, v long);
            @info(name='load') from Loader insert into T;
            @info(name='upd')
            from S select k, v update T on T.k == k;
            """)
            rt.start()
            lk = np.arange(n_rows, dtype=np.int64)
            rt.get_input_handler("Loader").send_columns(
                np.arange(n_rows, dtype=np.int64),
                {"k": lk, "v": lk},
            )
            rng = np.random.default_rng(3)
            ks = rng.integers(0, n_rows, size=batch * batches).astype(np.int64)
            vs = np.arange(batch * batches, dtype=np.int64)
            h = rt.get_input_handler("S")
            # warm with the SAME send size so the fused-ingest program
            # compiles before the clock starts (updates are key-idempotent)
            h.send_columns(np.arange(batch * batches, dtype=np.int64), {"k": ks, "v": vs})
            _truth_sync(rt)
            t0 = time.perf_counter()
            h.send_columns(np.arange(batch * batches, dtype=np.int64), {"k": ks, "v": vs})
            _truth_sync(rt)
            dt = time.perf_counter() - t0
            status = _snapshot_status(rt)
            rt.shutdown()
            mgr.shutdown()
            label = f"{n_rows // 1000}k" if n_rows < 1_000_000 else f"{n_rows // 1_000_000}m"
            out[f"table_update_{label}{label_sfx}"] = round(batch * batches / dt, 1)
            if status is not None:
                out[f"table_update_{label}{label_sfx}_status"] = status
    return out


def _leg_p99(batch=256, batches=96) -> dict:
    """p99/p99.99 detection latency: wall time from the START of a
    micro-batch send to the query callback having DELIVERED that batch's
    matches, vs the measured per-batch floor of this backend (dispatch +
    completion cycle + readback in transfer-degraded mode). Target: p99 <=
    floor + 10 ms. The app runs with statistics on so the engine's
    continuous profiler (observability/profiler.py) attributes the WORST
    batch's stages (encode/dispatch/device/readback) into the detail blob —
    with <10k samples p9999 is the top sample, which is still the honest
    answer to "what did the worst send cost".

    The floor probe runs INTERLEAVED with the detection sends (one probe
    after each batch) so both distributions sample the SAME relay weather:
    the tunnel's round-trip latency drifts by tens of ms over a run, and a
    floor measured minutes later compares engine samples against different
    network conditions, not engine overhead (the r4 '+21.6 ms regression'
    was exactly this artifact — instrumented engine overhead above the d2h
    round trip is ~1 ms)."""
    import jax
    import jax.numpy as jnp

    from siddhi_tpu import SiddhiManager

    data = _make_stock_data(batch * (batches + 6))
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(f"""@app:batch(size='{batch}')
    @app:statistics(reporter='none')
    @app:patternCapacity(size='256')
    define stream StockStream (symbol string, price float, volume long);
    @info(name='q')
    from every a1=StockStream[price > 95] -> a2=StockStream[price < 5]
    within 1 sec
    select a1.symbol as s1, a2.symbol as s2
    insert into Out;
    """)
    _prime_interner(mgr, data["names"])
    fired = [0.0]
    rt.add_callback("q", lambda ts, i, r: fired.__setitem__(0, time.perf_counter()))
    rt.start()
    h = rt.get_input_handler("StockStream")
    cols = {k: v for k, v in data.items() if k not in ("ts", "names")}

    # floor probe: one dispatch + ready-wait + tiny readback in the same
    # (transfer-degraded) mode the callback path runs in
    x = jnp.zeros((batch,), jnp.float32)
    f = jax.jit(lambda v: v.sum())
    np.asarray(f(x))

    lat = []
    floors = []
    for i in range(batches + 5):
        lo, hi = i * batch, (i + 1) * batch
        fired[0] = 0.0
        t0 = time.perf_counter()
        h.send_columns(data["ts"][lo:hi], {k: v[lo:hi] for k, v in cols.items()})
        t1 = fired[0] if fired[0] > 0.0 else time.perf_counter()
        t2 = time.perf_counter()
        np.asarray(f(x))  # paired floor sample, same relay weather
        t3 = time.perf_counter()
        if i >= 5:  # skip compile warmup
            lat.append((t1 - t0) * 1000)
            floors.append((t3 - t2) * 1000)
    status = _snapshot_status(rt)
    profile = None
    try:
        profile = rt.profile_report()
    except Exception:
        pass
    rt.shutdown()
    mgr.shutdown()
    # paired deltas isolate ENGINE overhead from relay weather: each
    # detection sample is compared against its own immediately-following
    # floor probe, and the median delta is robust to the heavy-tailed
    # round-trip distribution (a p99-vs-p99 comparison is the single worst
    # sample of 60 draws on each side — pure noise at ±40 ms jitter)
    deltas = sorted(a - b for a, b in zip(lat, floors))
    lat.sort()
    floors.sort()
    p99 = lat[max(0, math.ceil(len(lat) * 0.99) - 1)]
    out = {
        "p99_detect_ms": round(p99, 2),
        "p9999_detect_ms": round(
            lat[max(0, math.ceil(len(lat) * 0.9999) - 1)], 2
        ),
        "p99_floor_ms": round(floors[max(0, math.ceil(len(floors) * 0.99) - 1)], 2),
        "p9999_floor_ms": round(
            floors[max(0, math.ceil(len(floors) * 0.9999) - 1)], 2
        ),
        "p50_floor_ms": round(floors[len(floors) // 2], 2),
        "p50_detect_ms": round(lat[len(lat) // 2], 2),
        "engine_overhead_p50_ms": round(deltas[len(deltas) // 2], 2),
    }
    if profile is not None:
        # stage-attributed waterfall of the WORST chunk (continuous
        # profiler top-K ring) + the leg's compile ledger: the per-stage
        # measurement behind "the sync floor bounds p99"
        slowest = profile.get("waterfalls", {}).get("slowest") or []
        if slowest:
            out["p99_worst_chunk_waterfall"] = slowest[0]
        out["p99_compiles"] = {
            comp: {"compiles": ent["compiles"], "causes": ent["causes"]}
            for comp, ent in profile.get("compile", {}).items()
        }
    if status is not None:
        out["p99_status"] = status
    return out


def _leg_calibration(batch=256, chunks=6) -> dict:
    """Plan-vs-actual calibration sentinel (`--leg calibration`): a fused
    app shaped to exercise every prediction kind the ledger pairs —
    shared filter+window queries (selectivity, state bytes, dispatch
    reduction), a declared dict wire lane plus an inferred delta lane
    (both wire B/ev kinds), compiling under the fused group (compiles).
    The full calibration blob lands in the detail JSON; the CI sentinel
    asserts all six kinds pair and tools/calib_report.py diffs the blob
    against the committed baseline to catch prediction-error drift."""
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(f"""@app:statistics(reporter='none')
    @app:batch(size='{batch}')
    @app:wire(dict.S.symbol='64')
    define stream S (symbol string, price float, volume long);
    @info(name='q1') from S[price > 50.0]#window.length(16)
    select symbol, price insert into Out1;
    @info(name='q2') from S[price > 50.0]#window.length(16)
    select symbol, max(price) as mp insert into Out2;
    @info(name='q3') from S#window.externalTimeBatch(volume, 1000)
    select symbol, sum(price) as sp insert into Out3;
    """)
    delivered = [0]
    for q in ("q1", "q2", "q3"):
        rt.add_callback(
            q,
            lambda ts, ins, rem, _d=delivered: _d.__setitem__(
                0, _d[0] + len(ins or ()) + len(rem or ())
            ),
        )
    rt.start()
    for s in ("A", "B", "C", "D"):
        mgr.interner.intern(s)
    n = batch * 4
    rng = np.random.default_rng(7)
    cols = {
        "symbol": rng.integers(1, 5, n).astype(np.int32),
        "price": rng.uniform(0, 100, n).astype(np.float32),
        "volume": (np.arange(n, dtype=np.int64) * 7) % 2000,
    }
    ts = np.arange(n, dtype=np.int64) + 1_700_000_000_000
    h = rt.get_input_handler("S")
    for k in range(chunks):
        h.send_columns(ts + k * n, cols, now=int(ts[-1] + k * n))
    _truth_sync(rt)
    rep = rt.calibration_report()
    status = _snapshot_status(rt)
    rt.shutdown()
    mgr.shutdown()
    out: dict = {"calibration_delivered_rows": delivered[0]}
    if rep is not None:
        out["calibration"] = rep
        out["calibration_kinds"] = rep.get("kinds_paired", [])
    if status is not None and "roofline" in status:
        out["calibration_roofline"] = status["roofline"]
    return out


def _leg_timebudget(batch=32768) -> dict:
    """Per-leg budget of the FUSED-INGEST PROGRAM ITSELF (VERDICT r3 item 1):
    for every headline leg, the wire width, host encode rate, one-chunk h2d
    time, and the device rate of the exact fused program the engine runs
    (pre-staged device wire, states donated, truth-synced). These terms
    provably bound the leg's end-to-end number and name its binding wall:
    e2e ~ K*B / (t_encode + t_h2d + t_device) per chunk, with h2d/d2h paying
    a ~fixed relay round trip on this tunnel."""
    import jax
    import jax.numpy as jnp

    from siddhi_tpu import SiddhiManager

    out = {}

    # shared fixed costs: sync floor + bulk h2d bandwidth
    f = jax.jit(lambda v: v.sum())
    x = jnp.zeros((16,), jnp.float32)
    np.asarray(f(x))  # compile + flip relay to truth mode
    floors = []
    for _ in range(10):
        t0 = time.perf_counter()
        np.asarray(f(x))
        floors.append(time.perf_counter() - t0)
    floors.sort()
    out["sync_floor_ms"] = round(floors[len(floors) // 2] * 1e3, 1)
    host = np.zeros((64 << 20,), dtype=np.uint8)
    t0 = time.perf_counter()
    dev = jax.device_put(host)
    np.asarray(dev[:1])
    out["h2d_mb_s"] = round(64 / (time.perf_counter() - t0), 1)
    del dev, host

    for name, (ql, stream, _mult, batch_override) in WORKLOADS.items():
        bsz = batch_override or batch
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(f"@app:batch(size='{bsz}')\n" + ql)
        _prime_interner(mgr, _make_stock_data(8)["names"])
        rt.start()
        fi = rt.junctions[stream].fused_ingest
        if fi is None or not fi.eligible():
            out[f"{name}_budget"] = "fused-ineligible"
            rt.shutdown(); mgr.shutdown()
            continue
        K = fi.K
        data = _make_stock_data(bsz * K)
        cols = {k: v for k, v in data.items() if k not in ("ts", "names")}
        # same narrow wire the engine would sample from this data
        encode, wire_bytes = fi.staged_codec(
            data["ts"][:bsz], {k: v[:bsz] for k, v in cols.items()}
        )
        t0 = time.perf_counter()
        bufs, counts, bases = [], np.full((K,), bsz, np.int32), np.zeros((K,), np.int64)
        for k in range(K):
            lo = k * bsz
            buf, base = encode(
                data["ts"][lo:lo + bsz],
                {kk: v[lo:lo + bsz] for kk, v in cols.items()}, bsz)
            bufs.append(buf)
            bases[k] = base
        wire = np.stack(bufs)
        t_encode = time.perf_counter() - t0
        ev = K * bsz

        def run_once(w):
            states = []
            for ep in fi.endpoints:
                if ep.qr.state is None:
                    ep.qr.state = ep.qr._fresh(ep.init_state(0))
                states.append(ep.qr.state)
            tstates = {}
            for ep in fi.endpoints:
                tstates.update(ep.qr._collect_table_states())
            ns, _t, _a, _lin, _p = fi._fused(
                tuple(states), tstates, w, counts, bases,
                np.int64(1_700_000_000_000))
            for ep, st in zip(fi.endpoints, ns):
                ep.qr.state = st
            return ns

        ns = run_once(wire)  # compile
        np.asarray(jax.tree_util.tree_leaves(ns)[0].ravel()[:1])
        dw = jax.device_put(wire)
        np.asarray(dw.ravel()[:1])
        # device-only: pre-staged wire, 3 calls, one truth sync
        t0 = time.perf_counter()
        for _ in range(3):
            ns = run_once(dw)
        np.asarray(jax.tree_util.tree_leaves(ns)[0].ravel()[:1])
        t_dev = (time.perf_counter() - t0) / 3
        # whole call as the ENGINE pays it: host wire shipped per call
        t0 = time.perf_counter()
        for _ in range(3):
            ns = run_once(wire)
        np.asarray(jax.tree_util.tree_leaves(ns)[0].ravel()[:1])
        t_call = (time.perf_counter() - t0) / 3
        t_h2d = max(t_call - t_dev, 0.0)
        walls = {"encode": t_encode, "h2d": t_h2d, "device": t_dev}
        out[f"{name}_wire_B_per_ev"] = round(wire.nbytes / ev, 1)
        # logical = what the FULL-WIDTH packed wire would ship for the same
        # events (core/wire.py); the ratio is the leg's wire reduction —
        # the acceptance signal of the compact-wire-encoding work
        from siddhi_tpu.core.wire import logical_row_bytes

        logical = logical_row_bytes(rt.junctions[stream].schema.attrs)
        out[f"{name}_logical_B_per_ev"] = logical
        out[f"{name}_wire_reduction"] = round(
            logical / max(wire.nbytes / ev, 0.1), 2
        )
        out[f"{name}_encode_mev_s"] = round(ev / t_encode / 1e6, 1)
        out[f"{name}_h2d_eff_ms"] = round(t_h2d * 1e3, 1)
        out[f"{name}_device_mev_s"] = round(ev / t_dev / 1e6, 2)
        # the engine PIPELINES encode with async dispatch, so the budget is
        # an interval, not a point: ceiling = perfectly overlapped (the
        # slowest single stage binds), floor = fully sequential. A measured
        # leg outside [floor, ceiling] means the budget's terms don't
        # describe the program it ran — main() flags it.
        out[f"{name}_ceiling_mev_s"] = round(
            ev / max(walls.values()) / 1e6, 2)
        out[f"{name}_floor_mev_s"] = round(
            ev / (t_encode + t_h2d + t_dev) / 1e6, 2)
        out[f"{name}_wall"] = max(walls, key=walls.get)
        # pipelined-vs-serial A/B through the REAL engine send path: the
        # same four-chunk send, once fully serialized and once with the
        # chunk pipeline (core/pipeline.py), so the measured overlap can be
        # compared against the budget's predicted interval — overlap_pred =
        # serial-sum / slowest-stage is the ceiling a perfect pipeline
        # could reach, overlap_meas = t_serial / t_pipelined is what the
        # engine actually got (four chunks: the first chunk has nothing to
        # overlap with, so a two-chunk send under-reports the steady state).
        data2 = _make_stock_data(bsz * K * 4)
        cols2 = {k: v for k, v in data2.items() if k not in ("ts", "names")}
        h = rt.get_input_handler(stream)
        ab = {}
        # 'raw' runs LAST: force_full_width discards the encoded programs
        # permanently (the same state a runtime misfit fallback lands in),
        # so enc (= the pipelined encoded send) vs raw is the engine-path
        # A/B of the wire encoding itself
        for mode, pipe_on in (
            ("serial", False), ("pipe", True), ("raw", True),
        ):
            fi.pipeline_enabled = pipe_on
            if mode == "raw":
                fi.force_full_width()
            h.send_columns(data2["ts"], cols2)  # warm this mode's path
            _truth_sync(rt)
            t0 = time.perf_counter()
            h.send_columns(data2["ts"], cols2)
            _truth_sync(rt)
            ab[mode] = time.perf_counter() - t0
        ev2 = bsz * K * 4
        out[f"{name}_serial_mev_s"] = round(ev2 / ab["serial"] / 1e6, 2)
        out[f"{name}_pipe_mev_s"] = round(ev2 / ab["pipe"] / 1e6, 2)
        out[f"{name}_enc_mev_s"] = out[f"{name}_pipe_mev_s"]
        out[f"{name}_raw_mev_s"] = round(ev2 / ab["raw"] / 1e6, 2)
        out[f"{name}_raw_B_per_ev"] = round(fi._wire_bytes / bsz, 1)
        out[f"{name}_overlap_meas"] = round(ab["serial"] / ab["pipe"], 2)
        out[f"{name}_overlap_pred"] = round(
            (t_encode + t_h2d + t_dev) / max(walls.values()), 2)
        rt.shutdown()
        mgr.shutdown()
    out.update(_fusedgroup_budget(batch))
    return out


# a stream with THREE fusable consumers, two of them sharing an identical
# filter+window chain: the shape the FusionPlan forms a group + shared ring
# on (core/fusion_exec.py). The unfused side of the A/B runs the same app
# with @app:fuse(disable='true') — per-batch dispatch to every consumer.
FUSED_GROUP_QL = """
define stream StockStream (symbol string, price float, volume long);
@info(name='q1') from StockStream[price > 50]#window.length(64)
select symbol, avg(price) as ap insert into Out1;
@info(name='q2') from StockStream[price > 50]#window.length(64)
select symbol, max(price) as mx insert into Out2;
@info(name='q3') from StockStream#window.lengthBatch(1024)
select sum(volume) as tv insert into Out3;
"""


def _fusedgroup_budget(batch: int) -> dict:
    """Whole-graph fusion A/B (timebudget detail, `fusedgroup_*` keys): one
    stream feeding a 3-query fusable group (two share a window ring). The
    fused run reports the group engine's achieved-vs-predicted dispatch
    reduction (n*K per-batch dispatches -> 1 per chunk) and the unfused run
    (@app:fuse(disable='true')) is the same app on the per-batch path —
    the dispatch-amortization headroom this engine's multi-query apps get."""
    # the A/B is driven by the per-mode @app:fuse annotation — a process-wide
    # SIDDHI_TPU_FUSE (as the CI parity steps export) overrides annotations
    # and would silently neutralize one side (=1 fuses the "unfused" control,
    # =0 never forms the group), so pin it off for the measurement
    saved_fuse = os.environ.pop("SIDDHI_TPU_FUSE", None)
    try:
        return _fusedgroup_budget_modes(batch)
    finally:
        if saved_fuse is not None:
            os.environ["SIDDHI_TPU_FUSE"] = saved_fuse


def _fusedgroup_budget_modes(batch: int) -> dict:
    from siddhi_tpu import SiddhiManager

    out: dict = {}
    K = None
    for mode, head in (("fused", ""), ("unfused", "@app:fuse(disable='true')\n")):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            f"{head}@app:batch(size='{batch}')\n" + FUSED_GROUP_QL
        )
        _prime_interner(mgr, _make_stock_data(8)["names"])
        rt.start()
        fi = rt.junctions["StockStream"].fused_ingest
        if mode == "fused":
            if fi is None or fi.plan_group is None:
                out["fusedgroup_budget"] = "group-not-formed"
                rt.shutdown(); mgr.shutdown()
                return out
            K = fi.K
        n = batch * (K or 32)
        data = _make_stock_data(n)
        cols = {k: v for k, v in data.items() if k not in ("ts", "names")}
        h = rt.get_input_handler("StockStream")
        h.send_columns(data["ts"], cols)  # warm: compile this mode's path
        _truth_sync(rt)
        t0 = time.perf_counter()
        h.send_columns(data["ts"], cols)
        _truth_sync(rt)
        dt = time.perf_counter() - t0
        out[f"fusedgroup_{mode}_mev_s"] = round(n / dt / 1e6, 2)
        if mode == "fused":
            rep = fi.group_report() or {}
            for k in (
                "component", "queries", "chunks", "batches",
                "dispatches_per_chunk_before", "dispatches_per_chunk_after",
                "predicted_dispatch_reduction",
                "achieved_dispatch_reduction", "shared_state",
            ):
                if k in rep:
                    out[f"fusedgroup_{k}"] = rep[k]
        rt.shutdown()
        mgr.shutdown()
    if out.get("fusedgroup_unfused_mev_s"):
        out["fusedgroup_speedup"] = round(
            out["fusedgroup_fused_mev_s"] / out["fusedgroup_unfused_mev_s"], 2
        )
    return out


# stateless multi-query app for the sharded-execution leg: both consumers
# are batch-axis shardable (parallel/shard.py router_eligible), so the whole
# junction round-robins micro-batches across the mesh. Checksums are integer
# sums over delivered rows — exact, so sharded == unsharded is a hard assert.
SHARD_WORKLOADS = {
    "shard_filter": """
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream[price > 50] select symbol, volume insert into Out;
        """,
    "shard_project": """
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream select symbol, volume * 2 as v2, volume % 7 as v7
        insert into Out;
        """,
}


def _leg_shard(n_shard: int, batch=4096, events=1_000_000) -> dict:
    """Sharded-vs-unsharded A/B of the batch-axis router (`--shard N`,
    meant to run under XLA_FLAGS=--xla_force_host_platform_device_count=N
    on CPU): for each stateless workload, the same columnar feed runs once
    with SIDDHI_TPU_SHARD=N and once unsharded; the leg reports per-device
    dispatch/event counts (their sum must equal the unsharded event count),
    an exact delivered-row checksum on both sides, per-workload scaling,
    and the geomean scaling vs 1 device."""
    import jax

    from siddhi_tpu import SiddhiManager

    out: dict = {
        "shard_devices_requested": n_shard,
        "shard_devices_visible": len(jax.devices()),
        "shard_batch": batch,
    }
    data = _make_stock_data(events)
    cols = {k: v for k, v in data.items() if k not in ("ts", "names")}
    scalings = []
    for name, ql in SHARD_WORKLOADS.items():
        ql = f"@app:batch(size='{batch}')\n" + ql
        res = {}
        for mode, env_val in (("unsharded", "0"), ("sharded", str(n_shard))):
            saved = os.environ.get("SIDDHI_TPU_SHARD")
            os.environ["SIDDHI_TPU_SHARD"] = env_val
            try:
                mgr = SiddhiManager()
                rt = mgr.create_siddhi_app_runtime(ql)
            finally:
                if saved is None:
                    os.environ.pop("SIDDHI_TPU_SHARD", None)
                else:
                    os.environ["SIDDHI_TPU_SHARD"] = saved
            _prime_interner(mgr, data["names"])
            sink = [0, 0]  # rows, integer checksum

            def cb(ts, ins, removed, _s=sink):
                for e in ins or ():
                    _s[0] += 1
                    _s[1] += int(e.data[-1])
            rt.add_callback("q", cb)
            rt.start()
            h = rt.get_input_handler("StockStream")
            warm = batch * 8
            h.send_columns(
                data["ts"][:warm], {k: v[:warm] for k, v in cols.items()}
            )
            _truth_sync(rt)
            sink[0] = sink[1] = 0
            t0 = time.perf_counter()
            h.send_columns(data["ts"], cols)
            _truth_sync(rt)
            dt = time.perf_counter() - t0
            res[mode] = {
                "mev_s": round(events / dt / 1e6, 3),
                "rows": sink[0],
                "checksum": sink[1],
            }
            if mode == "sharded":
                fi = rt.junctions["StockStream"].fused_ingest
                sr = getattr(fi, "shard_router", None) if fi else None
                if sr is not None:
                    res["per_device_dispatches"] = list(sr.dispatches)
                    res["per_device_events"] = list(sr.events)
            rt.shutdown()
            mgr.shutdown()
        out[f"{name}_unsharded_mev_s"] = res["unsharded"]["mev_s"]
        out[f"{name}_sharded_mev_s"] = res["sharded"]["mev_s"]
        out[f"{name}_scaling"] = round(
            res["sharded"]["mev_s"] / res["unsharded"]["mev_s"], 3
        )
        scalings.append(out[f"{name}_scaling"])
        out[f"{name}_per_device_dispatches"] = res.get(
            "per_device_dispatches", []
        )
        out[f"{name}_per_device_events"] = res.get("per_device_events", [])
        # warmup events ride the router too, so compare the TIMED window
        # via delivered rows + checksum, and the full per-device event sum
        # against everything sent (warm + timed)
        out[f"{name}_per_device_events_sum"] = int(
            sum(res.get("per_device_events", []))
        )
        out[f"{name}_events_sent_total"] = events + batch * 8
        out[f"{name}_rows_match"] = (
            res["sharded"]["rows"] == res["unsharded"]["rows"]
        )
        out[f"{name}_checksum_match"] = (
            res["sharded"]["checksum"] == res["unsharded"]["checksum"]
        )
        out[f"{name}_checksum"] = res["sharded"]["checksum"]
    out["shard_scaling_geomean"] = round(
        math.exp(sum(math.log(max(s, 1e-9)) for s in scalings) / len(scalings)),
        3,
    ) if scalings else 0.0
    return out


# key-sharded STATEFUL workloads (`--leg shardstate`, parallel/keyshard.py):
# the keys axis hashes group-by aggregation state and join window rings
# across the mesh. Both sides of each A/B must deliver identical rows AND
# an identical integer checksum (the byte-parity contract), and the
# sharded group-by's per-device key ownership must sum to the total key
# count. Integer aggregators only — float scans are reassociation-
# sensitive under the owner mask and deliberately ineligible.
SHARDSTATE_GROUPBY = """
        define stream StockStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream
        select symbol, sum(volume) as sv, min(volume) as mn, count() as c
        group by symbol insert into Out;
        """

SHARDSTATE_JOIN = """
        @app:joinCapacity(size='65536')
        define stream StockStream (symbol string, price float, volume long);
        define stream QuoteStream (symbol string, price float, volume long);
        @info(name='q')
        from StockStream#window.length(8) join QuoteStream#window.length(8)
            on StockStream.symbol == QuoteStream.symbol
        select StockStream.symbol as s, QuoteStream.price as qp,
            StockStream.volume as av
        insert into Out;
        """


def _make_keyed_data(n: int, n_keys: int, seed: int = 7):
    rng = np.random.default_rng(seed)
    return {
        "ts": np.arange(n, dtype=np.int64) + 1_700_000_000_000,
        "symbol": rng.integers(1, n_keys + 1, size=n).astype(np.int32),
        "price": rng.uniform(0.0, 100.0, size=n).astype(np.float32),
        "volume": rng.integers(1, 1000, size=n).astype(np.int64),
        "names": [f"K{i}" for i in range(n_keys)],
    }


def _leg_shardstate(n_shard: int, batch=4096, events=400_000) -> dict:
    """Keyed-shard A/B (`--leg shardstate --shard N`): group-by-heavy and
    join workloads run the same feed with SIDDHI_TPU_SHARD=N +
    SIDDHI_TPU_SHARD_AXIS=keys and once unsharded. Reports per-workload
    throughput and scaling, exact row/checksum parity, per-device key
    ownership (must sum to the total), a key-count scaling sweep, and the
    geomean scaling."""
    import jax

    from siddhi_tpu import SiddhiManager

    out: dict = {
        "shardstate_devices_requested": n_shard,
        "shardstate_devices_visible": len(jax.devices()),
        "shardstate_batch": batch,
    }

    def run(ql, data, sharded: bool, join_feed=False):
        saved = {
            k: os.environ.get(k)
            for k in ("SIDDHI_TPU_SHARD", "SIDDHI_TPU_SHARD_AXIS")
        }
        os.environ["SIDDHI_TPU_SHARD"] = str(n_shard) if sharded else "0"
        os.environ["SIDDHI_TPU_SHARD_AXIS"] = "keys"
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(
                f"@app:batch(size='{batch}')\n" + ql
            )
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        _prime_interner(mgr, data["names"])
        sink = [0, 0]  # rows, integer checksum

        def cb(ts, ins, removed, _s=sink):
            for e in ins or ():
                _s[0] += 1
                _s[1] += int(e.data[-1])
        rt.add_callback("q", cb)
        rt.start()
        cols = {k: v for k, v in data.items() if k not in ("ts", "names")}
        n = len(data["ts"])
        if join_feed:
            # prime the quote ring once so both sides probe identical state
            qn = batch
            rt.get_input_handler("QuoteStream").send_columns(
                data["ts"][:qn], {k: v[:qn] for k, v in cols.items()}
            )
        h = rt.get_input_handler("StockStream")
        warm = min(batch * 4, n)
        h.send_columns(
            data["ts"][:warm], {k: v[:warm] for k, v in cols.items()}
        )
        _truth_sync(rt)
        sink[0] = sink[1] = 0
        t0 = time.perf_counter()
        h.send_columns(data["ts"], cols)
        _truth_sync(rt)
        dt = time.perf_counter() - t0
        res = {
            "mev_s": round(n / dt / 1e6, 3),
            "rows": sink[0],
            "checksum": sink[1],
        }
        qr = rt.queries["q"]
        ks = getattr(qr, "_keyshard", None)
        if ks is not None:
            desc = ks.describe_state()
            res["per_device_keys"] = desc.get("per_device_keys", [])
            res["total_keys"] = desc.get("total_keys", 0)
            res["skew"] = desc.get("skew")
        res["join_sharded"] = bool(getattr(qr, "_joinshard", False))
        rt.shutdown()
        mgr.shutdown()
        return res

    scalings = []
    for name, ql, join_feed in (
        ("keyshard_groupby", SHARDSTATE_GROUPBY, False),
        ("keyshard_join", SHARDSTATE_JOIN, True),
    ):
        data = _make_keyed_data(events, 8)
        a = run(ql, data, sharded=False, join_feed=join_feed)
        b = run(ql, data, sharded=True, join_feed=join_feed)
        out[f"{name}_unsharded_mev_s"] = a["mev_s"]
        out[f"{name}_sharded_mev_s"] = b["mev_s"]
        out[f"{name}_scaling"] = round(b["mev_s"] / a["mev_s"], 3)
        scalings.append(out[f"{name}_scaling"])
        out[f"{name}_rows_match"] = a["rows"] == b["rows"]
        out[f"{name}_checksum_match"] = a["checksum"] == b["checksum"]
        out[f"{name}_checksum"] = b["checksum"]
        if name == "keyshard_groupby":
            out[f"{name}_per_device_keys"] = b.get("per_device_keys", [])
            out[f"{name}_total_keys"] = b.get("total_keys", 0)
            out[f"{name}_keys_sum_match"] = (
                sum(b.get("per_device_keys", [])) == b.get("total_keys", -1)
            )
            out[f"{name}_skew"] = b.get("skew")
        else:
            out[f"{name}_join_sharded"] = b["join_sharded"]
    # key-count sweep: same sharded group-by at rising key cardinality —
    # occupancy spreads, throughput should hold or improve per key
    sweep = {}
    for n_keys in (8, 64, 512):
        data = _make_keyed_data(min(events, 200_000), n_keys, seed=11)
        b = run(SHARDSTATE_GROUPBY, data, sharded=True)
        sweep[str(n_keys)] = {
            "mev_s": b["mev_s"],
            "total_keys": b.get("total_keys", 0),
            "keys_sum_match": (
                sum(b.get("per_device_keys", [])) == b.get("total_keys", -1)
            ),
        }
    out["keyshard_key_sweep"] = sweep
    out["shardstate_scaling_geomean"] = round(
        math.exp(sum(math.log(max(s, 1e-9)) for s in scalings) / len(scalings)),
        3,
    ) if scalings else 0.0
    return out


# compact-wire-encoding workloads (`--leg wire`, core/wire.py): one
# dictionary-heavy stream (low-cardinality interned symbols + a declared
# qty range) and one delta-timestamp stream (monotone LONG seq). Each runs
# the SAME columnar feed with SIDDHI_TPU_WIRE=1 vs =0 (full width) and
# must deliver identical rows; the leg reports both sides' bytes/event,
# throughput, and the encoded-over-raw reduction, plus a forced MID-STREAM
# fallback case (cardinality overflow after the encoded steady state).
WIRE_WORKLOADS = {
    "wire_dict": (
        """
        @app:wire(dict.Ticks.sym='64', range.Ticks.qty='0..30000')
        define stream Ticks (sym string, price float, qty long);
        @info(name='q') from Ticks[qty > 10] select sym, qty insert into Out;
        """,
        "Ticks",
    ),
    "wire_delta": (
        """
        @app:wire(delta.Meters.seq='int16')
        define stream Meters (seq long, v float);
        @info(name='q') from Meters[v >= 0] select seq, v insert into Out;
        """,
        "Meters",
    ),
    # the UN-annotated twin of wire_delta: no @app:wire at all — the value
    # analysis (analysis/values.py) must PROVE seq monotone from its use as
    # externalTimeBatch's event-time variable and delta-encode it with no
    # hint. The leg reports how much of wire_delta's hinted reduction the
    # inference recovers (`wire_delta_inferred_recovery`).
    "wire_delta_inferred": (
        """
        define stream Meters (seq long, v float);
        @info(name='q') from Meters#window.externalTimeBatch(seq, 1000)
        select seq, v insert into Out;
        """,
        "Meters",
    ),
}


def _leg_wire(batch=4096, events=400_000) -> dict:
    """Wire-encoding A/B (`--leg wire`): per workload, the same feed runs
    encoded (SIDDHI_TPU_WIRE=1: the @app:wire static spec engages) and raw
    (=0: full-width wire), with exact delivered-row counts + integer
    checksums on both sides, per-side wire bytes/event, and the byte
    reduction. Ends with the runtime-guard case: a batch violating the
    declared dictionary cardinality arrives AFTER the encoded steady
    state, the engine falls back full-width mid-stream, and the delivered
    rows must still match the raw run exactly."""
    from siddhi_tpu import SiddhiManager

    out: dict = {"wire_batch": batch}
    rng = np.random.default_rng(11)
    n = max(batch * 16, min(events, 1_000_000))
    feeds = {
        "wire_dict": (
            np.arange(n, dtype=np.int64) + 1_700_000_000_000,
            {
                "sym": rng.integers(1, 33, n).astype(np.int32),
                "price": rng.uniform(0, 100, n).astype(np.float32),
                "qty": rng.integers(0, 1000, n).astype(np.int64),
            },
        ),
        "wire_delta": (
            np.arange(n, dtype=np.int64) + 1_700_000_000_000,
            {
                "seq": np.arange(n, dtype=np.int64) + 10**12,
                "v": rng.uniform(0, 10, n).astype(np.float32),
            },
        ),
    }
    feeds["wire_delta_inferred"] = feeds["wire_delta"]

    def run(name, ql, stream, env_val, feed, cb_col):
        saved = os.environ.get("SIDDHI_TPU_WIRE")
        os.environ["SIDDHI_TPU_WIRE"] = env_val
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(
                f"@app:batch(size='{batch}')\n" + ql
            )
        finally:
            if saved is None:
                os.environ.pop("SIDDHI_TPU_WIRE", None)
            else:
                os.environ["SIDDHI_TPU_WIRE"] = saved
        for i in range(1, 400):
            mgr.interner.intern(f"SYM{i}")
        sink = [0, 0]  # rows, integer checksum

        def cb(ts, ins, removed, _s=sink):
            for e in ins or ():
                _s[0] += 1
                _s[1] += int(e.data[cb_col])
        rt.add_callback("q", cb)
        rt.start()
        h = rt.get_input_handler(stream)
        ts_arr, cols = feed
        warm = batch * 4
        h.send_columns(
            ts_arr[:warm], {k: v[:warm] for k, v in cols.items()}
        )
        _truth_sync(rt)
        sink[0] = sink[1] = 0
        t0 = time.perf_counter()
        h.send_columns(ts_arr, cols)
        _truth_sync(rt)
        dt = time.perf_counter() - t0
        fi = rt.junctions[stream].fused_ingest
        res = {
            "mev_s": round(len(ts_arr) / dt / 1e6, 3),
            "rows": sink[0],
            "checksum": sink[1],
            "B_per_ev": round(fi._wire_bytes / batch, 2) if fi else None,
        }
        rt.shutdown()
        mgr.shutdown()
        return res

    for name, (ql, stream) in WIRE_WORKLOADS.items():
        cb_col = 1 if name == "wire_dict" else 0
        enc = run(name, ql, stream, "1", feeds[name], cb_col)
        raw = run(name, ql, stream, "0", feeds[name], cb_col)
        out[f"{name}_enc_mev_s"] = enc["mev_s"]
        out[f"{name}_raw_mev_s"] = raw["mev_s"]
        out[f"{name}_enc_B_per_ev"] = enc["B_per_ev"]
        out[f"{name}_raw_B_per_ev"] = raw["B_per_ev"]
        if enc["B_per_ev"] and raw["B_per_ev"]:
            out[f"{name}_reduction"] = round(
                raw["B_per_ev"] / enc["B_per_ev"], 2
            )
        out[f"{name}_rows_match"] = enc["rows"] == raw["rows"]
        out[f"{name}_checksum_match"] = enc["checksum"] == raw["checksum"]
        out[f"{name}_rows"] = enc["rows"]
    # how much of the DECLARED delta hint's byte reduction pure inference
    # recovers on the un-annotated twin (ISSUE: must be >= 0.8 in CI)
    if out.get("wire_delta_reduction") and out.get(
        "wire_delta_inferred_reduction"
    ):
        out["wire_delta_inferred_recovery"] = round(
            out["wire_delta_inferred_reduction"]
            / out["wire_delta_reduction"], 3
        )

    # forced mid-stream fallback: after the dict-encoded steady state, a
    # burst with 300 distinct symbols (> the declared 64) arrives — the
    # runtime guard rebuilds full-width and NOTHING may be lost or differ
    ql, stream = WIRE_WORKLOADS["wire_dict"]
    ts_arr, cols = feeds["wire_dict"]
    nb = batch * 8
    burst = {
        "sym": (np.arange(nb, dtype=np.int32) % 300) + 1,
        "price": np.full(nb, 50.0, np.float32),
        "qty": np.full(nb, 500, np.int64),
    }
    sides = {}
    for env_val in ("1", "0"):
        saved = os.environ.get("SIDDHI_TPU_WIRE")
        os.environ["SIDDHI_TPU_WIRE"] = env_val
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(
                f"@app:batch(size='{batch}')\n" + ql
            )
        finally:
            if saved is None:
                os.environ.pop("SIDDHI_TPU_WIRE", None)
            else:
                os.environ["SIDDHI_TPU_WIRE"] = saved
        for i in range(1, 400):
            mgr.interner.intern(f"SYM{i}")
        rows = []
        rt.add_callback(
            "q", lambda t, ins, rem, _r=rows: _r.extend(
                tuple(e.data) for e in (ins or ())
            )
        )
        rt.start()
        h = rt.get_input_handler(stream)
        steady = batch * 8
        h.send_columns(
            ts_arr[:steady], {k: v[:steady] for k, v in cols.items()}
        )
        h.send_columns(ts_arr[steady : steady + nb], burst)
        _truth_sync(rt)
        fi = rt.junctions[stream].fused_ingest
        sides[env_val] = (rows, fi._narrow if fi else None)
        rt.shutdown()
        mgr.shutdown()
    out["wire_fallback_rows_match"] = sides["1"][0] == sides["0"][0]
    out["wire_fallback_rows"] = len(sides["1"][0])
    out["wire_fallback_full_width"] = sides["1"][1] == {}
    return out


VERIFY_HEAD = (
    "@app:batch(size='32')\n"
    "define stream S (symbol string, price float, volume long);\n"
)

# ~20 representative behaviors for the CPU-vs-TPU differential (VERDICT r2
# item 4): the same app + events run on both backends; rows must match within
# float tolerance. Each case: (QL, store-queries to read afterwards).
VERIFY_CASES = {
    "filter_num": VERIFY_HEAD + "@info(name='q') from S[price > 50 and volume < 800] select symbol, price insert into Out;",
    "filter_str": VERIFY_HEAD + "@info(name='q') from S[symbol == 'IBM' or symbol == 'WSO2'] select symbol, volume insert into Out;",
    "arith_promote": VERIFY_HEAD + "@info(name='q') from S select symbol, price * 2 as p2, volume / 7 as v7, volume % 5 as v5 insert into Out;",
    "builtins": VERIFY_HEAD + "@info(name='q') from S select ifThenElse(price > 50, 'hi', 'lo') as tag, cast(volume, 'double') as vd, maximum(price, 50.0) as mx insert into Out;",
    "len_window_avg": VERIFY_HEAD + "@info(name='q') from S#window.length(7) select symbol, avg(price) as ap, sum(volume) as tv insert into Out;",
    "len_window_minmax": VERIFY_HEAD + "@info(name='q') from S#window.length(5) select min(price) as mn, max(price) as mx insert into Out;",
    "len_batch_group": VERIFY_HEAD + "@info(name='q') from S#window.lengthBatch(8) select symbol, sum(volume) as tv, count() as c group by symbol insert into Out;",
    "time_window": "@app:playback\n" + VERIFY_HEAD + "@info(name='q') from S#window.time(40) select symbol, sum(volume) as tv insert into Out;",
    "external_time": VERIFY_HEAD + "@info(name='q') from S#window.externalTime(volume, 500) select symbol, count() as c insert into Out;",
    "stddev_distinct": VERIFY_HEAD + "@info(name='q') from S#window.length(9) select stdDev(price) as sd, distinctCount(symbol) as dc insert into Out;",
    "having_order": VERIFY_HEAD + "@info(name='q') from S#window.lengthBatch(8) select symbol, sum(volume) as tv group by symbol having tv > 100 order by tv desc limit 3 insert into Out;",
    "self_join": VERIFY_HEAD + """@app:joinCapacity(size='256')
        @info(name='q') from S#window.length(4) as a join S#window.length(4) as b
        on a.volume == b.volume select a.symbol as s1, b.symbol as s2 insert into Out;""",
    "pattern_within": VERIFY_HEAD + """@app:patternCapacity(size='64')
        @info(name='q') from every a=S[price > 90] -> b=S[price < 10] within 100 milliseconds
        select a.symbol as s1, b.symbol as s2 insert into Out;""",
    "count_seq": VERIFY_HEAD + """@app:patternCapacity(size='64')
        @info(name='q') from every a=S[price > 80]<2:3> -> b=S[price < 20]
        select b.symbol as s2 insert into Out;""",
    "logical_pattern": VERIFY_HEAD + """@app:patternCapacity(size='64')
        @info(name='q') from every (a=S[price > 90] and b=S[volume > 500])
        select a.price as pa, b.volume as vb insert into Out;""",
    "sort_window": VERIFY_HEAD + "@info(name='q') from S#window.sort(5, price) select min(price) as mn, count() as c insert into Out;",
    "frequent": VERIFY_HEAD + "@info(name='q') from S#window.frequent(3, symbol) select symbol, count() as c insert into Out;",
    "stream_fn": VERIFY_HEAD + "@info(name='q') from S#log('v') select symbol, price insert into Out;",
    # multi-query-per-stream app: q/q2 share an identical filter+window
    # chain (one FusionPlan shared ring), q3 fuses alongside, and q4's rate
    # limiter is an SA124 hazard riding the residual per-batch path — rows
    # are collected PER QUERY so the fuse-on/off CI diff compares each
    # consumer's own delivery order (core/fusion_exec.py)
    "multi_query_shared": VERIFY_HEAD + """@info(name='q') from S[price > 40]#window.length(6) select symbol, avg(price) as ap insert into Out1;
        @info(name='q2') from S[price > 40]#window.length(6) select symbol, max(price) as mx insert into Out2;
        @info(name='q3') from S#window.lengthBatch(8) select sum(volume) as tv insert into Out3;
        @info(name='q4') from S[volume > 300] select symbol, volume output every 5 events insert into Out4;""",
}

# cases observed via store queries over tables instead of callbacks
VERIFY_TABLE_CASES = {
    "table_crud": (
        VERIFY_HEAD + """@capacity(size='512') define table T (symbol string, total long);
        @info(name='w') from S#window.lengthBatch(8)
        select symbol, sum(volume) as total group by symbol
        update or insert into T on T.symbol == symbol;""",
        "from T select symbol, total",
    ),
    "partitioned": (
        VERIFY_HEAD + """@app:partitionCapacity(size='16')
        @capacity(size='2048') define table T (symbol string, ap float);
        partition with (symbol of S) begin
        @info(name='w') from S[price > 20] select symbol, price as ap
        insert into T;
        end;""",
        "from T select symbol, ap",
    ),
}


def _leg_verify() -> dict:
    """Run every verify case on the CURRENT backend and return its rows.

    With SIDDHI_TPU_VERIFY_COLUMNAR=1 the same events are ingested
    COLUMNARLY (one send_columns call, symbols pre-interned) so the fused
    path actually engages — the CI parity step runs the leg twice in this
    mode, SIDDHI_TPU_PIPELINE=1 vs =0, and diffs the rows; holding the
    ingestion mode fixed isolates the pipeline (row-by-row vs columnar
    feeds legitimately batch differently), and a per-row feed would never
    reach try_send at all."""
    from siddhi_tpu import SiddhiManager

    columnar = os.environ.get("SIDDHI_TPU_VERIFY_COLUMNAR", "").lower() in (
        "1", "on", "true",
    )
    rng = np.random.default_rng(99)
    n = 96
    ts = np.arange(n, dtype=np.int64) * 7 + 1_700_000_000_000
    rows = [
        (
            ["WSO2", "IBM", "GOOG", "MSFT"][int(rng.integers(0, 4))],
            float(np.round(rng.uniform(0.0, 100.0), 3)),
            int(rng.integers(1, 1000)),
        )
        for _ in range(n)
    ]

    def feed(mgr, h):
        if columnar:
            cols = {
                "symbol": np.array(
                    [mgr.interner.intern(r[0]) for r in rows], np.int32
                ),
                "price": np.array([r[1] for r in rows], np.float32),
                "volume": np.array([r[2] for r in rows], np.int64),
            }
            h.send_columns(ts, cols, now=int(ts[-1]))
        else:
            for i, r in enumerate(rows):
                h.send(r, timestamp=int(ts[i]))

    out: dict = {}
    def _collector(rows: list):
        return lambda t, ins, rem: rows.extend(
            [("+",) + tuple(e.data) for e in (ins or [])]
            + [("-",) + tuple(e.data) for e in (rem or [])]
        )

    for name, ql in VERIFY_CASES.items():
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(ql)
            if len(rt.queries) > 1:
                # multi-query app: one row list per query, so the fused
                # group's per-endpoint drain order is compared per consumer
                got: dict = {qid: [] for qid in rt.queries}
                for qid in rt.queries:
                    rt.add_callback(qid, _collector(got[qid]))
            else:
                got = []
                rt.add_callback("q", _collector(got))
            rt.start()
            feed(mgr, rt.get_input_handler("S"))
            rt.shutdown()
            mgr.shutdown()
            out[name] = got
        except Exception as e:
            out[name] = f"ERROR: {type(e).__name__}: {e}"
    for name, (ql, sq) in VERIFY_TABLE_CASES.items():
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(ql)
            rt.start()
            feed(mgr, rt.get_input_handler("S"))
            out[name] = sorted(
                tuple(e.data) for e in rt.query(sq)
            )
            rt.shutdown()
            mgr.shutdown()
        except Exception as e:
            out[name] = f"ERROR: {type(e).__name__}: {e}"
    import jax

    return {"cases": out, "backend": jax.default_backend()}


def _rows_match(a, b, tol=2e-4):
    if type(a) is not type(b):
        return False
    if isinstance(a, dict):  # multi-query cases: rows keyed per query
        return set(a) == set(b) and all(
            _rows_match(a[k], b[k], tol) for k in a
        )
    if isinstance(a, (list, tuple)):
        return len(a) == len(b) and all(_rows_match(x, y, tol) for x, y in zip(a, b))
    if isinstance(a, float):
        if b == 0:
            return abs(a) < tol
        return abs(a - b) <= tol * max(1.0, abs(a), abs(b))
    return a == b


def _verify_tpu_vs_cpu(args) -> dict:
    """Run the verify cases on the default (TPU) backend and on CPU in
    separate subprocesses; diff per case with float tolerance."""
    results = {}
    backends = {}
    for plat in ("tpu", "cpu"):
        cmd = [sys.executable, os.path.abspath(__file__), "--leg", "verify_cases"]
        env = dict(os.environ)
        env["SIDDHI_TPU_AUX_DRAIN_S"] = "0"
        if plat == "cpu":
            env["JAX_PLATFORMS"] = "cpu"
            env["PALLAS_AXON_POOL_IPS"] = ""
        else:
            # the accelerator side must not inherit a dev shell's CPU pin,
            # or the differential silently compares CPU against CPU
            env.pop("JAX_PLATFORMS", None)
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=650, env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else "{}"
        got = json.loads(line) if proc.returncode == 0 else {}
        results[plat] = got.get("cases", {})
        backends[plat] = got.get("backend", "subprocess-failed")
    per_case = {}
    for name in sorted(set(results["tpu"]) | set(results["cpu"])):
        a, b = results["tpu"].get(name), results["cpu"].get(name)
        if isinstance(a, str) or isinstance(b, str):
            per_case[name] = "FAIL"  # an ERROR on either side never passes
            continue
        # JSON round-trip turns tuples into lists on both sides equally
        per_case[name] = "pass" if _rows_match(a, b) else "FAIL"
    if backends["tpu"] == backends["cpu"]:
        # same backend on both sides = no differential at all; fail loudly
        per_case = {k: "FAIL(same-backend)" for k in per_case}
    n_pass = sum(1 for v in per_case.values() if v == "pass")
    artifact = {
        "n_pass": n_pass,
        "n_cases": len(per_case),
        "backends": backends,
        "per_case": per_case,
    }
    try:
        with open(
            os.path.join(os.path.dirname(os.path.abspath(__file__)), "VERIFY.json"),
            "w",
        ) as f:
            json.dump(
                {**artifact, "tpu": results["tpu"], "cpu": results["cpu"]},
                f, indent=1, default=str,
            )
    except Exception:
        pass
    return {"verify_pass": n_pass, "verify_cases": len(per_case)}


def _leg_disorder(events: int) -> dict:
    """A/B disorder run under @app:watermark: an ordered feed vs the SAME
    feed shuffled within the watermark bound by the seeded `ingest_disorder`
    fault site, pushed through the bounded reorder stage. Reports the
    shuffled run's throughput, reorder-buffer occupancy, watermark-lag p99
    across the feed, late-event counts, and whether the two runs' emissions
    (rows + checksum) match exactly — the engine-level parity headline."""
    import zlib

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.testing import faults

    n = max(4_096, min(int(events), 200_000))
    base = 1_700_000_000_000
    step_ms = 7
    jitter_ms = 1500  # < the 2 sec bound below; displaces rows ~214 slots
    ql = """
    @app:watermark(bound='2 sec')
    define stream S (sym string, price double, vol long);
    @info(name='q')
    from S#window.length(64)
    select sym, sum(price) as total, count() as cnt
    insert into Out;
    """
    rng = np.random.default_rng(5)
    ts = base + np.arange(n, dtype=np.int64) * step_ms
    syms = np.asarray([f"S{i % 8}" for i in range(n)])
    price = np.round(rng.uniform(10.0, 100.0, n), 2)
    vol = rng.integers(1, 500, n).astype(np.int64)
    chunk = 2048

    def run(disorder: bool) -> dict:
        if disorder:
            faults.install(faults.parse_plan(
                f"seed=29;ingest_disorder:jitter={jitter_ms},times=-1"
            ))
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(ql)
            crc = [0]
            rows = [0]

            def on_out(evs):
                for e in evs:
                    s = f"{e.timestamp}|{e.data[0]}|{e.data[1]:.3f}|{e.data[2]}"
                    crc[0] = zlib.crc32(s.encode(), crc[0])
                rows[0] += len(evs)

            rt.add_callback("Out", on_out)
            rt.start()
            tracker = rt._watermark.trackers
            lags, occupancy = [], []
            h = rt.get_input_handler("S")
            t0 = time.perf_counter()
            for i in range(0, n, chunk):
                h.send_columns(
                    ts[i:i + chunk],
                    {
                        "sym": syms[i:i + chunk],
                        "price": price[i:i + chunk],
                        "vol": vol[i:i + chunk],
                    },
                )
                d = tracker["S"].describe()
                if d["lag_ms"] is not None:
                    lags.append(d["lag_ms"])
                occupancy.append(d["buffered"])
            rt.drain_watermarks()
            wall = time.perf_counter() - t0
            ws = rt.snapshot_status()["watermark"]["streams"]["S"]
            rt.shutdown()
            mgr.shutdown()
            return {
                "events_per_s": n / wall if wall > 0 else 0.0,
                "rows": rows[0],
                "crc": crc[0],
                "lag_p99_ms": (
                    float(np.percentile(np.asarray(lags), 99)) if lags else 0.0
                ),
                "mean_buffered": (
                    float(np.mean(occupancy)) if occupancy else 0.0
                ),
                "peak_buffered": ws["peak_buffered"],
                "released": ws["released"],
                "late_total": ws["late_total"],
            }
        finally:
            if disorder:
                faults.uninstall()

    ordered = run(disorder=False)
    shuffled = run(disorder=True)
    return {
        "disorder": round(shuffled["events_per_s"], 1),
        "disorder_parity": (
            ordered["rows"] == shuffled["rows"]
            and ordered["crc"] == shuffled["crc"]
            and ordered["rows"] > 0
        ),
        "disorder_rows": shuffled["rows"],
        "disorder_lag_p99_ms": round(shuffled["lag_p99_ms"], 1),
        "disorder_peak_buffered": shuffled["peak_buffered"],
        "disorder_mean_buffered": round(shuffled["mean_buffered"], 1),
        "disorder_released": shuffled["released"],
        "disorder_late_total": shuffled["late_total"],
        "disorder_ordered_events_per_s": round(ordered["events_per_s"], 1),
    }


def _leg_blackbox(events: int, batch: int) -> dict:
    """A/B cost of the always-on black-box recorder (ISSUE 20): the SAME
    columnar feed runs with `@app:blackbox` armed and unarmed, reporting
    the recorder's throughput overhead (ring writes are preallocated
    column copies — the FlightRecorder budget), then fires a synthetic
    incident and replays the frozen bundle in-process, reporting whether
    the replay reproduced the live emissions byte-identical."""
    import tempfile

    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.observability.blackbox import (
        attach_emission_collector, emissions_checksum, replay_incident,
    )

    n = max(4_096, min(int(events), 400_000))
    base = 1_700_000_000_000
    rng = np.random.default_rng(11)
    ts = base + np.arange(n, dtype=np.int64) * 3
    price = np.round(rng.uniform(5.0, 100.0, n), 2)
    vol = rng.integers(1, 500, n).astype(np.int64)
    ql = """
    @app:name('bbbench')
    {ann}
    define stream S (price double, vol long);
    @info(name='q')
    from S[price > 20.0]#window.length(64)
    select sum(price) as total, count() as cnt insert into Out;
    """

    def run(armed: bool, bb_dir: str) -> dict:
        ann = (
            f"@app:blackbox(window='30 sec', triggers='crash', "
            f"ring='65536', keep='2', dir='{bb_dir}')" if armed else ""
        )
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql.format(ann=ann))
        rows = [0]
        rt.add_callback("Out", lambda evs: rows.__setitem__(
            0, rows[0] + len(evs)
        ))
        rt.start()
        h = rt.get_input_handler("S")
        t0 = time.perf_counter()
        for i in range(0, n, batch):
            h.send_columns(
                ts[i:i + batch],
                {"price": price[i:i + batch], "vol": vol[i:i + batch]},
            )
        wall = time.perf_counter() - t0
        out = {
            "events_per_s": n / wall if wall > 0 else 0.0,
            "rows": rows[0],
        }
        if armed:
            iid = rt._blackbox.fire("crash", "bench synthetic")
            out["incident"] = iid
            out["bundle"] = rt.incidents()[-1]["path"] if iid else None
        mgr.shutdown()
        return out

    with tempfile.TemporaryDirectory(prefix="bench_blackbox_") as d:
        off = run(False, d)
        on = run(True, d)
        parity = False
        replay_rows = 0
        if on.get("bundle"):
            # the synthetic incident's ring only holds the last `ring`
            # rows; replay that tail against a fresh live run of the tail
            replay = replay_incident(on["bundle"])
            tail = min(n, 65536)
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(ql.format(ann=""))
            ref = attach_emission_collector(rt)
            rt.start()
            rt.get_input_handler("S").send_columns(
                ts[n - tail:],
                {"price": price[n - tail:], "vol": vol[n - tail:]},
            )
            mgr.shutdown()
            replay_rows = sum(len(v) for v in replay.emissions.values())
            parity = (
                replay.emissions == ref
                and replay.checksum() == emissions_checksum(ref)
            )
    ratio = (
        on["events_per_s"] / off["events_per_s"]
        if off["events_per_s"] else 0.0
    )
    return {
        "blackbox": round(on["events_per_s"], 1),
        "blackbox_off_events_per_s": round(off["events_per_s"], 1),
        "blackbox_overhead_ratio": round(ratio, 3),
        "blackbox_rows_match": on["rows"] == off["rows"],
        "blackbox_replay_rows": replay_rows,
        "blackbox_replay_parity": parity,
    }


def _run_leg(name: str, args) -> dict:
    if name in WORKLOADS or name.endswith("_delivered"):
        v = _leg_throughput(name, args.events, args.batch)
        out = {name: round(v, 1)}
        if _LAST_STATUS[0] is not None:
            out[f"{name}_status"] = _LAST_STATUS[0]
        return out
    if name == "tables":
        return _leg_table_scaling()
    if name == "p99":
        return _leg_p99()
    if name == "timebudget":
        return _leg_timebudget(args.batch)
    if name == "calibration":
        return _leg_calibration()
    if name == "verify_cases":
        return _leg_verify()
    if name == "blackbox":
        return _leg_blackbox(args.events, args.batch)
    if name == "disorder":
        return _leg_disorder(args.events)
    if name == "verify":
        return _verify_tpu_vs_cpu(args)
    if name == "wire":
        # keep this leg's own default batch (a 4096 chunk shape shows the
        # dict/delta amortization honestly) unless --batch was passed
        batch = args.batch if getattr(args, "batch_explicit", True) else 4096
        return _leg_wire(batch=batch, events=min(args.events, 1_000_000))
    if name == "shard":
        if not args.shard:
            return {"shard_error": "pass --shard N (e.g. --shard 8 under "
                    "XLA_FLAGS=--xla_force_host_platform_device_count=8)"}
        # honor --batch like every other leg, but keep this leg's own
        # default: at the driver-wide 32768 a 200k-event feed is fewer
        # micro-batches than devices and the router can't even engage
        batch = args.batch if getattr(args, "batch_explicit", True) else 4096
        return _leg_shard(
            args.shard, batch=batch, events=min(args.events, 1_000_000)
        )
    if name == "shardstate":
        if not args.shard:
            return {"shardstate_error": "pass --shard N (e.g. --shard 8 "
                    "under XLA_FLAGS=--xla_force_host_platform_device_"
                    "count=8)"}
        batch = args.batch if getattr(args, "batch_explicit", True) else 4096
        return _leg_shardstate(
            args.shard, batch=batch, events=min(args.events, 400_000)
        )
    raise SystemExit(f"unknown leg {name!r}")


def main():
    ap = argparse.ArgumentParser()
    # 1M events (r05 ran 2M): throughput is a rate, halving the volume
    # halves each headline leg's wall without moving the number — part of
    # fitting the full suite back under the harness budget (ROADMAP item)
    ap.add_argument("--events", type=int, default=1_000_000)
    # default=None so an EXPLICIT `--batch 32768` is distinguishable from
    # "unset": the shard/wire legs keep their own smaller defaults only
    # when the caller didn't pick a batch
    ap.add_argument("--batch", type=int, default=None,
                    help="micro-batch size (default 32768)")
    ap.add_argument(
        "--shard", type=int, default=0,
        help="device count for the sharded-execution leg (`--leg shard`); "
        "also appends the leg to a full run. Run under XLA_FLAGS="
        "--xla_force_host_platform_device_count=N for a virtual CPU mesh",
    )
    ap.add_argument("--leg", help="run ONE leg in-process and print its JSON")
    ap.add_argument(
        "--deadline", type=float,
        default=float(os.environ.get("SIDDHI_BENCH_DEADLINE_S", "") or 2400),
        help="overall wall-clock budget in seconds. BENCH_r05 exited rc=124 "
        "with NO output: the harness's outer `timeout` matched the old "
        "2700 s default, leaving zero slack for the final JSON line — the "
        "default is now 2400 s and a snapshot JSON line is printed after "
        "every completed leg, so even an uncooperative SIGKILL leaves the "
        "last snapshot as a parseable tail. Pass 0 to opt out; legs that "
        "would not fit are skipped so the final JSON line always prints",
    )
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()
    args.batch_explicit = args.batch is not None
    if args.batch is None:
        args.batch = 32768

    # SIDDHI_TPU_BENCH_BUDGET=<seconds>: one knob for constrained harnesses —
    # trims the overall deadline AND the per-leg subprocess caps (no single
    # leg may eat more than a third of the budget), so the suite provably
    # finishes (or skip-records) inside the budget
    try:
        budget = float(os.environ.get("SIDDHI_TPU_BENCH_BUDGET", "") or 0)
    except ValueError:
        budget = 0.0
    if budget > 0:
        args.deadline = (
            min(args.deadline, budget) if args.deadline else budget
        )

    if args.leg:
        print(json.dumps(_run_leg(args.leg, args)))
        return

    # driver resilience contract (BENCH_r05 shipped rc=124 and NO output when
    # one wedged leg ate the harness budget): every leg runs under its own
    # subprocess timeout, the overall --deadline skips legs that cannot fit,
    # and the final JSON line is emitted exactly once on EVERY exit path —
    # normal completion, per-leg timeout, driver crash, or SIGTERM/SIGINT
    # from an outer `timeout`.
    import signal

    detail: dict = {}
    failed: list = []
    current_leg = [None]
    current_child = [None]
    emitted = [False]

    def _line(extra: dict | None = None) -> str:
        d = dict(detail)
        if extra:
            d.update(extra)
        if failed:
            d["failed_legs"] = list(failed)
        per = [d.get(k) for k in WORKLOADS]
        per = [v for v in per if v]
        geomean = (
            math.exp(sum(math.log(v) for v in per) / len(per)) if per else 0.0
        )
        return json.dumps(
            {
                "metric": "engine_throughput_geomean",
                "value": round(geomean, 1),
                "unit": "events/s",
                "vs_baseline": round(geomean / REFERENCE_EVENTS_PER_SEC, 3),
                "detail": d,
            }
        )

    def _emit(via_fd: bool = False):
        """Print the final JSON line exactly once. `via_fd` (signal path)
        bypasses the buffered stdout object with one os.write straight to
        fd 1: a SIGKILL 10 s later (`timeout -k 10`) cannot lose an
        unflushed buffer, and os.write is async-signal-safe where print +
        flush on a partially-written buffer is not (BENCH_r05 shipped
        rc=124 with NO JSON at all — this path plus the per-leg snapshot
        lines below are the fix, held by tests/test_bench_driver.py +
        tier1.yml)."""
        if emitted[0]:
            return
        emitted[0] = True
        line = _line()
        if via_fd:
            try:
                os.write(1, (line + "\n").encode())
            except OSError:
                pass
            return
        print(line)
        sys.stdout.flush()

    def _on_signal(signum, frame):
        # EMIT FIRST: the JSON must be on fd 1 before anything that could
        # block (killing a wedged child can); the outer `timeout -k` only
        # grants a grace window, not cooperation
        leg = current_leg[0]
        if leg is not None:
            failed.append({"leg": leg, "error": f"signal{signum}"})
            detail[f"{leg}_error"] = f"signal{signum}"
        _emit(via_fd=True)
        child = current_child[0]
        if child is not None:  # don't orphan a leg burning the machine
            try:
                child.kill()
            except Exception:
                pass
        os._exit(0)

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    if hasattr(signal, "SIGALRM") and args.deadline:
        # belt-and-suspenders: even if the per-leg timeouts wedge (a child
        # that ignores kill, a hung communicate()), the alarm fires shortly
        # after the deadline and the handler emits from in-process
        signal.signal(signal.SIGALRM, _on_signal)
        signal.alarm(int(args.deadline) + 60)

    t_start = time.monotonic()
    legs = list(WORKLOADS) + [
        "filter_window_avg_delivered", "pattern_2state_delivered",
        "tumbling_groupby_delivered", "p99", "tables", "wire", "timebudget",
        "calibration", "disorder", "verify",
    ]
    if args.shard:
        legs.append("shard")
    try:
        for leg in legs:
            current_leg[0] = leg
            # trimmed per-leg caps (was 1200/2800): one wedged leg can no
            # longer eat half the suite budget before the deadline logic
            # even gets a say
            leg_timeout = 1500 if leg == "verify" else 900
            if budget > 0:
                leg_timeout = min(leg_timeout, max(20.0, budget / 3.0))
            if args.deadline:
                remaining = args.deadline - (time.monotonic() - t_start)
                if remaining < 60:
                    failed.append({"leg": leg, "error": "skipped(deadline)"})
                    detail[f"{leg}_error"] = "skipped(deadline)"
                    print(_line({"partial_through_leg": leg}))
                    sys.stdout.flush()
                    continue
                # keep ~30 s of slack so the driver itself always finishes
                leg_timeout = min(leg_timeout, remaining - 30)
            cmd = [sys.executable, os.path.abspath(__file__), "--leg", leg,
                   "--events", str(args.events)]
            if args.batch_explicit:
                # forward --batch only when the caller chose one, so leg
                # subprocesses keep their own defaults otherwise
                cmd += ["--batch", str(args.batch)]
            if args.shard:
                cmd += ["--shard", str(args.shard)]
            env = dict(os.environ)
            env["SIDDHI_TPU_AUX_DRAIN_S"] = "0"
            env.setdefault(
                "PYTHONPATH", os.path.dirname(os.path.abspath(__file__))
            )
            out_text, err_text = "", ""
            try:
                child = subprocess.Popen(
                    cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True, env=env,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )
                current_child[0] = child
                try:
                    out_text, err_text = child.communicate(timeout=leg_timeout)
                except subprocess.TimeoutExpired:
                    child.kill()
                    child.communicate()
                    raise
                line = (
                    out_text.strip().splitlines()[-1]
                    if out_text.strip()
                    else "{}"
                )
                got = json.loads(line)
                if child.returncode != 0 and not got:
                    raise RuntimeError(f"rc={child.returncode}")
            except subprocess.TimeoutExpired:
                failed.append({"leg": leg, "error": "timeout"})
                got = {f"{leg}_error": "timeout"}
            except Exception as e:
                if args.verbose:
                    print(f"# leg {leg} FAILED: {e}", file=sys.stderr)
                    if err_text:
                        print(err_text[-2000:], file=sys.stderr)
                failed.append({"leg": leg, "error": type(e).__name__})
                got = {f"{leg}_error": f"{type(e).__name__}"}
            finally:
                current_child[0] = None
            detail.update(got)
            if args.verbose:
                print(f"# {leg}: {got}")
            # crash-proof progress: a snapshot of everything measured so far
            # after EVERY leg — if anything (even SIGKILL) takes the driver
            # down mid-suite, the tail line on fd 1 is still parseable JSON
            # (consumers read the LAST line; _emit prints the final one)
            print(_line({"partial_through_leg": leg}))
            sys.stdout.flush()
        current_leg[0] = None

        # budget sanity: every measured leg must fall inside its published
        # [floor, ceiling] interval (10% tolerance for run-to-run drift
        # between the leg subprocess and the budget subprocess)
        for leg in WORKLOADS:
            v = detail.get(leg)
            ceil_v = detail.get(f"{leg}_ceiling_mev_s")
            floor_v = detail.get(f"{leg}_floor_mev_s")
            if not v or not ceil_v or not floor_v:
                continue
            if v > ceil_v * 1e6 * 1.1 or v < floor_v * 1e6 * 0.5:
                detail[f"{leg}_budget_flag"] = (
                    f"measured {v:.0f} outside [{floor_v}M/2, {ceil_v}M*1.1]"
                )
    finally:
        _emit()


if __name__ == "__main__":
    main()
