"""REST service, doc-gen, and native ring tests.

Reference: modules/siddhi-service/src/test (SiddhiApiTestCase REST deploy),
siddhi-doc-gen mojos, and the @async Disruptor substrate
(StreamJunction.java:262-298) which the native ring re-platforms.
"""

import json
import time
import urllib.request

import pytest

from siddhi_tpu import SiddhiManager


class TestService:
    def test_deploy_and_undeploy(self):
        from siddhi_tpu.service import SiddhiService

        svc = SiddhiService()
        svc.start()
        base = f"http://{svc.host}:{svc.port}"
        try:
            body = (
                "@app:name('SvcApp')\n"
                "define stream S (a int);\n"
                "from S select a insert into Out;"
            ).encode()
            req = urllib.request.Request(
                f"{base}/siddhi/artifact/deploy", data=body, method="POST"
            )
            with urllib.request.urlopen(req) as resp:
                out = json.loads(resp.read())
            assert out == {"status": "deployed", "appName": "SvcApp"}
            assert svc.manager.get_siddhi_app_runtime("SvcApp") is not None

            with urllib.request.urlopen(
                f"{base}/siddhi/artifact/undeploy/SvcApp"
            ) as resp:
                out = json.loads(resp.read())
            assert out["status"] == "undeployed"
            assert svc.manager.get_siddhi_app_runtime("SvcApp") is None

            bad = urllib.request.Request(
                f"{base}/siddhi/artifact/deploy", data=b"define junk;", method="POST"
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(bad)
            assert ei.value.code == 400
        finally:
            svc.stop()


class TestDocGen:
    def test_markdown_contains_inventory(self, tmp_path):
        from siddhi_tpu.docgen import write_docs

        path = write_docs(str(tmp_path))
        text = open(path).read()
        for needle in ("lossyFrequent", "pol2Cart", "## Windows", "## Mappers"):
            assert needle in text


class TestNativeRingAsync:
    def test_async_uses_native_ring_and_delivers(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @async(buffer.size='4096')
        define stream S (symbol string, volume long);
        @info(name='q')
        from S select count() as n insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(e.data for e in i or []))
        rt.start()
        j = rt.junctions["S"]
        assert j._ring is not None  # toolchain available in this image
        h = rt.get_input_handler("S")
        h.send_many([("A", i) for i in range(500)], timestamps=list(range(500)))
        t0 = time.time()
        while (not got or got[-1][0] < 500) and time.time() - t0 < 10.0:
            time.sleep(0.05)
        assert got[-1][0] == 500
        rt.shutdown()
        mgr.shutdown()

    def test_string_roundtrip_through_ring(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @async(buffer.size='64')
        define stream S (symbol string, price float);
        @info(name='q')
        from S select symbol, price insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(e.data for e in i or []))
        rt.start()
        rt.get_input_handler("S").send(("WSO2", 55.5), timestamp=1)
        t0 = time.time()
        while not got and time.time() - t0 < 10.0:
            time.sleep(0.05)
        assert got == [("WSO2", 55.5)]
        rt.shutdown()
        mgr.shutdown()
