"""End-to-end filter/projection/aggregation tests over the minimum slice.

Mirrors the reference's dominant test shape (reference:
core/src/test/java/.../query/FilterTestCase1.java, CallbackTestCase.java):
SiddhiQL string -> runtime -> callbacks -> send -> assert collected outputs.
"""

import pytest

from siddhi_tpu import SiddhiManager


def make_runtime(ql):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    rt.start()
    return mgr, rt


def test_filter_passes_and_drops():
    mgr, rt = make_runtime(
        """
        define stream cseEventStream (symbol string, price float, volume long);
        @info(name='q1')
        from cseEventStream[volume < 150] select symbol, price insert into outputStream;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("cseEventStream")
    h.send(("WSO2", 55.6, 100))
    h.send(("IBM", 75.6, 400))
    h.send(("GOOG", 50.0, 30))
    assert [e.data for e in got] == [("WSO2", 55.599998474121094), ("GOOG", 50.0)]
    mgr.shutdown()


def test_stream_callback_on_output_stream():
    mgr, rt = make_runtime(
        """
        define stream S (a int, b int);
        from S[a > 0] select a + b as total insert into Out;
        """
    )
    got = []
    rt.add_callback("Out", lambda events: got.extend(events))
    h = rt.get_input_handler("S")
    h.send_many([(1, 2), (-5, 3), (10, 20)])
    assert [e.data for e in got] == [(3,), (30,)]
    mgr.shutdown()


def test_chained_queries():
    mgr, rt = make_runtime(
        """
        define stream S (v int);
        from S[v > 0] select v * 2 as v2 insert into Mid;
        from Mid[v2 > 10] select v2 insert into Out;
        """
    )
    got = []
    rt.add_callback("Out", lambda events: got.extend(events))
    rt.get_input_handler("S").send_many([(1,), (4,), (6,), (-9,)])
    assert [e.data for e in got] == [(12,)]
    mgr.shutdown()


def test_select_star():
    mgr, rt = make_runtime(
        "define stream S (a int, b string); from S insert into Out;"
    )
    got = []
    rt.add_callback("Out", lambda events: got.extend(events))
    rt.get_input_handler("S").send((7, "x"))
    assert got[0].data == (7, "x")
    mgr.shutdown()


def test_running_aggregators_without_window():
    mgr, rt = make_runtime(
        """
        define stream S (p float);
        @info(name='q')
        from S select sum(p) as s, count() as c, avg(p) as a,
                      min(p) as mn, max(p) as mx
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send((10.0,))
    h.send((20.0,))
    h.send((6.0,))
    rows = [e.data for e in got]
    assert rows[0] == (10.0, 1, 10.0, 10.0, 10.0)
    assert rows[1] == (30.0, 2, 15.0, 10.0, 20.0)
    assert rows[2] == (36.0, 3, 12.0, 6.0, 20.0)
    mgr.shutdown()


def test_aggregator_in_expression_and_having():
    mgr, rt = make_runtime(
        """
        define stream S (p float);
        @info(name='q')
        from S select p, sum(p) / count() as mean having mean > 5.0 insert into Out;
        """
    )
    got = []
    rt.add_callback("q", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send_many([(2.0,), (3.0,), (25.0,)])  # means: 2, 2.5, 10
    assert [e.data[1] for e in got] == [10.0]
    mgr.shutdown()


def test_batched_send_matches_single_sends():
    ql = """
    define stream S (v int);
    @info(name='q') from S select sum(v) as s insert into Out;
    """
    mgr1, rt1 = make_runtime(ql)
    got1 = []
    rt1.add_callback("q", lambda ts, ins, removed: got1.extend(ins or []))
    h1 = rt1.get_input_handler("S")
    for i in range(1, 8):
        h1.send((i,))

    mgr2, rt2 = make_runtime(ql)
    got2 = []
    rt2.add_callback("q", lambda ts, ins, removed: got2.extend(ins or []))
    rt2.get_input_handler("S").send_many([(i,) for i in range(1, 8)])

    assert [e.data for e in got1] == [e.data for e in got2]
    assert got1[-1].data == (28,)
    mgr1.shutdown()
    mgr2.shutdown()


def test_undefined_stream_raises():
    from siddhi_tpu.core.errors import DefinitionNotExistError

    mgr = SiddhiManager()
    with pytest.raises(DefinitionNotExistError):
        mgr.create_siddhi_app_runtime(
            "define stream S (a int); from Nope select a insert into O;"
        )


def test_schema_mismatch_on_insert_raises():
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    mgr = SiddhiManager()
    with pytest.raises(SiddhiAppCreationError):
        mgr.create_siddhi_app_runtime(
            """
            define stream S (a int);
            define stream Out (a string);
            from S select a insert into Out;
            """
        )


def test_int_long_arith_and_string_compare_e2e():
    mgr, rt = make_runtime(
        """
        define stream S (sym string, v int);
        from S[sym == 'WSO2' and v % 2 == 0] select sym, v / 3 as d insert into Out;
        """
    )
    got = []
    rt.add_callback("Out", lambda events: got.extend(events))
    rt.get_input_handler("S").send_many(
        [("WSO2", 10), ("IBM", 10), ("WSO2", 7), ("WSO2", -8)]
    )
    assert [e.data for e in got] == [("WSO2", 3), ("WSO2", -2)]
    mgr.shutdown()
