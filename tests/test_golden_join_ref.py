"""Golden corpus: reference query/join/JoinTestCase.java (data-level
translation; wall-clock sleeps become @app:playback timestamps). Tests with
no count assertions in the reference (5-9, 13-17: parse/validation smokes)
are not translated; OuterJoinTestCase 1-2 live in test_golden_windows_ref.
"""

from __future__ import annotations

import pytest

from siddhi_tpu import SiddhiManager

D2 = """@app:playback @app:batch(size='8')
define stream cseEventStream (symbol string, price float, volume int);
define stream twitterStream (user string, tweet string, company string);
"""


def run_pb(ql, steps, query_name="query1"):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    ins, rem = [], []
    rt.add_callback(
        query_name,
        lambda ts, i, r: (
            ins.extend(tuple(e.data) for e in i or []),
            rem.extend(tuple(e.data) for e in r or []),
        ),
    )
    rt.start()
    hs = {}
    for ts, stream, row in steps:
        hs.setdefault(stream, rt.get_input_handler(stream)).send(
            row, timestamp=ts
        )
    rt.shutdown()
    mgr.shutdown()
    return ins, rem


class TestJoinGolden:
    def test1_time_window_join_all_events(self):
        ql = D2 + """@info(name = 'query1')
        from cseEventStream#window.time(1 sec) join twitterStream#window.time(1 sec)
        on cseEventStream.symbol== twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert all events into outputStream ;"""
        ins, rem = run_pb(ql, [
            (0, "cseEventStream", ("WSO2", 55.6, 100)),
            (10, "twitterStream", ("User1", "Hello World", "WSO2")),
            (20, "cseEventStream", ("IBM", 75.6, 100)),
            (520, "cseEventStream", ("WSO2", 57.6, 100)),
            (2000, "cseEventStream", ("ZZZ", 1.0, 0)),  # clock advance
        ])
        assert len(ins) == 2, ins
        assert len(rem) == 2, rem
        assert ins[0][:2] == ("WSO2", "Hello World") and abs(ins[0][2] - 55.6) < 1e-3, ins

    def test2_aliased_time_window_join(self):
        ql = D2 + """@info(name = 'query1')
        from cseEventStream#window.time(1 sec) as a join twitterStream#window.time(1 sec) as b
        on a.symbol== b.company
        select a.symbol as symbol, b.tweet, a.price
        insert all events into outputStream ;"""
        ins, rem = run_pb(ql, [
            (0, "cseEventStream", ("WSO2", 55.6, 100)),
            (10, "twitterStream", ("User1", "Hello World", "WSO2")),
            (20, "cseEventStream", ("IBM", 75.6, 100)),
            (520, "cseEventStream", ("WSO2", 57.6, 100)),
            (2000, "cseEventStream", ("ZZZ", 1.0, 0)),
        ])
        assert len(ins) == 2 and len(rem) == 2, (ins, rem)

    def test3_self_join(self):
        ql = """@app:playback @app:batch(size='8')
        define stream cseEventStream (symbol string, price float, volume int);
        @info(name = 'query1')
        from cseEventStream#window.time(500 milliseconds) as a
        join cseEventStream#window.time(500 milliseconds) as b
        on a.symbol== b.symbol
        select a.symbol as symbol, a.price as priceA, b.price as priceB
        insert all events into outputStream ;"""
        ins, rem = run_pb(ql, [
            (0, "cseEventStream", ("IBM", 75.6, 100)),
            (10, "cseEventStream", ("WSO2", 57.6, 100)),
            (2000, "cseEventStream", ("ZZZ", 1.0, 0)),
        ])
        # each event self-joins once (the trailing clock-advance row also
        # self-joins; exclude it)
        real = [r for r in ins if r[0] != "ZZZ"]
        assert len(real) == 2, ins
        syms = sorted((s, round(a, 2), round(b, 2)) for s, a, b in real)
        assert syms == [("IBM", 75.6, 75.6), ("WSO2", 57.6, 57.6)], ins

    def test4_longer_window_join(self):
        ql = D2 + """@info(name = 'query1')
        from cseEventStream#window.time(2 sec) join twitterStream#window.time(2 sec)
        on cseEventStream.symbol== twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert all events into outputStream ;"""
        ins, rem = run_pb(ql, [
            (0, "cseEventStream", ("WSO2", 55.6, 100)),
            (10, "twitterStream", ("User1", "Hello World", "WSO2")),
            (20, "cseEventStream", ("IBM", 75.6, 100)),
            (1020, "cseEventStream", ("WSO2", 57.6, 100)),
            (4000, "cseEventStream", ("ZZZ", 1.0, 0)),
        ])
        assert len(ins) == 2 and len(rem) == 2, (ins, rem)

    def test10_windowless_side_joins_length1(self):
        ql = D2 + """@info(name = 'query1')
        from cseEventStream join twitterStream#window.length(1)
        select count() as events, symbol
        insert into outputStream ;"""
        ins, rem = run_pb(ql, [
            (0, "cseEventStream", ("WSO2", 55.6, 100)),
            (10, "twitterStream", ("User1", "Hello World", "WSO2")),
            (20, "cseEventStream", ("IBM", 75.6, 100)),
            (30, "cseEventStream", ("WSO2", 57.6, 100)),
        ])
        assert len(ins) == 2, ins
        assert len(rem) == 0, rem

    def test11_unidirectional_join(self):
        ql = D2 + """@info(name = 'query1')
        from cseEventStream unidirectional join twitterStream#window.length(1)
        select count() as events, symbol, tweet
        insert all events into outputStream ;"""
        ins, rem = run_pb(ql, [
            (0, "cseEventStream", ("WSO2", 55.6, 100)),
            (10, "twitterStream", ("User1", "Hello World", "WSO2")),
            (20, "cseEventStream", ("IBM", 75.6, 100)),
            (30, "cseEventStream", ("WSO2", 57.6, 100)),
        ])
        assert len(ins) == 2, ins

    def test12_select_star_join(self):
        ql = D2 + """@info(name = 'query1')
        from cseEventStream#window.time(1 sec) join twitterStream#window.time(1 sec)
        on cseEventStream.symbol== twitterStream.company
        select *
        insert into outputStream ;"""
        ins, rem = run_pb(ql, [
            (0, "cseEventStream", ("WSO2", 55.6, 100)),
            (10, "twitterStream", ("User1", "Hello World", "WSO2")),
        ])
        assert len(ins) == 1, ins
        assert len(rem) == 0, rem

    @pytest.mark.xfail(
        reason="deviation: the reference aggregates a windowless table join "
        "per TRIGGER chunk (count()==matched rows, reset each trigger); this "
        "engine keeps the running aggregate across triggers (1..N). "
        "Recorded in PARITY.md.", strict=True)
    def test19_stream_table_join_count(self):
        ql = """@app:playback @app:batch(size='8')
        define stream dataIn (id int, data string);
        define stream countIn (id int);
        define stream deleteIn (id int);
        define table dataTable (id int, data string);
        from dataIn insert into dataTable;
        from deleteIn delete dataTable on dataTable.id == id;
        @info(name = 'query1')
        from countIn as c join dataTable as d
        select count() as count
        insert into countOut;"""
        ins, rem = run_pb(ql, [
            (0, "dataIn", (1, "item1")),
            (10, "dataIn", (2, "item2")),
            (20, "dataIn", (3, "item3")),
            (30, "countIn", (1,)),
            (40, "deleteIn", (1,)),
            (50, "countIn", (1,)),
        ])
        # first count sees 3 rows, second (after delete) sees 2
        assert [r[0] for r in ins] == [3, 2], ins

    @pytest.mark.xfail(
        reason="same per-trigger-chunk aggregation deviation as test19",
        strict=True)
    def test20_left_outer_table_join_count(self):
        ql = """@app:playback @app:batch(size='8')
        define stream dataIn (id int, data string);
        define stream countIn (id int);
        define stream deleteIn (id int);
        define table dataTable (id int, data string);
        from dataIn insert into dataTable;
        from deleteIn delete dataTable on dataTable.id == id;
        @info(name = 'query1')
        from countIn as c left outer join dataTable as d
        on d.data == 'abc'
        select count() as count
        insert into countOut;"""
        ins, rem = run_pb(ql, [
            (0, "dataIn", (1, "abc")),
            (10, "dataIn", (2, "abc")),
            (20, "dataIn", (3, "abc")),
            (30, "countIn", (1,)),
            (40, "deleteIn", (1,)),
            (50, "countIn", (1,)),
        ])
        assert [r[0] for r in ins] == [3, 2], ins
