"""Static cost model + fusion-feasibility planner (analysis/cost.py,
analysis/fusion.py) and the analyzer satellites that ride with them.

Layers:
* plan snapshot — `--plan --format=json` over a fixed app is byte-stable
  (the FusionPlan is the contract the fusion PR consumes; drift is a
  breaking change);
* planner semantics — hazards (async/partition/rate/scheduler/
  multi-stream/ordering), shared-state candidates, dispatch estimates;
* cost model — window/pattern/join state bytes, tail-variant ladder,
  predicted compile causes;
* explain integration — static cost + fusion summary render in
  `runtime.explain()` next to the live counters;
* satellites — `aggregate by` typing (SA116), aggregation-join and
  store-query `within`/`per` checks (SA117), store-query analysis (SA118).
"""

from __future__ import annotations

import glob
import io
import json
import os
import contextlib

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.analysis import (
    analyze,
    analyze_store_query,
    build_fusion_plan,
    compute_costs,
)
from siddhi_tpu.analysis.__main__ import main as lint_main

CORPUS = sorted(
    glob.glob(os.path.join(
        os.path.dirname(__file__), "analysis_corpus", "*.siddhi"
    ))
)

SNAPSHOT_APP = """define stream S (symbol string, price float);
@info(name='avg50') from S[price > 10]#window.length(50)
select symbol, avg(price) as ap insert into AvgOut;
@info(name='max50') from S[price > 10]#window.length(50)
select symbol, max(price) as mx insert into MaxOut;
@info(name='slow') from S#window.time(1 sec)
select symbol insert into SlowOut;
"""

# the FusionPlan contract for SNAPSHOT_APP (costs asserted separately)
SNAPSHOT_PLAN = {
    "version": 3,
    "app": "SiddhiApp",
    "chunk": {"batch_size": 64, "chunk_batches": 32},
    "groups": [
        {
            "stream": "S",
            "component": "stream.S.fusedgroup.0",
            "queries": ["avg50", "max50"],
            "chunk": {"batch_size": 64, "chunk_batches": 32},
            "state_bytes": 3200,
            "dispatches_per_chunk_before": 64,
            "dispatches_per_chunk_after": 1,
            "est_dispatch_reduction": 0.9844,
        }
    ],
    "blockers": [
        {
            "stream": "S",
            "query": "slow",
            "hazard": "scheduler",
            "why": "timer-armed operator needs host scheduling between "
                   "batches",
        }
    ],
    "shared_state": [
        {
            "stream": "S",
            "signature": "filter[(price > 10)] window.length(50)",
            "queries": ["avg50", "max50"],
            "est_bytes_saved": 1600,
        }
    ],
    # v2: the per-stream static WireSpec (core/wire.py) — SNAPSHOT_APP
    # declares no @app:wire hints and no BOOL columns, so nothing is
    # statically encodable; the section still names the predicted
    # logical bytes/event the sampled narrow wire shrinks from
    "wire": {
        "S": {
            "version": 1,
            "source": "static",
            "encodings": {},
            "logical_B_per_ev": 16,
            "encoded_B_per_ev_est": 12,
        }
    },
    # v3: value-analysis sections — SNAPSHOT_APP has no provable rewrite,
    # and the only non-TOP fact is max(price) under the price > 10 filter
    # (float: narrowed to non-null only, never to an interval)
    "rewrites": [],
    "domains": {
        "MaxOut": {"mx": {"non_null": True}},
    },
}


class TestPlanSnapshot:
    def test_plan_dict_is_stable(self):
        plan = build_fusion_plan(SNAPSHOT_APP).to_dict()
        costs = plan.pop("costs")
        assert plan == SNAPSHOT_PLAN
        # cost model invariants for the same app
        avg = costs["queries"]["avg50"]
        assert avg["state_bytes"] == 1600  # 50 x (4+4 attrs + 24 lanes)
        assert avg["est_selectivity"] == 0.5  # filter 0.25 x sliding 2.0
        assert avg["programs"] == [{
            "component": "query.avg50",
            "input_rows": 64,
            "predicted_compiles": 1,
            "predicted_causes": {"first_compile": 1},
        }]
        slow = costs["queries"]["slow"]
        assert slow["scheduler_armed"] is True
        assert slow["programs"][0]["predicted_causes"] == {
            "first_compile": 1, "shape_change": 1,
        }
        assert costs["streams"]["S"] == {
            "stream": "S",
            "component": "stream.S.fused",
            "wire_row_bytes": 16,
            "chunk_batches": 32,
            "tail_variants": [2, 4, 8, 16],
            "narrow_rebuild_hazard": True,
            "predicted_compiles": 6,
            "predicted_causes": {
                "first_compile": 1,
                "tail_variant_k": 4,
                "full_width_rebuild": 1,
            },
        }

    def test_cli_plan_json_matches_api(self, tmp_path, capsys):
        p = tmp_path / "app.siddhi"
        p.write_text(SNAPSHOT_APP)
        assert lint_main(["--plan", "--format=json", str(p)]) == 0
        via_cli = json.loads(capsys.readouterr().out)
        assert via_cli == build_fusion_plan(SNAPSHOT_APP).to_dict()

    def test_cli_plan_text(self, tmp_path, capsys):
        p = tmp_path / "app.siddhi"
        p.write_text(SNAPSHOT_APP)
        assert lint_main(["--plan", str(p)]) == 0
        out = capsys.readouterr().out
        assert "FUSION PLAN v3" in out
        assert "stream S: avg50, max50" in out
        assert "slow on S: scheduler" in out
        assert "shared-state candidates:" in out

    @pytest.mark.parametrize(
        "path", CORPUS, ids=[os.path.basename(p)[:-7] for p in CORPUS]
    )
    def test_plan_never_crashes_on_corpus(self, path, capsys):
        # the CI lint job runs --plan over every corpus + bench app: bad
        # apps still plan (rc 0); only unparsable input is rc 2
        assert lint_main(["--plan", "--format=json", path]) == 0
        json.loads(capsys.readouterr().out)

    def test_plan_over_bench_workloads(self, capsys):
        import bench

        for name, (ql, _stream, _mult, _batch) in sorted(
            bench.WORKLOADS.items()
        ):
            plan = build_fusion_plan(ql).to_dict()
            assert plan["version"] == 3, name
            assert plan["costs"]["queries"], name


class TestPlannerSemantics:
    def test_async_stream_blocks_every_consumer(self):
        plan = build_fusion_plan("""
        @async(buffer.size='128')
        define stream S (a int);
        from S select a insert into Out1;
        from S select a insert into Out2;
        """)
        assert not plan.groups
        assert {b["hazard"] for b in plan.blockers} == {"async-ingress"}
        assert len(plan.blockers) == 2

    def test_partition_blocks_fusion(self):
        r = analyze("""
        define stream S (symbol string, price float);
        from S select symbol insert into Out1;
        partition with (symbol of S) begin
        from S select price insert into #x;
        from #x select price insert into Out2;
        end;
        """)
        assert r.fusion_plan is not None
        hazards = {
            (b["query"], b["hazard"]) for b in r.fusion_plan.blockers
        }
        assert ("partition0_query0", "partition") in hazards
        assert any(d.code == "SA124" for d in r.warnings)

    def test_ordering_hazard_intra_group_chain(self):
        plan = build_fusion_plan("""
        define stream S (a int);
        define stream Mid (a int);
        from S select a insert into Mid;
        from S[a > 0] select a insert into Out;
        from Mid select a insert into Out2;
        """)
        # query0 inserts into Mid which query2 consumes -> fusing query0
        # with query1 on S would reorder Mid's delivery
        assert any(b["hazard"] == "ordering" for b in plan.blockers)

    def test_pattern_multi_stream_is_blocked(self):
        plan = build_fusion_plan("""
        define stream A (x int);
        define stream B (y int);
        from A select x insert into OutA;
        from e1=A -> e2=B select e1.x as x insert into OutP;
        """)
        assert any(
            b["hazard"] == "multi-stream" and b["query"] == "query1"
            for b in plan.blockers
        )

    def test_table_join_side_is_not_multi_stream(self):
        # a table side is a passive probe, not stream consumption: two
        # stream-to-table join queries on one stream still fuse
        plan = build_fusion_plan("""
        define stream S (k long, v int);
        define table T (k long, w int);
        from S join T on S.k == T.k select S.k as k, T.w as w
        insert into Out1;
        from S join T on S.k == T.k select S.k as k, S.v as v
        insert into Out2;
        """)
        assert not plan.blockers
        assert len(plan.groups) == 1
        assert plan.groups[0]["queries"] == ["query0", "query1"]

    def test_single_consumer_streams_plan_empty(self):
        plan = build_fusion_plan("""
        define stream S (a int);
        from S select a insert into Out;
        """)
        assert not plan.groups and not plan.blockers
        assert not plan.shared_state

    def test_shared_state_needs_identical_chain(self):
        # different filter => different window content => NOT shareable
        plan = build_fusion_plan("""
        define stream S (a int);
        from S[a > 1]#window.length(10) select a insert into O1;
        from S[a > 2]#window.length(10) select a insert into O2;
        """)
        assert not plan.shared_state
        assert len(plan.groups) == 1  # still fusable, just no shared ring

    def test_every_with_within_is_clean_sa120(self):
        r = analyze("""
        define stream S (a int);
        from every e1=S[a > 1] -> e2=S[a < 0] within 1 sec
        select e1.a as x insert into Out;
        """)
        assert not any(d.code == "SA120" for d in r.diagnostics)

    def test_sa122_batch_shape_drift_downstream(self):
        r = analyze("""
        @app:batch(size='256')
        define stream S (a int);
        define stream Mid (a int);
        from S select a insert into Mid;
        from Mid[a > 0] select a insert into Out;
        """)
        churn = [d for d in r.warnings if d.code == "SA122"]
        assert churn and "256" in churn[0].message


class TestExplainStaticCost:
    def test_static_plan_carries_cost_nodes(self):
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
        from siddhi_tpu.observability.explain import explain_static

        app = SiddhiCompiler.parse(SNAPSHOT_APP)
        plan = explain_static(app, fmt="dict")
        nodes = {n["id"]: n for n in plan["nodes"]}
        st = nodes["query:avg50"]["static"]
        assert st["state_bytes"] == 1600
        assert st["predicted_compiles"] == 1
        assert plan["fusion"]["groups"][0]["queries"] == ["avg50", "max50"]
        text = explain_static(app)
        assert "static: state=1600B" in text
        assert "fusion plan:" in text and "blocked: slow on S" in text

    def test_live_explain_renders_static_next_to_counters(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:statistics(reporter='none')
        define stream S (symbol string, price float);
        @info(name='q') from S[price > 10]#window.length(50)
        select symbol, avg(price) as ap insert into Out;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(8):
            h.send(("A", 40.0 + i))
        plan = rt.explain(fmt="dict")
        node = next(n for n in plan["nodes"] if n["id"] == "query:q")
        assert node["static"]["state_bytes"] == 1600
        assert node["counters"]["dispatches"] >= 1  # live ledger present
        text = rt.explain()
        assert "EXPLAIN ANALYZE" in text
        assert "static: state=1600B" in text  # prediction next to counters
        mgr.shutdown()


class TestCostModel:
    def test_tail_variants_ladder(self):
        from siddhi_tpu.analysis.cost import _tail_variants

        assert _tail_variants(32) == [2, 4, 8, 16]
        assert _tail_variants(2) == []
        assert len(_tail_variants(1024)) == 9

    def test_pattern_cost_tensors_and_programs(self):
        model = compute_costs("""
        @app:patternCapacity(size='4096')
        define stream S (a int, b long);
        @info(name='p') from every e1=S[a > 1] -> e2=S[a < 0] within 1 sec
        select e1.a as x insert into Out;
        """)
        qc = model.queries["p"]
        assert qc.kind == "pattern"
        (op,) = [o for o in qc.operators if o.op == "pattern"]
        assert "T=4096" in op.detail and "2 slot(s)" in op.detail
        # one per-stream step program, telemetry component naming
        assert [p.component for p in qc.programs] == ["query.p[S]"]
        # token bookkeeping lanes scale with T
        lanes = {t.lane: t for t in op.tensors}
        assert lanes["tok.active"].shape == (4096,)
        assert lanes["cap0.ts"].shape == (4096, 1)

    def test_join_cost_sides_and_capacity(self):
        model = compute_costs("""
        @app:joinCapacity(size='2048')
        define stream L (k long, v int);
        define stream R (k long, w int);
        @info(name='j') from L#window.length(100) as a
        join R#window.length(100) as b on a.k == b.k
        select a.k as k, b.w as w insert into Out;
        """)
        qc = model.queries["j"]
        assert qc.kind == "join"
        comps = [p.component for p in qc.programs]
        assert comps == ["query.j[left]", "query.j[right]"]
        sides = [o for o in qc.operators if o.op.startswith("join:")]
        assert len(sides) == 2
        assert all("cap=2048" in o.detail for o in sides)

    def test_scheduler_armed_predicts_shape_change(self):
        model = compute_costs("""
        define stream S (ts long, ip string);
        @info(name='q')
        from S#window.externalTimeBatch(ts, 1 sec, 0, 1 sec)
        select ts, count() as c insert into Out;
        """)
        qc = model.queries["q"]
        assert qc.scheduler_armed  # idle-timeout param arms a wall timer
        assert qc.programs[0].predicted_causes["shape_change"] == 1

    def test_state_budget_env_override(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_STATE_BUDGET_MB", "1")
        r = analyze("""
        define stream S (a int, b long);
        from S#window.length(100000) select a, b insert into Out;
        """)
        assert any(d.code == "SA121" for d in r.warnings)
        monkeypatch.setenv("SIDDHI_TPU_STATE_BUDGET_MB", "1024")
        r = analyze("""
        define stream S (a int, b long);
        from S#window.length(100000) select a, b insert into Out;
        """)
        assert not any(d.code == "SA121" for d in r.warnings)


class TestSatellites:
    APP = """
    define stream Trades (symbol string, price float, volume long, ts long);
    define table Totals (symbol string, total double);
    define aggregation TradeAgg
    from Trades
    select symbol, sum(price) as total
    group by symbol
    aggregate by ts every sec ... hour;
    """

    def test_aggregate_by_long_attr_is_clean(self):
        assert analyze(self.APP).ok

    def test_aggregate_by_string_attr_sa116(self):
        r = analyze("""
        define stream Trades (symbol string, price float);
        define aggregation A
        from Trades select symbol, sum(price) as total group by symbol
        aggregate by symbol every sec ... min;
        """)
        assert [d.code for d in r.errors] == ["SA116"]
        assert "INT/LONG" in r.errors[0].message

    def test_agg_join_clean_and_bad_duration(self):
        ok = analyze(self.APP + """
        from Trades as t join TradeAgg as a
        on t.symbol == a.symbol
        within '2024-**-** **:**:**'
        per 'hours'
        select t.symbol as s, a.total as total insert into Out;
        """)
        assert ok.ok, ok.format()
        bad = analyze(self.APP + """
        from Trades as t join TradeAgg as a
        on t.symbol == a.symbol
        per 'days'
        select t.symbol as s, a.total as total insert into Out;
        """)
        assert [d.code for d in bad.errors] == ["SA117"]
        assert "no 'days' duration" in bad.errors[0].message

    def test_plain_join_within_is_warning_only(self):
        r = analyze(self.APP + """
        define table Ref (symbol string, total double);
        from Trades as t join Ref as r2 on t.symbol == r2.symbol
        per 'hours'
        select t.symbol as s, r2.total as total insert into Out;
        """)
        assert r.ok
        assert any(
            d.code == "SA117" and d.severity == "warning" for d in r.warnings
        )

    def test_store_query_clean(self):
        r = analyze_store_query(
            "from Totals on total > 1.0 select symbol, total", self.APP
        )
        assert r.ok and not r.diagnostics

    def test_store_query_unknown_store(self):
        r = analyze_store_query("from Nope select 1 as x", self.APP)
        assert [d.code for d in r.errors] == ["SA108"]

    def test_store_query_agg_clauses(self):
        no_per = analyze_store_query("from TradeAgg select symbol", self.APP)
        assert [d.code for d in no_per.errors] == ["SA117"]
        bad_range = analyze_store_query(
            "from TradeAgg within '2024-02-01', '2024-01-01' per 'sec' "
            "select symbol",
            self.APP,
        )
        assert [d.code for d in bad_range.errors] == ["SA117"]
        assert "before the end" in bad_range.errors[0].message
        nonagg = analyze_store_query(
            "from Totals within '2024-01-01' per 'sec' select symbol",
            self.APP,
        )
        assert [d.code for d in nonagg.errors] == ["SA117"]

    def test_store_query_shapes(self):
        aimless = analyze_store_query("select 1 as x", self.APP)
        assert [d.code for d in aimless.errors] == ["SA118"]
        bad_target = analyze_store_query(
            "select 'a' as s, 2.0 as t insert into Missing", self.APP
        )
        assert [d.code for d in bad_target.errors] == ["SA108"]
        bad_attr = analyze_store_query("from Totals select nope", self.APP)
        assert [d.code for d in bad_attr.errors] == ["SA103"]
        parse_err = analyze_store_query("from from from", self.APP)
        assert [d.code for d in parse_err.errors] == ["SA001"]

    def test_store_query_runtime_agreement(self):
        # the analyzer's verdict must match StoreQueryRuntime: a clean
        # store query executes; a flagged one raises
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(self.APP)
        rt.start()
        assert analyze_store_query(
            "from Totals select symbol, total", self.APP
        ).ok
        rows = rt.query("from Totals select symbol, total")
        assert rows == []
        bad = "from Totals within '2024-01-01' per 'sec' select symbol"
        assert not analyze_store_query(bad, self.APP).ok
        with pytest.raises(Exception):
            rt.query(bad)
        mgr.shutdown()


class TestAnalyzeCarriesPlan:
    def test_analyze_result_has_fusion_plan(self):
        r = analyze(SNAPSHOT_APP)
        assert r.fusion_plan is not None
        assert r.fusion_plan.to_dict()["groups"][0]["queries"] == [
            "avg50", "max50",
        ]

    def test_plan_text_renders_without_stdout_noise(self):
        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            plan = build_fusion_plan(SNAPSHOT_APP)
        assert buf.getvalue() == ""
        from siddhi_tpu.analysis.fusion import render_plan_text

        assert "FUSION PLAN v3" in render_plan_text(plan)
