"""Test configuration: force an 8-device virtual CPU platform BEFORE jax initializes.

Multi-chip hardware is not available in CI; sharding tests run against a virtual
8-device CPU mesh per the build spec. Must run before any jax import.
"""

import os
import sys

# Force, don't default: the environment pre-sets JAX_PLATFORMS=axon (the real
# TPU tunnel); tests must run on the virtual 8-device CPU platform. The axon
# site hook imports jax at interpreter startup, so the env var alone is read
# too early — update the jax config explicitly as well.
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# persistent compilation cache: pattern/window programs take O(minutes) to
# compile on CPU; cached across test runs they load in milliseconds
_cache_dir = os.environ.get(
    "SIDDHI_TPU_TEST_CACHE", os.path.expanduser("~/.cache/siddhi_tpu_jax")
)
try:
    os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
except Exception:
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long wall-clock tests excluded from tier-1 (-m 'not slow')"
    )


# ---------------------------------------------------------------------------
# analyzer sweep: every app that successfully builds a runtime anywhere in the
# suite must also analyze clean (zero errors, no SA000 internal faults) — the
# whole test corpus doubles as the analyzer's false-positive regression net.
# Disable with SIDDHI_ANALYSIS_SWEEP=0.
# ---------------------------------------------------------------------------

if os.environ.get("SIDDHI_ANALYSIS_SWEEP", "1") != "0":
    from siddhi_tpu.core.manager import SiddhiManager as _SM

    _orig_create = _SM.create_siddhi_app_runtime

    def _checked_create(self, app, strict=False):
        runtime = _orig_create(self, app, strict=strict)
        # only sweep apps that construct successfully: tests asserting
        # creation errors must keep seeing the original exception
        try:
            from siddhi_tpu.analysis import analyze

            result = analyze(runtime.app)
        except Exception as exc:  # analyzer crash = sweep failure
            raise AssertionError(f"analyzer crashed on a valid app: {exc!r}")
        problems = result.errors + [
            d for d in result.warnings if d.code == "SA000"
        ]
        if problems:
            msgs = "\n".join(d.format() for d in problems)
            raise AssertionError(
                "analyzer flagged a valid app (false positive):\n" + msgs
            )
        return runtime

    _SM.create_siddhi_app_runtime = _checked_create
    _SM.create_runtime = _checked_create
