"""Test configuration: force an 8-device virtual CPU platform BEFORE jax initializes.

Multi-chip hardware is not available in CI; sharding tests run against a virtual
8-device CPU mesh per the build spec. Must run before any jax import.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
