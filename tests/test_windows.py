"""Window behavior tests.

Mirrors the reference window test corpus semantics (reference:
core/src/test/java/.../query/window/LengthWindowTestCase.java,
LengthBatchWindowTestCase.java, ExternalTimeWindowTestCase.java,
TimeWindowTestCase.java): CURRENT/EXPIRED accounting through QueryCallback and
running aggregates over window contents.
"""

import time

from siddhi_tpu import SiddhiManager


def run_app(ql):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    rt.start()
    return mgr, rt


def collect(rt, qname):
    got = {"in": [], "removed": [], "events": []}

    def cb(ts, ins, removed):
        got["in"].extend(ins or [])
        got["removed"].extend(removed or [])
        got["events"].append((ts, ins, removed))

    rt.add_callback(qname, cb)
    return got


def test_length_window_sum():
    mgr, rt = run_app(
        """
        define stream S (sym string, p float);
        @info(name='q')
        from S#window.length(3) select sym, sum(p) as total insert all events into O;
        """
    )
    got = collect(rt, "q")
    h = rt.get_input_handler("S")
    for i, v in enumerate([10.0, 20.0, 30.0, 40.0, 50.0]):
        h.send(("A", v), timestamp=1000 + i)
    # running sums: 10, 30, 60, then window slides: 60-10+40=90, 90-20+50=120
    assert [e.data[1] for e in got["in"]] == [10.0, 30.0, 60.0, 90.0, 120.0]
    # expired events carry the evicted payloads
    assert [e.data[0] for e in got["removed"]] == ["A", "A"]
    mgr.shutdown()


def test_length_window_min_max_exact_expiry():
    mgr, rt = run_app(
        """
        define stream S (p float);
        @info(name='q')
        from S#window.length(2) select min(p) as mn, max(p) as mx insert into O;
        """
    )
    got = collect(rt, "q")
    h = rt.get_input_handler("S")
    for v in [5.0, 9.0, 3.0, 7.0, 1.0]:
        h.send((v,))
    # windows: [5], [5,9], [9,3], [3,7], [7,1]
    assert [e.data for e in got["in"]] == [
        (5.0, 5.0), (5.0, 9.0), (3.0, 9.0), (3.0, 7.0), (1.0, 7.0),
    ]
    mgr.shutdown()


def test_length_batch_window():
    mgr, rt = run_app(
        """
        define stream S (sym string, p float);
        @info(name='q')
        from S#window.lengthBatch(3) select sym, sum(p) as total insert all events into O;
        """
    )
    got = collect(rt, "q")
    h = rt.get_input_handler("S")
    for v in [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0]:
        h.send(("A", v))
    # batch + aggregator + no group-by: only the LAST chunk event survives,
    # carrying the bucket's final aggregate (reference:
    # QuerySelector.processInBatchNoGroupBy lastEvent)
    assert [e.data[1] for e in got["in"]] == [6.0, 15.0]
    # the final CURRENT wins the chunk, so no expired rows are emitted
    assert len(got["removed"]) == 0
    mgr.shutdown()


def test_length_batch_across_large_send():
    mgr, rt = run_app(
        """
        define stream S (v int);
        @info(name='q')
        from S#window.lengthBatch(2) select sum(v) as s insert into O;
        """
    )
    got = collect(rt, "q")
    rt.get_input_handler("S").send_many([(i,) for i in range(1, 8)])  # 1..7
    # buckets (1,2), (3,4), (5,6); 7 pending — one final sum per flush
    assert [e.data[0] for e in got["in"]] == [3, 7, 11]
    mgr.shutdown()


def test_external_time_window():
    mgr, rt = run_app(
        """
        define stream S (ts long, p float);
        @info(name='q')
        from S#window.externalTime(ts, 1 sec) select sum(p) as total
        insert all events into O;
        """
    )
    got = collect(rt, "q")
    h = rt.get_input_handler("S")
    h.send((1000, 10.0), timestamp=1000)
    h.send((1500, 20.0), timestamp=1500)
    h.send((2100, 5.0), timestamp=2100)   # expires ts=1000 first: 30-10+5=25
    h.send((3600, 1.0), timestamp=3600)   # expires 1500 and 2100
    ins = [e.data[0] for e in got["in"]]
    assert ins == [10.0, 30.0, 25.0, 1.0]
    # expired rows emitted before their triggering current; running sums at
    # each removal: 30-10=20, then 25-20=5, then 5-5=0
    rem = [e.data[0] for e in got["removed"]]
    assert rem == [20.0, 5.0, 0.0]
    mgr.shutdown()


def test_time_window_with_system_scheduler():
    mgr, rt = run_app(
        """
        define stream S (p float);
        @info(name='q')
        from S#window.time(200 millisec) select sum(p) as total insert all events into O;
        """
    )
    got = collect(rt, "q")
    h = rt.get_input_handler("S")
    # first send triggers jit compile (can exceed the window duration), so only
    # the timer-driven behaviors are asserted, not inter-send running sums
    h.send((4.0,))
    h.send((6.0,))
    assert got["in"][0].data[0] == 4.0
    # wait for timer-driven expiry with no further events
    deadline = time.time() + 5
    while len(got["removed"]) < 2 and time.time() < deadline:
        time.sleep(0.02)
    assert len(got["removed"]) == 2
    assert got["removed"][-1].data[0] == 0.0  # sum back to 0 after all expired
    mgr.shutdown()


def test_time_length_window():
    mgr, rt = run_app(
        """
        define stream S (ts long, p float);
        @info(name='q')
        from S#window.timeLength(1 sec, 2) select sum(p) as total insert into O;
        """
    )
    got = collect(rt, "q")
    h = rt.get_input_handler("S")
    # wall-clock timestamps (the system scheduler would instantly expire
    # back-dated events); length cap = 2 evicts oldest on the 3rd send
    h.send((0, 1.0))
    h.send((0, 2.0))
    h.send((0, 4.0))
    ins = [e.data[0] for e in got["in"]]
    assert ins[0] == 1.0
    # unless the 1-sec window lapsed between sends (slow CI), the length cap
    # governs: running sums 1, 3, then (3-1)+4
    if len(got["removed"]) == 1:
        assert ins == [1.0, 3.0, 6.0]
    mgr.shutdown()


def test_time_batch_event_driven():
    mgr, rt = run_app(
        """
        define stream S (ts long, p float);
        @info(name='q')
        from S#window.externalTimeBatch(ts, 1 sec) select sum(p) as total
        insert all events into O;
        """
    )
    got = collect(rt, "q")
    h = rt.get_input_handler("S")
    h.send((1000, 1.0), timestamp=1000)
    h.send((1400, 2.0), timestamp=1400)
    h.send((2100, 4.0), timestamp=2100)  # crosses boundary -> flush bucket 1
    h.send((3050, 8.0), timestamp=3050)  # crosses -> flush bucket 2
    # flushes emit one final bucket sum each (processInBatchNoGroupBy)
    assert [e.data[0] for e in got["in"]] == [3.0, 4.0]
    mgr.shutdown()


def test_window_with_groupless_avg_and_filter_downstream():
    mgr, rt = run_app(
        """
        define stream S (p float);
        @info(name='q')
        from S#window.length(2) select avg(p) as a insert into Mid;
        from Mid[a > 5.0] select a insert into Out;
        """
    )
    out = []
    rt.add_callback("Out", lambda events: out.extend(events))
    h = rt.get_input_handler("S")
    for v in [2.0, 6.0, 20.0]:
        h.send((v,))
    # avgs: 2, 4, 13 -> only 13 passes downstream
    assert [e.data[0] for e in out] == [13.0]
    mgr.shutdown()


def test_in_batch_time_eviction_no_double_expiry():
    """Regression: a row time-evicted within its own arrival batch must not be
    re-inserted into the ring (it would expire twice and corrupt sums)."""
    mgr, rt = run_app(
        """
        define stream S (ts long, p float);
        @info(name='q')
        from S#window.externalTime(ts, 1 sec) select sum(p) as total
        insert all events into O;
        """
    )
    got = collect(rt, "q")
    h = rt.get_input_handler("S")
    h.send_many([(1000, 10.0), (2100, 20.0)], timestamps=[1000, 2100])
    h.send((3600, 1.0), timestamp=3600)
    assert [e.data[0] for e in got["in"]] == [10.0, 20.0, 1.0]
    assert [e.data[0] for e in got["removed"]] == [0.0, 0.0]
    mgr.shutdown()


def test_post_window_filter_keeps_timer_scheduling():
    """Regression: a filter after the window must not drop the window's
    next_timer aux, or time windows never expire without new events."""
    mgr, rt = run_app(
        """
        define stream S (p float);
        @info(name='q')
        from S#window.time(300 millisec)[p > 0] select sum(p) as total
        insert all events into O;
        """
    )
    got = collect(rt, "q")
    rt.get_input_handler("S").send((5.0,))
    deadline = time.time() + 5
    while not got["removed"] and time.time() < deadline:
        time.sleep(0.02)
    assert got["removed"], "timer-driven expiry never fired through post-window filter"
    mgr.shutdown()
