"""Zero-downtime churn (core/churn.py): hot deploy/undeploy splice parity,
checkpoint state seeding, rolling redeploy state-compat matrix, shard
rebalancing across a device-count change, fault-injected rollback, the
paused replay mode, and the SA130 candidate lint."""

import threading
import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import InMemoryPersistenceStore
from siddhi_tpu.testing import faults


def _collect(rt, name):
    rows = []
    rt.add_callback(
        name, lambda ts, i, r: rows.extend(tuple(e.data) for e in i or [])
    )
    return rows


def _feed_columns(h, lo, hi):
    ts = np.arange(lo, hi, dtype=np.int64)
    cols = {
        "a": np.arange(lo, hi, dtype=np.int64),
        "b": (np.arange(lo, hi) % 7).astype(np.int64),
    }
    h.send_columns(ts, cols)


FUSED_APP = """
@app:name('F')
define stream S (a long, b long);
@info(name='q1') from S[a % 2 == 0] select a, b insert into O1;
@info(name='q2') from S#window.length(8) select a, sum(b) as t insert into O2;
"""


class TestSpliceByteParity:
    def _run(self, app, churn: bool):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(app)
        r1 = _collect(rt, "q1")
        r2 = _collect(rt, "q2")
        rt.start()
        h = rt.get_input_handler("S")
        _feed_columns(h, 0, 512)
        if churn:
            rt.add_query(
                "@info(name='hot') from S[a > 100000] select a insert into O3;"
            )
        _feed_columns(h, 512, 1024)
        if churn:
            rt.remove_query("hot")
        _feed_columns(h, 1024, 1536)
        rt.shutdown()
        mgr.shutdown()
        return r1, r2

    def test_fused_survivors_byte_identical_across_splice(self):
        a1, a2 = self._run(FUSED_APP, churn=False)
        b1, b2 = self._run(FUSED_APP, churn=True)
        assert a1 == b1
        assert a2 == b2
        assert len(a1) == 768 and len(a2) == 1536

    def test_unfused_survivors_byte_identical_across_splice(self):
        app = FUSED_APP.replace(
            "@app:name('F')", "@app:name('F')\n@app:fuse(disable='true')"
        )
        a1, a2 = self._run(app, churn=False)
        b1, b2 = self._run(app, churn=True)
        assert a1 == b1
        assert a2 == b2

    def test_fusion_group_reforms_around_hot_query(self):
        # the hot query joins the stream's fused group: the rebuilt engine
        # must carry THREE members while deployed, two after undeploy
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(FUSED_APP)
        _collect(rt, "q1")
        _collect(rt, "q2")
        rt.start()
        h = rt.get_input_handler("S")
        _feed_columns(h, 0, 256)
        fi = rt.junctions["S"].fused_ingest
        assert fi is not None and len(fi.endpoints) == 2
        rt.add_query("@info(name='hot') from S[a < 0] select a insert into O3;")
        fi2 = rt.junctions["S"].fused_ingest
        assert fi2 is not None and fi2 is not fi
        assert len(fi2.endpoints) == 3
        _feed_columns(h, 256, 512)
        rt.remove_query("hot")
        fi3 = rt.junctions["S"].fused_ingest
        assert fi3 is not None and len(fi3.endpoints) == 2
        _feed_columns(h, 512, 768)
        rt.shutdown()
        mgr.shutdown()


class TestHotDeploy:
    def test_add_query_routes_and_remove_stops(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "define stream S (v long);\n"
            "@info(name='base') from S[v > 2] select v insert into Out;"
        )
        base = _collect(rt, "base")
        rt.start()
        h = rt.get_input_handler("S")
        h.send_many([(i,) for i in range(5)], timestamps=list(range(5)))
        qid = rt.add_query(
            "@info(name='hot') from S[v % 2 == 0] select v insert into O2;"
        )
        assert qid == "hot"
        hot = _collect(rt, "hot")
        h.send_many([(i,) for i in range(5, 9)], timestamps=list(range(5, 9)))
        assert hot == [(6,), (8,)]
        rt.remove_query(qid)
        h.send_many([(10,)], timestamps=[10])
        assert hot == [(6,), (8,)]  # undeployed: no further rows
        assert len(base) == 6 + 1  # base survived both splices
        assert "hot" not in rt.queries
        # the retained AST shrank back: a rebuild cannot resurrect it
        from siddhi_tpu.query_api.execution import assign_execution_ids

        ids = [e[1] for e in assign_execution_ids(rt.app)]
        assert ids == ["base"]
        rt.shutdown()
        mgr.shutdown()

    def test_add_query_survives_supervised_restart(self):
        # the splice grows the retained AST, so the supervisor's rebuild
        # includes the hot-deployed query
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('HotSup')\n"
            "define stream S (v long);\n"
            "@info(name='base') from S select v insert into Out;"
        )
        rt.start()
        rt.add_query("@info(name='hot') from S[v > 1] select v insert into O2;")
        sup = mgr.supervise(poll_interval_s=0.05)
        rt._health.mark_fatal(RuntimeError("boom"), "test")
        t0 = time.time()
        while mgr.get_siddhi_app_runtime("HotSup") is rt and time.time() - t0 < 10:
            time.sleep(0.05)
        rt2 = mgr.get_siddhi_app_runtime("HotSup")
        assert rt2 is not rt
        t0 = time.time()
        while not rt2._running and time.time() - t0 < 10:
            time.sleep(0.05)
        assert "hot" in rt2.queries
        hot = _collect(rt2, "hot")
        rt2.get_input_handler("S").send((5,), timestamp=1)
        assert hot == [(5,)]
        mgr.shutdown()

    def test_duplicate_query_id_rejected(self):
        from siddhi_tpu.core.errors import SiddhiAppCreationError

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "define stream S (v long);\n"
            "@info(name='q') from S select v insert into Out;"
        )
        rt.start()
        with pytest.raises(SiddhiAppCreationError, match="duplicate query"):
            rt.add_query("@info(name='q') from S select v insert into O2;")
        rt.shutdown()
        mgr.shutdown()

    def test_undeclared_stream_rejected(self):
        from siddhi_tpu.core.errors import SiddhiAppCreationError

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("define stream S (v long);")
        rt.start()
        with pytest.raises(SiddhiAppCreationError, match="undeclared stream"):
            rt.add_query(
                "@info(name='x') from Nope select v insert into Out;"
            )
        rt.shutdown()
        mgr.shutdown()

    def test_unnamed_candidate_rejected(self):
        # auto-numbered ids renumber as unnamed queries churn in and out
        # (and across supervised rebuilds): not a stable handle — SA130
        from siddhi_tpu.core.errors import SiddhiAppCreationError

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("define stream S (v long);")
        rt.start()
        with pytest.raises(SiddhiAppCreationError, match="@info"):
            rt.add_query("from S select v insert into Out;")
        rt.shutdown()
        mgr.shutdown()

    def test_remove_partition_inner_query_rejected(self):
        from siddhi_tpu.core.errors import SiddhiAppCreationError

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "define stream S (k string, v long);\n"
            "partition with (k of S) begin\n"
            "@info(name='p') from S select k, v insert into Out;\n"
            "end;"
        )
        rt.start()
        with pytest.raises(SiddhiAppCreationError, match="partition"):
            rt.remove_query("p")
        rt.shutdown()
        mgr.shutdown()


class TestStateSeeding:
    APP = (
        "@app:name('Seed')\n"
        "define stream S (v long);\n"
        "@info(name='w') from S#window.length(4) select v, sum(v) as t "
        "insert into O;"
    )
    Q = (
        "@info(name='w') from S#window.length(4) select v, sum(v) as t "
        "insert into O;"
    )

    def _deployed_app(self):
        mgr = SiddhiManager()
        mgr.set_persistence_store(InMemoryPersistenceStore())
        rt = mgr.create_siddhi_app_runtime(self.APP)
        rows = _collect(rt, "w")
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(1, 5):
            h.send((i,), timestamp=i)
        rt.persist()
        assert rows[-1] == (4, 10)
        return mgr, rt, h

    def test_window_seeded_from_checkpoint(self):
        mgr, rt, h = self._deployed_app()
        rt.remove_query("w")
        rt.add_query(self.Q, seed="checkpoint")
        rows = _collect(rt, "w")
        h.send((5,), timestamp=5)
        assert rows[-1] == (5, 14)  # ring carried 2+3+4 across the splice
        assert mgr.churn_stats("Seed").last_seed == {"query:w": "seeded"}
        mgr.shutdown()

    def test_window_cold_start(self):
        mgr, rt, h = self._deployed_app()
        rt.remove_query("w")
        rt.add_query(self.Q, seed="cold")
        rows = _collect(rt, "w")
        h.send((5,), timestamp=5)
        assert rows[-1] == (5, 5)
        assert mgr.churn_stats("Seed").last_seed == {"query:w": "cold"}
        mgr.shutdown()

    def test_incompatible_checkpoint_starts_cold(self):
        # the re-added query has a DIFFERENT window length: the snapshot
        # element's tree shapes mismatch, so the seed surfaces
        # 'incompatible' and the query starts cold (state never coerced)
        mgr, rt, h = self._deployed_app()
        rt.remove_query("w")
        rt.add_query(self.Q.replace("length(4)", "length(8)"), seed="checkpoint")
        rows = _collect(rt, "w")
        h.send((5,), timestamp=5)
        assert rows[-1] == (5, 5)
        assert mgr.churn_stats("Seed").last_seed == {"query:w": "incompatible"}
        mgr.shutdown()


class TestRollback:
    def test_add_query_rolls_back_on_injected_splice_fault(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(FUSED_APP)
        r1 = _collect(rt, "q1")
        r2 = _collect(rt, "q2")
        rt.start()
        h = rt.get_input_handler("S")
        _feed_columns(h, 0, 256)
        faults.install(faults.FaultPlan(
            [faults.FaultRule(site="churn_splice", match="+bad")]
        ))
        try:
            with pytest.raises(faults.InjectedFault):
                rt.add_query(
                    "@info(name='bad') from S select a insert into OB;"
                )
        finally:
            faults.uninstall()
        # rolled back to the pre-churn runtime: query gone, AST unchanged,
        # fused engines rebuilt, traffic flows with identical semantics
        assert "bad" not in rt.queries
        assert rt.junctions["S"].fused_ingest is not None
        assert mgr.churn_stats("F").rollbacks == 1
        _feed_columns(h, 256, 512)
        rt.shutdown()
        mgr.shutdown()
        # parity against an un-churned control
        mgr2 = SiddhiManager()
        c = mgr2.create_siddhi_app_runtime(FUSED_APP)
        c1 = _collect(c, "q1")
        c2 = _collect(c, "q2")
        c.start()
        ch = c.get_input_handler("S")
        _feed_columns(ch, 0, 512)
        c.shutdown()
        mgr2.shutdown()
        assert r1 == c1 and r2 == c2

    def test_remove_query_fault_leaves_runtime_untouched(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "define stream S (v long);\n"
            "@info(name='q') from S select v insert into Out;"
        )
        rows = _collect(rt, "q")
        rt.start()
        faults.install(faults.FaultPlan(
            [faults.FaultRule(site="churn_splice", match="-q")]
        ))
        try:
            with pytest.raises(faults.InjectedFault):
                rt.remove_query("q")
        finally:
            faults.uninstall()
        assert "q" in rt.queries
        rt.get_input_handler("S").send((1,), timestamp=1)
        assert rows == [(1,)]
        rt.shutdown()
        mgr.shutdown()


class TestRedeploy:
    V1 = (
        "@app:name('App')\n"
        "define stream S (v long);\n"
        "define table T (k long, total long);\n"
        "@info(name='q') from S#window.length(4) select v, sum(v) as t "
        "insert into O;"
    )

    def test_state_compat_matrix(self):
        # restored: unchanged query + table; incompatible: changed window
        # length; dropped: removed table; cold: brand-new query
        v2 = (
            "@app:name('App')\n"
            "define stream S (v long);\n"
            "@info(name='q') from S#window.length(8) select v, sum(v) as t "
            "insert into O;\n"
            "@info(name='q2') from S[v > 100] select v insert into Big;"
        )
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(self.V1)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(1, 5):
            h.send((i,), timestamp=i)
        report = mgr.redeploy("App", v2)
        assert "query:q" in report["incompatible"]
        assert "table:T" in report["dropped"]
        assert "query:q2" in report["cold"]
        assert mgr.churn_stats("App").redeploys == 1
        mgr.shutdown()

    def test_compatible_state_carries_and_stale_handles_forward(self):
        v2 = self.V1 + "\n@info(name='q2') from S[v > 100] select v insert into Big;"
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(self.V1)
        rows = _collect(rt, "q")
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(1, 5):
            h.send((i,), timestamp=i)
        assert rows[-1] == (4, 10)
        report = mgr.redeploy("App", v2)
        assert "query:q" in report["restored"]
        assert "table:T" in report["restored"]
        rt2 = mgr.get_siddhi_app_runtime("App")
        assert rt2 is not rt
        rows2 = _collect(rt2, "q")
        # the STALE pre-redeploy handle forwards through the released gate
        h.send((5,), timestamp=5)
        assert rows2[-1] == (5, 14)  # window ring carried across the swap
        mgr.shutdown()

    def test_redeploy_buffers_concurrent_ingress(self):
        # a live sender races the swap window: every event must land
        # exactly once (buffered and drained in order, never dropped)
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('App')\ndefine stream S (v long);\n"
            "@info(name='q') from S select v insert into O;"
        )
        seen: list = []
        rt.add_callback("q", lambda ts, i, r: seen.extend(
            e.data[0] for e in i or []
        ))
        rt.start()
        h = rt.get_input_handler("S")
        stop = threading.Event()
        sent = []

        def pump():
            i = 0
            while not stop.is_set():
                h.send((i,), timestamp=i)
                sent.append(i)
                i += 1
                time.sleep(0.001)

        t = threading.Thread(target=pump, daemon=True)
        t.start()
        time.sleep(0.1)
        report = mgr.redeploy(
            "App",
            "@app:name('App')\ndefine stream S (v long);\n"
            "@info(name='q') from S select v insert into O;",
        )
        rt2 = mgr.get_siddhi_app_runtime("App")
        rt2.add_callback("q", lambda ts, i, r: seen.extend(
            e.data[0] for e in i or []
        ))
        time.sleep(0.1)
        stop.set()
        t.join(timeout=5)
        time.sleep(0.2)
        mgr.shutdown()
        # the callback re-registration races the drain by a few events
        # (events drained between swap and re-register are processed by
        # the new runtime before the observer attaches); the CONTRACT is
        # zero loss at the engine: monotone, gap-free delivery afterwards
        assert seen == sorted(seen)
        observed = set(seen)
        missing = [i for i in sent if i not in observed and i > min(seen or [0])]
        assert not missing, f"events lost across the swap: {missing[:10]}"
        assert report["gates"]["S"]["shed"] == 0

    def test_failed_redeploy_rolls_back_to_old_app(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(self.V1)
        rows = _collect(rt, "q")
        rt.start()
        h = rt.get_input_handler("S")
        h.send((1,), timestamp=1)
        # the replacement fails to BUILD (undefined stream in a query):
        # the old deployment must keep serving
        bad = (
            "@app:name('App')\n"
            "define stream S (v long);\n"
            "@info(name='q') from Nope select v insert into O;"
        )
        with pytest.raises(Exception):
            mgr.redeploy("App", bad)
        assert mgr.get_siddhi_app_runtime("App") is rt
        h.send((2,), timestamp=2)
        assert rows[-1] == (2, 3)
        assert mgr.churn_stats("App").rollbacks == 1
        mgr.shutdown()

    def test_redeploy_restore_fault_keeps_old_serving(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(self.V1)
        rows = _collect(rt, "q")
        rt.start()
        h = rt.get_input_handler("S")
        h.send((1,), timestamp=1)
        faults.install(faults.FaultPlan(
            [faults.FaultRule(site="churn_restore", match="App")]
        ))
        try:
            with pytest.raises(faults.InjectedFault):
                mgr.redeploy("App", self.V1)
        finally:
            faults.uninstall()
        assert mgr.get_siddhi_app_runtime("App") is rt
        h.send((2,), timestamp=2)
        assert rows[-1] == (2, 3)
        mgr.shutdown()

    def test_rename_rejected(self):
        from siddhi_tpu.core.errors import SiddhiAppCreationError

        mgr = SiddhiManager()
        mgr.create_siddhi_app_runtime(self.V1).start()
        with pytest.raises(SiddhiAppCreationError, match="rename"):
            mgr.redeploy("App", self.V1.replace("'App'", "'Other'"))
        mgr.shutdown()


class TestShardRebalance:
    V = (
        "@app:name('Sh')\n"
        "@app:shard(devices='{d}')\n"
        "@app:partitionCapacity(size='8')\n"
        "define stream S (k long, v long);\n"
        "partition with (k of S) begin\n"
        "@info(name='p') from S#window.length(4) select k, sum(v) as t "
        "insert into O;\n"
        "end;"
    )

    def test_mesh_size_change_migrates_partitioned_state(self):
        # [P] state built on a 2-device mesh redeploys onto a 4-device
        # mesh through the host snapshot; emissions across the rebalance
        # are byte-identical to a 4-device control run, and the report's
        # per-device placement proves the new mesh
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(self.V.format(d=2))
        rows = _collect(rt, "p")
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(16):
            h.send((i % 4, i), timestamp=i)
        assert rt._shard.describe_state()["devices"] == 2
        pre = list(rows)
        report = mgr.redeploy("Sh", self.V.format(d=4))
        assert "partition:0:keys" in report["restored"]
        assert "query:p" in report["restored"]
        assert report["shard"]["before"]["devices"] == 2
        assert report["shard"]["after"]["devices"] == 4
        rt2 = mgr.get_siddhi_app_runtime("Sh")
        rows2 = _collect(rt2, "p")
        h2 = rt2.get_input_handler("S")
        for i in range(16, 32):
            h2.send((i % 4, i), timestamp=i)
        placed = rt2._shard.describe_state()["partitioned"]["p"]
        assert placed == {
            "sharded": True, "devices": 4, "axis": "part", "local_slots": 2,
        }
        mgr.shutdown()
        # control: the same 32 events on a 4-device mesh from scratch
        mgr2 = SiddhiManager()
        c = mgr2.create_siddhi_app_runtime(
            self.V.format(d=4).replace("'Sh'", "'C'")
        )
        crows = _collect(c, "p")
        c.start()
        ch = c.get_input_handler("S")
        for i in range(32):
            ch.send((i % 4, i), timestamp=i)
        c.shutdown()
        mgr2.shutdown()
        assert pre + rows2 == crows


class TestPausedReplay:
    def _app(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('R')\ndefine stream S (v long);\n"
            "@info(name='q') from S select v insert into O;"
        )
        rows = _collect(rt, "q")
        rt.start()
        return mgr, rt, rows

    def _store_entries(self, mgr, n):
        from siddhi_tpu.core.error_store import ORIGIN_STREAM, make_entry

        for i in range(n):
            mgr.error_store.store(make_entry(
                "R", ORIGIN_STREAM, "S", RuntimeError("boom"),
                events=[(i, (-(i + 1),))],
            ))

    def _patch_live_send_mid_replay(self, mgr, rt, live_rows):
        """After each replayed entry, a HELPER thread sends one live row —
        live mode interleaves it, paused mode holds it behind the backlog."""
        orig = rt.replay_error
        it = iter(live_rows)

        def patched(entry):
            ok = orig(entry)
            v = next(it, None)
            if v is not None:
                t = threading.Thread(
                    target=lambda: rt.get_input_handler("S").send(
                        (v,), timestamp=1000 + v
                    )
                )
                t.start()
                t.join(timeout=30)
            return ok

        rt.replay_error = patched

    def test_paused_mode_strict_stored_order(self):
        mgr, rt, rows = self._app()
        self._store_entries(mgr, 4)
        self._patch_live_send_mid_replay(mgr, rt, [10, 11, 12, 13])
        n = mgr.replay_errors(mode="paused")
        assert n == 4
        # every replayed row lands BEFORE every held live row, and the
        # live rows resume in their arrival order
        assert [v for (v,) in rows] == [-1, -2, -3, -4, 10, 11, 12, 13]
        assert rt.junctions["S"].ingress_gate is None  # gate removed
        mgr.shutdown()

    def test_live_mode_interleaves(self):
        mgr, rt, rows = self._app()
        self._store_entries(mgr, 4)
        self._patch_live_send_mid_replay(mgr, rt, [10, 11, 12, 13])
        n = mgr.replay_errors()  # default mode='live'
        assert n == 4
        got = [v for (v,) in rows]
        assert sorted(got) == [-4, -3, -2, -1, 10, 11, 12, 13]
        # the live sends dispatched immediately: at least one live row sits
        # BEFORE the last replayed row
        assert got != [-1, -2, -3, -4, 10, 11, 12, 13]
        mgr.shutdown()


class TestChurnObservability:
    def test_status_explain_and_prometheus(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:name('Obs')\ndefine stream S (v long);\n"
            "@info(name='q') from S select v insert into O;"
        )
        rt.start()
        rt.add_query("@info(name='h') from S[v > 0] select v insert into O2;")
        rt.remove_query("h")
        st = rt.snapshot_status()["churn"]
        assert st["deploys"] == 1 and st["undeploys"] == 1
        assert "last_splice_ms" in st
        assert st["last_seed"] == {"query:h": "cold"}
        plan = rt.explain(fmt="dict")
        assert plan["churn"]["deploys"] == 1
        text = rt.explain()
        assert "churn: deploys=1 undeploys=1" in text
        prom = mgr.prometheus_text()
        assert 'siddhi_churn_total{app="Obs",op="deploy"} 1' in prom
        assert 'siddhi_churn_total{app="Obs",op="undeploy"} 1' in prom
        rt.shutdown()
        mgr.shutdown()


class TestSA130:
    def test_analyze_add_query_reports_all(self):
        from siddhi_tpu.analysis import analyze_add_query

        app = (
            "define stream S (v long);\n"
            "@info(name='q') from S select v insert into Out;"
        )
        res = analyze_add_query(
            app, "@info(name='q') from Nope select v insert into O2;"
        )
        codes = [d.code for d in res.errors]
        assert codes == ["SA130", "SA130"]
        msgs = " | ".join(d.message for d in res.errors)
        assert "duplicate query name 'q'" in msgs
        assert "undeclared stream 'Nope'" in msgs

    def test_unnamed_candidate_flagged(self):
        from siddhi_tpu.analysis import analyze_add_query

        res = analyze_add_query(
            "define stream S (v long);",
            "from S select v insert into Out;",
        )
        assert [d.code for d in res.errors] == ["SA130"]
        assert "@info" in res.errors[0].message

    def test_clean_candidate_ok(self):
        from siddhi_tpu.analysis import analyze_add_query

        res = analyze_add_query(
            "define stream S (v long);",
            "@info(name='n') from S select v insert into Out;",
        )
        assert res.ok
