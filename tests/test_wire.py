"""Compact wire encodings (core/wire.py): codec round-trips + runtime
guards, @app:wire resolution (annotation/env precedence, SA132 analyzer =
runtime rule set), static-spec engagement with byte-identical emissions
encode-on vs encode-off, the mid-stream full-width fallback, the
logical-vs-encoded roofline split, the FusionPlan v2 wire section, and the
explain()/describe_state() surfacing."""

from __future__ import annotations

import os

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core import wire as W
from siddhi_tpu.core.event import StreamSchema, WireNarrowMisfit
from siddhi_tpu.core.types import AttrType


SCHEMA = StreamSchema("S", [
    ("sym", AttrType.STRING),
    ("price", AttrType.FLOAT),
    ("vol", AttrType.LONG),
    ("seq", AttrType.LONG),
    ("flag", AttrType.BOOL),
])


def _sample(cap=16):
    ts = np.arange(cap, dtype=np.int64) * 3 + 1_700_000_000_000
    cols = {
        "sym": (np.arange(cap, dtype=np.int32) % 4) + 5,
        "price": np.linspace(0, 10, cap).astype(np.float32),
        "vol": np.arange(cap, dtype=np.int64) * 100,
        "seq": np.arange(cap, dtype=np.int64) + 10**12,
        "flag": (np.arange(cap) % 2 == 0),
    }
    return ts, cols


ENC = {
    "sym": ("dict", np.dtype(np.uint8), 4),
    "vol": ("narrow", np.dtype(np.int16)),
    "seq": ("delta", np.dtype(np.int16)),
    "flag": ("bitpack",),
    "__tsd__": np.dtype(np.int8),
}


class TestCodec:
    def test_round_trip_all_encoders(self):
        cap = 16
        ts, cols = _sample(cap)
        encode, decode, total = SCHEMA.wire_codec(cap, None, ENC)
        # the encoded wire is a fraction of the full-width one
        assert total < W.logical_row_bytes(SCHEMA.attrs) * cap / 2
        buf, base = encode(ts, cols, cap)
        b = decode(buf, np.int32(cap), base)
        assert np.array_equal(np.asarray(b.ts), ts)
        for k, v in cols.items():
            assert np.array_equal(np.asarray(b.cols[k]), v), k
        assert bool(np.asarray(b.valid).all())

    def test_partial_batch(self):
        cap = 16
        ts, cols = _sample(cap)
        encode, decode, _ = SCHEMA.wire_codec(cap, None, ENC)
        buf, base = encode(ts, cols, 5)
        b = decode(buf, np.int32(5), base)
        assert np.array_equal(np.asarray(b.valid), np.arange(cap) < 5)
        for k, v in cols.items():
            assert np.array_equal(np.asarray(b.cols[k])[:5], v[:5]), k

    def test_empty_batch(self):
        cap = 8
        ts, cols = _sample(cap)
        encode, decode, _ = SCHEMA.wire_codec(cap, None, ENC)
        buf, base = encode(ts[:0], {k: v[:0] for k, v in cols.items()}, 0)
        b = decode(buf, np.int32(0), base)
        assert not bool(np.asarray(b.valid).any())

    def test_dict_cardinality_guard(self):
        cap = 16
        ts, cols = _sample(cap)
        encode, _d, _t = SCHEMA.wire_codec(cap, None, ENC)
        bad = dict(cols)
        bad["sym"] = np.arange(cap, dtype=np.int32)  # 16 distinct > 4
        with pytest.raises(WireNarrowMisfit):
            encode(ts, bad, cap)

    def test_narrow_range_guard(self):
        cap = 16
        ts, cols = _sample(cap)
        encode, _d, _t = SCHEMA.wire_codec(cap, None, ENC)
        bad = dict(cols)
        bad["vol"] = np.full(cap, 10**6, np.int64)  # > int16
        with pytest.raises(WireNarrowMisfit):
            encode(ts, bad, cap)

    def test_delta_jump_guard(self):
        cap = 16
        ts, cols = _sample(cap)
        encode, _d, _t = SCHEMA.wire_codec(cap, None, ENC)
        bad = dict(cols)
        s = cols["seq"].copy()
        s[8] = s[7] + 10**6  # diff > int16
        bad["seq"] = s
        with pytest.raises(WireNarrowMisfit):
            encode(ts, bad, cap)

    def test_projection_still_applies(self):
        cap = 8
        ts, cols = _sample(cap)
        keep = frozenset(("sym", "flag"))
        encode, decode, total = SCHEMA.wire_codec(cap, keep, ENC)
        _e, _d, total_all = SCHEMA.wire_codec(cap, None, ENC)
        assert total < total_all
        buf, base = encode(ts, cols, cap)
        b = decode(buf, np.int32(cap), base)
        assert np.array_equal(np.asarray(b.cols["sym"]), cols["sym"])
        assert set(b.cols) == {n for n, _t in SCHEMA.attrs}  # shape kept


class TestSpec:
    def test_build_wire_spec_from_hints(self):
        hints = {
            ("S", "vol"): ("range", 0, 30000),
            ("S", "sym"): ("dict", 16),
            ("S", "seq"): ("delta", np.dtype(np.int16)),
        }
        spec = W.build_wire_spec("S", SCHEMA.attrs, hints)
        assert spec.encodings["vol"] == ("narrow", np.dtype(np.int16))
        assert spec.encodings["sym"] == ("dict", np.dtype(np.uint8), 16)
        assert spec.encodings["seq"] == ("delta", np.dtype(np.int16))
        # BOOL bitpack needs no hint
        assert spec.encodings["flag"] == ("bitpack",)
        d = spec.to_dict()
        assert d["version"] == W.WIRE_SPEC_VERSION
        assert d["encodings"]["sym"] == "dict:uint8[16]"

    def test_spec_none_without_static_material(self):
        attrs = [("a", AttrType.INT), ("b", AttrType.FLOAT)]
        assert W.build_wire_spec("X", attrs, {}) is None

    def test_choose_encodings_disabled_is_full_width(self):
        ts, cols = _sample(8)
        assert W.choose_encodings(SCHEMA, None, None, False, ts, cols) == {}

    def test_choose_encodings_static_beats_sampled(self):
        ts, cols = _sample(8)
        spec = W.build_wire_spec(
            "S", SCHEMA.attrs, {("S", "vol"): ("range", 0, 100000)}
        )
        enc = W.choose_encodings(SCHEMA, None, spec, True, ts, cols)
        # sampled would pick int16 for the small vol sample; the declared
        # 0..100000 contract forces int32 (no mid-stream rebuild when
        # bigger-but-declared values arrive)
        assert enc["vol"] == ("narrow", np.dtype(np.int32))
        assert enc["flag"] == ("bitpack",)

    def test_estimates(self):
        spec = W.build_wire_spec(
            "S", SCHEMA.attrs, {("S", "sym"): ("dict", 16)}
        )
        logical = W.logical_row_bytes(SCHEMA.attrs)
        assert logical == 8 + 4 + 4 + 8 + 8 + 1
        assert W.estimate_wire_bytes(SCHEMA.attrs, spec) < logical


class TestAnnotation:
    def test_resolve_defaults_on(self):
        enabled, hints = W.resolve_wire_annotation(None)
        assert enabled is True and hints == {}

    def test_env_precedence(self, monkeypatch):
        monkeypatch.setenv(W.WIRE_ENV, "0")
        enabled, _ = W.resolve_wire_annotation(None)
        assert enabled is False
        monkeypatch.setenv(W.WIRE_ENV, "1")

        class Ann:
            elements = [("disable", "true")]

            @staticmethod
            def element(k, default=None):
                return "true" if k == "disable" else default

        enabled, _ = W.resolve_wire_annotation(Ann())
        assert enabled is True  # env force-on beats the annotation

    def test_malformed_raises_at_creation(self):
        from siddhi_tpu.core.errors import SiddhiAppCreationError

        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            @app:wire(disable='maybe')
            define stream S (a int);
            from S select a insert into Out;
            """)
        mgr.shutdown()

    def test_sa132_analyzer_same_rules(self):
        from siddhi_tpu.analysis import analyze

        res = analyze("""
        @app:wire(disable='maybe', range.S.price='1..2',
                  dict.Ghost.col='8', zap.S.a='1')
        define stream S (a int, price float);
        from S select a insert into Out;
        """)
        codes = [d for d in res.diagnostics if d.code == "SA132"]
        msgs = "\n".join(d.message for d in codes)
        assert len(codes) == 4, msgs
        assert "must be true or false" in msgs
        assert "FLOAT" in msgs           # encoder-type mismatch
        assert "unknown stream 'Ghost'" in msgs
        assert "unknown @app:wire option" in msgs

    def test_sa133_dominant_long_warns_and_hint_silences(self):
        from siddhi_tpu.analysis import analyze

        base = """
        define stream M (seq long);
        from M[seq > 0] select seq insert into Out;
        """
        res = analyze(base)
        assert any(d.code == "SA133" for d in res.warnings), res.diagnostics
        hinted = "@app:wire(delta.M.seq='int16')" + base
        res2 = analyze(hinted)
        assert not any(d.code == "SA133" for d in res2.diagnostics)


WIRE_APP = """
@app:batch(size='32')
@app:wire(dict.S.symbol='16', range.S.volume='0..30000')
define stream S (symbol string, price float, volume long, up bool);
@info(name='q') from S[price > 20]#window.length(8)
select symbol, up, avg(price) as ap, sum(volume) as tv insert into Out;
"""


def _feed(n=256, seed=3):
    rng = np.random.default_rng(seed)
    ts = np.arange(n, dtype=np.int64) + 1_700_000_000_000
    cols = {
        "symbol": rng.integers(1, 9, n).astype(np.int32),
        "price": rng.uniform(0, 100, n).astype(np.float32),
        "volume": rng.integers(1, 1000, n).astype(np.int64),
        "up": rng.integers(0, 2, n).astype(bool),
    }
    return ts, cols


def _run_app(ql, env_val, feed_calls, seed=3):
    saved = os.environ.get(W.WIRE_ENV)
    os.environ[W.WIRE_ENV] = env_val
    try:
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
    finally:
        if saved is None:
            os.environ.pop(W.WIRE_ENV, None)
        else:
            os.environ[W.WIRE_ENV] = saved
    for i in range(1, 20):
        mgr.interner.intern(f"SYM{i}")
    rows = []
    rt.add_callback("q", lambda t, ins, rem: rows.extend(
        [("+",) + tuple(e.data) for e in (ins or [])]
        + [("-",) + tuple(e.data) for e in (rem or [])]
    ))
    rt.start()
    h = rt.get_input_handler("S")
    for ts, cols in feed_calls:
        h.send_columns(ts, cols, now=int(ts[-1]))
    fi = rt.junctions["S"].fused_ingest
    state = {
        "narrow": dict(fi._narrow) if fi and fi._narrow is not None else None,
        "wire_bytes": fi._wire_bytes if fi else None,
        "describe": fi.describe_state() if fi else None,
    }
    rt.shutdown()
    mgr.shutdown()
    return rows, state


class TestEngineIntegration:
    def test_static_spec_engages_and_parity(self):
        ts, cols = _feed()
        on_rows, on_state = _run_app(WIRE_APP, "1", [(ts, cols)])
        off_rows, off_state = _run_app(WIRE_APP, "0", [(ts, cols)])
        assert on_rows == off_rows and on_rows
        assert on_state["wire_bytes"] < off_state["wire_bytes"]
        assert isinstance(on_state["narrow"].get("symbol"), tuple)
        assert on_state["narrow"].get("up") == ("bitpack",)
        assert off_state["narrow"] == {}  # WIRE=0 = full width, no sampling
        w = on_state["describe"]["wire"]
        assert w["source"] in ("static", "static+sampled")
        assert w["encoded_B_per_ev"] < w["logical_B_per_ev"]
        assert "dict" in w["lanes"]["symbol"]

    def test_annotation_disable(self):
        ql = WIRE_APP.replace(
            "@app:wire(dict.S.symbol='16', range.S.volume='0..30000')",
            "@app:wire(disable='true', dict.S.symbol='16')",
        )
        ts, cols = _feed()
        # no env override: the annotation's disable wins
        rows, state = _run_app(ql, "", [(ts, cols)])
        assert state["narrow"] == {}

    def test_mid_stream_range_fallback_byte_identical(self):
        ts, cols = _feed()
        ts2 = ts + len(ts)
        cols2 = dict(cols)
        cols2["volume"] = cols["volume"] + 10**6  # > declared-range dtype
        feed = [(ts, cols), (ts2, cols2)]
        on_rows, on_state = _run_app(WIRE_APP, "1", feed)
        off_rows, _ = _run_app(WIRE_APP, "0", feed)
        assert on_state["narrow"] == {}  # fell back full-width, permanent
        assert on_rows == off_rows

    def test_mid_stream_dict_overflow_fallback(self):
        ts, cols = _feed()
        ts2 = ts + len(ts)
        cols2 = dict(cols)
        cols2["symbol"] = (
            np.arange(len(ts), dtype=np.int32) % 18
        ) + 1  # 18 distinct > declared 16
        feed = [(ts, cols), (ts2, cols2)]
        on_rows, on_state = _run_app(WIRE_APP, "1", feed)
        off_rows, _ = _run_app(WIRE_APP, "0", feed)
        assert on_state["narrow"] == {}
        assert on_rows == off_rows

    def test_roofline_logical_vs_encoded(self):
        saved = os.environ.get(W.WIRE_ENV)
        os.environ[W.WIRE_ENV] = "1"
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(
                "@app:statistics(reporter='none')\n" + WIRE_APP
            )
        finally:
            if saved is None:
                os.environ.pop(W.WIRE_ENV, None)
            else:
                os.environ[W.WIRE_ENV] = saved
        rt.start()
        ts, cols = _feed()
        rt.get_input_handler("S").send_columns(ts, cols, now=int(ts[-1]))
        roof = rt.statistics_manager.roofline()
        ent = roof.get("stream.S")
        assert ent is not None, roof
        assert 0 < ent["wire_bytes_per_event"] < ent[
            "wire_logical_bytes_per_event"
        ], ent
        assert ent["wire_reduction"] > 1.5, ent
        # the Prometheus exposition carries both gauges
        text = rt.statistics_manager.prometheus_text()
        assert "siddhi_wire_bytes_per_event" in text
        assert "siddhi_wire_logical_bytes_per_event" in text
        rt.shutdown()
        mgr.shutdown()

    def test_explain_renders_wire(self):
        ts, cols = _feed()
        saved = os.environ.get(W.WIRE_ENV)
        os.environ[W.WIRE_ENV] = "1"
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(WIRE_APP)
        finally:
            if saved is None:
                os.environ.pop(W.WIRE_ENV, None)
            else:
                os.environ[W.WIRE_ENV] = saved
        rt.start()
        rt.get_input_handler("S").send_columns(ts, cols, now=int(ts[-1]))
        text = rt.explain()
        assert "wire[" in text, text
        assert "dict" in text
        rt.shutdown()
        mgr.shutdown()


class TestPlanWireSection:
    def test_plan_carries_versioned_specs(self):
        from siddhi_tpu.analysis import build_fusion_plan

        plan = build_fusion_plan(WIRE_APP).to_dict()
        assert plan["version"] == 3
        w = plan["wire"]["S"]
        assert w["version"] == W.WIRE_SPEC_VERSION
        assert w["encodings"]["symbol"] == "dict:uint8[16]"
        assert w["encodings"]["up"] == "bitpack:1bit"
        assert w["encoded_B_per_ev_est"] < w["logical_B_per_ev"]

    def test_plan_marks_disabled(self):
        from siddhi_tpu.analysis import build_fusion_plan

        ql = WIRE_APP.replace(
            "@app:wire(dict.S.symbol='16', range.S.volume='0..30000')",
            "@app:wire(disable='true', dict.S.symbol='16')",
        )
        plan = build_fusion_plan(ql).to_dict()
        assert plan["wire"]["S"].get("disabled") is True

    def test_plan_text_renders_wire(self):
        from siddhi_tpu.analysis import build_fusion_plan
        from siddhi_tpu.analysis.fusion import render_plan_text

        text = render_plan_text(build_fusion_plan(WIRE_APP))
        assert "wire encodings:" in text
        assert "symbol=dict:uint8[16]" in text
