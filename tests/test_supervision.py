"""Supervised runtime tests: deterministic fault injection, auto-checkpoint,
crash recovery, and restart policies.

The chaos contract under test (ISSUE 9): with fault injection on, the
supervisor auto-restarts a crashed app within `max.attempts`, restored
window/aggregation state matches a never-crashed control run, and no
`@OnError(action='STORE')` event is lost across the crash. The subprocess
SIGKILL variant of the same proof runs in CI (`tools/chaos_smoke.py`).
"""

import logging
import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.persistence import (
    FileSystemPersistenceStore,
    IncrementalFileSystemPersistenceStore,
    InMemoryPersistenceStore,
)
from siddhi_tpu.core.supervision import prune_revisions
from siddhi_tpu.testing import FaultPlan, FaultRule, InjectedFault, faults


@pytest.fixture(autouse=True)
def _no_leaked_plan():
    yield
    faults.uninstall()


def _wait_for(pred, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        v = pred()
        if v:
            return v
        time.sleep(0.01)
    return pred()


# ---------------------------------------------------------------------------
# fault-injection harness
# ---------------------------------------------------------------------------


class TestFaultPlan:
    def test_after_and_times(self):
        plan = FaultPlan([FaultRule(site="x", after=2, times=2)])
        fired = []
        for i in range(6):
            try:
                plan.check("x")
            except InjectedFault:
                fired.append(i)
        assert fired == [2, 3]
        assert plan.report()["rules"][0] == {
            "site": "x", "match": "", "after": 2, "times": 2, "p": 1.0,
            "hits": 6, "fired": 2,
        }

    def test_match_filters_by_key(self):
        plan = FaultPlan([FaultRule(site="x", match="S:", times=None)])
        plan.check("x", "T:query.q")  # no match, no fire
        with pytest.raises(InjectedFault):
            plan.check("x", "S:query.q")
        assert plan.log == [("x", "S:query.q")]

    def test_probability_is_seed_deterministic(self):
        def run(seed):
            plan = FaultPlan(
                [FaultRule(site="x", p=0.3, times=None)], seed=seed
            )
            fired = []
            for i in range(50):
                try:
                    plan.check("x")
                except InjectedFault:
                    fired.append(i)
            return fired

        a, b, c = run(7), run(7), run(8)
        assert a == b  # same seed, same schedule
        assert a != c  # different seed, different schedule
        assert 0 < len(a) < 50

    def test_parse_grammar(self):
        plan = faults.parse_plan(
            "seed=42;junction_dispatch:after=10,times=2;"
            "sink_publish@Out:p=0.2,times=-1;drain_worker:error=conn,times=1"
        )
        assert plan.seed == 42
        r0, r1, r2 = plan.rules
        assert (r0.site, r0.after, r0.times) == ("junction_dispatch", 10, 2)
        assert (r1.site, r1.match, r1.p, r1.times) == (
            "sink_publish", "Out", 0.2, None,
        )
        assert r2.error == "conn"

    def test_parse_rejects_malformed(self):
        for bad in (
            "site_with_no_opts",
            "x:notkv",
            "x:p=1.5",
            "x:error=boom",
            "x:frobnicate=1",
        ):
            with pytest.raises(ValueError):
                faults.parse_plan(bad)

    def test_sink_site_defaults_to_connection_error(self):
        from siddhi_tpu.core.errors import ConnectionUnavailableError

        plan = FaultPlan([FaultRule(site="sink_publish")])
        with pytest.raises(ConnectionUnavailableError):
            plan.check("sink_publish", "app:Out")

    def test_inactive_plan_is_free(self):
        assert faults.ACTIVE is None
        faults.hit("junction_dispatch", "anything")  # no-op, no raise


# ---------------------------------------------------------------------------
# @app:persist — auto-checkpoint + retention
# ---------------------------------------------------------------------------


PERSIST_APP = """
@app:name('AutoPersistApp')
@app:persist(interval='100 millisec', keep='2')
define stream S (sym string, v long);
@info(name='q')
from S#window.length(3) select sym, sum(v) as total insert into Out;
"""


class TestAutoPersist:
    def test_periodic_persist_and_retention(self, tmp_path):
        store = FileSystemPersistenceStore(str(tmp_path))
        mgr = SiddhiManager()
        mgr.set_persistence_store(store)
        rt = mgr.create_siddhi_app_runtime(PERSIST_APP)
        rt.start()
        rt.get_input_handler("S").send(("A", 10), timestamp=1)
        assert _wait_for(lambda: rt._autopersist.persists >= 3, timeout=10)
        # poll: a FOURTH cycle may be mid-flight (persist done, prune not
        # yet) at the moment the wait above returns — retention converges
        # to keep=2 between cycles
        assert _wait_for(
            lambda: len(store.list_revisions("AutoPersistApp")) <= 2
            and rt._autopersist.pruned >= 1,
            timeout=10,
        ), "retention must prune to keep=2"
        st = rt.snapshot_status()["autopersist"]
        assert st["persists"] >= 3 and st["keep"] == 2
        mgr.shutdown()

    def test_restore_from_auto_checkpoint(self, tmp_path):
        store = FileSystemPersistenceStore(str(tmp_path))
        mgr = SiddhiManager()
        mgr.set_persistence_store(store)
        rt = mgr.create_siddhi_app_runtime(PERSIST_APP)
        rt.start()
        rt.get_input_handler("S").send(("A", 10), timestamp=1)
        rt.get_input_handler("S").send(("A", 20), timestamp=2)
        # wait for a checkpoint taken AFTER both sends (an earlier interval
        # may have fired between them)
        p0 = rt._autopersist.persists
        assert _wait_for(lambda: rt._autopersist.persists > p0, timeout=10)
        mgr.shutdown()

        mgr2 = SiddhiManager()
        mgr2.set_persistence_store(store)
        rt2 = mgr2.create_siddhi_app_runtime(PERSIST_APP)
        got = []
        rt2.add_callback("q", lambda ts, i, r: got.extend(
            e.data for e in i or []
        ))
        rt2.restore_last_revision()
        rt2.start()
        rt2.get_input_handler("S").send(("A", 5), timestamp=3)
        assert _wait_for(lambda: got)
        assert got[-1] == ("A", 35)  # 10 + 20 restored + 5
        mgr2.shutdown()

    def test_persist_save_fault_counts_and_recovers(self, tmp_path):
        store = FileSystemPersistenceStore(str(tmp_path))
        mgr = SiddhiManager()
        mgr.set_persistence_store(store)
        rt = mgr.create_siddhi_app_runtime(PERSIST_APP)
        faults.install(faults.parse_plan("persist_save:times=1"))
        rt.start()
        assert _wait_for(lambda: rt._autopersist.failures >= 1, timeout=10)
        # the next interval succeeds: the injected fault fired once
        assert _wait_for(lambda: rt._autopersist.persists >= 1, timeout=10)
        assert rt._autopersist.last_error is None
        mgr.shutdown()

    def test_incremental_base_not_shifted_by_failed_save(self, tmp_path):
        """A failed FULL-snapshot save must not advance the delta base:
        the next persist must emit a full again (a delta against a base
        that never reached the store restores wrong state or no-ops)."""
        import pickle

        store = IncrementalFileSystemPersistenceStore(str(tmp_path))
        mgr = SiddhiManager()
        mgr.set_persistence_store(store)
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('IncBase')
        define stream S (v long);
        @info(name='q')
        from S#window.length(3) select sum(v) as total insert into Out;
        """)
        rt.start()
        rt.get_input_handler("S").send((10,), timestamp=1)
        faults.install(faults.parse_plan("persist_save:times=1"))
        try:
            with pytest.raises(InjectedFault):
                rt.persist()  # full staged, save fails -> base NOT committed
        finally:
            faults.uninstall()
        rt.get_input_handler("S").send((20,), timestamp=2)
        rev = rt.persist()
        data = pickle.loads(store.load("IncBase", rev))
        assert data["type"] == "full", (
            "first persisted revision must be a full snapshot, not a delta "
            "against a base that never reached the store"
        )
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(
            e.data for e in i or []
        ))
        rt.restore_last_revision()
        rt.get_input_handler("S").send((5,), timestamp=3)
        assert _wait_for(lambda: got)
        assert got[-1] == (35,)  # 10 + 20 restored + 5
        mgr.shutdown()

    def test_no_store_disables_autopersist(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(PERSIST_APP)
        rt.start()  # logs a warning, must not raise or schedule failures
        time.sleep(0.25)
        assert rt._autopersist.persists == 0
        assert rt._autopersist.failures == 0
        mgr.shutdown()

    def test_bad_annotation_rejected_at_creation(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime(
                "@app:persist(interval='sometimes')\n"
                "define stream S (a int);\n"
                "from S select a insert into Out;"
            )
        mgr.shutdown()

    def test_prune_keeps_incremental_base(self, tmp_path):
        store = IncrementalFileSystemPersistenceStore(str(tmp_path))
        mgr = SiddhiManager()
        mgr.set_persistence_store(store)
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('IncPrune')
        define stream S (v long);
        @info(name='q')
        from S#window.length(3) select sum(v) as total insert into Out;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        h.send((1,), timestamp=1)
        rt.persist()  # full
        h.send((2,), timestamp=2)
        rt.persist()  # delta
        h.send((3,), timestamp=3)
        rt.persist()  # delta
        pruned = prune_revisions(store, "IncPrune", keep=1)
        revs = store.list_revisions("IncPrune")
        # the full base must survive: the kept delta replays from it
        import pickle

        kinds = [
            pickle.loads(store.load("IncPrune", r))["type"] for r in revs
        ]
        assert "full" in kinds, (pruned, revs, kinds)
        rt.restore_last_revision()  # must still resolve its chain
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(
            e.data for e in i or []
        ))
        h.send((4,), timestamp=4)
        assert _wait_for(lambda: got)
        assert got[-1] == ((2 + 3 + 4),)
        mgr.shutdown()


# ---------------------------------------------------------------------------
# supervisor: crash -> restart -> restore -> replay
# ---------------------------------------------------------------------------


SUP_APP = """
@app:name('SupApp')
@app:restart(policy='on-failure', max.attempts='3')
@OnError(action='STORE')
define stream S (sym string, v long);
define stream C (x long);
@info(name='q')
from S#window.length(3) select sym, sum(v) as total insert into Out;
@info(name='qc')
from C select x insert into COut;
"""


def _sup_setup(tmp_path, app=SUP_APP):
    mgr = SiddhiManager()
    mgr.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
    sup = mgr.supervise(poll_interval_s=0.05)
    rt = mgr.create_siddhi_app_runtime(app)
    got = []
    rt.add_callback("q", lambda ts, i, r: got.extend(e.data for e in i or []))
    rt.start()
    return mgr, sup, rt, got


class TestSupervisor:
    def test_crash_restart_restore_replay_matches_control(self, tmp_path):
        # control: the same feed with no faults and no crash
        cmgr = SiddhiManager()
        crt = cmgr.create_siddhi_app_runtime(SUP_APP.replace("SupApp", "Ctl"))
        control = []
        crt.add_callback("q", lambda ts, i, r: control.extend(
            e.data for e in i or []
        ))
        crt.start()
        ch = crt.get_input_handler("S")
        for ts, v in ((1, 10), (2, 20), (3, 30), (4, 40)):
            ch.send(("A", v), timestamp=ts)
        cmgr.shutdown()

        mgr, sup, rt, got = _sup_setup(tmp_path)
        h = sup.input_handler("SupApp", "S")
        h.send(("A", 10), timestamp=1)
        h.send(("A", 20), timestamp=2)
        rt.persist()
        # guarded dispatch failure on S: the batch lands in the error store
        faults.install(faults.parse_plan("junction_dispatch@S:times=1"))
        h.send(("A", 30), timestamp=3)
        assert len(mgr.error_store.load()) == 1
        # unguarded crash on C: fatal signal -> supervised restart
        faults.install(faults.parse_plan("junction_dispatch@C:times=1"))
        with pytest.raises(InjectedFault):
            sup.input_handler("SupApp", "C").send((1,), timestamp=3)
        assert _wait_for(lambda: sup.restarts.get("SupApp", 0) >= 1)
        faults.uninstall()
        # zero STORE'd-event loss: the stored entry was replayed and purged
        assert _wait_for(lambda: not mgr.error_store.load())
        h.send(("A", 40), timestamp=4)
        assert _wait_for(lambda: len(got) >= 4)
        assert got == control, (
            "restored + replayed outputs must match the never-crashed run"
        )
        st = mgr.snapshot_status()
        assert st["supervisor"]["restarts_total"] == 1
        assert 'siddhi_supervisor_restarts_total{app="SupApp"} 1' in (
            mgr.prometheus_text()
        )
        mgr.shutdown()

    def test_restart_within_max_attempts_then_gives_up(self, tmp_path):
        app = SUP_APP.replace("max.attempts='3'", "max.attempts='2'").replace(
            "SupApp", "GiveUp"
        )
        mgr, sup, rt, _got = _sup_setup(tmp_path, app)
        # every dispatch to C fails, forever: each restart crashes again on
        # the next send until the budget runs out
        faults.install(faults.parse_plan("junction_dispatch@C:times=-1"))
        for ts in range(3):
            try:
                sup.input_handler("GiveUp", "C").send((ts,), timestamp=ts)
            except InjectedFault:
                pass
            time.sleep(0.3)
        assert _wait_for(lambda: "GiveUp" in sup.gave_up, timeout=15)
        assert sup.restarts.get("GiveUp", 0) <= 2
        rt2 = mgr.get_siddhi_app_runtime("GiveUp")
        assert rt2 is None or not rt2._running  # left down, not flapping
        mgr.shutdown()

    def test_policy_never_leaves_app_down(self, tmp_path):
        app = SUP_APP.replace(
            "policy='on-failure', max.attempts='3'", "policy='never'"
        ).replace("SupApp", "NeverApp")
        mgr, sup, rt, _got = _sup_setup(tmp_path, app)
        faults.install(faults.parse_plan("junction_dispatch@C:times=1"))
        with pytest.raises(InjectedFault):
            sup.input_handler("NeverApp", "C").send((1,), timestamp=1)
        assert _wait_for(lambda: "NeverApp" in sup.gave_up)
        assert sup.restarts.get("NeverApp", 0) == 0
        mgr.shutdown()

    def test_dead_async_drain_worker_detected(self, tmp_path):
        app = """
        @app:name('AsyncDead')
        @app:restart(max.attempts='3')
        @async(buffer.size='64', workers='1')
        define stream S (v long);
        @info(name='q')
        from S select v insert into Out;
        """
        mgr = SiddhiManager()
        mgr.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
        sup = mgr.supervise(poll_interval_s=0.05)
        rt = mgr.create_siddhi_app_runtime(app)
        rt.start()
        # the injected fault fires OUTSIDE the worker's poison-batch guard,
        # killing the drain thread; the supervisor's liveness probe catches
        # the silent death and restarts the app
        faults.install(faults.parse_plan("drain_worker@S:times=1"))
        rt.get_input_handler("S").send((1,))
        assert _wait_for(lambda: sup.restarts.get("AsyncDead", 0) >= 1)
        faults.uninstall()
        # the rebuilt app has a live worker again
        rt2 = mgr.get_siddhi_app_runtime("AsyncDead")
        got = []
        rt2.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt2.get_input_handler("S").send((2,))
        assert _wait_for(lambda: got)
        mgr.shutdown()

    def test_exception_handler_survives_restart(self, tmp_path):
        mgr, sup, rt, _got = _sup_setup(tmp_path)
        seen = []
        rt.set_exception_handler(seen.append)
        faults.install(faults.parse_plan("junction_dispatch@C:times=1"))
        # the handler GUARDS dispatch, so this is not fatal — crash via a
        # dead drain path instead: use device-independent fatal marker
        sup.input_handler("SupApp", "C").send((1,), timestamp=1)
        assert len(seen) == 1  # handler owned it; no restart
        time.sleep(0.3)
        assert sup.restarts.get("SupApp", 0) == 0
        mgr.shutdown()

    def test_intentional_shutdown_not_restarted(self, tmp_path):
        mgr, sup, rt, _got = _sup_setup(tmp_path)
        mgr.shutdown_siddhi_app_runtime("SupApp")
        time.sleep(0.3)
        assert sup.restarts.get("SupApp", 0) == 0
        assert mgr.get_siddhi_app_runtime("SupApp") is None
        mgr.shutdown()

    def test_bad_restart_annotation_rejected(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime(
                "@app:restart(policy='perhaps')\n"
                "define stream S (a int);\n"
                "from S select a insert into Out;"
            )
        mgr.shutdown()


# ---------------------------------------------------------------------------
# device-dispatch + pipeline fault sites
# ---------------------------------------------------------------------------


class TestDeviceFaultSites:
    def test_device_dispatch_fault_rides_failure_policy(self):
        import numpy as np

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('DevFault')
        define stream S (v long);
        @info(name='q')
        from S#window.length(4) select sum(v) as total insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(
            e.data for e in i or []
        ))
        seen = []
        rt.set_exception_handler(seen.append)
        rt.start()
        h = rt.get_input_handler("S")
        n = 256
        ts = np.arange(1, n + 1, dtype=np.int64)
        cols = {"v": np.ones(n, dtype=np.int64)}
        h.send_columns(ts, cols)  # warm up the fused path
        if not any(
            j.fused_ingest is not None for j in rt.junctions.values()
        ):
            pytest.skip("fused ingest not engaged on this backend")
        before = len(got)
        faults.install(faults.parse_plan("device_dispatch:times=1"))
        h.send_columns(ts, cols)
        faults.uninstall()
        assert seen, "handler must own the injected chunk failure"
        # the engine keeps processing after the failed chunk (donated-state
        # reset path): later sends deliver
        h.send_columns(ts[:8], {"v": cols["v"][:8]})
        assert _wait_for(lambda: len(got) > before)
        mgr.shutdown()


# ---------------------------------------------------------------------------
# restore-then-fused-send parity (restored rings must survive
# _maybe_unshare/donation)
# ---------------------------------------------------------------------------


FUSED_SHARE_APP = """
@app:name('RestoreFuse')
define stream S (v long);
@info(name='q1')
from S#window.length(8) select sum(v) as total insert into O1;
@info(name='q2')
from S#window.length(8) select max(v) as m insert into O2;
"""


class TestRestoreFusedParity:
    def test_restore_then_fused_send_parity(self, tmp_path):
        import numpy as np

        store = FileSystemPersistenceStore(str(tmp_path))

        def build():
            mgr = SiddhiManager()
            mgr.set_persistence_store(store)
            rt = mgr.create_siddhi_app_runtime(FUSED_SHARE_APP)
            got = {"q1": [], "q2": []}
            for q in ("q1", "q2"):
                rt.add_callback(q, lambda ts, i, r, _q=q: got[_q].extend(
                    e.data for e in i or []
                ))
            rt.start()
            return mgr, rt, got

        n = 128
        ts = np.arange(1, n + 1, dtype=np.int64)
        feed_a = {"v": np.arange(n, dtype=np.int64)}
        feed_b = {"v": np.arange(n, 2 * n, dtype=np.int64)}

        mgr, rt, got = build()
        h = rt.get_input_handler("S")
        h.send_columns(ts, feed_a)
        rt.persist()
        for q in got:
            got[q].clear()
        h.send_columns(ts + n, feed_b)
        expected = {q: list(v) for q, v in got.items()}

        # restore into the RUNNING app, then replay the same post-persist
        # feed: a row send in between forces the per-batch path (and the
        # unshare guard) onto the restored states before the fused send
        rt.restore_last_revision()
        for q in got:
            got[q].clear()
        h.send(
            (int(feed_b["v"][0]),), timestamp=int(ts[0] + n)
        )  # per-batch row send on restored state
        h.send_columns(
            ts[1:] + n, {"v": feed_b["v"][1:]}
        )  # fused send resumes
        assert got == expected, (
            "restored rings must survive per-batch donation and fused "
            "re-engagement byte-identically"
        )
        mgr.shutdown()


# ---------------------------------------------------------------------------
# non-blocking replay
# ---------------------------------------------------------------------------


class TestNonBlockingReplay:
    def _wait_sink_setup(self):
        from siddhi_tpu.core.io import SINKS, Sink
        from siddhi_tpu.core.errors import ConnectionUnavailableError

        instances = []

        class _DownSink(Sink):
            def __init__(self):
                self.delivered = []
                self.down = True
                instances.append(self)

            def connect(self):
                if self.down:
                    raise ConnectionUnavailableError("still down")

            def publish(self, payload):
                if self.down:
                    raise ConnectionUnavailableError("still down")
                self.delivered.append(payload)

        mgr = SiddhiManager()
        SINKS["downtest"] = _DownSink
        try:
            rt = mgr.create_siddhi_app_runtime("""
            @app:name('WaitApp')
            define stream In (v int);
            @sink(type='downtest', on.error='WAIT',
                  @map(type='passThrough'))
            define stream Out (v int);
            from In select v insert into Out;
            """)
        finally:
            del SINKS["downtest"]
        return mgr, rt, instances[0]

    def test_skip_unavailable_does_not_block(self):
        from siddhi_tpu.core.error_store import ORIGIN_SINK, make_entry

        mgr, rt, sink = self._wait_sink_setup()
        rt.start()
        mgr.error_store.store(make_entry(
            "WaitApp", ORIGIN_SINK, "Out", "down", payload=[(1,)],
        ))
        t0 = time.monotonic()
        n = mgr.replay_errors(skip_unavailable=True)
        assert time.monotonic() - t0 < 2.0, "skip must not block on WAIT"
        assert n == 0
        assert len(mgr.error_store.load()) == 1  # skipped, not lost
        # transport recovers: the same call now drains the entry
        sink.down = False
        sink.connected = True
        n = mgr.replay_errors(skip_unavailable=True)
        assert n == 1 and not mgr.error_store.load()
        assert sink.delivered == [[(1,)]]
        mgr.shutdown()

    def test_timeout_bounds_the_loop(self):
        from siddhi_tpu.core.error_store import ORIGIN_STREAM, make_entry

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('TimeoutApp')
        define stream S (v int);
        @info(name='q') from S select v insert into Out;
        """)
        rt.start()
        for i in range(5):
            mgr.error_store.store(make_entry(
                "TimeoutApp", ORIGIN_STREAM, "S", "boom",
                events=[(i, (i,))],
            ))
        n = mgr.replay_errors(timeout=0.0)  # deadline already passed
        assert n == 0 and len(mgr.error_store.load()) == 5
        n = mgr.replay_errors(timeout=30.0)
        assert n == 5 and not mgr.error_store.load()
        mgr.shutdown()


# ---------------------------------------------------------------------------
# analyzer integration (SA126-128 ride the shared rule sets)
# ---------------------------------------------------------------------------


class TestRestartAttemptFailures:
    def test_failed_restart_attempt_retries_until_budget(self, tmp_path):
        """A restart ATTEMPT that itself fails (restore raises) leaves the
        app down but must NOT abandon it: the next poll retries against the
        remaining budget, and only exhaustion lands in gave_up."""
        app = SUP_APP.replace("max.attempts='3'", "max.attempts='2'").replace(
            "SupApp", "RetryDown"
        )
        mgr, sup, rt, _got = _sup_setup(tmp_path, app)
        rt.get_input_handler("S").send(("A", 1), timestamp=1)
        rt.persist()
        # one crash trigger + a PERSISTENT restore fault: every restart
        # attempt dies in restore_last_revision
        faults.install(faults.parse_plan(
            "junction_dispatch@C:times=1;persist_load:times=-1"
        ))
        try:
            with pytest.raises(InjectedFault):
                sup.input_handler("RetryDown", "C").send((1,), timestamp=1)
            assert _wait_for(lambda: "RetryDown" in sup.gave_up, timeout=20)
            # BOTH budgeted attempts were consumed by the retry loop (the
            # old behavior stalled after the first failed attempt)
            assert sup._attempts.get("RetryDown") == 2
            assert sup.restarts.get("RetryDown", 0) == 0
            assert "RetryDown" not in sup._down
        finally:
            faults.uninstall()
        mgr.shutdown()

    def test_redeploy_resets_supervision_budget(self, tmp_path):
        """An operator redeploy under the same name starts a fresh
        supervision life — gave_up and the attempt streak are cleared —
        while the supervisor's OWN rebuild must not reset the streak."""
        app = SUP_APP.replace("max.attempts='3'", "max.attempts='1'").replace(
            "SupApp", "Redeploy"
        )
        mgr, sup, rt, _got = _sup_setup(tmp_path, app)
        faults.install(faults.parse_plan("junction_dispatch@C:times=-1"))
        try:
            for ts in range(2):
                try:
                    sup.input_handler("Redeploy", "C").send(
                        (ts,), timestamp=ts
                    )
                except InjectedFault:
                    pass
                time.sleep(0.2)
            assert _wait_for(lambda: "Redeploy" in sup.gave_up, timeout=15)
        finally:
            faults.uninstall()
        # redeploy: the fixed app is supervised afresh
        rt2 = mgr.create_siddhi_app_runtime(app)
        assert "Redeploy" not in sup.gave_up
        assert sup._attempts.get("Redeploy") is None
        rt2.start()
        faults.install(faults.parse_plan("junction_dispatch@C:times=1"))
        try:
            with pytest.raises(InjectedFault):
                sup.input_handler("Redeploy", "C").send((9,), timestamp=9)
            assert _wait_for(
                lambda: sup.restarts.get("Redeploy", 0) >= 1, timeout=15
            )
        finally:
            faults.uninstall()
        mgr.shutdown()


class TestSupervisionAnalysis:
    def test_clean_supervised_app_lints_clean(self):
        from siddhi_tpu.analysis import analyze
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

        app = SiddhiCompiler.parse("""
        @app:name('CleanSup')
        @app:persist(interval='30 sec', keep='5')
        @app:restart(policy='on-failure', max.attempts='3',
                     backoff='2 sec')
        @app:admission(policy='block', rate.limit='50000',
                       max.pending='8192')
        define stream S (v long);
        from S select v insert into Out;
        """)
        result = analyze(app)
        assert result.ok, result.format()

    def test_diagnostics_fire(self):
        from siddhi_tpu.analysis import analyze
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

        app = SiddhiCompiler.parse("""
        @app:persist(interval='1 millisec')
        @app:restart(policy='maybe')
        @app:admission(policy='block')
        define stream S (v long);
        from S select v insert into Out;
        """)
        codes = sorted(d.code for d in analyze(app).errors)
        assert codes == ["SA126", "SA127", "SA128"]
