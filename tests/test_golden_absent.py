"""Golden corpus: absent patterns, translated from the reference test data
(reference: siddhi-core/src/test/java/org/wso2/siddhi/core/query/pattern/
absent/{AbsentPatternTestCase,LogicalAbsentPatternTestCase}.java — data-level
translation with waiting times scaled from 1 sec to 150 ms so the suite stays
fast; the semantics under test are unchanged)."""

import time

import pytest

from siddhi_tpu import SiddhiManager

S123 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
define stream Stream3 (symbol string, price float, volume int);
"""


def run_timed(ql, steps, query_name="query1", settle=0.5, warm=()):
    """steps: list of ('send', stream, row) | ('sleep', seconds).

    `warm`: (stream, row) pairs sent BEFORE the timed phase to trigger each
    per-stream step's jit compile (first compile takes seconds, which would
    otherwise blow the wall-clock absent windows under test). Warm rows must
    be semantically inert (not matching any pattern condition)."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    got = []
    rt.add_callback(
        query_name, lambda ts, i, r: got.extend(tuple(e.data) for e in i or [])
    )
    rt.start()
    handlers = {}
    for stream, row in warm:
        h = handlers.setdefault(stream, rt.get_input_handler(stream))
        h.send(row)
    for step in steps:
        if step[0] == "sleep":
            time.sleep(step[1])
        else:
            _, stream, row = step
            h = handlers.setdefault(stream, rt.get_input_handler(stream))
            h.send(row)
    time.sleep(settle)
    rt.shutdown()
    mgr.shutdown()
    return got


class TestAbsentPatternGolden:
    def test_absent1_no_arrival_emits(self):
        ql = S123 + """
        @info(name = 'query1')
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 150 milliseconds
        select e1.symbol as symbol1
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 55.6, 100)),
            ("sleep", 0.4),
        ])
        assert got == [("WSO2",)], got

    def test_absent2_late_arrival_still_emits(self):
        ql = S123 + """
        @info(name = 'query1')
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 150 milliseconds
        select e1.symbol as symbol1
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 55.6, 100)),
            ("sleep", 0.4),
            ("send", "Stream2", ("IBM", 58.7, 100)),
        ])
        assert got == [("WSO2",)], got

    def test_absent3_arrival_inside_window_suppresses(self):
        ql = S123 + """
        @info(name = 'query1')
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 150 milliseconds
        select e1.symbol as symbol1
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 55.6, 100)),
            ("send", "Stream2", ("IBM", 58.7, 100)),
            ("sleep", 0.4),
        ], warm=[("Stream1", ("W", 5.0, 1)), ("Stream2", ("W", 5.0, 1))])
        assert got == [], got

    def test_absent4_nonmatching_arrival_does_not_suppress(self):
        ql = S123 + """
        @info(name = 'query1')
        from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 150 milliseconds
        select e1.symbol as symbol1
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 55.6, 100)),
            ("send", "Stream2", ("IBM", 50.7, 100)),  # not > 55.6
            ("sleep", 0.4),
        ], warm=[("Stream1", ("W", 5.0, 1)), ("Stream2", ("W", 5.0, 1))])
        assert got == [("WSO2",)], got


class TestLogicalAbsentPatternGolden:
    def test_absent1_and_without_waiting(self):
        # `not B and e3`: e3 arrival with no prior B completes immediately
        ql = S123 + """
        @info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
        ], settle=0.2)
        assert got == [("WSO2", "GOOGLE")], got

    def test_absent2_and_killed_by_arrival(self):
        ql = S123 + """
        @info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("send", "Stream2", ("IBM", 25.0, 100)),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
        ], settle=0.2)
        assert got == [], got

    def test_absent3_and_as_start_state(self):
        ql = S123 + """
        @info(name = 'query1')
        from not Stream1[price>10] and e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream2", ("IBM", 25.0, 100)),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
        ], settle=0.2)
        assert got == [("IBM", "GOOGLE")], got

    def test_absent4_and_start_killed(self):
        ql = S123 + """
        @info(name = 'query1')
        from not Stream1[price>10] and e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("send", "Stream2", ("IBM", 25.0, 100)),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
        ], settle=0.2)
        assert got == [], got

    def test_absent5_and_with_waiting_e3_after_deadline(self):
        ql = S123 + """
        @info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 150 milliseconds and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("sleep", 0.4),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
        ], settle=0.3)
        assert got == [("WSO2", "GOOGLE")], got

    def test_absent5b_and_with_waiting_e3_before_deadline(self):
        # e3 inside the window: completion waits for the deadline
        ql = S123 + """
        @info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 150 milliseconds and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
            ("sleep", 0.45),
        ], settle=0.3, warm=[
            ("Stream1", ("W", 5.0, 1)), ("Stream2", ("W", 5.0, 1)),
            ("Stream3", ("W", 5.0, 1)),
        ])
        assert got == [("WSO2", "GOOGLE")], got

    def test_absent5c_and_with_waiting_b_arrival_kills(self):
        ql = S123 + """
        @info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 150 milliseconds and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("send", "Stream2", ("IBM", 25.0, 100)),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
            ("sleep", 0.45),
        ], settle=0.3, warm=[
            ("Stream1", ("W", 5.0, 1)), ("Stream2", ("W", 5.0, 1)),
            ("Stream3", ("W", 5.0, 1)),
        ])
        assert got == [], got

    def test_every_logical_absent_rearm_restarts_window(self):
        # the re-armed generator's absence window must measure from the
        # re-arm; a B arriving at the START-of-pattern element does not kill
        # the cycle — it restarts the wait (reference:
        # LogicalAbsentPatternTestCase testQueryAbsent10 — a violating
        # arrival at the initial state re-waits and the pattern still
        # completes once a clean window elapses)
        ql = S123 + """
        @info(name = 'query1')
        from every (e1=Stream1[price>10] and not Stream2[price>20] for 150 milliseconds)
        select e1.symbol as symbol1
        insert into OutputStream ;
        """
        got = run_timed(ql, [
            ("send", "Stream1", ("A1", 15.0, 100)),
            ("sleep", 0.4),          # window B-free -> (A1,) at its deadline
            ("send", "Stream1", ("A2", 16.0, 100)),  # window already elapsed
            ("send", "Stream2", ("B", 25.0, 100)),   # re-arms the A3 cycle
            ("send", "Stream1", ("A3", 17.0, 100)),  # captured after re-arm
            ("sleep", 0.4),          # clean window -> A3 completes too
        ], settle=0.3, warm=[
            ("Stream1", ("W", 5.0, 1)), ("Stream2", ("W", 5.0, 1)),
        ])
        assert got == [("A1",), ("A2",), ("A3",)], got


class TestOrAbsentWithWaitingGolden:
    """`A or not B for t` forms — reference LogicalAbsentPatternTestCase
    testQueryAbsent11-16 (data translated, 1 sec scaled to 150 ms)."""

    QL = S123 + """
    @info(name = 'query1')
    from e1=Stream1[price>10] -> not Stream2[price>20] for 150 milliseconds or e3=Stream3[price>30]
    select e1.symbol as symbol1, e3.symbol as symbol3
    insert into OutputStream ;
    """
    WARM = (
        ("Stream1", ("X", 1.0, 1)),
        ("Stream2", ("X", 1.0, 1)),
        ("Stream3", ("X", 1.0, 1)),
    )

    def test_or11_present_side_completes(self):
        # testQueryAbsent11: e1 then e3 -> one event via the present side
        got = run_timed(self.QL, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("sleep", 0.05),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
        ], warm=self.WARM)
        assert got == [("WSO2", "GOOGLE")]

    def test_or12_no_duplicate_at_deadline(self):
        # testQueryAbsent12: completion via e3 then waiting past the deadline
        # must not emit a second (absent-side) event
        got = run_timed(self.QL, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("sleep", 0.05),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
            ("sleep", 0.3),
        ], warm=self.WARM)
        assert got == [("WSO2", "GOOGLE")]

    def test_or13_absent_side_fires_with_null_ref(self):
        # testQueryAbsent13: e1 only; the deadline fires with e3 = null
        got = run_timed(self.QL, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("sleep", 0.4),
        ], warm=self.WARM)
        assert got == [("WSO2", None)]

    @pytest.mark.slow
    def test_or14_nothing_before_deadline(self):
        # testQueryAbsent14: e1 only, checked before the waiting time elapses.
        # The check races the 150 ms wall-clock deadline with ~100 ms of
        # margin, so a loaded machine can legitimately cross it before the
        # assert runs; retry a bounded number of times — a deterministic
        # too-early emission still fails every attempt. Marked slow (excluded
        # from tier-1): the deterministic playback variant below covers the
        # semantics without the wall-clock race.
        for attempt in range(3):
            got = run_timed(self.QL, [
                ("send", "Stream1", ("WSO2", 15.0, 100)),
            ], settle=0.05, warm=self.WARM)
            if got == []:
                break
        assert got == []

    def test_or14_nothing_before_deadline_playback(self):
        # Deterministic @app:playback variant of test_or14: the event-time
        # clock advances to 100 ms — short of the 150 ms absent deadline — so
        # nothing may fire, with no wall-clock race at all (ROADMAP flake
        # item: playback-clock variants of the wall-clock absent goldens).
        from siddhi_tpu import SiddhiManager

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("@app:playback\n" + self.QL)
        got = []
        rt.add_callback(
            "query1", lambda ts, i, r: got.extend(tuple(e.data) for e in i or [])
        )
        rt.start()
        h1 = rt.get_input_handler("Stream1")
        h1.send(("WSO2", 15.0, 100), timestamp=0)
        # inert clock advance to just before the deadline (matches no
        # condition: price <= 10)
        h1.send(("ZZZ", 1.0, 0), timestamp=100)
        rt.shutdown()
        mgr.shutdown()
        assert got == []

    def test_or15_b_arrival_disables_absent_side(self):
        # testQueryAbsent15 shape: e1 then e2 inside the window; no e3 ->
        # nothing may fire, even after the deadline
        got = run_timed(self.QL, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("sleep", 0.05),
            ("send", "Stream2", ("IBM", 25.0, 100)),
            ("sleep", 0.3),
        ], warm=self.WARM)
        assert got == []

    def test_or16_b_arrival_then_present_still_completes(self):
        # e2 disables only the absent side: a later e3 still completes the or
        got = run_timed(self.QL, [
            ("send", "Stream1", ("WSO2", 15.0, 100)),
            ("sleep", 0.05),
            ("send", "Stream2", ("IBM", 25.0, 100)),
            ("sleep", 0.3),
            ("send", "Stream3", ("GOOGLE", 35.0, 100)),
        ], warm=self.WARM)
        assert got == [("WSO2", "GOOGLE")]


class TestPartitionedAbsentLateKey:
    def test_late_key_gets_a_fresh_absence_window(self):
        """A key first seen long after app start must wait the full absence
        window from ITS first event, not inherit the shared lane's elapsed
        clock (reference: AbsentStreamPreStateProcessor armed at
        partition-instance creation, PartitionRuntime.java:256-315)."""
        import time as _t

        from siddhi_tpu import SiddhiManager

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:partitionCapacity(size='8')
        define stream S (k string, price float);
        partition with (k of S)
        begin
            @info(name = 'q')
            from not S[price > 100] for 150 milliseconds -> e2=S[price < 50]
            select e2.k as k
            insert into Out;
        end;
        """)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(tuple(e.data) for e in i or []))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("WARM", 75.0))  # compile warm-up; matches neither side
        _t.sleep(0.5)           # well past the absence window from app start
        h.send(("X", 10.0))     # X's FIRST event: must NOT complete yet
        _t.sleep(0.05)
        n_after_first = len(got)
        _t.sleep(0.4)           # X's own absence window elapses
        h.send(("X", 10.0))     # now the advanced token completes
        _t.sleep(0.3)
        rt.shutdown()
        mgr.shutdown()
        assert n_after_first == 0, f"late key inherited an elapsed window: {got}"
        assert ("X",) in got
