"""Compile-time semantic analyzer (siddhi_tpu.analysis) tests.

Three layers:
* golden corpus — every bad app under tests/analysis_corpus/ declares its
  exact expected diagnostics (code + line:col) in trailing
  `-- expect[-warning]: SA### L:C` comments, asserted exactly;
* API — strict runtime creation, error aggregation, source locations;
* CLI — text/json formats, --werror, exit codes.

(The fourth layer lives in conftest.py: every app the full test suite
successfully builds a runtime for is re-analyzed and must be clean.)
"""

from __future__ import annotations

import glob
import json
import os
import re

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.analysis import CODES, SiddhiAnalysisError, analyze
from siddhi_tpu.analysis.__main__ import main as lint_main

CORPUS = sorted(
    glob.glob(os.path.join(os.path.dirname(__file__), "analysis_corpus", "*.siddhi"))
)

_EXPECT = re.compile(
    r"^--\s*(expect|expect-warning):\s*(SA\d{3})\s+(\d+|-):(\d+|-)\s*$"
)


def _parse_expectations(src: str):
    errors, warnings = [], []
    for line in src.splitlines():
        m = _EXPECT.match(line.strip())
        if not m:
            continue
        kind, code, ln, col = m.groups()
        loc = (
            code,
            None if ln == "-" else int(ln),
            None if col == "-" else int(col),
        )
        (errors if kind == "expect" else warnings).append(loc)
    return sorted(errors), sorted(warnings)


def test_corpus_is_populated():
    assert len(CORPUS) >= 20, "analysis corpus shrank below ~20 bad apps"


@pytest.mark.parametrize(
    "path", CORPUS, ids=[os.path.basename(p)[:-7] for p in CORPUS]
)
def test_corpus_exact_diagnostics(path):
    src = open(path).read()
    want_errors, want_warnings = _parse_expectations(src)
    assert want_errors or want_warnings, f"{path} declares no expectations"
    result = analyze(src)
    got_errors = sorted((d.code, d.line, d.col) for d in result.errors)
    got_warnings = sorted((d.code, d.line, d.col) for d in result.warnings)
    assert got_errors == want_errors, result.format(path)
    assert got_warnings == want_warnings, result.format(path)


def test_every_corpus_code_is_documented():
    for path in CORPUS:
        for code, _l, _c in sum(_parse_expectations(open(path).read()), []):
            assert code in CODES, f"{code} missing from diagnostics.CODES"


# ---------------------------------------------------------------------------
# API
# ---------------------------------------------------------------------------

BAD_APP = """
define stream S (a int, b string);
from Missing select a insert into Out;
from S[b > 3] select a insert into Out2;
"""


def test_analyze_accepts_source_and_ast():
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

    r1 = analyze(BAD_APP)
    r2 = analyze(SiddhiCompiler.parse(BAD_APP))
    assert [d.code for d in r1.errors] == [d.code for d in r2.errors]
    assert not r1.ok and len(r1.errors) == 2


def test_diagnostics_carry_locations():
    r = analyze(BAD_APP)
    codes = {(d.code, d.line, d.col) for d in r.errors}
    assert ("SA101", 3, 6) in codes  # `from Missing`
    assert ("SA201", 4, 10) in codes  # `b > 3`


def test_strict_runtime_creation_aggregates_all_errors():
    mgr = SiddhiManager()
    with pytest.raises(SiddhiAnalysisError) as exc_info:
        mgr.create_siddhi_app_runtime(BAD_APP, strict=True)
    err = exc_info.value
    assert len(err.diagnostics) == 2
    assert {d.code for d in err.diagnostics} == {"SA101", "SA201"}
    assert "SA101" in str(err) and "SA201" in str(err)
    mgr.shutdown()


def test_create_runtime_alias_and_strict_clean_app():
    mgr = SiddhiManager()
    rt = mgr.create_runtime(
        """
        define stream S (a int);
        @info(name='q') from S[a > 0] select a insert into Out;
        """,
        strict=True,
    )
    got = []
    rt.add_callback("q", lambda ts, i, r: got.extend(e.data for e in i or []))
    rt.start()
    rt.get_input_handler("S").send((5,))
    rt.shutdown()
    mgr.shutdown()
    assert got == [(5,)]


def test_strict_false_keeps_legacy_behavior():
    # without strict, a semantically-bad-but-buildable app still constructs
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(
        """
        define stream S (a int);
        define stream Dead (z int);
        from S select a insert into Out;
        """
    )
    assert rt is not None
    mgr.shutdown()


def test_programmatic_ast_without_locations():
    from siddhi_tpu.query_api import execution as ex
    from siddhi_tpu.query_api import expression as E
    from siddhi_tpu.query_api.definition import Attribute, StreamDefinition
    from siddhi_tpu.query_api.siddhi_app import SiddhiApp
    from siddhi_tpu.core.types import AttrType

    app = SiddhiApp()
    app.define_stream(StreamDefinition("S", [Attribute("a", AttrType.INT)]))
    q = ex.Query().from_(ex.SingleInputStream("Nope")).insert_into("Out")
    q.selector = ex.Selector(select_all=True)
    app.add_query(q)
    r = analyze(app)
    assert [d.code for d in r.errors] == ["SA101"]
    assert r.errors[0].line is None  # no source positions programmatically


def test_warning_severities_do_not_fail_ok():
    r = analyze(
        """
        define stream A (x int);
        from A[x > 0] select x insert into B;
        from B select x insert into A;
        """
    )
    assert r.ok
    assert {d.code for d in r.warnings} == {"SA403"}


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def _write(tmp_path, name, body):
    p = tmp_path / name
    p.write_text(body)
    return str(p)


def test_cli_clean_app(tmp_path, capsys):
    path = _write(
        tmp_path, "ok.siddhi",
        "define stream S (a int);\nfrom S select a insert into Out;\n",
    )
    assert lint_main([path]) == 0
    out = capsys.readouterr().out
    assert "0 error(s)" in out


def test_cli_bad_app_text(tmp_path, capsys):
    path = _write(
        tmp_path, "bad.siddhi",
        "define stream S (a int);\nfrom Missing select a insert into Out;\n",
    )
    assert lint_main([path]) == 1
    out = capsys.readouterr().out
    assert "SA101" in out and f"{path}:2:6" in out


def test_cli_json_format(tmp_path, capsys):
    path = _write(
        tmp_path, "bad.siddhi",
        "define stream S (a int);\nfrom S[a + 1] select a insert into Out;\n",
    )
    assert lint_main([path, "--format=json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["errors"] == 1
    (d,) = [x for x in payload["diagnostics"] if x["severity"] == "error"]
    assert d["code"] == "SA203" and d["line"] == 2


def test_cli_werror_promotes_warnings(tmp_path, capsys):
    body = (
        "define stream A (x int);\n"
        "from A[x > 0] select x insert into B;\n"
        "from B select x insert into A;\n"
    )
    path = _write(tmp_path, "warn.siddhi", body)
    assert lint_main([path]) == 0
    assert lint_main([path, "--werror"]) == 1
    capsys.readouterr()


def test_cli_parse_error_is_sa001(tmp_path, capsys):
    path = _write(tmp_path, "broken.siddhi", "define stream (;\n")
    assert lint_main([path]) == 2
    assert "SA001" in capsys.readouterr().out


def test_cli_codes_catalog(capsys):
    assert lint_main(["--codes"]) == 0
    out = capsys.readouterr().out
    for code in ("SA101", "SA206", "SA301", "SA403"):
        assert code in out


def test_partition_key_validation_sa115():
    """OBJECT-typed keys and un-keyed consumed streams are SA115 errors —
    the analyzer-side analog of PartitionRuntime's 'cannot partition by
    OBJECT' / 'partition has no key for stream' creation errors."""
    result = analyze("""
    define stream S (symbol string, payload object);
    define stream R (k string);
    partition with (payload of S) begin
    from S select symbol insert into Out;
    from R select k insert into Out2;
    end;
    """)
    codes = [d.code for d in result.errors]
    assert codes.count("SA115") == 2, result.format()
    msgs = " ".join(d.message for d in result.errors)
    assert "OBJECT" in msgs and "no key for stream 'R'" in msgs


def test_partition_inner_and_keyed_streams_are_clean():
    result = analyze("""
    define stream S (symbol string, price float);
    partition with (symbol of S) begin
    from S select symbol, price insert into #tmp;
    from #tmp select symbol insert into Out;
    end;
    """)
    assert not any(d.code == "SA115" for d in result.diagnostics), (
        result.format()
    )


def test_cli_explain_renders_static_plan(tmp_path, capsys):
    p = tmp_path / "app.siddhi"
    p.write_text(
        "define stream S (a int);\n"
        "@info(name='q') from S select a insert into Out;\n"
    )
    assert lint_main(["--explain", str(p)]) == 0
    out = capsys.readouterr().out
    assert "EXPLAIN" in out and "query q" in out and "Out" in out
    assert lint_main(["--explain", "--format=json", str(p)]) == 0
    plan = json.loads(capsys.readouterr().out)
    assert plan["analyzed"] and not plan["live"]
    assert any(n["id"] == "query:q" for n in plan["nodes"])
    assert any(e["from"] == "stream:S" for e in plan["edges"])


def test_explain_survives_invalid_partition_keys():
    """/explain renders partitioned plans best-effort: an app the analyzer
    rejects (SA115) must still produce a plan, not a crash."""
    from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
    from siddhi_tpu.observability.explain import explain_static

    app = SiddhiCompiler.parse("""
    define stream S (symbol string, payload object);
    define stream R (k string);
    partition with (payload of S) begin
    from R select k insert into Out2;
    end;
    """)
    text = explain_static(app)
    assert "partition0_query0" in text and "R" in text
