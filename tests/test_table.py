"""Table end-to-end tests.

Mirrors the reference's table test semantics
(reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/query/table/
InsertIntoTableTestCase, UpdateFromTableTestCase, DeleteFromTableTestCase,
UpdateOrInsertTableTestCase, InTableTestCase, JoinTableTestCase,
PrimaryKeyTableTestCase; store/StoreQueryTableTestCase).
"""

import pytest

from siddhi_tpu import SiddhiManager


def build(ql):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    rt.start()
    return mgr, rt


BASE = """
define stream StockStream (symbol string, price float, volume long);
define table StockTable (symbol string, price float, volume long);
"""


class TestInsertIntoTable:
    def test_insert_and_store_query(self):
        mgr, rt = build(BASE + """
        from StockStream insert into StockTable;
        """)
        h = rt.get_input_handler("StockStream")
        h.send(("WSO2", 55.5, 100), timestamp=1)
        h.send(("IBM", 75.5, 10), timestamp=2)
        rows = rt.query("from StockTable select *")
        assert [e.data for e in rows] == [("WSO2", 55.5, 100), ("IBM", 75.5, 10)]
        rt.shutdown()
        mgr.shutdown()

    def test_insert_with_filter_and_on_condition(self):
        mgr, rt = build(BASE + """
        from StockStream[volume > 50] insert into StockTable;
        """)
        h = rt.get_input_handler("StockStream")
        h.send(("WSO2", 55.5, 100), timestamp=1)
        h.send(("IBM", 75.5, 10), timestamp=2)
        h.send(("GOOG", 50.0, 200), timestamp=3)
        rows = rt.query("from StockTable on volume > 150 select symbol, volume")
        assert [e.data for e in rows] == [("GOOG", 200)]
        rt.shutdown()
        mgr.shutdown()

    def test_store_query_aggregation(self):
        mgr, rt = build(BASE + """
        from StockStream insert into StockTable;
        """)
        h = rt.get_input_handler("StockStream")
        for i, (s, p, v) in enumerate(
            [("WSO2", 50.0, 10), ("WSO2", 60.0, 20), ("IBM", 70.0, 5)]
        ):
            h.send((s, p, v), timestamp=i + 1)
        total = rt.query("from StockTable select sum(volume) as t")
        assert [e.data for e in total] == [(35,)]
        by_sym = rt.query(
            "from StockTable select symbol, sum(volume) as t group by symbol"
        )
        assert sorted(e.data for e in by_sym) == [("IBM", 5), ("WSO2", 30)]
        rt.shutdown()
        mgr.shutdown()


class TestTableCrud:
    def test_delete_on_condition(self):
        mgr, rt = build(BASE + """
        define stream DeleteStream (symbol string);
        from StockStream insert into StockTable;
        from DeleteStream delete StockTable on StockTable.symbol == symbol;
        """)
        rt.get_input_handler("StockStream").send(("WSO2", 55.5, 100), timestamp=1)
        rt.get_input_handler("StockStream").send(("IBM", 75.5, 10), timestamp=2)
        rt.get_input_handler("DeleteStream").send(("WSO2",), timestamp=3)
        rows = rt.query("from StockTable select symbol")
        assert [e.data for e in rows] == [("IBM",)]
        rt.shutdown()
        mgr.shutdown()

    def test_update_set(self):
        mgr, rt = build(BASE + """
        define stream UpdateStream (symbol string, newPrice float);
        from StockStream insert into StockTable;
        from UpdateStream
        update StockTable
        set StockTable.price = newPrice
        on StockTable.symbol == symbol;
        """)
        rt.get_input_handler("StockStream").send(("WSO2", 55.5, 100), timestamp=1)
        rt.get_input_handler("StockStream").send(("IBM", 75.5, 10), timestamp=2)
        rt.get_input_handler("UpdateStream").send(("WSO2", 99.0), timestamp=3)
        rows = rt.query("from StockTable select symbol, price")
        assert sorted(e.data for e in rows) == [("IBM", 75.5), ("WSO2", 99.0)]
        rt.shutdown()
        mgr.shutdown()

    def test_update_default_overwrite(self):
        # no `set` clause: same-named output attrs overwrite table columns
        mgr, rt = build(BASE + """
        define stream UpdateStream (symbol string, price float, volume long);
        from StockStream insert into StockTable;
        from UpdateStream
        select symbol, price, volume
        update StockTable
        on StockTable.symbol == symbol;
        """)
        rt.get_input_handler("StockStream").send(("WSO2", 55.5, 100), timestamp=1)
        rt.get_input_handler("UpdateStream").send(("WSO2", 77.0, 200), timestamp=2)
        rows = rt.query("from StockTable select *")
        assert [e.data for e in rows] == [("WSO2", 77.0, 200)]
        rt.shutdown()
        mgr.shutdown()

    def test_update_sequential_within_batch(self):
        # two updating events in one batch apply sequentially
        mgr, rt = build("""
        @app:batch(size='8')
        define stream S (symbol string, add long);
        define table T (symbol string, total long);
        define stream Init (symbol string, total long);
        from Init insert into T;
        from S
        select symbol, add
        update T
        set T.total = T.total + add
        on T.symbol == symbol;
        """)
        rt.get_input_handler("Init").send(("WSO2", 0), timestamp=1)
        h = rt.get_input_handler("S")
        h.send_many([("WSO2", 5), ("WSO2", 7)], timestamps=[2, 2])
        rows = rt.query("from T select total")
        assert [e.data for e in rows] == [(12,)]
        rt.shutdown()
        mgr.shutdown()

    def test_update_or_insert(self):
        mgr, rt = build(BASE + """
        define stream UpsertStream (symbol string, price float, volume long);
        from UpsertStream
        select symbol, price, volume
        update or insert into StockTable
        on StockTable.symbol == symbol;
        """)
        h = rt.get_input_handler("UpsertStream")
        h.send(("WSO2", 55.5, 100), timestamp=1)
        h.send(("IBM", 75.5, 10), timestamp=2)
        h.send(("WSO2", 57.5, 150), timestamp=3)
        rows = rt.query("from StockTable select *")
        assert sorted(e.data for e in rows) == [
            ("IBM", 75.5, 10), ("WSO2", 57.5, 150)
        ]
        rt.shutdown()
        mgr.shutdown()


class TestInTable:
    def test_filter_in_table(self):
        mgr, rt = build(BASE + """
        define stream CheckStream (symbol string, price float);
        @info(name='q')
        from CheckStream[(StockTable.symbol == symbol) in StockTable]
        select symbol, price
        insert into Out;
        from StockStream insert into StockTable;
        """)
        got = []
        rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in ins or []))
        rt.get_input_handler("StockStream").send(("WSO2", 55.5, 100), timestamp=1)
        rt.get_input_handler("CheckStream").send(("WSO2", 1.0), timestamp=2)
        rt.get_input_handler("CheckStream").send(("IBM", 2.0), timestamp=3)
        assert got == [("WSO2", 1.0)]
        rt.shutdown()
        mgr.shutdown()


class TestJoinTable:
    def test_stream_join_table(self):
        mgr, rt = build(BASE + """
        define stream CheckStream (company string);
        @info(name='q')
        from CheckStream join StockTable
        on CheckStream.company == StockTable.symbol
        select company, StockTable.price as price, StockTable.volume as volume
        insert into Out;
        from StockStream insert into StockTable;
        """)
        got = []
        rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in ins or []))
        rt.get_input_handler("StockStream").send(("WSO2", 55.5, 100), timestamp=1)
        rt.get_input_handler("StockStream").send(("IBM", 75.5, 10), timestamp=2)
        rt.get_input_handler("CheckStream").send(("WSO2",), timestamp=3)
        assert got == [("WSO2", 55.5, 100)]
        rt.shutdown()
        mgr.shutdown()

    def test_table_join_left_outer(self):
        mgr, rt = build(BASE + """
        define stream CheckStream (company string);
        @info(name='q')
        from CheckStream left outer join StockTable
        on CheckStream.company == StockTable.symbol
        select company, StockTable.volume as volume
        insert into Out;
        from StockStream insert into StockTable;
        """)
        got = []
        rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in ins or []))
        rt.get_input_handler("StockStream").send(("WSO2", 55.5, 100), timestamp=1)
        rt.get_input_handler("CheckStream").send(("AMZN",), timestamp=2)
        rt.get_input_handler("CheckStream").send(("WSO2",), timestamp=3)
        assert got == [("AMZN", None), ("WSO2", 100)]
        rt.shutdown()
        mgr.shutdown()


class TestPrimaryKey:
    def test_primary_key_insert_drops_duplicates(self):
        # insert keeps the FIRST row per key (reference:
        # IndexEventHolder.add putIfAbsent drops + logs duplicates;
        # `update or insert into` is the overwriting form)
        mgr, rt = build("""
        define stream StockStream (symbol string, price float, volume long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        from StockStream insert into StockTable;
        """)
        h = rt.get_input_handler("StockStream")
        h.send(("WSO2", 55.5, 100), timestamp=1)
        h.send(("IBM", 75.5, 10), timestamp=2)
        h.send(("WSO2", 57.5, 200), timestamp=3)
        rows = rt.query("from StockTable select *")
        assert sorted(e.data for e in rows) == [
            ("IBM", 75.5, 10), ("WSO2", 55.5, 100)
        ]
        rt.shutdown()
        mgr.shutdown()

    def test_primary_key_same_batch_dedupe_first_wins(self):
        mgr, rt = build("""
        @app:batch(size='8')
        define stream StockStream (symbol string, price float, volume long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        from StockStream insert into StockTable;
        """)
        h = rt.get_input_handler("StockStream")
        h.send_many(
            [("WSO2", 55.5, 100), ("WSO2", 57.5, 200), ("IBM", 75.5, 10)],
            timestamps=[1, 1, 1],
        )
        rows = rt.query("from StockTable select *")
        assert sorted(e.data for e in rows) == [
            ("IBM", 75.5, 10), ("WSO2", 55.5, 100)
        ]
        rt.shutdown()
        mgr.shutdown()


class TestStoreQueryCrud:
    def test_store_delete(self):
        mgr, rt = build(BASE + """
        from StockStream insert into StockTable;
        """)
        h = rt.get_input_handler("StockStream")
        h.send(("WSO2", 55.5, 100), timestamp=1)
        h.send(("IBM", 75.5, 10), timestamp=2)
        rt.query("from StockTable select symbol delete StockTable on StockTable.symbol == symbol")
        rows = rt.query("from StockTable select *")
        assert rows == []
        rt.shutdown()
        mgr.shutdown()


class TestRecordStore:
    def test_store_backed_table_survives_restart(self):
        from siddhi_tpu.core.record_table import InMemoryRecordStore

        InMemoryRecordStore.clear_all()
        app = """
        define stream S (symbol string, volume long);
        @store(type='memory', store.id='t1')
        define table T (symbol string, volume long);
        from S insert into T;
        """
        mgr, rt = build(app)
        rt.get_input_handler("S").send(("WSO2", 100), timestamp=1)
        rt.get_input_handler("S").send(("IBM", 10), timestamp=2)
        rt.shutdown()
        mgr.shutdown()

        # a NEW runtime loads the durable contents back
        mgr2, rt2 = build(app)
        rows = rt2.query("from T select symbol, volume")
        assert sorted(e.data for e in rows) == [("IBM", 10), ("WSO2", 100)]
        rt2.shutdown()
        mgr2.shutdown()
        InMemoryRecordStore.clear_all()


class TestStoreQueryInsert:
    def test_constant_insert(self):
        mgr, rt = build(BASE)
        rt.query("select 'WSO2' as symbol, 55.5f as price, 100L as volume "
                 "insert into StockTable")
        rows = rt.query("from StockTable select *")
        assert [e.data for e in rows] == [("WSO2", 55.5, 100)]
        rt.shutdown()
        mgr.shutdown()

    def test_copy_between_tables(self):
        mgr, rt = build(BASE + """
        define table Backup (symbol string, price float, volume long);
        from StockStream insert into StockTable;
        """)
        rt.get_input_handler("StockStream").send(("IBM", 75.5, 10), timestamp=1)
        rt.query("from StockTable select symbol, price, volume insert into Backup")
        rows = rt.query("from Backup select *")
        assert [e.data for e in rows] == [("IBM", 75.5, 10)]
        rt.shutdown()
        mgr.shutdown()


class TestLazyQueryableStore:
    def test_lazy_store_pushdown(self):
        # reference: AbstractQueryableRecordTable — a store too big to
        # materialize serves finds through condition pushdown
        from siddhi_tpu.core.extension import extension
        from siddhi_tpu.core.record_table import RecordStore
        from siddhi_tpu.query_api.expression import Compare, CompareOp, Constant, Variable

        calls = []

        @extension("store", "bigmock")
        class BigMockStore(RecordStore):
            ROWS = [(f"S{i}", i) for i in range(10_000)]

            def load(self):
                return None  # lazy

            def query(self, on, interner):
                calls.append(on)
                if on is None:
                    return list(self.ROWS)
                # understand `volume > <const>` pushdown
                if (
                    isinstance(on, Compare)
                    and on.op is CompareOp.GT
                    and isinstance(on.left, Variable)
                    and isinstance(on.right, Constant)
                ):
                    return [r for r in self.ROWS if r[1] > on.right.value]
                return None

        from siddhi_tpu import SiddhiManager

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (symbol string, volume long);
        @store(type='bigmock')
        define table T (symbol string, volume long);
        """)
        rt.start()
        rows = rt.query("from T on volume > 9997L select symbol, volume")
        rt.shutdown()
        mgr.shutdown()
        assert len(calls) == 1 and calls[0] is not None
        assert sorted(e.data for e in rows) == [("S9998", 9998), ("S9999", 9999)]
