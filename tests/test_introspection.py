"""Engine self-observation tests: state introspection (`describe_state` /
`snapshot_status` / `/status`), the `@app:selfmon` CEP-native self-monitoring
stream, the per-junction flight recorder, and the file-backed error store.

Reference analogs: the runtime object graph SiddhiAppRuntime exposes for
inspection plus this engine's additions (siddhi_tpu/observability/).
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.error_store import FileErrorStore, InMemoryErrorStore
from siddhi_tpu.core.event import StreamSchema
from siddhi_tpu.core.types import AttrType, InternTable
from siddhi_tpu.observability.flight import FlightRecorder


# ---------------------------------------------------------------------------
# flight recorder unit semantics
# ---------------------------------------------------------------------------


def _mk_recorder(size):
    schema = StreamSchema("S", [("k", AttrType.LONG), ("s", AttrType.STRING)])
    return FlightRecorder(schema, InternTable(), size), schema


class TestFlightRecorderUnit:
    def test_ring_keeps_newest_oldest_first(self):
        fr, _ = _mk_recorder(4)
        x = fr.interner.intern("x")
        for i in range(10):
            fr.record_columns(
                np.asarray([i]), {"k": np.asarray([i]), "s": np.asarray([x])},
                1,
            )
        ev = fr.events()
        assert ev == [(6, (6, "x")), (7, (7, "x")), (8, (8, "x")),
                      (9, (9, "x"))]
        assert fr.describe_state()["recorded"] == 4
        assert fr.describe_state()["total"] == 10
        assert fr.describe_state()["oldest_ts"] == 6
        assert fr.describe_state()["newest_ts"] == 9

    def test_oversized_batch_keeps_only_tail(self):
        fr, _ = _mk_recorder(3)
        x = fr.interner.intern("x")
        n = 11
        fr.record_columns(
            np.arange(n), {"k": np.arange(n), "s": np.full(n, x)}, n
        )
        assert [ts for ts, _ in fr.events()] == [8, 9, 10]
        assert fr.describe_state()["total"] == n

    def test_wrap_across_batches(self):
        fr, _ = _mk_recorder(5)
        x = fr.interner.intern("x")
        fr.record_columns(
            np.arange(3), {"k": np.arange(3), "s": np.full(3, x)}, 3
        )
        fr.record_columns(
            np.arange(3, 7), {"k": np.arange(3, 7), "s": np.full(4, x)}, 4
        )
        assert [ts for ts, _ in fr.events()] == [2, 3, 4, 5, 6]
        assert [ts for ts, _ in fr.events(limit=2)] == [5, 6]

    def test_string_attrs_decode_through_interner(self):
        fr, _ = _mk_recorder(4)
        interner = fr.interner
        a, b = interner.intern("A"), interner.intern("B")
        fr.record_columns(
            np.asarray([1, 2]),
            {"k": np.asarray([10, 20]), "s": np.asarray([a, b])},
            2,
        )
        assert fr.events() == [(1, (10, "A")), (2, (20, "B"))]


# ---------------------------------------------------------------------------
# flight recorder in the engine
# ---------------------------------------------------------------------------


class TestFlightRecorderEngine:
    def test_per_batch_sends_recorded(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @flightRecorder(size='4')
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(7):
            h.send((i,), timestamp=i)
        ev = rt.flight_record("S")
        assert [data for _ts, data in ev] == [(3,), (4,), (5,), (6,)]
        # un-recorded stream raises a descriptive error
        with pytest.raises(SiddhiAppCreationError):
            rt.flight_record("Out")
        mgr.shutdown()

    def test_fused_columnar_path_recorded(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:batch(size='32')
        @flightRecorder(size='8')
        define stream S (k long, v long);
        @info(name='q') from S select k, sum(v) as t group by k insert into Out;
        """)
        rt.start()
        n = 32 * 8
        rt.get_input_handler("S").send_columns(
            np.arange(n, dtype=np.int64),
            {
                "k": np.arange(n, dtype=np.int64) % 4,
                "v": np.ones(n, dtype=np.int64),
            },
        )
        j = rt.junctions["S"]
        assert j.fused_ingest is not None and j.fused_ingest.eligible()
        ev = rt.flight_record("S")
        assert len(ev) == 8
        assert [ts for ts, _ in ev] == list(range(n - 8, n))
        mgr.shutdown()

    def test_env_override_arms_every_junction(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_FLIGHT", "6")
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send((i,), timestamp=i)
        recs = rt.flight_records()
        # the internal insert-into junction records the query's outputs too
        assert set(recs) >= {"S", "Out"}
        assert [d for _t, d in recs["S"]] == [(0,), (1,), (2,)]
        assert [d for _t, d in recs["Out"]] == [(0,), (1,), (2,)]
        mgr.shutdown()

    def test_dispatch_failure_dumps_flight_into_error_store(self):
        # acceptance: on an induced dispatch failure with the recorder
        # enabled, the error-store entry carries the junction's last-N events
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @OnError(action='STORE')
        @flightRecorder(size='4')
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)
        fail = [False]

        def maybe_boom(batch, now):
            if fail[0]:
                raise ValueError("poison")

        rt.junctions["S"].subscribe(maybe_boom, name="custom.boom")
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(5):
            h.send((i,), timestamp=i)
        fail[0] = True
        h.send((99,), timestamp=5)
        entries = mgr.error_store.load(app_name="SiddhiApp")
        assert len(entries) == 1
        e = entries[0]
        assert e.events == [(5, (99,))]
        # last-N ring: the 3 events before the failure + the failing one
        assert e.flight == [(2, (2,)), (3, (3,)), (4, (4,)), (5, (99,))]
        mgr.shutdown()

    def test_bad_annotation_rejected(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            @flightRecorder(size='0')
            define stream S (v long);
            from S select v insert into Out;
            """)


# ---------------------------------------------------------------------------
# state introspection: describe_state / snapshot_status
# ---------------------------------------------------------------------------


MULTI_APP = """
@app:statistics(reporter='none')
define stream S (symbol string, price float, volume long);
define stream T (symbol string, price float, volume long);
define table Prices (symbol string, price float);
define window W (symbol string, price float) length(8) output all events;
@info(name='win') from S#window.length(4)
select symbol, avg(price) as ap insert into Out;
@info(name='pat') from every a1=S[price > 90] -> a2=S[price < 10]
select a1.symbol as s1, a2.symbol as s2 insert into Matches;
@info(name='tab') from S select symbol, price insert into Prices;
@info(name='feedw') from S select symbol, price insert into W;
"""


class TestSnapshotStatus:
    def test_live_multi_component_snapshot(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(MULTI_APP)
        rt.start()
        h = rt.get_input_handler("S")
        rows = [("A", 95.0, 10), ("B", 50.0, 20), ("C", 40.0, 30)]
        for i, r in enumerate(rows):
            h.send(r, timestamp=i)
        st = rt.snapshot_status()
        assert st["app"] == "SiddhiApp" and st["running"]

        # junctions: queue depth + subscriber wiring
        s_state = st["streams"]["S"]
        assert s_state["queue_depth"] == 0
        assert set(s_state["subscribers"]) == {
            "query.win", "query.pat", "query.tab", "query.feedw"
        }
        assert "pipeline" in s_state  # fused ingest depth/occupancy

        # window runtime inside a query: type/fill/capacity/ts bounds
        w = st["queries"]["win"]["window"]
        assert w["type"] == "SlidingWindow"
        assert w["capacity"] == 4 and w["fill"] == 3
        assert w["oldest_ts"] == 0 and w["newest_ts"] == 2

        # pattern NFA: per-state active instance counts
        pat = st["queries"]["pat"]
        states = pat["states"]
        assert [s["refs"] for s in states] == [["a1"], ["a2"]]
        # one virgin token waits at a1; the price>90 event armed one at a2
        assert states[0]["active"] == 1
        assert states[1]["active"] == 1
        assert pat["active_instances"] == 2
        assert pat["token_capacity"] == 128

        # named window fed by a query
        nw = st["windows"]["W"]
        assert nw["capacity"] == 8 and nw["fill"] == 3

        # table row count + capacity
        tab = st["tables"]["Prices"]
        assert tab["rows"] == 3 and tab["capacity"] > 0

        # unfed stream still present, empty
        assert st["streams"]["T"]["queue_depth"] == 0
        mgr.shutdown()

    def test_aggregation_buckets_and_watermark(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (symbol string, price float, ts long);
        define aggregation AggP
        from S select symbol, sum(price) as total
        group by symbol aggregate by ts every sec, min;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        base = 1_700_000_000_000
        h.send(("A", 10.0, base), timestamp=base)
        h.send(("B", 20.0, base + 100), timestamp=base + 100)
        h.send(("A", 30.0, base + 61_000), timestamp=base + 61_000)
        st = rt.snapshot_status()
        d = st["aggregations"]["AggP"]["durations"]
        assert set(d) == {"SECONDS", "MINUTES"}
        # the open second-bucket moved to base+61s; the first second's two
        # groups closed into the SECONDS duration table
        assert d["SECONDS"]["watermark_ms"] == base + 61_000
        assert d["SECONDS"]["open_groups"] == 1
        assert d["SECONDS"]["closed_rows"] == 2
        # the minute boundary also passed: both groups closed into the
        # MINUTES table and its open bucket advanced to base's next minute
        assert d["MINUTES"]["closed_rows"] == 2
        assert d["MINUTES"]["watermark_ms"] == 1_700_000_040_000
        mgr.shutdown()

    def test_pattern_absent_deadline_exposed(self):
        # within-clause/absent deadlines: an armed `not ... for` atom must
        # surface its pending wall-clock deadline in the NFA snapshot
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:playback
        define stream S1 (price float);
        define stream S2 (price float);
        @info(name='q')
        from e1=S1[price>20] -> not S2[price>e1.price] for 150 milliseconds
        select e1.price as p insert into Out;
        """)
        rt.start()
        rt.get_input_handler("S1").send((30.0,), timestamp=1_000)
        d = rt.queries["q"].describe_state()
        assert d["states"][1]["absent"]
        assert d["states"][1]["active"] == 1  # armed, waiting on the clock
        assert d["next_deadline_ms"] == 1_150
        mgr.shutdown()

    def test_async_junction_health(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @async(buffer.size='64', workers='1')
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)
        rt.start()
        d = rt.junctions["S"].describe_state()
        assert d["async"]["workers"] == 1
        assert d["async"]["workers_alive"] == 1
        mgr.shutdown()

    def test_status_endpoints(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(MULTI_APP)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send(("A", 50.0, 1), timestamp=i)
        port = mgr.serve_metrics(0)
        base = f"http://127.0.0.1:{port}"
        sj = json.loads(
            urllib.request.urlopen(f"{base}/status.json", timeout=5).read()
        )
        app = sj["apps"]["SiddhiApp"]
        assert app["queries"]["win"]["window"]["fill"] == 3
        assert app["streams"]["S"]["queue_depth"] == 0
        assert "depth" in app["streams"]["S"]["pipeline"]
        text = (
            urllib.request.urlopen(f"{base}/status", timeout=5)
            .read().decode()
        )
        assert "app SiddhiApp [running]" in text
        assert "queue_depth" in text and "fill=3" in text
        mgr.shutdown()

    def test_device_fields_degrade_on_relay_backends(self, monkeypatch):
        # on transfer-degraded relays one d2h read permanently poisons
        # dispatch: device-derived fields must report None there, and the
        # SIDDHI_TPU_STATUS_DEVICE=1 opt-in restores them
        import siddhi_tpu.utils.backend as backend

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (v long);
        define table T (v long);
        @info(name='q') from S#window.length(4) select v insert into Out;
        @info(name='t') from S select v insert into T;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send((i,), timestamp=i)
        monkeypatch.setattr(backend, "transfer_degrades_dispatch", lambda: True)
        st = rt.snapshot_status()
        assert st["queries"]["q"]["window"]["fill"] is None
        assert st["tables"]["T"]["rows"] is None
        monkeypatch.setenv("SIDDHI_TPU_STATUS_DEVICE", "1")
        st = rt.snapshot_status()
        assert st["queries"]["q"]["window"]["fill"] == 3
        assert st["tables"]["T"]["rows"] == 3
        mgr.shutdown()

    def test_manager_snapshot_includes_error_store(self):
        mgr = SiddhiManager()
        mgr.set_error_store(InMemoryErrorStore(capacity=10))
        rt = mgr.create_siddhi_app_runtime("""
        @OnError(action='STORE')
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)

        def boom(batch, now):
            raise ValueError("poison")

        rt.junctions["S"].subscribe(boom, name="custom.boom")
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send((i,))
        st = mgr.snapshot_status()
        es = st["error_store"]
        assert es["depth"] == 3
        assert es["by_app"] == {"SiddhiApp": 3}
        assert st["apps"]["SiddhiApp"]["streams"]["S"]["on_error"] == "STORE"
        mgr.shutdown()


# ---------------------------------------------------------------------------
# @app:selfmon — CEP over the engine's own health
# ---------------------------------------------------------------------------


class TestSelfMonitor:
    def test_alert_query_fires_on_latency_condition(self):
        # acceptance: a SiddhiQL query over the selfmon stream raises an
        # alert event when a component's p99 crosses a threshold, end to end
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:selfmon(interval='100 millisec')
        @app:statistics(reporter='none')
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        @info(name='alerts')
        from SelfMonitorStream[metric == 'latency_ms' and p99 > 0.0]
        select component, p99 insert into AlertStream;
        """)
        alerts = []
        rt.add_callback(
            "alerts", lambda ts, ins, rem: alerts.extend(ins or [])
        )
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(5):
            h.send((i,))
        t0 = time.time()
        while not alerts and time.time() - t0 < 10:
            time.sleep(0.02)
        assert alerts, "selfmon latency alert must fire"
        comps = {e.data[0] for e in alerts}
        assert "query.q" in comps
        assert all(e.data[1] > 0.0 for e in alerts)
        mgr.shutdown()

    def test_error_and_depth_rows_without_statistics(self):
        # selfmon rides introspection even with @app:statistics absent
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:selfmon(interval='100 millisec')
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        @info(name='mon')
        from SelfMonitorStream[metric == 'queue_depth']
        select component, value insert into DepthStream;
        """)
        rows = []
        rt.add_callback("mon", lambda ts, ins, rem: rows.extend(ins or []))
        rt.start()
        t0 = time.time()
        while not rows and time.time() - t0 < 10:
            time.sleep(0.02)
        assert rows
        assert {e.data[0] for e in rows} >= {"stream.S", "stream.Out"}
        assert rt.snapshot_status()["selfmon"]["ticks"] >= 1
        mgr.shutdown()

    def test_bad_interval_rejected(self):
        mgr = SiddhiManager()
        for ann in ("interval='soon'", "interval='1 millisec'", "bogus='1'"):
            with pytest.raises(SiddhiAppCreationError):
                mgr.create_siddhi_app_runtime(f"""
                @app:selfmon({ann})
                define stream S (v long);
                from S select v insert into Out;
                """)

    def test_reserved_stream_name_rejected(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            @app:selfmon(interval='5 sec')
            define stream SelfMonitorStream (component string, metric string,
                                             value double, p99 double);
            from SelfMonitorStream select component insert into Out;
            """)

    def test_nothing_wired_without_annotations(self):
        # acceptance: describe_state/selfmon/flight cost is zero when
        # disabled — nothing scheduled, nothing attached to the junctions
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)
        rt.start()
        assert rt._selfmon is None
        assert "SelfMonitorStream" not in rt.stream_schemas
        assert all(j.flight is None for j in rt.junctions.values())
        assert "selfmon" not in rt.snapshot_status()
        # the scheduler has no recurring selfmon target armed
        assert not rt._scheduler._heap
        mgr.shutdown()


# ---------------------------------------------------------------------------
# file-backed error store (ROADMAP satellite)
# ---------------------------------------------------------------------------


def _entry(app="App1", v=1):
    from siddhi_tpu.core.error_store import ORIGIN_STREAM, make_entry

    return make_entry(
        app, ORIGIN_STREAM, "S", ValueError("boom"), events=[(7, (v, "x"))]
    )


class TestFileErrorStore:
    def test_store_load_purge_roundtrip(self, tmp_path):
        store = FileErrorStore(str(tmp_path))
        for v in range(3):
            store.store(_entry(v=v))
        store.store(_entry(app="App2", v=9))
        assert store.size() == 4
        got = store.load(app_name="App1")
        assert [e.events for e in got] == [[(7, (v, "x"))] for v in range(3)]
        assert got[0].error == "ValueError: boom"
        assert store.load(origin="sink") == []
        assert store.purge([got[0].id]) == 1
        assert store.size() == 3
        assert store.purge() == 3
        assert store.size() == 0

    def test_entries_survive_restart_and_ids_stay_unique(self, tmp_path):
        s1 = FileErrorStore(str(tmp_path))
        s1.store(_entry(v=1))
        s1.store(_entry(v=2))
        s2 = FileErrorStore(str(tmp_path))  # "restart"
        assert [e.events[0][1][0] for e in s2.load()] == [1, 2]
        s2.store(_entry(v=3))
        ids = [e.id for e in s2.load()]
        assert len(set(ids)) == 3 and max(ids) == 3
        assert s2.describe_state()["by_app"] == {"App1": 3}

    def test_capacity_evicts_oldest(self, tmp_path):
        store = FileErrorStore(str(tmp_path), capacity=2)
        for v in range(4):
            store.store(_entry(v=v))
        kept = [e.events[0][1][0] for e in store.load()]
        assert kept == [2, 3]
        assert store.dropped == 2

    def test_flight_dump_survives_restart(self, tmp_path):
        e = _entry(v=5)
        e.flight = [(1, (10, "a")), (2, (20, "b"))]
        s1 = FileErrorStore(str(tmp_path))
        s1.store(e)
        got = FileErrorStore(str(tmp_path)).load()[0]
        assert got.flight == [(1, (10, "a")), (2, (20, "b"))]

    def test_store_survives_exception_with_custom_init(self, tmp_path):
        # dataclasses.asdict would deep-copy the live exception in `cause`
        # and blow up on non-default __init__ signatures — from inside the
        # very store() call capturing the failure
        from siddhi_tpu.core.error_store import ORIGIN_STREAM, make_entry

        class CodedError(Exception):
            def __init__(self, code, msg):
                super().__init__(f"{code}: {msg}")

        store = FileErrorStore(str(tmp_path))
        store.store(make_entry(
            "App1", ORIGIN_STREAM, "S", CodedError(7, "bad"),
            events=[(1, (1, "x"))],
        ))
        got = store.load()[0]
        assert got.error == "CodedError: 7: bad"
        assert got.events == [(1, (1, "x"))]

    def test_size_is_constant_time_counter(self, tmp_path):
        # selfmon polls size() every tick: it must come from the running
        # count, not a directory re-parse
        store = FileErrorStore(str(tmp_path))
        store.store(_entry(v=1))
        store.store(_entry(v=2))
        real_iter = store._iter_entries
        store._iter_entries = lambda: (_ for _ in ()).throw(
            AssertionError("size() must not re-read the directory")
        )
        assert store.size() == 2
        store._iter_entries = real_iter

    def test_replay_from_file_store(self, tmp_path):
        mgr = SiddhiManager()
        mgr.set_error_store(FileErrorStore(str(tmp_path)))
        rt = mgr.create_siddhi_app_runtime("""
        @OnError(action='STORE')
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)
        fail = [True]

        def boom(batch, now):
            if fail[0]:
                raise ValueError("poison")

        rt.junctions["S"].subscribe(boom, name="custom.boom")
        rt.start()
        got = []
        rt.add_callback("q", lambda ts, ins, rem: got.extend(ins or []))
        rt.get_input_handler("S").send((42,))
        assert mgr.error_store.size() == 1
        fail[0] = False
        got.clear()
        assert mgr.replay_errors() == 1
        assert [e.data for e in got] == [(42,)]
        assert mgr.error_store.size() == 0  # purged after replay
        mgr.shutdown()
