"""Event-time robustness (core/watermark.py): `@app:watermark` bounded
reorder, watermark tracking/propagation, late-event policies, observability.

Layers:
* annotation/env config — shared rule set (SA134 + runtime resolver);
* ReorderTracker unit behavior (ordering, lateness split, flush);
* end-to-end policies — drop (metered), stream (`!S` divert), apply
  (closed-bucket correction in aggregation duration tables);
* zero-cost contract — no annotation means no wrapper on the send path;
* observability — snapshot_status section, Prometheus families, explain();
* fault-injection shuffle (`ingest_disorder` jitter rules) determinism.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.watermark import (
    LatenessHistogram,
    ReorderTracker,
    WatermarkConfig,
    iter_watermark_annotation_problems,
    parse_watermark_spec,
    resolve_watermark_annotation,
)
from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.testing import faults

BASE = 1_700_000_000_000


def _ann(*pairs):
    return Annotation("app:watermark", list(pairs))


# ---------------------------------------------------------------------------
# configuration: annotation + env, one rule set for analyzer and runtime
# ---------------------------------------------------------------------------


class TestConfig:
    def test_valid_annotation_resolves(self):
        cfg = resolve_watermark_annotation(_ann(
            ("bound", "5 sec"), ("idle.timeout", "30 sec"),
            ("late.policy", "apply"), ("allowed.lateness", "1 min"),
        ), env="")
        assert cfg == WatermarkConfig(5000, 30000, "apply", 60000)

    def test_bare_element_is_bound(self):
        cfg = resolve_watermark_annotation(_ann((None, "2 sec")), env="")
        assert cfg.bound_ms == 2000

    def test_apply_defaults_allowed_lateness(self):
        cfg = resolve_watermark_annotation(
            _ann(("bound", "1 sec"), ("late.policy", "apply")), env="",
        )
        assert cfg.allowed_lateness_ms == 60_000

    def test_problems_enumerated(self):
        bad = _ann(
            ("bound", "0 sec"), ("idle.timeout", "soon"),
            ("late.policy", "retry"), ("allowed.lateness", "1 min"),
            ("jitter", "5 sec"),
        )
        msgs = list(iter_watermark_annotation_problems(bad))
        assert len(msgs) == 5
        assert any("bound" in m for m in msgs)
        assert any("late.policy" in m for m in msgs)
        assert any("unknown" in m for m in msgs)

    def test_missing_bound_is_a_problem(self):
        msgs = list(iter_watermark_annotation_problems(
            _ann(("late.policy", "drop"))
        ))
        assert any("bound" in m for m in msgs)

    def test_runtime_resolver_raises_on_first_problem(self):
        with pytest.raises(SiddhiAppCreationError):
            resolve_watermark_annotation(_ann(("bound", "-3 sec")), env="")

    def test_env_spec_parsing(self):
        assert parse_watermark_spec("off") == "off"
        spec = parse_watermark_spec("bound=2 sec;late.policy=stream")
        assert spec == {"bound": "2 sec", "late.policy": "stream"}
        with pytest.raises(ValueError):
            parse_watermark_spec("bound")

    def test_env_overrides_annotation(self):
        cfg = resolve_watermark_annotation(
            _ann(("bound", "5 sec")), env="bound=9 sec;late.policy=stream",
        )
        assert cfg.bound_ms == 9000 and cfg.late_policy == "stream"

    def test_env_off_disables(self):
        assert resolve_watermark_annotation(
            _ann(("bound", "5 sec")), env="off"
        ) is None

    def test_env_arms_unannotated_app(self):
        cfg = resolve_watermark_annotation(None, env="bound=4 sec")
        assert cfg is not None and cfg.bound_ms == 4000

    def test_sa134_shares_the_rule_set(self):
        from siddhi_tpu.analysis import analyze

        res = analyze("""
        @app:watermark(bound='nope', late.policy='retry')
        define stream S (a string);
        from S select a insert into Out;
        """)
        codes = [d.code for d in res.diagnostics]
        assert codes.count("SA134") == 2

    def test_sa134_clean_on_valid(self):
        from siddhi_tpu.analysis import analyze

        res = analyze("""
        @app:watermark(bound='5 sec', late.policy='stream')
        define stream S (a string);
        from S select a insert into Out;
        """)
        assert not [d for d in res.diagnostics if d.code == "SA134"]


# ---------------------------------------------------------------------------
# ReorderTracker unit behavior
# ---------------------------------------------------------------------------


class TestReorderTracker:
    def _mk(self, bound=1000):
        released, late = [], []
        tr = ReorderTracker(
            "S", bound,
            deliver=lambda ts, cols: released.extend(int(t) for t in ts),
            on_late=lambda ts, cols, lat: late.extend(int(t) for t in ts),
        )
        return tr, released, late

    def test_releases_sorted_below_watermark(self):
        tr, released, late = self._mk(bound=1000)
        tr.offer([BASE + 500], {"v": np.asarray([1])})
        tr.offer([BASE + 200], {"v": np.asarray([2])})
        tr.offer([BASE + 1500], {"v": np.asarray([3])})  # wm -> BASE+500
        assert released == [BASE + 200, BASE + 500]
        assert late == []
        tr.flush()
        assert released == [BASE + 200, BASE + 500, BASE + 1500]

    def test_strictly_late_rows_split_out(self):
        tr, released, late = self._mk(bound=100)
        tr.offer([BASE + 1000], {"v": np.asarray([1])})  # wm -> BASE+900
        tr.offer([BASE + 100], {"v": np.asarray([2])})   # < wm: late
        assert late == [BASE + 100]
        assert tr.late_total == 1

    def test_row_at_watermark_is_on_time(self):
        tr, released, late = self._mk(bound=100)
        tr.offer([BASE + 1000], {"v": np.asarray([1])})  # wm -> BASE+900
        tr.offer([BASE + 900], {"v": np.asarray([2])})   # == wm: on time
        assert late == []

    def test_columnar_batch_sorted_within(self):
        tr, released, late = self._mk(bound=10)
        ts = [BASE + d for d in (5, 1, 3, 2, 4)]
        tr.offer(ts, {"v": np.asarray([0, 1, 2, 3, 4])})
        tr.flush()
        assert released == sorted(ts)

    def test_describe_counters(self):
        tr, released, _ = self._mk(bound=1000)
        tr.offer([BASE, BASE + 100], {"v": np.asarray([0, 1])})
        d = tr.describe()
        assert d["buffered"] == 2 and d["max_event_ms"] == BASE + 100
        tr.flush()
        d = tr.describe()
        assert d["buffered"] == 0 and d["released"] == 2 and d["idle"]


class TestLatenessHistogram:
    def test_quantile_shape(self):
        h = LatenessHistogram()
        for v in (1, 10, 100, 1000):
            h.record(v)
        s = h.snapshot()
        assert s["count"] == 4 and s["sum"] == 1111 and s["max"] == 1000
        assert s["p50"] <= s["p99"] <= s["p9999"]


# ---------------------------------------------------------------------------
# end-to-end: reorder + policies + status
# ---------------------------------------------------------------------------


def _run_app(ql, feeds, callbacks=("Out",), drain=True):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    got = {name: [] for name in callbacks}
    for name in callbacks:
        rt.add_callback(
            name,
            lambda evs, _n=name: got[_n].extend(
                (e.timestamp, tuple(e.data)) for e in evs
            ),
        )
    rt.start()
    try:
        for sid, row, ts in feeds:
            rt.get_input_handler(sid).send(row, timestamp=ts)
        if drain:
            rt.drain_watermarks()
        status = rt.snapshot_status()
    finally:
        rt.shutdown()
        mgr.shutdown()
    return got, status


class TestEndToEnd:
    QL = """
    @app:watermark(bound='2 sec')
    define stream S (sym string, v long);
    from S select sym, v insert into Out;
    """

    def test_disordered_feed_released_in_order(self):
        feeds = [
            ("S", ("a", d), BASE + d)
            for d in (0, 1500, 500, 3000, 2500, 4000, 9000)
        ]
        got, status = _run_app(self.QL, feeds)
        assert [t - BASE for t, _ in got["Out"]] == [
            0, 500, 1500, 2500, 3000, 4000, 9000
        ]
        ws = status["watermark"]["streams"]["S"]
        assert ws["released"] == 7 and ws["late_total"] == 0
        assert status["watermark"]["derived"]["Out"]["watermark_ms"] == \
            ws["watermark_ms"]

    def test_drop_policy_meters(self):
        feeds = [
            ("S", ("a", 1), BASE),
            ("S", ("a", 2), BASE + 5000),   # wm -> BASE+3000
            ("S", ("late", 3), BASE + 100),
        ]
        got, status = _run_app(self.QL, feeds)
        assert all(r[0] != "late" for _, r in got["Out"])
        ws = status["watermark"]["streams"]["S"]
        assert ws["dropped"] == 1 and ws["late_total"] == 1
        assert ws["lateness_ms"]["count"] == 1
        assert ws["lateness_ms"]["max"] == 2900

    def test_stream_policy_diverts_to_fault_stream(self):
        ql = """
        @app:watermark(bound='1 sec', late.policy='stream')
        define stream S (sym string, v long);
        from S select sym, v insert into Out;
        from !S select sym, v, _error insert into LateOut;
        """
        feeds = [
            ("S", ("a", 1), BASE),
            ("S", ("b", 2), BASE + 5000),
            ("S", ("z", 99), BASE + 100),
        ]
        got, status = _run_app(ql, feeds, callbacks=("Out", "LateOut"))
        assert [r for _, r in got["LateOut"]] == [("z", 99, "late[3900 ms]")]
        assert status["watermark"]["streams"]["S"]["streamed"] == 1

    def test_apply_policy_corrects_closed_bucket(self):
        ql = """
        @app:watermark(bound='1 sec', late.policy='apply',
                       allowed.lateness='1 min')
        define stream T (sym string, v long, ts long);
        define aggregation AggT from T select sym, sum(v) as total,
            count() as n group by sym aggregate by ts
            every seconds...minutes;
        """
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        rt.start()
        try:
            h = rt.get_input_handler("T")
            h.send(("x", 10, BASE), timestamp=BASE)
            h.send(("x", 20, BASE + 200), timestamp=BASE + 200)
            # closes the first seconds bucket (wm -> BASE+6000)
            h.send(("x", 5, BASE + 7000), timestamp=BASE + 7000)
            # late into the CLOSED bucket: existing group corrected in place
            h.send(("x", 100, BASE + 500), timestamp=BASE + 500)
            # late new group: fresh closed row inserted
            h.send(("y", 7, BASE + 300), timestamp=BASE + 300)
            rt.drain_watermarks()
            rows = sorted(
                tuple(e.data) for e in rt.query(
                    f"from AggT within {BASE - 1000}L, {BASE + 60_000}L "
                    "per 'sec' select AGG_TIMESTAMP, sym, total, n"
                )
            )
            assert rows == [
                (BASE, "x", 130, 3),
                (BASE, "y", 7, 1),
                (BASE + 7000, "x", 5, 1),
            ]
            ws = rt.snapshot_status()["watermark"]["streams"]["T"]
            assert ws["applied"] == 2 and ws["expired"] == 0
            # drain flushed the tracker: the stream watermark caught up to
            # the max event time
            aggs = rt.snapshot_status()["aggregations"]["AggT"]
            assert aggs["stream_watermark_ms"] == BASE + 7000
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_apply_policy_expires_past_allowed_lateness(self):
        ql = """
        @app:watermark(bound='1 sec', late.policy='apply',
                       allowed.lateness='2 sec')
        define stream T (sym string, v long, ts long);
        define aggregation AggT from T select sym, sum(v) as total
            group by sym aggregate by ts every seconds;
        from !T select sym, _error insert into Exp;
        """
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        got = []
        rt.add_callback("Exp", lambda evs: got.extend(tuple(e.data) for e in evs))
        rt.start()
        try:
            h = rt.get_input_handler("T")
            h.send(("x", 1, BASE), timestamp=BASE)
            h.send(("x", 1, BASE + 60_000), timestamp=BASE + 60_000)
            h.send(("old", 9, BASE + 100), timestamp=BASE + 100)  # 58.9s late
            rt.drain_watermarks()
            ws = rt.snapshot_status()["watermark"]["streams"]["T"]
            assert ws["expired"] == 1 and ws["applied"] == 0
            assert got and got[0][0] == "old" and "expired" in got[0][1]
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_late_rows_are_never_silently_lost(self):
        # drop policy still METERS every late row; totals must reconcile
        feeds = [("S", ("a", 1), BASE), ("S", ("b", 2), BASE + 9000)]
        feeds += [("S", ("l", i), BASE + 100 + i) for i in range(5)]
        got, status = _run_app(self.QL, feeds)
        ws = status["watermark"]["streams"]["S"]
        assert ws["late_total"] == 5 == ws["dropped"]
        assert ws["released"] + ws["late_total"] == len(feeds)

    def test_reserved_error_attr_rejected_with_late_stream(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            @app:watermark(bound='1 sec', late.policy='stream')
            define stream S (sym string, _error string);
            from S select sym insert into Out;
            """)
        mgr.shutdown()

    def test_idle_timeout_flushes_quiet_stream(self):
        ql = """
        @app:watermark(bound='10 sec', idle.timeout='200 millisec')
        define stream S (sym string, v long);
        from S select sym, v insert into Out;
        """
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(tuple(e.data) for e in evs))
        rt.start()
        try:
            rt.get_input_handler("S").send(("a", 1), timestamp=BASE)
            # bound is 10s and nothing else arrives: only the idle timeout
            # can release the buffered row
            deadline = time.monotonic() + 5.0
            while not got and time.monotonic() < deadline:
                time.sleep(0.05)
            assert got == [("a", 1)]
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_watermark_drives_window_timers(self):
        # time-window expiry fires on WATERMARK advance, not wall clock:
        # 1 sec of event time passes in microseconds of wall time
        ql = """
        @app:watermark(bound='100 millisec')
        define stream S (sym string, v long);
        from S#window.time(1 sec) select sym, count() as n insert all events into Out;
        """
        feeds = [
            ("S", ("a", 1), BASE),
            ("S", ("a", 2), BASE + 5000),
            ("S", ("a", 3), BASE + 5100),
        ]
        got, _ = _run_app(ql, feeds)
        # the first row expired from the window when event time crossed
        # BASE+1000 — visible as an expired/current emission beyond it
        assert len(got["Out"]) >= 3


# ---------------------------------------------------------------------------
# zero-cost contract
# ---------------------------------------------------------------------------


class TestZeroCost:
    def test_no_annotation_no_wrapper(self):
        from siddhi_tpu.core.app_runtime import _WatermarkInputHandler

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (sym string);
        from S select sym insert into Out;
        """)
        try:
            assert rt._watermark is None
            h = rt.get_input_handler("S")
            probe = h
            while probe is not None:
                assert not isinstance(probe, _WatermarkInputHandler)
                probe = getattr(probe, "_inner", None)
            assert "watermark" not in rt.snapshot_status()
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_annotation_installs_wrapper(self):
        from siddhi_tpu.core.app_runtime import _WatermarkInputHandler

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:watermark(bound='1 sec')
        define stream S (sym string);
        from S select sym insert into Out;
        """)
        try:
            h = rt.get_input_handler("S")
            found, probe = False, h
            while probe is not None and not found:
                found = isinstance(probe, _WatermarkInputHandler)
                probe = getattr(probe, "_inner", None)
            assert found
        finally:
            rt.shutdown()
            mgr.shutdown()


# ---------------------------------------------------------------------------
# observability: Prometheus + explain
# ---------------------------------------------------------------------------


class TestObservability:
    def test_prometheus_families(self):
        from siddhi_tpu.observability.reporters import render_prometheus

        ql = """
        @app:statistics(reporter='none')
        @app:watermark(bound='1 sec')
        define stream S (sym string, v long);
        from S select sym, v insert into Out;
        """
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        rt.start()
        try:
            h = rt.get_input_handler("S")
            h.send(("a", 1), timestamp=BASE)
            h.send(("a", 2), timestamp=BASE + 5000)
            h.send(("z", 3), timestamp=BASE + 100)  # late -> dropped
            rt.drain_watermarks()
            text = render_prometheus([rt.statistics_manager.report()])
            wm_lines = [
                ln for ln in text.splitlines()
                if ln.startswith("siddhi_watermark_ms{") and 'stream="S"' in ln
            ]
            assert wm_lines, text
            assert any(
                ln.startswith("siddhi_watermark_lag_ms{")
                for ln in text.splitlines()
            )
            dropped = [
                ln for ln in text.splitlines()
                if ln.startswith("siddhi_late_events_total{")
                and 'outcome="dropped"' in ln and 'stream="S"' in ln
            ]
            assert dropped and dropped[0].endswith(" 1")
            assert "siddhi_lateness_ms" in text
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_explain_includes_watermark(self):
        from siddhi_tpu.observability.explain import explain

        ql = """
        @app:watermark(bound='1 sec')
        define stream S (sym string, v long);
        from S select sym, v insert into Out;
        """
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        rt.start()
        try:
            rt.get_input_handler("S").send(("a", 1), timestamp=BASE)
            rt.drain_watermarks()
            text = explain(rt, fmt="text")
            assert "watermark[" in text
        finally:
            rt.shutdown()
            mgr.shutdown()


# ---------------------------------------------------------------------------
# fault-injection disorder site
# ---------------------------------------------------------------------------


class TestDisorderFaultSite:
    def test_permutation_deterministic_and_bounded(self):
        ts = [BASE + i * 10 for i in range(64)]
        p1 = faults.parse_plan("seed=7;ingest_disorder:jitter=50,times=-1")
        p2 = faults.parse_plan("seed=7;ingest_disorder:jitter=50,times=-1")
        perm1 = p1.permute("ingest_disorder", "a:S", ts)
        perm2 = p2.permute("ingest_disorder", "a:S", ts)
        assert perm1 == perm2 and sorted(perm1) == list(range(64))
        assert perm1 != list(range(64))
        # displacement bound: a row never lands behind one > jitter newer
        shuffled = [ts[i] for i in perm1]
        for i, t in enumerate(shuffled):
            assert max(shuffled[: i + 1]) - t <= 50

    def test_different_seed_different_shuffle(self):
        ts = [BASE + i * 10 for i in range(64)]
        a = faults.parse_plan("seed=1;ingest_disorder:jitter=50,times=-1")
        b = faults.parse_plan("seed=2;ingest_disorder:jitter=50,times=-1")
        assert a.permute("ingest_disorder", "k", ts) != \
            b.permute("ingest_disorder", "k", ts)

    def test_jitter_rules_never_raise_via_check(self):
        plan = faults.parse_plan("ingest_disorder:jitter=50,times=-1")
        plan.check("ingest_disorder", "k")  # transform rules are not errors

    def test_disorder_wrapper_installed_only_with_plan(self):
        from siddhi_tpu.core.app_runtime import _DisorderInputHandler

        ql = """
        define stream S (sym string, v long);
        from S select sym, v insert into Out;
        """
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        try:
            probe = rt.get_input_handler("S")
            while probe is not None:
                assert not isinstance(probe, _DisorderInputHandler)
                probe = getattr(probe, "_inner", None)
        finally:
            rt.shutdown()
            mgr.shutdown()

        faults.install(faults.parse_plan(
            "seed=3;ingest_disorder:jitter=20,times=-1"
        ))
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(ql)
            try:
                found, probe = False, rt.get_input_handler("S")
                while probe is not None and not found:
                    found = isinstance(probe, _DisorderInputHandler)
                    probe = getattr(probe, "_inner", None)
                assert found
            finally:
                rt.shutdown()
                mgr.shutdown()
        finally:
            faults.uninstall()
