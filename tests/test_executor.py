"""Expression compiler tests — type promotion / Java arithmetic semantics.

Mirrors behaviors pinned by the reference's per-type executors
(reference: core/executor/math/*, condition/*, function/*).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from siddhi_tpu.core.executor import Env, Scope, compile_expression
from siddhi_tpu.core.types import AttrType, InternTable
from siddhi_tpu.query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    Divide,
    Expression,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    Variable,
)

E = Expression


def make_scope():
    interner = InternTable()
    scope = Scope(interner)
    scope.add_stream(
        "S",
        {
            "i": AttrType.INT,
            "l": AttrType.LONG,
            "f": AttrType.FLOAT,
            "d": AttrType.DOUBLE,
            "b": AttrType.BOOL,
            "s": AttrType.STRING,
        },
    )
    return scope, interner


def make_env(interner, **over):
    cols = {
        ("S", None, "i"): jnp.array([1, -7, 3], dtype=jnp.int32),
        ("S", None, "l"): jnp.array([10, 20, 30], dtype=jnp.int64),
        ("S", None, "f"): jnp.array([1.5, 2.5, 3.5], dtype=jnp.float32),
        ("S", None, "d"): jnp.array([0.5, 1.0, 2.0], dtype=jnp.float32),
        ("S", None, "b"): jnp.array([True, False, True]),
        ("S", None, "s"): jnp.array(
            [interner.intern("WSO2"), interner.intern("IBM"), 0], dtype=jnp.int32
        ),
        ("S", None, "__ts__"): jnp.array([100, 200, 300], dtype=jnp.int64),
    }
    cols.update(over)
    return Env(cols, now=jnp.asarray(12345, dtype=jnp.int64))


def run(expr, scope=None, interner=None):
    if scope is None:
        scope, interner = make_scope()
    c = compile_expression(expr, scope)
    return c, np.asarray(c(make_env(interner)))


def test_promotion_matrix():
    scope, interner = make_scope()
    cases = [
        (Add(Variable("i"), Variable("i")), AttrType.INT),
        (Add(Variable("i"), Variable("l")), AttrType.LONG),
        (Add(Variable("l"), Variable("f")), AttrType.FLOAT),
        (Add(Variable("f"), Variable("d")), AttrType.DOUBLE),
        (Multiply(Variable("i"), Variable("d")), AttrType.DOUBLE),
    ]
    for expr, want in cases:
        c = compile_expression(expr, scope)
        assert c.type is want, (expr, c.type)


def test_java_int_division_truncates():
    # Java: -7 / 2 == -3 (trunc), not floor(-3.5) == -4
    c, out = run(Divide(Variable("i"), Constant(2, AttrType.INT)))
    assert c.type is AttrType.INT
    assert out.tolist() == [0, -3, 1]


def test_java_mod_sign():
    # Java: -7 % 3 == -1
    c, out = run(Mod(Variable("i"), Constant(3, AttrType.INT)))
    assert out.tolist() == [1, -1, 0]


def test_float_divide():
    c, out = run(Divide(Variable("f"), Constant(2, AttrType.INT)))
    assert c.type is AttrType.FLOAT
    np.testing.assert_allclose(out, [0.75, 1.25, 1.75])


def test_compare_cross_type():
    _, out = run(Compare(Variable("i"), CompareOp.GT, Variable("d")))
    assert out.tolist() == [True, False, True]


def test_string_equality_and_order_rejected():
    scope, interner = make_scope()
    c = compile_expression(
        Compare(Variable("s"), CompareOp.EQ, Constant("WSO2", AttrType.STRING)), scope
    )
    out = np.asarray(c(make_env(interner)))
    assert out.tolist() == [True, False, False]
    with pytest.raises(TypeError):
        compile_expression(
            Compare(Variable("s"), CompareOp.LT, Constant("A", AttrType.STRING)), scope
        )


def test_bool_ops():
    _, out = run(
        And(Variable("b"), Not(Or(Variable("b"), Constant(False, AttrType.BOOL))))
    )
    assert out.tolist() == [False, False, False]
    with pytest.raises(TypeError):
        run(And(Variable("i"), Variable("b")))


def test_is_null_string():
    _, out = run(IsNull(Variable("s")))
    assert out.tolist() == [False, False, True]


def test_coalesce_and_default():
    scope, interner = make_scope()
    c = compile_expression(
        AttributeFunction(None, "coalesce", [Variable("s"), Constant("dflt", AttrType.STRING)]),
        scope,
    )
    env = make_env(interner)
    out = [interner.lookup(int(v)) for v in np.asarray(c(env))]
    assert out == ["WSO2", "IBM", "dflt"]

    c2 = compile_expression(
        AttributeFunction(None, "default", [Variable("s"), Constant("x", AttrType.STRING)]),
        scope,
    )
    out2 = [interner.lookup(int(v)) for v in np.asarray(c2(env))]
    assert out2 == ["WSO2", "IBM", "x"]


def test_if_then_else_and_minmax():
    _, out = run(
        AttributeFunction(
            None,
            "ifThenElse",
            [
                Compare(Variable("i"), CompareOp.GE, Constant(0, AttrType.INT)),
                Variable("i"),
                Constant(0, AttrType.INT),
            ],
        )
    )
    assert out.tolist() == [1, 0, 3]

    c, out = run(AttributeFunction(None, "maximum", [Variable("i"), Variable("f")]))
    assert c.type is AttrType.FLOAT
    np.testing.assert_allclose(out, [1.5, 2.5, 3.5])


def test_cast_and_instanceof():
    scope, interner = make_scope()
    c = compile_expression(
        AttributeFunction(None, "cast", [Variable("f"), Constant("int", AttrType.STRING)]),
        scope,
    )
    assert c.type is AttrType.INT
    out = np.asarray(c(make_env(interner)))
    assert out.tolist() == [1, 2, 3]

    c2 = compile_expression(
        AttributeFunction(None, "instanceOfFloat", [Variable("f")]), scope
    )
    assert np.asarray(c2(make_env(interner))).tolist() == [True, True, True]
    c3 = compile_expression(
        AttributeFunction(None, "instanceOfString", [Variable("f")]), scope
    )
    assert np.asarray(c3(make_env(interner))).tolist() == [False, False, False]


def test_event_timestamp_and_now():
    _, out = run(AttributeFunction(None, "eventTimestamp", []))
    assert out.tolist() == [100, 200, 300]
    _, out = run(AttributeFunction(None, "currentTimeMillis", []))
    assert int(out) == 12345


def test_unqualified_ambiguity():
    interner = InternTable()
    scope = Scope(interner)
    scope.add_stream("A", {"x": AttrType.INT})
    scope.add_stream("B", {"x": AttrType.INT})
    with pytest.raises(KeyError):
        compile_expression(Variable("x"), scope)
    c = compile_expression(Variable("x", stream_id="B"), scope)
    env = Env({("B", None, "x"): jnp.array([5], dtype=jnp.int32)})
    assert np.asarray(c(env)).tolist() == [5]


def test_aggregator_rejected_in_scalar_position():
    scope, _ = make_scope()
    with pytest.raises(TypeError):
        compile_expression(AttributeFunction(None, "sum", [Variable("i")]), scope)
