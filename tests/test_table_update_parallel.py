"""The vectorized (last-writer-wins) table update must match the sequential
reference semantics wherever the compile-time analysis enables it, and the
analysis must refuse the cases where they could diverge."""

from __future__ import annotations

import numpy as np
import pytest

import siddhi_tpu.core.table as table_mod
from siddhi_tpu import SiddhiManager

BASE = """
define stream L (k long, v long);
define stream S (k long, v long);
@capacity(size='64') define table T (k long, v long);
@info(name='load') from L insert into T;
"""

CASES = {
    "default_set_pk_eq": "@info(name='u') from S select k, v update T on T.k == k;",
    "explicit_set": "@info(name='u') from S select k, v update T set T.v = v * 2 on T.k == k;",
    "table_dependent_set": "@info(name='u') from S select k, v update T set T.v = T.v + v on T.k == k;",
    "range_condition": "@info(name='u') from S select k, v update T set T.v = v on T.k < k;",
}


def _run(ql, force_sequential: bool):
    orig = table_mod._update_parallel_vectorizable
    if force_sequential:
        table_mod._update_parallel_vectorizable = lambda *a: False
    try:
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        rt.start()
        for row in [(int(i), int(i * 10)) for i in range(20)]:
            rt.get_input_handler("L").send(row)
        rng = np.random.default_rng(5)
        h = rt.get_input_handler("S")
        # duplicate keys within the update stream: order must matter equally
        for k, v in zip(rng.integers(0, 20, 40), rng.integers(100, 200, 40)):
            h.send((int(k), int(v)))
        rows = sorted(tuple(e.data) for e in rt.query("from T select *"))
        rt.shutdown()
        mgr.shutdown()
        return rows
    finally:
        table_mod._update_parallel_vectorizable = orig


@pytest.mark.parametrize("name", sorted(CASES))
def test_parallel_update_matches_sequential(name):
    ql = BASE + CASES[name]
    assert _run(ql, force_sequential=False) == _run(ql, force_sequential=True)


def test_analysis_gate():
    def decide(update_clause):
        got = []
        orig = table_mod._update_parallel_vectorizable
        table_mod._update_parallel_vectorizable = (
            lambda *a: got.append(orig(*a)) or got[-1]
        )
        try:
            mgr = SiddhiManager()
            mgr.create_siddhi_app_runtime("""
            define stream S (k long, v long);
            @capacity(size='16') define table T (k long, v long);
            """ + update_clause)
            mgr.shutdown()
        finally:
            table_mod._update_parallel_vectorizable = orig
        return got == [True]

    assert decide("@info(name='u') from S select k, v update T on T.k == k;")
    assert decide("@info(name='u') from S select k, v update T set T.v = v on T.k == k;")
    # set value reads the table: last-writer-wins would drop accumulation
    assert not decide(
        "@info(name='u') from S select k, v update T set T.v = T.v + v on T.k == k;"
    )
    # the condition reads a column the set rewrites to an un-pinned value
    assert not decide(
        "@info(name='u') from S select k, v update T set T.k = v on T.k == k;"
    )


PK_BASE = """
define stream L (k long, v long);
define stream S (k long, v long);
@PrimaryKey('k')
@capacity(size='64') define table T (k long, v long);
@info(name='load') from L insert into T;
"""


@pytest.mark.parametrize("name", ["default_set_pk_eq", "explicit_set"])
def test_pk_probe_path_matches_sequential(name):
    ql = PK_BASE + CASES[name]
    assert _run(ql, force_sequential=False) == _run(ql, force_sequential=True)


def test_pk_rewrite_then_pk_probe_stays_correct():
    """An update that rewrites the PK (non-PK path, reindex_after) must leave
    the sorted index fresh for a later PK-probe update."""
    def go(force_seq):
        orig = table_mod._update_parallel_vectorizable
        if force_seq:
            table_mod._update_parallel_vectorizable = lambda *a: False
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(PK_BASE + """
            @info(name='rekey') from S[v > 500] select k, v update T set T.k = v on T.v == k;
            @info(name='upd') from S[v <= 500] select k, v update T on T.k == k;
            """)
            rt.start()
            for i in range(10):
                rt.get_input_handler("L").send((i, i))
            h = rt.get_input_handler("S")
            h.send((3, 900))     # rekey: row with v==3 gets k := 900
            h.send((900, 111))   # pk probe on the REWRITTEN key must find it
            rows = sorted(tuple(e.data) for e in rt.query("from T select *"))
            rt.shutdown()
            mgr.shutdown()
            return rows
        finally:
            table_mod._update_parallel_vectorizable = orig

    fast, slow = go(False), go(True)
    assert fast == slow
    assert (900, 111) in fast


def test_null_pk_probe_matches_nothing():
    """A null probe key must not 'match' a null-keyed row — parity with the
    dense path's null-comparison semantics."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(PK_BASE + """
    @info(name='upd') from S select k, v update T on T.k == k;
    """)
    rt.start()
    rt.get_input_handler("L").send((None, 7))
    rt.get_input_handler("S").send((None, 999))
    rows = sorted(tuple(e.data) for e in rt.query("from T select *"))
    rt.shutdown()
    mgr.shutdown()
    assert rows == [(None, 7)]
