"""Golden corpus: reference query/window/ExternalTimeBatchWindowTestCase.java
externalTimeBatchWindowTest1-8 (data-level translation). The 4th parameter
(idle timeout) arms a wall-clock flush the reference asserts BEFORE it can
fire (sleep 1s < timeout 2-6s), so the event-driven counts below are exact
with the timeout ignored. Tests 02NoMsg/05EdgeCase live in
test_golden_windows_ref; test9 is a thread-race harness and the perf tests
are not behavioral contracts."""

from __future__ import annotations

import pytest

from siddhi_tpu import SiddhiManager

LOGIN = "define stream LoginEvents (timestamp long, ip string) ;\n"


def run_counts(ql, sends):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    n_in, n_rem = [0], [0]
    rt.add_callback(
        "query1",
        lambda ts, i, r: (
            n_in.__setitem__(0, n_in[0] + len(i or ())),
            n_rem.__setitem__(0, n_rem[0] + len(r or ())),
        ),
    )
    rt.start()
    h = rt.get_input_handler("LoginEvents")
    for row in sends:
        h.send(row)
    rt.shutdown()
    mgr.shutdown()
    return n_in[0], n_rem[0]


class TestExternalTimeBatchGolden:
    def test1_two_flushes_with_timeout_param(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.externalTimeBatch(timestamp, 1 sec, 0, 6 sec)
        select timestamp, ip, count() as total
        insert all events into uniqueIps ;""", [
            (1366335804341, "192.10.1.3"),
            (1366335804342, "192.10.1.4"),
            (1366335814341, "192.10.1.5"),
            (1366335814345, "192.10.1.6"),
            (1366335824341, "192.10.1.7"),
        ])
        assert (ins, rem) == (2, 0), (ins, rem)

    def test2_two_flushes_no_timeout(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.externalTimeBatch(timestamp, 1 sec)
        select timestamp, ip, count() as total
        insert all events into uniqueIps ;""", [
            (1366335804341, "192.10.1.3"),
            (1366335804342, "192.10.1.4"),
            (1366335805340, "192.10.1.4"),
            (1366335814341, "192.10.1.5"),
            (1366335814345, "192.10.1.6"),
            (1366335824341, "192.10.1.7"),
        ])
        assert (ins, rem) == (2, 0), (ins, rem)

    def test3_boundary_starts_new_bucket(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.externalTimeBatch(timestamp, 1 sec)
        select timestamp, ip, count() as total
        insert all events into uniqueIps ;""", [
            (1366335804341, "192.10.1.3"),
            (1366335804342, "192.10.1.4"),
            (1366335805341, "192.10.1.4"),
            (1366335814341, "192.10.1.5"),
            (1366335814345, "192.10.1.6"),
            (1366335824341, "192.10.1.7"),
        ])
        assert (ins, rem) == (3, 0), (ins, rem)

    def test4_exact_second_boundaries(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.externalTimeBatch(timestamp, 1 sec, 0, 6 sec)
        select timestamp, ip, count() as total
        insert all events into uniqueIps ;""", [
            (1366335804341, "192.10.1.3"),
            (1366335804999, "192.10.1.4"),
            (1366335805000, "192.10.1.4"),
            (1366335805999, "192.10.1.5"),
            (1366335806000, "192.10.1.6"),
            (1366335806001, "192.10.1.6"),
            (1366335824341, "192.10.1.7"),
        ])
        assert (ins, rem) == (3, 0), (ins, rem)

    def _run_timeout(self, ql, sends, want, timeout=12.0):
        """Wait for the idle-timeout flush (reference sleeps past the window's
        timeout parameter)."""
        import time

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        n_in = [0]
        rt.add_callback(
            "query1",
            lambda ts, i, r: n_in.__setitem__(0, n_in[0] + len(i or ())),
        )
        rt.start()
        h = rt.get_input_handler("LoginEvents")
        for row in sends:
            h.send(row)
        t0 = time.time()
        while n_in[0] < want and time.time() - t0 < timeout:
            time.sleep(0.05)
        rt.shutdown()
        mgr.shutdown()
        return n_in[0]

    def test5_idle_timeout_flushes_single_bucket(self):
        # reference test5: all 4 events sit in one open bucket; the 1-sec
        # idle timeout (wall clock) force-closes it -> one aggregate row
        # (timeout shortened from the reference's 3 sec to keep the test
        # fast; the contract — timeout flushes the open bucket — is the same)
        ins = self._run_timeout(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.externalTimeBatch(timestamp, 1 sec, 0, 1 sec)
        select timestamp, ip, count() as total
        insert all events into uniqueIps ;""", [
            (1366335804341, "192.10.1.3"),
            (1366335804599, "192.10.1.4"),
            (1366335804600, "192.10.1.5"),
            (1366335804607, "192.10.1.6"),
        ], want=1)
        assert ins == 1, ins

    def test6_event_flush_then_idle_timeout(self):
        # reference test6 shape: bucket0 closes on bucket1's first event,
        # bucket1 closes on the idle timeout -> two aggregate rows
        ins = self._run_timeout(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.externalTimeBatch(timestamp, 1 sec, 0, 1 sec)
        select timestamp, ip, count() as total
        insert all events into uniqueIps ;""", [
            (1366335804341, "192.10.1.3"),
            (1366335804599, "192.10.1.4"),
            (1366335804600, "192.10.1.5"),
            (1366335804607, "192.10.1.6"),
            (1366335805599, "192.10.1.4"),
            (1366335805600, "192.10.1.5"),
            (1366335805607, "192.10.1.6"),
        ], want=2)
        assert ins == 2, ins

    # reference tests 7-8 interleave three Thread.sleep(>timeout) pauses
    # with out-of-order sends, so their expected counts depend on exactly
    # which pauses let the idle timeout fire between sends — a wall-clock
    # orchestration, not a data contract; the timeout behavior they add over
    # test5/6 is covered above without the flakiness.


class TestIdleTimeoutMixedBatch:
    """Positional timeout semantics inside ONE batch.

    The reference processes a batch event-by-event: a CURRENT event re-arms
    the idle deadline BEFORE a later TIMER row in the same batch is
    examined, so a stale-elapsed timer must not force-close the bucket the
    event just (re)filled. The engine's batch-level check (`timeout_flush`
    in core/windows.py BatchWindow.apply) guards on `rank == 0`: any
    CURRENT row earlier in the batch re-arms the deadline to now + timeout
    (which cannot have elapsed at the same now), so a TIMER preceded by a
    CURRENT row never force-closes."""

    def test_stale_timer_after_refill_in_same_batch(self):
        from siddhi_tpu.core.event import KIND_CURRENT, KIND_TIMER

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:playback
        define stream LoginEvents (timestamp long, ip string);
        @info(name = 'query1')
        from LoginEvents#window.externalTimeBatch(timestamp, 1 sec, 0, 1 sec)
        select timestamp, count() as total
        insert into uniqueIps;
        """)
        ins = [0]
        rt.add_callback(
            "query1",
            lambda ts, i, r: ins.__setitem__(0, ins[0] + len(i or ())),
        )
        rt.start()
        j = rt.junctions["LoginEvents"]
        # open a bucket (grid [1000, 2000), start 0) at now=1000; the idle
        # deadline arms at 1000 + 1 sec = 2000
        b1 = j.schema.to_batch(
            [1400, 1500], [(1400, "a"), (1500, "b")], rt.interner,
            capacity=j.batch_size,
        )
        j.publish_batch(b1, 1000)
        assert ins[0] == 0
        # ONE mixed batch at now=5000: a refill event (same grid bucket,
        # re-arms the deadline to 6000) positioned BEFORE a stale TIMER
        # armed for the old deadline — the timer must NOT force-close
        mixed = j.schema.to_batch(
            [1600, 5000], [(1600, "c"), (None, None)], rt.interner,
            capacity=j.batch_size, kinds=[KIND_CURRENT, KIND_TIMER],
        )
        j.publish_batch(mixed, 5000)
        try:
            assert ins[0] == 0, (
                "stale-elapsed timer force-closed a bucket refilled earlier "
                "in the same batch"
            )
        finally:
            rt.shutdown()
            mgr.shutdown()
