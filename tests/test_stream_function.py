"""Stream function / script function / UDF tests.

Reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/query/
streamfunction/Pol2CartFunctionTestCase, function/ScriptTestCase,
extension/ExtensionTestCase.
"""

import math

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.extension import extension


def run_app(ql, sends, callback_name="q"):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    ins = []
    rt.add_callback(callback_name, lambda ts, i, r: ins.extend(e.data for e in i or []))
    rt.start()
    h = {}
    for sid, row, ts in sends:
        h.setdefault(sid, rt.get_input_handler(sid)).send(row, timestamp=ts)
    rt.shutdown()
    mgr.shutdown()
    return ins


class TestPol2Cart:
    def test_appends_xy(self):
        ql = """
        define stream P (theta double, rho double);
        @info(name='q')
        from P#pol2Cart(theta, rho)
        select x, y
        insert into Out;
        """
        ins = run_app(ql, [("P", (0.0, 2.0), 1), ("P", (90.0, 3.0), 2)])
        assert ins[0][0] == pytest.approx(2.0)
        assert ins[0][1] == pytest.approx(0.0, abs=1e-6)
        assert ins[1][0] == pytest.approx(0.0, abs=1e-6)
        assert ins[1][1] == pytest.approx(3.0)

    def test_appended_attr_usable_in_filter_and_window(self):
        ql = """
        define stream P (theta double, rho double);
        @info(name='q')
        from P#pol2Cart(theta, rho)[x > 1.0]#window.length(2)
        select sum(x) as sx
        insert into Out;
        """
        ins = run_app(ql, [
            ("P", (0.0, 2.0), 1),    # x=2 passes
            ("P", (90.0, 3.0), 2),   # x~0 filtered
            ("P", (0.0, 5.0), 3),    # x=5 passes
        ])
        assert [round(v[0], 4) for v in ins] == [2.0, 7.0]


class TestLogStreamProcessor:
    def test_log_passthrough(self, caplog):
        import logging

        ql = """
        define stream S (symbol string);
        @info(name='q')
        from S#log('saw event')
        select symbol insert into Out;
        """
        with caplog.at_level(logging.INFO, logger="siddhi_tpu.log.S"):
            ins = run_app(ql, [("S", ("WSO2",), 1)])
        assert ins == [("WSO2",)]


class TestScriptFunction:
    def test_python_function(self):
        ql = """
        define function half[python] return double {
            return data[0] / 2.0
        };
        define stream S (v double);
        @info(name='q')
        from S select half(v) as h insert into Out;
        """
        ins = run_app(ql, [("S", (10.0,), 1), ("S", (3.0,), 2)])
        assert ins == [(5.0,), (1.5,)]

    def test_python_expression_body(self):
        ql = """
        define function addUp[python] return long { data[0] + data[1] };
        define stream S (a long, b long);
        @info(name='q')
        from S select addUp(a, b) as s insert into Out;
        """
        ins = run_app(ql, [("S", (3, 4), 1)])
        assert ins == [(7,)]


class TestCustomExtensions:
    def test_custom_scalar_function(self):
        from siddhi_tpu.core.executor import CompiledExpr
        from siddhi_tpu.core.types import AttrType
        import jax.numpy as jnp

        @extension("function", "doubled", namespace="custom")
        def _doubled(params, scope):
            (arg,) = params
            return CompiledExpr(arg.type, lambda env: arg(env) * 2)

        ql = """
        define stream S (v long);
        @info(name='q')
        from S select custom:doubled(v) as d insert into Out;
        """
        ins = run_app(ql, [("S", (21,), 1)])
        assert ins == [(42,)]

    def test_custom_stream_function(self):
        from siddhi_tpu.core.stream_function import StreamFunctionStage
        from siddhi_tpu.core.types import AttrType

        @extension("stream_function", "custom:tag")
        def _tag(params, schema_attrs, ref, scope):
            return StreamFunctionStage(
                ref, [("tagged", AttrType.LONG)],
                lambda env, _p=params: {"tagged": _p[0](env) + 1000},
            )

        ql = """
        define stream S (v long);
        @info(name='q')
        from S#custom:tag(v) select v, tagged insert into Out;
        """
        ins = run_app(ql, [("S", (1,), 1)])
        assert ins == [(1, 1001)]
