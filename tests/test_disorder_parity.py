"""Golden disorder parity — the @app:watermark headline proof.

A feed shuffled WITHIN the watermark bound by the seeded `ingest_disorder`
fault site, pushed through the bounded reorder stage, must produce emissions
EXACTLY equal to the ordered control run — same rows, same order, same
timestamps — for every stateful operator class, under the fused and sharded
execution paths both on and off.

Mechanics that make the equality exact (not just set-equal):
* each case feeds ONE columnar send with unique strictly-increasing
  timestamps, so the ordered and shuffled runs share one watermark
  trajectory and identical release boundaries;
* jitter <= bound, so the shuffle never creates a late event — every row
  re-sorts back to its original position before dispatch.

FUSE/SHARD toggles are read from the environment per app start (conftest
boots 8 host devices), so the matrix runs in-process.
"""

from __future__ import annotations

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.testing import faults

BASE = 1_700_000_000_000
N = 96
JITTER_MS = 1500  # < the 2 sec bound in every app below

WM = "@app:watermark(bound='2 sec')\n"

CASES = {
    "sliding_window": (
        WM + """
        define stream S (sym string, price double, vol long);
        @info(name='q')
        from S#window.length(5)
        select sym, sum(price) as total, count() as n
        insert into Out;
        """,
    ),
    "length_batch_group_by": (
        WM + """
        define stream S (sym string, price double, vol long);
        @info(name='q')
        from S#window.lengthBatch(8)
        select sym, sum(vol) as v, max(price) as hi
        group by sym
        insert into Out;
        """,
    ),
    "pattern_within": (
        WM + """
        define stream S (sym string, price double, vol long);
        @info(name='q')
        from every a=S[price > 60] -> b=S[price < 40] within 3 sec
        select a.sym as asym, b.sym as bsym, a.price as ap, b.price as bp
        insert into Out;
        """,
    ),
    "join": (
        WM + """
        define stream S (sym string, price double, vol long);
        define stream R (sym string, lo double);
        @info(name='q')
        from S#window.length(6) join R#window.length(4)
            on S.sym == R.sym
        select S.sym as sym, S.price as price, R.lo as lo
        insert into Out;
        """,
    ),
    # windowless running aggregation with exact (integer) aggregators —
    # the query class the keys axis actually key-shards
    "keyed_group_by": (
        WM + """
        define stream S (sym string, price double, vol long);
        @info(name='q')
        from S
        select sym, sum(vol) as v, count() as n, max(vol) as hi
        group by sym
        insert into Out;
        """,
    ),
}


def _feed(seed=11):
    rng = np.random.default_rng(seed)
    ts = BASE + np.arange(N, dtype=np.int64) * 97  # unique, increasing
    syms = np.asarray([f"S{i % 5}" for i in range(N)])
    price = np.round(rng.uniform(10.0, 100.0, N), 2)
    vol = rng.integers(1, 500, N).astype(np.int64)
    return ts, {"sym": syms, "price": price, "vol": vol}


def _run_case(ql, disorder: bool):
    if disorder:
        faults.install(faults.parse_plan(
            f"seed=23;ingest_disorder:jitter={JITTER_MS},times=-1"
        ))
    try:
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        got = []
        rt.add_callback(
            "Out",
            lambda evs: got.extend((e.timestamp, tuple(e.data)) for e in evs),
        )
        rt.start()
        ts, cols = _feed()
        if "define stream R" in ql:
            # join partner: ordered side-feed primed first so both runs see
            # identical R state before S flows
            rt.get_input_handler("R").send_columns(
                np.asarray([BASE - 10, BASE - 9, BASE - 8], np.int64),
                {
                    "sym": np.asarray(["S0", "S1", "S2"]),
                    "lo": np.asarray([20.0, 30.0, 40.0]),
                },
            )
        rt.get_input_handler("S").send_columns(ts, cols)
        rt.drain_watermarks()
        status = rt.snapshot_status()
        rt.shutdown()
        mgr.shutdown()
        return got, status
    finally:
        if disorder:
            faults.uninstall()


@pytest.mark.parametrize("fuse", ["1", "0"])
@pytest.mark.parametrize("shard", ["8", "8:keys", "0"])
@pytest.mark.parametrize("case", sorted(CASES))
def test_disorder_parity(case, fuse, shard, monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_FUSE", fuse)
    devices, _, axis = shard.partition(":")
    monkeypatch.setenv("SIDDHI_TPU_SHARD", devices)
    if axis:
        monkeypatch.setenv("SIDDHI_TPU_SHARD_AXIS", axis)
    else:
        monkeypatch.delenv("SIDDHI_TPU_SHARD_AXIS", raising=False)
    (ql,) = CASES[case]
    ordered, _ = _run_case(ql, disorder=False)
    shuffled, status = _run_case(ql, disorder=True)
    assert ordered, f"{case}: control run produced no emissions"
    assert shuffled == ordered, (
        f"{case} fuse={fuse} shard={shard}: disorder parity broken\n"
        f"ordered ({len(ordered)}): {ordered[:5]}...\n"
        f"shuffled ({len(shuffled)}): {shuffled[:5]}..."
    )
    # the shuffle really happened and the reorder stage really undid it:
    # rows buffered, none late
    ws = status["watermark"]["streams"]["S"]
    assert ws["released"] == N and ws["late_total"] == 0
    assert ws["peak_buffered"] > 1


def test_shuffle_is_genuinely_disordered(monkeypatch):
    # guard against the parity matrix silently testing ordered-vs-ordered
    ts, _ = _feed()
    plan = faults.parse_plan(
        f"seed=23;ingest_disorder:jitter={JITTER_MS},times=-1"
    )
    perm = plan.permute("ingest_disorder", "x:S", [int(t) for t in ts])
    assert perm is not None and perm != list(range(N))
