"""Golden corpus: partitions, translated from the reference test data
(reference: siddhi-core/src/test/.../query/partition/{PartitionTestCase1,
WindowPartitionTestCase,PatternPartitionTestCase}.java)."""

import pytest

from tests.test_golden_count import assert_rows, run_app


class TestPartitionGolden:
    def test_query0_value_partition_passthrough(self):
        ql = """
        define stream streamA (symbol string, price int);
        partition with (symbol of streamA)
        begin
            @info(name = 'query1')
            from streamA select symbol, price insert into StockQuote ;
        end;
        """
        got = run_app(ql, [
            ("streamA", ("IBM", 700)),
            ("streamA", ("WSO2", 60)),
            ("streamA", ("WSO2", 60)),
        ])
        assert len(got) == 3, got

    def test_query1_per_key_running_sum(self):
        # PartitionTestCase1.testPartitionQuery1: sum accumulates per key;
        # the filtered-out WSO2 event contributes nothing
        ql = """
        define stream cseEventStream (symbol string, price float, volume long);
        partition with (symbol of cseEventStream)
        begin
            @info(name = 'query1')
            from cseEventStream[700 > price]
            select symbol, sum(price) as price, volume
            insert into OutStockStream ;
        end;
        """
        got = run_app(ql, [
            ("cseEventStream", ("IBM", 75.6, 100)),
            ("cseEventStream", ("WSO2", 70005.6, 100)),
            ("cseEventStream", ("IBM", 75.6, 100)),
            ("cseEventStream", ("ORACLE", 75.6, 100)),
        ])
        assert len(got) == 3, got
        sums = [round(g[1], 3) for g in got]
        assert sums == [75.6, 151.2, 75.6], got

    def test_window_partition1_length_expired(self):
        # WindowPartitionTestCase.testWindowPartitionQuery1: per-key length(2)
        # expired events
        ql = """
        define stream cseEventStream (symbol string, price float, volume int);
        partition with (symbol of cseEventStream)
        begin
            @info(name = 'query1')
            from cseEventStream#window.length(2)
            select symbol, sum(price) as price, volume
            insert expired events into OutStockStream ;
        end;
        """
        from siddhi_tpu import SiddhiManager

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        removed = []
        rt.add_callback(
            "query1",
            lambda ts, i, r: removed.extend(tuple(e.data) for e in r or []),
        )
        rt.start()
        h = rt.get_input_handler("cseEventStream")
        for row in [
            ("IBM", 70.0, 100), ("WSO2", 700.0, 100), ("IBM", 100.0, 100),
            ("IBM", 200.0, 100), ("ORACLE", 75.6, 100), ("WSO2", 1000.0, 100),
            ("WSO2", 500.0, 100),
        ]:
            h.send(row)
        rt.shutdown()
        assert len(removed) == 2, removed
        # evicted IBM(70): per-key window now holds 100,200 -> sum 300 minus
        # the expiring 70 leaves the running value the reference reports
        assert round(removed[0][1], 1) == 100.0, removed
        assert round(removed[1][1], 1) == 1000.0, removed

    def test_window_partition2_length_batch(self):
        ql = """
        define stream cseEventStream (symbol string, price float, volume int);
        partition with (symbol of cseEventStream)
        begin
            @info(name = 'query1')
            from cseEventStream#window.lengthBatch(2)
            select symbol, sum(price) as price, volume
            insert all events into OutStockStream ;
        end;
        """
        got = run_app(ql, [
            ("cseEventStream", ("IBM", 70.0, 100)),
            ("cseEventStream", ("WSO2", 700.0, 100)),
            ("cseEventStream", ("IBM", 100.0, 100)),
            ("cseEventStream", ("IBM", 200.0, 100)),
            ("cseEventStream", ("WSO2", 1000.0, 100)),
        ])
        assert len(got) == 2, got
        assert round(got[0][1], 1) == 170.0, got
        assert round(got[1][1], 1) == 1700.0, got

    def test_pattern_partition_counts_per_key(self):
        # PatternPartitionTestCase.testPatternPartitionQuery1 analog: an
        # A->B chain completes only within one key's lane
        ql = """
        define stream Stream1 (symbol string, price float, volume int);
        partition with (symbol of Stream1)
        begin
            @info(name = 'query1')
            from every e1=Stream1[price>20] -> e2=Stream1[price>e1.price]
            select e1.price as price1, e2.price as price2
            insert into OutputStream ;
        end;
        """
        got = run_app(ql, [
            ("Stream1", ("IBM", 55.0, 100)),
            ("Stream1", ("WSO2", 85.0, 100)),
            ("Stream1", ("IBM", 75.0, 100)),   # completes IBM chain
            ("Stream1", ("WSO2", 65.0, 100)),  # below 85 -> WSO2 waits
        ])
        assert len(got) == 1, got
        assert round(got[0][0], 1) == 55.0 and round(got[0][1], 1) == 75.0, got


class TestPartitionInteriorGolden:
    def test_time_window_in_partition_playback(self):
        # WindowPartitionTestCase.testWindowPartitionQuery3 analog under the
        # playback clock: per-key time windows expire independently
        from siddhi_tpu import SiddhiManager

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""@app:playback
        define stream cseEventStream (symbol string, price float, volume int);
        partition with (symbol of cseEventStream)
        begin
            @info(name = 'query1')
            from cseEventStream#window.time(1 sec)
            select symbol, sum(price) as price
            insert all events into OutStockStream ;
        end;
        """)
        ins = []
        rt.add_callback(
            "query1", lambda ts, i, r: ins.extend(tuple(e.data) for e in i or [])
        )
        rt.start()
        h = rt.get_input_handler("cseEventStream")
        h.send(("IBM", 70.0, 100), timestamp=1000)
        h.send(("WSO2", 700.0, 100), timestamp=1100)
        h.send(("IBM", 100.0, 200), timestamp=1200)
        h.send(("IBM", 200.0, 300), timestamp=2300)   # IBM 70+100 expired
        h.send(("WSO2", 1000.0, 100), timestamp=2400)  # WSO2 700 expired
        rt.shutdown()
        mgr.shutdown()
        sums = [round(r[1], 1) for r in ins]
        assert sums == [70.0, 700.0, 170.0, 200.0, 1000.0], ins

    def test_table_write_in_partition(self):
        # TablePartitionTestCase analog: per-key queries write ONE shared table
        from siddhi_tpu import SiddhiManager

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (symbol string, price float);
        define table T (symbol string, price float);
        partition with (symbol of S)
        begin
            @info(name = 'q')
            from S[price > 10]
            select symbol, price
            insert into T;
        end;
        @info(name = 'reader')
        from S[price < 0] select symbol, price insert into Sink;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("IBM", 70.0))
        h.send(("WSO2", 700.0))
        h.send(("IBM", 5.0))    # filtered out
        h.send(("ORACLE", 30.0))
        rows = rt.query("from T select symbol, price")
        rt.shutdown()
        mgr.shutdown()
        got = sorted((e.data[0], round(e.data[1], 1)) for e in rows)
        assert got == [("IBM", 70.0), ("ORACLE", 30.0), ("WSO2", 700.0)], got

    def test_absent_pattern_in_partition(self):
        # per-key absent: only the key with no follow-up B emits.
        # Deterministic via the playback (event-time) clock with NO idle
        # heartbeat: the absent kill is decided device-side against event
        # time (B's ts 220 precedes IBM's deadline 350), and the deadline
        # TIMERs fire synchronously when the final event advances the
        # virtual clock past them. Wall-clock stamps raced both ways on
        # slow CPU backends: each partitioned vmapped dispatch costs tens
        # of wall-ms, so the 150 ms window could expire before B's send
        # was even processed (IBM's late-B emission then being CORRECT
        # absent2 semantics) — and with explicit past timestamps under the
        # wall-clock scheduler, the already-due deadline fired from the
        # scheduler thread before B's send landed.
        from siddhi_tpu import SiddhiManager

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:playback()
        define stream A (symbol string, price float);
        define stream B (symbol string, price float);
        partition with (symbol of A, symbol of B)
        begin
            @info(name = 'q')
            from e1=A[price>20] -> not B[price>20] for 150 milliseconds
            select e1.symbol as s
            insert into Out;
        end;
        """)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(tuple(e.data) for e in i or []))
        rt.start()
        ha, hb = rt.get_input_handler("A"), rt.get_input_handler("B")
        # warm both streams' compiled steps with inert rows
        ha.send(("W", 5.0), timestamp=100)
        hb.send(("W", 5.0), timestamp=110)
        ha.send(("IBM", 50.0), timestamp=200)    # deadline: 350
        ha.send(("WSO2", 60.0), timestamp=210)   # deadline: 360
        hb.send(("IBM", 90.0), timestamp=220)    # kills IBM's wait; WSO2's survives
        # advance the virtual clock past both deadlines: the event-time
        # scheduler fires the TIMERs synchronously before this send returns
        ha.send(("Z", 5.0), timestamp=1000)
        rt.shutdown()
        mgr.shutdown()
        assert got == [("WSO2",)], got
