"""Wire-format projection/narrowing for fused ingest (event.wire_codec)."""

from __future__ import annotations

import numpy as np

from siddhi_tpu import SiddhiManager


def test_projection_drops_unread_columns_and_shrinks_wire():
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime("""@app:batch(size='64')
    define stream S (symbol string, price float, volume long);
    @info(name='q') from S[price > 50] select symbol, price insert into Out;
    """)
    rt.start()
    fi = rt.junctions["S"].fused_ingest
    assert fi is not None
    fi._build()
    assert fi._keep is not None and "volume" not in fi._keep
    assert {"symbol", "price"} <= set(fi._keep)
    # wire: 4B ts-delta + 4B symbol + 4B price = 12B/event vs 24B packed
    assert fi._wire_bytes == 64 * 12
    rt.shutdown()
    mgr.shutdown()


def test_select_star_keeps_everything():
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime("""@app:batch(size='64')
    define stream S (symbol string, price float, volume long);
    @info(name='q') from S select * insert into Out;
    """)
    rt.start()
    fi = rt.junctions["S"].fused_ingest
    fi._build()
    assert fi._keep is None
    rt.shutdown()
    mgr.shutdown()


def test_wire_codec_roundtrip_with_dropped_column():
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime("""@app:batch(size='8')
    define stream S (symbol string, price float, volume long);
    @info(name='q') from S select symbol insert into Out;
    """)
    rt.start()
    schema = rt.junctions["S"].schema
    enc, dec, nb = schema.wire_codec(8, frozenset({"symbol"}))
    ts = np.arange(5, dtype=np.int64) + 1_700_000_000_000
    cols = {
        "symbol": np.arange(1, 6, dtype=np.int32),
        "price": np.ones(5, np.float32),
        "volume": np.ones(5, np.int64),
    }
    buf, base = enc(ts, cols, 5)
    b = dec(buf, np.int32(5), base)
    assert np.array_equal(np.asarray(b.ts[:5]), ts)
    assert np.array_equal(np.asarray(b.cols["symbol"][:5]), cols["symbol"])
    assert np.asarray(b.valid).sum() == 5
    # dropped columns exist with schema dtype (null-filled)
    assert b.cols["price"].shape == (8,)
    assert b.cols["volume"].shape == (8,)
    rt.shutdown()
    mgr.shutdown()
