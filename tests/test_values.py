"""Abstract-interpretation value analysis (analysis/values.py): the
lattice, the fixpoint (widening on cyclic insert-into graphs), fact
propagation through filters/selectors/windows, the SA135-SA138 lints, the
inferred wire hints that overlay `core/wire.py build_wire_spec`, the
cost-model selectivity refinement, and end-to-end runtime parity: an
UN-annotated app whose wire shrinks purely from inference must emit
byte-identical rows inference-on vs full-width."""

from __future__ import annotations

import glob
import os

import numpy as np
import pytest

from siddhi_tpu.analysis import analyze
from siddhi_tpu.analysis.symbols import build_symbols
from siddhi_tpu.analysis.values import (
    MAX_CONSTS,
    MAX_ROUNDS,
    TOP,
    ValueFact,
    analyze_values,
    fact_join,
    fact_widen,
    filter_selectivity,
    infer_wire_hints,
    infer_wire_hints_for_app,
)
from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
from siddhi_tpu.core.types import AttrType


def _va(ql: str):
    app = SiddhiCompiler.parse(ql)
    sym = build_symbols(app, [])
    return analyze_values(app, sym), sym


# ---------------------------------------------------------------------------
# lattice
# ---------------------------------------------------------------------------


class TestLattice:
    def test_join_interval_hull(self):
        a = ValueFact(lo=0, hi=10, nullable=False)
        b = ValueFact(lo=5, hi=20, nullable=True)
        j = fact_join(a, b)
        assert (j.lo, j.hi) == (0, 20)
        assert j.nullable is True  # nullable ORs

    def test_join_open_bound_absorbs(self):
        a = ValueFact(lo=0, hi=10)
        j = fact_join(a, ValueFact(lo=None, hi=5))
        assert j.lo is None and j.hi == 10

    def test_join_consts_union_and_cap(self):
        a = ValueFact(consts=frozenset(range(10)))
        b = ValueFact(consts=frozenset(range(5, 15)))
        assert fact_join(a, b).consts == frozenset(range(15))
        big = ValueFact(consts=frozenset(range(MAX_CONSTS)))
        other = ValueFact(consts=frozenset(range(MAX_CONSTS, 2 * MAX_CONSTS)))
        assert fact_join(big, other).consts is None  # cap collapses

    def test_join_monotone_ands(self):
        m = ValueFact(monotone=True)
        assert fact_join(m, m).monotone is True
        assert fact_join(m, TOP).monotone is False

    def test_widen_opens_moving_bounds(self):
        old = ValueFact(lo=0, hi=10)
        grown = ValueFact(lo=0, hi=12)
        w = fact_widen(old, grown)
        assert w.lo == 0 and w.hi is None  # still-moving hi opens
        stable = fact_widen(old, ValueFact(lo=0, hi=10))
        assert (stable.lo, stable.hi) == (0, 10)

    def test_contradiction(self):
        assert ValueFact(lo=5, hi=4).contradiction()
        assert ValueFact(consts=frozenset()).contradiction()
        assert not ValueFact(lo=4, hi=4).contradiction()

    def test_to_dict_omits_top_fields(self):
        assert TOP.to_dict() == {}
        d = ValueFact(lo=1, hi=2, nullable=False, monotone=True).to_dict()
        assert d == {"interval": [1, 2], "non_null": True, "monotone": True}


# ---------------------------------------------------------------------------
# fixpoint + widening
# ---------------------------------------------------------------------------


CYCLE_APP = """
define stream Seed (x int);
@info(name='seed') from Seed[x > 0 and x < 10] select x insert into Loop;
@info(name='grow') from Loop select x + 1 as x insert into Loop;
"""


class TestFixpoint:
    def test_cycle_terminates_via_widening(self):
        va, _sym = _va(CYCLE_APP)
        assert va.rounds < MAX_ROUNDS
        assert ("Loop", "x") in va.widened
        f = va.facts_for("Loop")["x"]
        assert f.hi is None  # the growing bound opened
        assert f.nullable is False  # non-null survives the cycle

    def test_analysis_is_deterministic(self):
        va1, _ = _va(CYCLE_APP)
        va2, _ = _va(CYCLE_APP)
        assert va1.domains_dict() == va2.domains_dict()
        assert va1.rewrites == va2.rewrites
        assert va1.lint_sites == va2.lint_sites


# ---------------------------------------------------------------------------
# propagation
# ---------------------------------------------------------------------------


class TestPropagation:
    def test_filter_interval_through_insert_into(self):
        va, _ = _va("""
        define stream Orders (sym string, price int);
        from Orders[price > 10 and price < 500]
        select sym, price insert into Mid;
        """)
        f = va.facts_for("Mid")["price"]
        assert (f.lo, f.hi) == (11, 499)
        assert f.nullable is False

    def test_declared_range_seeds_interval(self):
        va, _ = _va("""
        @app:wire(range.S.qty='0..30000')
        define stream S (qty long);
        from S select qty insert into Out;
        """)
        f = va.facts_for("S")["qty"]
        assert (f.lo, f.hi) == (0, 30000)
        assert va.facts_for("Out")["qty"].hi == 30000

    def test_declared_dict_seeds_cardinality(self):
        va, _ = _va("""
        @app:wire(dict.S.sym='64')
        define stream S (sym string);
        from S select sym insert into Out;
        """)
        assert va.facts_for("S")["sym"].card == 64

    def test_float_narrows_nullability_only(self):
        # exclusive-bound integer rounding is UNSOUND on floats: a filter
        # over a float attr must never manufacture an interval
        va, _ = _va("""
        define stream S (price float);
        from S[price > 10 and price < 5] select price insert into Out;
        """)
        f = va.facts_for("Out")["price"]
        assert f.lo is None and f.hi is None
        assert f.nullable is False
        # ... and the impossible-float-filter app carries NO SA135
        r = analyze("""
        define stream S (price float);
        from S[price > 10 and price < 5] select price insert into Out;
        """)
        assert not [d for d in r.warnings if d.code == "SA135"]

    def test_external_time_consumer_proves_monotone(self):
        va, _ = _va("""
        define stream Ticks (seq long, v int);
        from Ticks#window.externalTimeBatch(seq, 1000)
        select seq, v insert into Out;
        """)
        assert va.facts_for("Ticks")["seq"].monotone is True
        assert va.facts_for("Out")["seq"].monotone is True

    def test_group_by_kills_monotone(self):
        va, _ = _va("""
        define stream Ticks (seq long, v int);
        from Ticks#window.externalTimeBatch(seq, 1000)
        select seq, sum(v) as s group by seq insert into G;
        """)
        assert va.facts_for("G")["seq"].monotone is False

    def test_join_kills_monotone(self):
        va, _ = _va("""
        define stream A (seq long);
        define stream B (seq long);
        from A#window.externalTime(seq, 1000) select seq insert into MA;
        from MA#window.length(4) join B#window.length(4) on MA.seq == B.seq
        select MA.seq as seq insert into J;
        """)
        assert va.facts_for("MA")["seq"].monotone is True
        assert va.facts_for("J")["seq"].monotone is False

    def test_count_aggregator_fact(self):
        va, _ = _va("""
        define stream S (v int);
        from S#window.lengthBatch(8) select count() as c insert into Out;
        """)
        f = va.facts_for("Out")["c"]
        assert f.lo == 0 and f.nullable is False


# ---------------------------------------------------------------------------
# lints SA135-SA138
# ---------------------------------------------------------------------------


class TestLints:
    def test_sa135_location_and_severity(self):
        r = analyze(
            "define stream O (p int);\n"
            "from O[p > 10 and p < 5] select p insert into Out;\n"
        )
        (d,) = [d for d in r.diagnostics if d.code == "SA135"]
        assert d.severity == "warning"
        assert (d.line, d.col) == (2, 15)

    def test_sa136_on_decided_disjunct(self):
        r = analyze(
            "@app:wire(range.R.status='0..3')\n"
            "define stream R (status int, size int);\n"
            "from R[status == 7 or size > 0] select size insert into Out;\n"
        )
        (d,) = [d for d in r.diagnostics if d.code == "SA136"]
        assert "status == 7" in d.message and "always false" in d.message

    def test_sa137_overflow_and_div_by_zero(self):
        r = analyze(
            "@app:wire(range.M.a='0..2000000')\n"
            "define stream M (a int);\n"
            "from M select a * a as sq, 1 / (a - a) as bad insert into Out;\n"
        )
        codes = [d.code for d in r.diagnostics]
        assert codes.count("SA137") == 2

    def test_sa137_silent_on_unbounded(self):
        r = analyze(
            "define stream M (a int);\n"
            "from M select a * a as sq insert into Out;\n"
        )
        assert not [d for d in r.diagnostics if d.code == "SA137"]

    def test_sa133_downgrades_to_sa138_when_provable(self):
        # UN-provable dominant LONG: the actionable-annotation lint stays
        unprovable = analyze(
            "define stream Meters (seq long);\n"
            "from Meters[seq > 0] select seq insert into Out;\n"
        )
        assert [d.code for d in unprovable.warnings] == ["SA133"]
        # provably monotone via its externalTime consumer: SA138 instead
        provable = analyze(
            "define stream Ticks (seq long);\n"
            "from Ticks#window.externalTime(seq, 1000) "
            "select seq insert into Out;\n"
        )
        assert [d.code for d in provable.warnings] == ["SA138"]
        (d,) = provable.warnings
        assert "monotone" in d.message and "no annotation" in d.message


# ---------------------------------------------------------------------------
# inferred wire hints
# ---------------------------------------------------------------------------


class TestInferWireHints:
    def test_monotone_gives_delta(self):
        va, sym = _va("""
        define stream Ticks (seq long);
        from Ticks#window.externalTime(seq, 1000) select seq insert into Out;
        """)
        hints = infer_wire_hints(va, sym)
        assert hints[("Ticks", "seq")] == ("delta", np.dtype(np.int16))

    def test_const_set_gives_dict(self):
        va, sym = _va("""
        define stream S (status int);
        from S[status == 1 or status == 2] select status insert into T;
        """)
        hints = infer_wire_hints(va, sym)
        assert hints[("T", "status")] == ("dict", 2)

    def test_bounded_interval_gives_range(self):
        va, sym = _va("""
        define stream S (qty int);
        from S[qty >= 0 and qty <= 30000] select qty insert into T;
        """)
        hints = infer_wire_hints(va, sym)
        assert hints[("T", "qty")] == ("range", 0, 30000)

    def test_for_app_never_raises(self):
        # unknown stream: analysis still returns (empty or partial), no throw
        app = SiddhiCompiler.parse(
            "define stream S (a int);\n"
            "from Missing select a insert into Out;\n"
        )
        assert isinstance(infer_wire_hints_for_app(app), dict)


# ---------------------------------------------------------------------------
# selectivity refinement
# ---------------------------------------------------------------------------


class TestFilterSelectivity:
    def _pred(self, ql_pred: str):
        app = SiddhiCompiler.parse(
            "define stream S (x int, y float);\n"
            f"from S[{ql_pred}] select x insert into Out;\n"
        )
        q = app.execution_elements[0]
        return q.input_stream.handlers[0].expression

    def test_interval_overlap_ratio(self):
        facts = {"x": ValueFact(lo=0, hi=99, atype=AttrType.INT)}
        sel = filter_selectivity(self._pred("x < 50"), facts)
        assert sel == 0.5

    def test_provably_false_is_zero(self):
        facts = {"x": ValueFact(lo=0, hi=9, atype=AttrType.INT)}
        assert filter_selectivity(self._pred("x > 100"), facts) == 0.0

    def test_unbounded_returns_none(self):
        assert filter_selectivity(self._pred("x < 50"), {"x": TOP}) is None

    def test_cost_model_consumes_intervals(self):
        from siddhi_tpu.analysis.cost import compute_costs

        ql = """
        @app:wire(range.S.x='0..99')
        define stream S (x int);
        @info(name='q') from S[x < 50]#window.length(8)
        select x insert into Out;
        """
        app = SiddhiCompiler.parse(ql)
        sym = build_symbols(app, [])
        va = analyze_values(app, sym)
        with_facts = compute_costs(app, sym, values=va)
        declared_only = compute_costs(app, sym)
        bare = compute_costs(SiddhiCompiler.parse(
            ql.replace("@app:wire(range.S.x='0..99')\n", "")
        ))
        q1 = with_facts.queries["q"].est_selectivity
        qd = declared_only.queries["q"].est_selectivity
        q0 = bare.queries["q"].est_selectivity
        # filter factor 0.5 (50 of [0,99]) x sliding-window 2.0, vs the
        # flat 0.25 default; the declared range hint alone refines too —
        # no value analysis needed
        assert q1 == qd == 1.0
        assert q0 == 0.5


# ---------------------------------------------------------------------------
# wire-spec overlay (core/wire.py)
# ---------------------------------------------------------------------------


class TestWireSpecOverlay:
    def test_inferred_fills_unhinted_lane_declared_wins(self):
        from siddhi_tpu.core.wire import build_wire_spec

        attrs = [("seq", AttrType.LONG), ("qty", AttrType.LONG)]
        declared = {("S", "qty"): ("range", 0, 100)}
        inferred = {
            ("S", "seq"): ("delta", np.dtype(np.int16)),
            ("S", "qty"): ("range", 0, 10**9),  # must NOT override declared
        }
        spec = build_wire_spec("S", attrs, declared, 64, inferred)
        assert spec.encodings["seq"][0] == "delta"
        assert spec.encodings["qty"] == ("narrow", np.dtype(np.int8))
        assert spec.inferred_lanes == ["seq"]
        assert spec.source == "static+inferred"
        assert sorted(spec.to_dict()["inferred_lanes"]) == ["seq"]

    def test_pure_inference_source_label(self):
        from siddhi_tpu.core.wire import build_wire_spec

        spec = build_wire_spec(
            "S", [("seq", AttrType.LONG)], {}, 64,
            {("S", "seq"): ("delta", np.dtype(np.int16))},
        )
        assert spec.source == "inferred"

    def test_env_kill_switch(self, monkeypatch):
        from siddhi_tpu.core import wire as W

        monkeypatch.setenv(W.WIRE_INFER_ENV, "0")
        assert not W.wire_inference_enabled()
        app = SiddhiCompiler.parse("""
        define stream Ticks (seq long);
        from Ticks#window.externalTime(seq, 1000) select seq insert into Out;
        """)
        sym = build_symbols(app, [])
        va = analyze_values(app, sym)
        _dis, specs = W.app_wire_specs(
            app, sym.streams, ["Ticks"], 64,
            inferred=infer_wire_hints(va, sym),
        )
        _attrs, spec = specs["Ticks"]
        # inference off + no declared hints: nothing statically encodable
        assert spec is None


# ---------------------------------------------------------------------------
# declared-vs-inferred agreement sweep
# ---------------------------------------------------------------------------


CORPUS = sorted(glob.glob(os.path.join(
    os.path.dirname(__file__), "analysis_corpus", "*.siddhi"
)))


class TestAgreementSweep:
    @pytest.mark.parametrize(
        "path", CORPUS, ids=[os.path.basename(p)[:-7] for p in CORPUS]
    )
    def test_declared_lanes_inferred_or_explicitly_unprovable(self, path):
        from siddhi_tpu.core.wire import parse_wire_hints
        from siddhi_tpu.query_api.annotation import find_annotation

        try:
            app = SiddhiCompiler.parse(open(path).read())
        except Exception:
            pytest.skip("corpus app does not parse")
        hints = parse_wire_hints(find_annotation(app.annotations, "app:wire"))
        sym = build_symbols(app, [])
        va = analyze_values(app, sym)
        inferred = infer_wire_hints(va, sym)
        unprovable = {(u["stream"], u["attr"]) for u in va.unprovable}
        for (sid, col), _hint in hints.items():
            assert (sid, col) in inferred or (sid, col) in unprovable, (
                f"{path}: declared lane {sid}.{col} neither re-inferred "
                f"nor recorded unprovable"
            )


# ---------------------------------------------------------------------------
# plan + rewrites integration
# ---------------------------------------------------------------------------


class TestPlanIntegration:
    def test_dead_column_prune_rewrite(self):
        from siddhi_tpu.analysis import build_fusion_plan

        plan = build_fusion_plan("""
        define stream S (a int, b int, c int);
        from S[a > 0] select a insert into Out;
        """).to_dict()
        (prune,) = [
            r for r in plan["rewrites"] if r["kind"] == "prune-dead-columns"
        ]
        assert prune["stream"] == "S"
        assert prune["columns"] == ["b", "c"]
        assert plan["wire"]["S"]["pruned"] == ["b", "c"]

    def test_plan_json_byte_stable(self):
        from siddhi_tpu.analysis import build_fusion_plan

        ql = """
        @app:wire(range.S.qty='0..30000')
        define stream S (sym string, qty long);
        from S[qty > 10 and qty > 5] select sym, qty insert into Mid;
        from Mid select sym insert into Out;
        """
        assert build_fusion_plan(ql).to_json() == build_fusion_plan(
            ql
        ).to_json()

    def test_explain_carries_rewrites(self):
        from siddhi_tpu.observability.explain import explain_static

        app = SiddhiCompiler.parse(
            "define stream O (p int);\n"
            "from O[p > 10 and p < 5] select p insert into Out;\n"
        )
        plan = explain_static(app, fmt="dict")
        kinds = {r["kind"] for r in plan["fusion"]["rewrites"]}
        assert "unreachable-filter" in kinds
        assert "rewrites (value analysis):" in explain_static(app)


# ---------------------------------------------------------------------------
# runtime parity: inference-on vs full-width, un-annotated app
# ---------------------------------------------------------------------------


INFER_APP = """
define stream Meters (seq long, v float);
@info(name='q') from Meters#window.externalTimeBatch(seq, 64)
select seq, v insert into Out;
"""


def _run_infer(env: dict, n=512):
    from siddhi_tpu import SiddhiManager

    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("@app:batch(size='64')\n" + INFER_APP)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    rows = []
    rt.add_callback("q", lambda t, ins, rem: rows.extend(
        tuple(e.data) for e in (ins or [])
    ))
    rt.start()
    ts = np.arange(n, dtype=np.int64) + 1_700_000_000_000
    cols = {
        "seq": np.arange(n, dtype=np.int64) + 10**12,
        "v": np.linspace(0, 10, n).astype(np.float32),
    }
    rt.get_input_handler("Meters").send_columns(ts, cols, now=int(ts[-1]))
    fi = rt.junctions["Meters"].fused_ingest
    wire_bytes = fi._wire_bytes if fi else None
    rt.shutdown()
    mgr.shutdown()
    return rows, wire_bytes


class TestRuntimeParity:
    def test_unannotated_inference_parity_and_shrink(self):
        on_rows, on_bytes = _run_infer(
            {"SIDDHI_TPU_WIRE": "1", "SIDDHI_TPU_WIRE_INFER": "1"}
        )
        off_rows, off_bytes = _run_infer({"SIDDHI_TPU_WIRE": "0"})
        assert on_rows == off_rows and on_rows
        assert on_bytes is not None and off_bytes is not None
        assert on_bytes < off_bytes  # the wire shrank with ZERO annotations

    def test_infer_kill_switch_still_parity(self):
        on_rows, _ = _run_infer(
            {"SIDDHI_TPU_WIRE": "1", "SIDDHI_TPU_WIRE_INFER": "0"}
        )
        off_rows, _ = _run_infer({"SIDDHI_TPU_WIRE": "0"})
        assert on_rows == off_rows and on_rows
