"""Named window + trigger end-to-end tests.

Reference semantics: core/window/Window.java (shared named windows),
core/trigger/ (PeriodicTrigger/StartTrigger/CronTrigger), and the
WindowTestCase / TriggerTestCase suites under
modules/siddhi-core/src/test/java/org/wso2/siddhi/core/.
"""

import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.utils.cron import CronSchedule


def build(ql):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    rt.start()
    return mgr, rt


class TestNamedWindow:
    def test_shared_window_two_readers(self):
        mgr, rt = build("""
        define stream S (symbol string, price float);
        define window W (symbol string, price float) length(2) output all events;
        from S insert into W;
        @info(name='sum')
        from W select sum(price) as total insert into T1;
        @info(name='count')
        from W select count() as n insert into T2;
        """)
        sums, counts = [], []
        rt.add_callback("sum", lambda ts, ins, rem: sums.extend(e.data for e in ins or []))
        rt.add_callback("count", lambda ts, ins, rem: counts.extend(e.data for e in ins or []))
        h = rt.get_input_handler("S")
        h.send(("WSO2", 10.0), timestamp=1)
        h.send(("IBM", 20.0), timestamp=2)
        h.send(("GOOG", 30.0), timestamp=3)  # evicts WSO2 from the length(2) window
        # running sum over window content: 10, 30, (30-10+30)=50
        assert sums == [(10.0,), (30.0,), (50.0,)]
        assert counts == [(1,), (2,), (2,)]
        rt.shutdown()
        mgr.shutdown()

    def test_current_events_only_window(self):
        mgr, rt = build("""
        define stream S (symbol string, price float);
        define window W (symbol string, price float) length(2) output current events;
        from S insert into W;
        @info(name='q')
        from W select sum(price) as total insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in ins or []))
        h = rt.get_input_handler("S")
        h.send(("A", 10.0), timestamp=1)
        h.send(("B", 20.0), timestamp=2)
        h.send(("C", 30.0), timestamp=3)  # expired A is suppressed by the window
        # without expired events the downstream sum only ever adds
        assert got == [(10.0,), (30.0,), (60.0,)]
        rt.shutdown()
        mgr.shutdown()

    def test_join_stream_with_named_window(self):
        mgr, rt = build("""
        define stream S (symbol string, price float);
        define stream Check (company string);
        define window W (symbol string, price float) length(10) output all events;
        from S insert into W;
        @info(name='q')
        from Check join W on Check.company == W.symbol
        select company, W.price as price insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in ins or []))
        rt.get_input_handler("S").send(("WSO2", 55.5), timestamp=1)
        rt.get_input_handler("S").send(("IBM", 75.5), timestamp=2)
        rt.get_input_handler("Check").send(("WSO2",), timestamp=3)
        assert got == [("WSO2", 55.5)]
        rt.shutdown()
        mgr.shutdown()

    def test_window_side_triggers_join(self):
        # the named window is an ACTIVE join side: its insertions probe the
        # other side (reference: WindowWindowProcessor join wiring)
        mgr, rt = build("""
        define stream S (symbol string, price float);
        define stream Check (company string);
        define window W (symbol string, price float) length(10) output all events;
        from S insert into W;
        @info(name='q')
        from Check#window.length(5) join W on Check.company == W.symbol
        select company, W.price as price insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in ins or []))
        rt.get_input_handler("Check").send(("WSO2",), timestamp=1)
        rt.get_input_handler("S").send(("WSO2", 55.5), timestamp=2)
        assert got == [("WSO2", 55.5)]
        rt.shutdown()
        mgr.shutdown()

    def test_store_query_over_window(self):
        mgr, rt = build("""
        define stream S (symbol string, price float);
        define window W (symbol string, price float) length(2) output all events;
        from S insert into W;
        """)
        h = rt.get_input_handler("S")
        h.send(("A", 10.0), timestamp=1)
        h.send(("B", 20.0), timestamp=2)
        h.send(("C", 30.0), timestamp=3)
        rows = rt.query("from W select symbol, price")
        assert [e.data for e in rows] == [("B", 20.0), ("C", 30.0)]
        rt.shutdown()
        mgr.shutdown()


class TestTrigger:
    def test_start_trigger(self):
        mgr, rt = build("""
        define trigger T at 'start';
        """)
        # trigger streams are plain streams: subscribe a stream callback
        got = []
        rt.add_callback("T", lambda events: got.extend(e.data for e in events))
        # 'start' already fired inside build(); re-create with callback first
        rt.shutdown()
        mgr2 = SiddhiManager()
        rt2 = mgr2.create_siddhi_app_runtime("define trigger T at 'start';")
        got2 = []
        rt2.add_callback("T", lambda events: got2.extend(e.data for e in events))
        rt2.start()
        assert len(got2) == 1 and isinstance(got2[0][0], int)
        rt2.shutdown()
        mgr.shutdown()
        mgr2.shutdown()

    def test_periodic_trigger(self):
        mgr, rt = build("""
        define stream Any (x int);
        define trigger T at every 100 milliseconds;
        """)
        got = []
        rt.add_callback("T", lambda events: got.extend(e.data for e in events))
        t0 = time.time()
        while len(got) < 2 and time.time() - t0 < 5.0:
            time.sleep(0.05)
        assert len(got) >= 2
        rt.shutdown()
        mgr.shutdown()

    def test_trigger_feeds_query(self):
        mgr, rt = build("""
        define trigger T at every 100 milliseconds;
        @info(name='q')
        from T select triggered_time insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in ins or []))
        t0 = time.time()
        while len(got) < 2 and time.time() - t0 < 5.0:
            time.sleep(0.05)
        assert len(got) >= 2
        rt.shutdown()
        mgr.shutdown()


class TestCron:
    def test_every_five_seconds(self):
        c = CronSchedule("*/5 * * * * ?")
        t0 = 1_700_000_000_000  # any epoch
        t1 = c.next_fire_ms(t0)
        assert 0 < t1 - t0 <= 5000 and (t1 // 1000) % 5 == 0

    def test_specific_minute(self):
        c = CronSchedule("0 30 * * * ?")
        import datetime

        base = datetime.datetime(2026, 7, 30, 10, 15, 0)
        t = c.next_fire_ms(int(base.timestamp() * 1000))
        fired = datetime.datetime.fromtimestamp(t / 1000)
        assert fired.minute == 30 and fired.second == 0 and fired.hour == 10

    def test_five_field_form(self):
        c = CronSchedule("*/10 * * * *")  # plain cron: every 10 min at :00s
        import datetime

        base = datetime.datetime(2026, 7, 30, 10, 3, 20)
        t = c.next_fire_ms(int(base.timestamp() * 1000))
        fired = datetime.datetime.fromtimestamp(t / 1000)
        assert fired.minute == 10 and fired.second == 0

    def test_day_of_week(self):
        c = CronSchedule("0 0 9 ? * MON")
        import datetime

        base = datetime.datetime(2026, 7, 30, 10, 0, 0)  # a Thursday
        t = c.next_fire_ms(int(base.timestamp() * 1000))
        fired = datetime.datetime.fromtimestamp(t / 1000)
        assert fired.weekday() == 0 and fired.hour == 9  # next Monday 09:00
