"""Multi-chip correctness: a partitioned app must produce IDENTICAL outputs
with its [P] partition axis sharded over an 8-device mesh and unsharded.

VERDICT r2 item 3: liveness (the dryrun) is not a correctness contract; this
runs 60+ steps with more keys than devices and key churn (keys appearing,
disappearing, and crossing shard boundaries as slots allocate) and compares
every emitted row. Reference contract: the per-key isolated query graphs of
PartitionRuntime.java:256-315 — outputs may not depend on WHERE a key's
partition lives."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

QL = """@app:batch(size='64')
@app:partitionCapacity(size='32')
define stream S (symbol string, price float, volume long);
partition with (symbol of S)
begin
    @info(name='q')
    from S[price > 0]#window.length(8)
    select symbol, sum(volume) as total, avg(price) as ap
    insert into Out;
end;
"""


def _build():
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(QL)
    rt.start()
    return mgr, rt, rt.queries["q"]


def _batches(n_steps=60, bsz=64, seed=11):
    """Key churn: early steps use keys 1..6, middle steps rotate through
    1..20 (over the 8 'devices'), late steps revisit early keys."""
    rng = np.random.default_rng(seed)
    out = []
    for s in range(n_steps):
        if s < 15:
            pool = np.arange(1, 7)
        elif s < 40:
            pool = np.arange(1 + (s % 5) * 4, 1 + (s % 5) * 4 + 8)
        else:
            pool = np.arange(1, 21)
        ts = np.arange(bsz, dtype=np.int64) + 1_700_000_000_000 + s * bsz
        cols = {
            "symbol": rng.choice(pool, size=bsz).astype(np.int32),
            "price": rng.uniform(1.0, 100.0, size=bsz).astype(np.float32),
            "volume": rng.integers(1, 100, size=bsz).astype(np.int64),
        }
        out.append((ts, cols))
    return out


def _run(qr, mgr, sharded: bool, feed):
    from siddhi_tpu.core.event import EventBatch

    schema = qr.in_schema
    if sharded:
        from jax.sharding import Mesh

        from siddhi_tpu.parallel.mesh import shard_partitioned_query

        mesh = Mesh(np.asarray(jax.devices()[:8]), ("part",))
        sq = shard_partitioned_query(qr, mesh)
        step = sq.step
    else:
        import jax.numpy as jnp

        fn = jax.jit(qr._pstep_outer_impl)
        state = qr._fresh(qr.init_state())
        ptable = {
            "keys": jnp.zeros((qr.p,), jnp.int64),
            "used": jnp.zeros((qr.p,), jnp.bool_),
            "n": jnp.zeros((), jnp.int32),
        }

        def step(batch, now, _box=[ptable, state]):
            _box[0], _box[1], outs, aux = fn(_box[0], _box[1], batch, np.int64(now))
            return outs, aux

    rows = []
    for ts, cols in feed:
        batch = schema.to_batch_cols(ts, cols, mgr.interner, capacity=64)
        outs, _aux = step(batch, int(ts[-1]))
        v = np.asarray(outs.valid)
        ts_a = np.asarray(outs.ts)
        cols_a = {c: np.asarray(a) for c, a in outs.cols.items()}
        step_rows = sorted(
            (int(ts_a[i]), *(cols_a[c][i].item() for c in cols_a))
            for i in map(tuple, np.argwhere(v))
        )
        rows.append(step_rows)
    return rows


def test_sharded_matches_unsharded_over_key_churn():
    feed = _batches()
    mgr1, rt1, qr1 = _build()
    unsharded = _run(qr1, mgr1, sharded=False, feed=feed)
    rt1.shutdown()
    mgr1.shutdown()

    mgr2, rt2, qr2 = _build()
    sharded = _run(qr2, mgr2, sharded=True, feed=feed)
    rt2.shutdown()
    mgr2.shutdown()

    assert len(unsharded) == len(sharded) == len(feed)
    n_rows = sum(len(r) for r in unsharded)
    assert n_rows > 1000, f"feed produced too few outputs ({n_rows}) to be meaningful"
    for i, (a, b) in enumerate(zip(unsharded, sharded)):
        assert a == b, f"step {i}: sharded output diverged"
