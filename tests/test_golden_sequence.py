"""Golden corpus: sequences, translated from the reference test data
(reference: siddhi-core/src/test/java/org/wso2/siddhi/core/query/sequence/
SequenceTestCase.java — data-level translation)."""

from tests.test_golden_count import assert_rows, run_app

S12 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""


class TestSequenceGolden:
    def test_query1(self):
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price>20],e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
        ])
        assert_rows(got, [("WSO2", "IBM")])

    def test_query2(self):
        # strict continuity: the WSO2 chain is broken by GOOG, which itself
        # starts the chain that completes
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream1[price>20], e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream1", ("GOOG", 57.6, 100)),
            ("Stream2", ("IBM", 65.7, 100)),
        ])
        assert_rows(got, [("GOOG", "IBM")])

    def test_query3(self):
        # trailing Kleene star emits immediately with zero captures
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream1[price>20], e2=Stream2[price>e1.price]*
        select e1.symbol as symbol1, e2[0].symbol as symbol2, e2[1].symbol as symbol3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream1", ("IBM", 55.7, 100)),
        ])
        assert_rows(got, [("WSO2", None, None), ("IBM", None, None)])

    def test_query4(self):
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream2[price>20]*, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e1[1].price as price2, e2.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 59.6, 100)),
            ("Stream2", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
            ("Stream1", ("WSO2", 57.6, 100)),
        ])
        assert_rows(got, [(55.6, 55.7, 57.6)])

    def test_query5(self):
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream2[price>20]*, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e1[1].price as price2, e2.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 59.6, 100)),
            ("Stream2", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 55.0, 100)),
            ("Stream1", ("WSO2", 57.6, 100)),
        ])
        assert_rows(got, [(55.6, 55.0, 57.6)])

    def test_query6(self):
        # optional (?): an overfull side kills the chain; every re-arms on the
        # killing event
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream2[price>20]?, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e2.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 59.6, 100)),
            ("Stream2", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
            ("Stream1", ("WSO2", 57.6, 100)),
        ])
        assert_rows(got, [(55.7, 57.6)])

    def test_query7(self):
        # sequence with or: chains re-arm per event
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream2[price>20], e2=Stream2[price>e1.price] or e3=Stream2[symbol=='IBM']
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream2", ("WSO2", 59.6, 100)),
            ("Stream2", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
            ("Stream2", ("WSO2", 57.6, 100)),
        ])
        assert len(got) == 2, got
        assert_rows(got, [(55.6, 55.7, None), (55.7, 57.6, None)])

    def test_query10(self):
        # Kleene plus inside every with strict continuity
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream2[price>20]+, e2=Stream1[price>e1[0].price]
        select e1[0].price as price1, e1[1].price as price2, e2.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 59.6, 100)),
            ("Stream2", ("WSO2", 55.6, 100)),
            ("Stream1", ("WSO2", 57.6, 100)),
        ])
        assert_rows(got, [(55.6, None, 57.6)])

    def test_query11(self):
        # self-referential count condition (e2[last] inside e2's own filter):
        # rising run then a fall
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream1[price>20],
           e2=Stream1[(e2[last].price is null and price>=e1.price) or ((not (e2[last].price is null)) and price>=e2[last].price)]+,
           e3=Stream1[price<e2[last].price]
        select e1.price as price1, e2[0].price as price2, e2[1].price as price3, e3.price as price4
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 29.6, 100)),
            ("Stream1", ("WSO2", 35.6, 100)),
            ("Stream1", ("WSO2", 57.6, 100)),
            ("Stream1", ("IBM", 47.6, 100)),
        ])
        assert_rows(got, [(29.6, 35.6, 57.6, 47.6)])
