"""Golden corpus: every patterns, translated from the reference test data
(reference: siddhi-core/src/test/java/org/wso2/siddhi/core/query/pattern/
EveryPatternTestCase.java — data-level translation of queries, inputs, and
expected outputs)."""

from tests.test_golden_count import assert_rows, run_app

S12 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""

S12B = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price1 float, volume int);
"""


class TestEveryPatternGolden:
    def test_query1(self):
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price>20] -> e2=Stream2[price>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
        ])
        assert_rows(got, [("WSO2", "IBM")])

    def test_query2(self):
        # without every: only the FIRST e1 arms the single token
        ql = S12B + """
        @info(name = 'query1')
        from e1=Stream1[price>20] -> e2=Stream2[price1>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream1", ("GOOG", 55.6, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
        ])
        assert_rows(got, [("WSO2", "IBM")])

    def test_query3(self):
        # every e1: a chain per e1 match, both fire on the same e2
        ql = S12B + """
        @info(name = 'query1')
        from every e1=Stream1[price>20] -> e2=Stream2[price1>e1.price]
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream1", ("GOOG", 55.6, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
        ])
        assert len(got) == 2 and set(got) == {("WSO2", "IBM"), ("GOOG", "IBM")}, got

    def test_query4(self):
        # every (e1 -> e3): serial block, one completion before e2
        ql = S12 + """
        @info(name = 'query1')
        from every ( e1=Stream1[price>20] -> e3=Stream1[price>20]) -> e2=Stream2[price>e1.price]
        select e1.price as price1, e3.price as price3, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream1", ("GOOG", 54.0, 100)),
            ("Stream2", ("IBM", 57.7, 100)),
        ])
        assert_rows(got, [(55.6, 54.0, 57.7)])

    def test_query5(self):
        # every (e1 -> e3): matches are strictly serial (NOT per-event forks)
        ql = S12 + """
        @info(name = 'query1')
        from every ( e1=Stream1[price>20] -> e3=Stream1[price>20]) -> e2=Stream2[price>e1.price]
        select e1.price as price1, e3.price as price3, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream1", ("GOOG", 54.0, 100)),
            ("Stream1", ("WSO2", 53.6, 100)),
            ("Stream1", ("GOOG", 53.0, 100)),
            ("Stream2", ("IBM", 57.7, 100)),
        ])
        assert len(got) == 2, got
        assert_rows(sorted(got), sorted([(55.6, 54.0, 57.7), (53.6, 53.0, 57.7)]))

    def test_query6(self):
        # prefix state + every block in the middle: re-arm keeps e4's capture
        ql = S12 + """
        @info(name = 'query1')
        from e4=Stream1[symbol=='MSFT'] -> every ( e1=Stream1[price>20] -> e3=Stream1[price>20]) ->
           e2=Stream2[price>e1.price]
        select e1.price as price1, e3.price as price3, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("MSFT", 55.6, 100)),
            ("Stream1", ("WSO2", 55.7, 100)),
            ("Stream1", ("GOOG", 54.0, 100)),
            ("Stream1", ("WSO2", 53.6, 100)),
            ("Stream1", ("GOOG", 53.0, 100)),
            ("Stream2", ("IBM", 57.7, 100)),
        ])
        assert len(got) == 2, got
        assert_rows(sorted(got), sorted([(55.7, 54.0, 57.7), (53.6, 53.0, 57.7)]))

    def test_query7(self):
        # whole pattern is one every block: serial non-overlapping pairs
        ql = S12 + """
        @info(name = 'query1')
        from  every ( e1=Stream1[price>20] -> e3=Stream1[price>20])
        select e1.price as price1, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("MSFT", 55.6, 100)),
            ("Stream1", ("WSO2", 57.6, 100)),
            ("Stream1", ("GOOG", 54.0, 100)),
            ("Stream1", ("WSO2", 53.6, 100)),
        ])
        assert_rows(got, [(55.6, 57.6), (54.0, 53.6)])

    def test_query8(self):
        # every over a single state: every match emits
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream1[price>20]
        select e1.price as price1
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("MSFT", 55.6, 100)),
            ("Stream1", ("WSO2", 57.6, 100)),
        ])
        assert_rows(got, [(55.6,), (57.6,)])
