"""Pattern/sequence NFA tests, mirroring the reference corpus semantics
(reference: siddhi-core/src/test/java/org/wso2/siddhi/core/query/pattern/
{EveryPatternTestCase,CountPatternTestCase,LogicalPatternTestCase,
WithinPatternTestCase,absent/*}.java and query/sequence/*.java)."""

import time

import pytest

from siddhi_tpu import SiddhiManager


def run_app(ql, sends, query_name="query1", wait_timers=0.0):
    """sends: list of (stream, [(data...), ...]) pushed in order."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    got = []

    def cb(ts, ins, removed):
        for e in ins or []:
            got.append(tuple(e.data))

    rt.add_callback(query_name, cb)
    rt.start()
    handlers = {}
    for stream, rows in sends:
        h = handlers.setdefault(stream, rt.get_input_handler(stream))
        for row in rows:
            h.send(row)
    if wait_timers:
        time.sleep(wait_timers)
    rt.shutdown()
    return got


S2 = """
define stream StreamA (symbol string, price float, volume int);
define stream StreamB (symbol string, price float, volume int);
"""


class TestPattern:
    def test_simple_pattern(self):
        ql = S2 + """
        @info(name = 'query1')
        from e1=StreamA[price > 20] -> e2=StreamB[price > e1.price]
        select e1.symbol as sym1, e2.symbol as sym2, e2.price as price2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("IBM", 25.0, 100)]),
            ("StreamB", [("WSO2", 20.0, 100)]),   # not > 25 — no match
            ("StreamB", [("GOOG", 30.0, 100)]),
        ])
        assert got == [("IBM", "GOOG", 30.0)]

    def test_pattern_without_every_matches_once(self):
        ql = S2 + """
        @info(name = 'query1')
        from e1=StreamA -> e2=StreamB
        select e1.volume as v1, e2.volume as v2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("A", 1.0, 1)]),
            ("StreamB", [("B", 1.0, 2)]),
            ("StreamA", [("A", 1.0, 3)]),
            ("StreamB", [("B", 1.0, 4)]),
        ])
        assert got == [(1, 2)]

    def test_every_rearms(self):
        ql = S2 + """
        @info(name = 'query1')
        from every e1=StreamA -> e2=StreamB
        select e1.volume as v1, e2.volume as v2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("A", 1.0, 1)]),
            ("StreamB", [("B", 1.0, 2)]),
            ("StreamA", [("A", 1.0, 3)]),
            ("StreamB", [("B", 1.0, 4)]),
        ])
        assert got == [(1, 2), (3, 4)]

    def test_every_two_pending(self):
        # two A's before a B: both tokens match the B
        ql = S2 + """
        @info(name = 'query1')
        from every e1=StreamA -> e2=StreamB
        select e1.volume as v1, e2.volume as v2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("A", 1.0, 1), ("A", 1.0, 2)]),
            ("StreamB", [("B", 1.0, 9)]),
        ])
        assert sorted(got) == [(1, 9), (2, 9)]

    def test_same_stream_chain(self):
        # A -> A on the same stream: in-batch sequencing via scan
        ql = S2 + """
        @info(name = 'query1')
        from every e1=StreamA[price > 20] -> e2=StreamA[price > e1.price]
        select e1.price as p1, e2.price as p2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("A", 25.0, 1), ("A", 30.0, 2), ("A", 10.0, 3)]),
        ])
        assert (25.0, 30.0) in got

    def test_logical_and(self):
        ql = S2 + """
        @info(name = 'query1')
        from e1=StreamA and e2=StreamB
        select e1.volume as v1, e2.volume as v2
        insert into OutStream;
        """
        # arrives in either order
        got = run_app(ql, [
            ("StreamB", [("B", 1.0, 7)]),
            ("StreamA", [("A", 1.0, 5)]),
        ])
        assert got == [(5, 7)]

    def test_logical_or_null_side(self):
        ql = S2 + """
        @info(name = 'query1')
        from e1=StreamA or e2=StreamB
        select e1.volume as v1, e2.volume as v2
        insert into OutStream;
        """
        got = run_app(ql, [("StreamB", [("B", 1.0, 7)])])
        assert got == [(None, 7)]

    def test_count_pattern(self):
        ql = S2 + """
        @info(name = 'query1')
        from e1=StreamA<2:4> -> e2=StreamB
        select e1[0].volume as c0, e1[1].volume as c1, e2.volume as v2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("A", 1.0, 1)]),
            ("StreamB", [("B", 1.0, 9)]),   # only 1 A so far — no match
            ("StreamA", [("A", 1.0, 2)]),
            ("StreamB", [("B", 1.0, 10)]),
        ])
        assert got == [(1, 2, 10)]

    def test_count_absorbs_up_to_max_then_waits(self):
        ql = S2 + """
        @info(name = 'query1')
        from e1=StreamA<1:2> -> e2=StreamB
        select e1[0].volume as c0, e1[1].volume as c1, e2.volume as v2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("A", 1.0, 1), ("A", 1.0, 2), ("A", 1.0, 3)]),
            ("StreamB", [("B", 1.0, 9)]),
        ])
        # max 2: third A is not absorbed
        assert got == [(1, 2, 9)]

    def test_within_expires(self):
        ql = S2 + """
        @info(name = 'query1')
        from every e1=StreamA -> e2=StreamB within 1 sec
        select e1.volume as v1, e2.volume as v2
        insert into OutStream;
        """
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        got = []
        rt.add_callback("query1", lambda ts, ins, rm: got.extend(
            tuple(e.data) for e in ins or []))
        rt.start()
        ha = rt.get_input_handler("StreamA")
        hb = rt.get_input_handler("StreamB")
        t0 = 1_700_000_000_000
        ha.send(("A", 1.0, 1), timestamp=t0)
        hb.send(("B", 1.0, 2), timestamp=t0 + 2000)  # too late
        ha.send(("A", 1.0, 3), timestamp=t0 + 3000)
        hb.send(("B", 1.0, 4), timestamp=t0 + 3500)  # in time
        rt.shutdown()
        assert got == [(3, 4)]

    def _absent_app(self):
        """Build the absent-pattern app with all steps pre-compiled, so
        real-time deadlines are not raced by jit compile latency."""
        ql = S2 + """
        @info(name = 'query1')
        from e1=StreamA[volume == 5] -> not StreamB for 300 milliseconds
        select e1.volume as v1
        insert into OutStream;
        """
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        got = []
        rt.add_callback("query1", lambda ts, ins, rm: got.extend(
            tuple(e.data) for e in ins or []))
        rt.start()
        ha = rt.get_input_handler("StreamA")
        hb = rt.get_input_handler("StreamB")
        ha.send(("warm", 1.0, 0))   # filtered out — compiles the A step
        hb.send(("warm", 1.0, 0))   # no armed token — compiles the B step
        qr = rt.queries["query1"]
        # compile the timer step (t=0: fires nothing); the step donates the
        # state buffers, so the returned state must replace the old one
        qr.state, _ts, _out, _aux = qr._timer_step(
            qr.state, qr._collect_table_states(),
            __import__("siddhi_tpu.core.app_runtime",
                       fromlist=["_pattern_timer_batch"])._pattern_timer_batch(0),
            0)
        return rt, ha, hb, got

    @staticmethod
    def _poll(got, n, timeout=5.0):
        t0 = time.time()
        while len(got) < n and time.time() - t0 < timeout:
            time.sleep(0.05)

    def test_absent_emits_on_timeout(self):
        rt, ha, hb, got = self._absent_app()
        ha.send(("A", 1.0, 5))
        self._poll(got, 1)
        rt.shutdown()
        assert got == [(5,)]

    def test_absent_killed_by_arrival(self):
        rt, ha, hb, got = self._absent_app()
        ha.send(("A", 1.0, 5))
        hb.send(("B", 1.0, 1))
        time.sleep(0.8)
        rt.shutdown()
        assert got == []


class TestSequence:
    def test_strict_sequence(self):
        ql = S2 + """
        @info(name = 'query1')
        from every e1=StreamA, e2=StreamA
        select e1.volume as v1, e2.volume as v2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("A", 1.0, 1), ("A", 1.0, 2), ("A", 1.0, 3)]),
        ])
        # consecutive pairs; e2 of one match can be e1 of the next (every)
        assert (1, 2) in got and (2, 3) in got

    def test_sequence_broken_by_intermediate(self):
        ql = S2 + """
        @info(name = 'query1')
        from e1=StreamA[volume == 1], e2=StreamA[volume == 3]
        select e1.volume as v1, e2.volume as v2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("A", 1.0, 1), ("A", 1.0, 2), ("A", 1.0, 3)]),
        ])
        assert got == []  # volume 2 breaks consecutiveness

    def test_kleene_plus(self):
        ql = S2 + """
        @info(name = 'query1')
        from every e1=StreamA[price > 20]+, e2=StreamB
        select e1[0].price as p0, e2.volume as v2
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("A", 25.0, 1), ("A", 30.0, 2)]),
            ("StreamB", [("B", 1.0, 9)]),
        ])
        assert got == [(25.0, 9)]


class TestPatternAggregation:
    def test_pattern_with_group_by(self):
        ql = S2 + """
        @info(name = 'query1')
        from every e1=StreamA -> e2=StreamB
        select e1.symbol as symbol, sum(e2.volume) as total
        group by e1.symbol
        insert into OutStream;
        """
        got = run_app(ql, [
            ("StreamA", [("IBM", 1.0, 1)]),
            ("StreamB", [("X", 1.0, 10)]),
            ("StreamA", [("IBM", 1.0, 2)]),
            ("StreamB", [("X", 1.0, 5)]),
        ])
        assert got[-1] == ("IBM", 15)
