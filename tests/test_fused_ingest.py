"""Fused mega-batch ingest (core/ingest.py) must be observationally identical
to the per-batch path.

Each case runs the same columnar feed twice — fused (the default when a
junction's subscribers are all fusable) and per-batch (fused engine detached)
— and compares the full contents of a results table written by the query.
Query callbacks ride the fused path too (deliver mode: device-side packed
egress drained once per chunk) and must see identical events."""

from __future__ import annotations

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager


def _feed(n, seed=42):
    rng = np.random.default_rng(seed)
    return (
        np.arange(n, dtype=np.int64) + 1_700_000_000_000,
        {
            "symbol": rng.integers(1, 5, size=n).astype(np.int32),
            "price": rng.uniform(0.0, 100.0, size=n).astype(np.float32),
            "volume": rng.integers(1, 100, size=n).astype(np.int64),
        },
    )


def _run(ql, n, fused: bool, store_q="from T select *"):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    for s in ["A", "B", "C", "D"]:
        mgr.interner.intern(s)
    rt.start()
    junction = rt.junctions["S"]
    if fused:
        assert junction.fused_ingest is not None, "fused engine not built"
    else:
        for j in rt.junctions.values():
            j.fused_ingest = None
    ts, cols = _feed(n)
    rt.get_input_handler("S").send_columns(ts, cols)
    rows = sorted(map(repr, rt.query(store_q)))
    rt.shutdown()
    mgr.shutdown()
    return rows


HEAD = "@app:batch(size='64')\ndefine stream S (symbol string, price float, volume long);\n"

CASES = {
    "filter_table": HEAD + """
        @capacity(size='16384') define table T (symbol string, price float);
        @info(name='q') from S[price > 60] select symbol, price insert into T;
    """,
    "batch_groupby": HEAD + """
        @capacity(size='4096') define table T (symbol string, total long);
        @info(name='q') from S[price > 10]#window.lengthBatch(32)
        select symbol, sum(volume) as total group by symbol insert into T;
    """,
    "sliding_update": HEAD + """
        @capacity(size='64') define table T (symbol string, ap double);
        @info(name='q') from S#window.length(16)
        select symbol, avg(price) as ap group by symbol
        update or insert into T on T.symbol == symbol;
    """,
    "self_join": HEAD + """
        @app:joinCapacity(size='512')
        @capacity(size='16384') define table T (s1 string, s2 string);
        @info(name='q')
        from S#window.length(4) as a join S#window.length(4) as b
        on a.volume == b.volume
        select a.symbol as s1, b.symbol as s2 insert into T;
    """,
    "pattern": HEAD + """
        @app:patternCapacity(size='128')
        @capacity(size='8192') define table T (s1 string, s2 string);
        @info(name='q')
        from every a=S[price > 95] -> b=S[price < 5]
        select a.symbol as s1, b.symbol as s2 insert into T;
    """,
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_fused_matches_per_batch(name):
    ql = CASES[name]
    n = 64 * 40
    fused = _run(ql, n, fused=True)
    per_batch = _run(ql, n, fused=False)
    assert fused == per_batch


DELIVER_CASES = {
    "filter_cb": HEAD
    + "@info(name='q') from S[price > 60] select symbol, price insert into Out;",
    "window_avg_cb": HEAD
    + """@info(name='q') from S#window.length(16)
        select symbol, avg(price) as ap insert into Out;""",
    "groupby_cb": HEAD
    + """@info(name='q') from S#window.lengthBatch(32)
        select symbol, sum(volume) as total group by symbol insert into Out;""",
    "all_events_cb": HEAD
    + """@info(name='q') from S#window.length(8)
        select symbol, price insert all events into Out;""",
}


def _run_cb(ql, n, fused: bool):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    got = []
    rt.add_callback(
        "q",
        lambda ts, ins, rem: got.append(
            (
                ts,
                [tuple(e.data) for e in (ins or [])],
                [tuple(e.data) for e in (rem or [])],
            )
        ),
    )
    for s in ["A", "B", "C", "D"]:
        mgr.interner.intern(s)
    rt.start()
    if not fused:
        for j in rt.junctions.values():
            j.fused_ingest = None
    else:
        assert rt.junctions["S"].fused_ingest is not None
    ts, cols = _feed(n)
    rt.get_input_handler("S").send_columns(ts, cols)
    rt.shutdown()
    mgr.shutdown()
    return got


@pytest.mark.parametrize("name", sorted(DELIVER_CASES))
def test_fused_delivery_matches_per_batch(name):
    """Query callbacks on the fused path: identical events, identical
    per-micro-batch grouping, identical order."""
    ql = DELIVER_CASES[name]
    n = 64 * 40
    fused = _run_cb(ql, n, fused=True)
    per_batch = _run_cb(ql, n, fused=False)
    assert fused == per_batch
    assert sum(len(i) for _t, i, _r in fused) > 50
