"""Golden corpus: reference query/ratelimit/EventOutputRateLimitTestCase.java
(all 16 @Test, data-level translation — event-count-driven limits are
deterministic) plus deterministic shapes from TimeOutputRateLimitTestCase /
SnapshotOutputRateLimitTestCase (time-driven limits poll wall clock with
generous bounds, mirroring the reference's Thread.sleep + waitForEvents)."""

from __future__ import annotations

import time

from siddhi_tpu import SiddhiManager

LOGIN = "define stream LoginEvents (timestamp long, ip string);\n"


def run_counts(ql, ips, query_name="query1"):
    """Send one row per ip; return (in_rows, remove_rows)."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    ins, rem = [], []
    rt.add_callback(
        query_name,
        lambda ts, i, r: (
            ins.extend(tuple(e.data) for e in i or []),
            rem.extend(tuple(e.data) for e in r or []),
        ),
    )
    rt.start()
    h = rt.get_input_handler("LoginEvents")
    for k, ip in enumerate(ips):
        h.send((1_000_000 + k, ip))
    rt.shutdown()
    mgr.shutdown()
    return ins, rem


IPS5 = ["192.10.1.3", "192.10.1.3", "192.10.1.4", "192.10.1.3", "192.10.1.5"]
IPS8 = ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
        "192.10.1.4", "192.10.1.4", "192.10.1.4", "192.10.1.30"]
IPS12 = ["192.10.1.5", "192.10.1.3", "192.10.1.3", "192.10.1.9",
         "192.10.1.3", "192.10.1.4", "192.10.1.4", "192.10.1.4",
         "192.10.1.30", "192.10.1.31", "192.10.1.32", "192.10.1.33"]


class TestEventOutputRateLimitGolden:
    def test1_all_every_2(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output all every 2 events
        insert into uniqueIps ;""", IPS5)
        assert len(ins) == 4 and not rem, (ins, rem)

    def test2_default_every_2(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output every 2 events
        insert into uniqueIps ;""", IPS5)
        assert len(ins) == 4 and not rem, (ins, rem)

    def test3_every_5(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output every 5 events
        insert into uniqueIps ;""", IPS8)
        assert len(ins) == 5 and not rem, (ins, rem)

    def test4_first_every_2(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output first every 2 events
        insert into uniqueIps ;""", ["192.10.1.5", "192.10.1.3", "192.10.1.9",
                                     "192.10.1.4", "192.10.1.3"])
        assert len(ins) == 3 and not rem, (ins, rem)
        assert all(r[0] in ("192.10.1.5", "192.10.1.9", "192.10.1.3")
                   for r in ins), ins

    def test5_first_every_3(self):
        ins, _ = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output first every 3 events
        insert into uniqueIps ;""", ["192.10.1.5", "192.10.1.3", "192.10.1.9",
                                     "192.10.1.4", "192.10.1.3"])
        assert len(ins) == 2, ins

    def test6_last_every_2(self):
        ins, _ = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output last every 2 events
        insert into uniqueIps ;""", ["192.10.1.3", "192.10.1.5", "192.10.1.3",
                                     "192.10.1.4", "192.10.1.3"])
        assert len(ins) == 2, ins

    def test7_last_every_4(self):
        ins, _ = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output last every 4 events
        insert into uniqueIps ;""", ["192.10.1.3", "192.10.1.5", "192.10.1.3",
                                     "192.10.1.4", "192.10.1.3"])
        assert len(ins) == 1 and ins[0][0] == "192.10.1.4", ins

    def test8_group_by_first_every_5(self):
        # per-group FIRST within each 5-event chunk
        ins, _ = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip group by ip output first every 5 events
        insert into uniqueIps ;""", IPS8)
        assert len(ins) == 4, ins

    def test9_group_by_last_every_5(self):
        ins, _ = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip group by ip output last every 5 events
        insert into uniqueIps ;""", IPS8)
        assert len(ins) == 4, ins

    def test10_group_by_first_every_5_ten_events(self):
        ins, _ = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip group by ip output first every 5 events
        insert into uniqueIps ;""",
            ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
             "192.10.1.4", "192.10.1.4", "192.10.1.4", "192.10.1.4",
             "192.10.1.4", "192.10.1.30"])
        assert len(ins) == 6, ins

    def test11_group_by_last_every_5_ten_events(self):
        ins, _ = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip group by ip output last every 5 events
        insert into uniqueIps ;""",
            ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
             "192.10.1.4", "192.10.1.4", "192.10.1.4", "192.10.1.30",
             "192.10.1.3", "192.10.1.30"])
        assert len(ins) == 7, ins

    def test12_window_group_by_last_every_5(self):
        ins, _ = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.lengthBatch(4)
        select ip , count() as total group by ip
        output last every 5 events
        insert into uniqueIps ;""", IPS12)
        assert len(ins) == 4, ins

    def test13_window_last_every_2(self):
        ins, _ = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.lengthBatch(4)
        select ip , count() as total
        output last every 2 events
        insert into uniqueIps ;""", IPS12)
        assert len(ins) == 1, ins

    def test14_window_last_every_2_expired(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.lengthBatch(4)
        select ip , count() as total
        output last every 2 events
        insert expired events into uniqueIps ;""", IPS12)
        assert not ins and len(rem) == 1, (ins, rem)

    def test15_window_all_every_2_expired(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.lengthBatch(4)
        select ip , count() as total
        output all every 2 events
        insert expired events into uniqueIps ;""", IPS12)
        assert not ins and len(rem) == 2, (ins, rem)

    def test16_window_group_by_all_every_2_expired(self):
        ins, rem = run_counts(LOGIN + """@info(name = 'query1')
        from LoginEvents#window.lengthBatch(4)
        select ip , count() as total group by ip
        output all every 2 events
        insert expired events into uniqueIps ;""", IPS12)
        assert not ins and len(rem) == 4, (ins, rem)


class TestTimeSnapshotRateLimitGolden:
    """Deterministic shapes of TimeOutputRateLimitTestCase /
    SnapshotOutputRateLimitTestCase: wall-clock-driven flushes are polled
    with generous bounds (the reference sleeps ~1.2 s and asserts counts)."""

    def _run_timed(self, ql, sends, want, timeout=12.0, until=None):
        """Wall-clock rate-limit harness. `want` stops the wait once that
        many rows were delivered; `until(ins, rem)` instead waits for a
        SEMANTIC condition — needed for snapshot outputs, where under a
        loaded suite the 1-sec timer can fire several times before the
        last sends are even processed, so a row count alone can stop the
        wait on a snapshot that predates them (the or14/partition-golden
        wall-clock-race class)."""
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        ins, rem = [], []
        rt.add_callback(
            "query1",
            lambda ts, i, r: (
                ins.extend(tuple(e.data) for e in i or []),
                rem.extend(tuple(e.data) for e in r or []),
            ),
        )
        rt.start()
        h = rt.get_input_handler("LoginEvents")
        for row in sends:
            h.send(row)
        t0 = time.time()
        while time.time() - t0 < timeout:
            if until is not None:
                if until(ins, rem):
                    break
            elif len(ins) + len(rem) >= want:
                break
            time.sleep(0.05)
        rt.shutdown()
        mgr.shutdown()
        return ins, rem

    def test_time1_all_every_1sec(self):
        # TimeOutputRateLimit test1: all buffered rows flush at the period
        ins, _ = self._run_timed(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output all every 1 sec
        insert into uniqueIps ;""",
            [(1, "192.10.1.5"), (2, "192.10.1.3"), (3, "192.10.1.9")], 3)
        assert sorted(r[0] for r in ins) == [
            "192.10.1.3", "192.10.1.5", "192.10.1.9"
        ], ins

    def test_time2_first_every_1sec(self):
        # TimeOutputRateLimit first-per-period: only the period's first row
        ins, _ = self._run_timed(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output first every 1 sec
        insert into uniqueIps ;""",
            [(1, "192.10.1.5"), (2, "192.10.1.3"), (3, "192.10.1.9")], 1)
        assert len(ins) >= 1 and ins[0][0] == "192.10.1.5", ins

    def test_time3_last_every_1sec(self):
        ins, _ = self._run_timed(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output last every 1 sec
        insert into uniqueIps ;""",
            [(1, "192.10.1.5"), (2, "192.10.1.3"), (3, "192.10.1.9")], 1)
        assert len(ins) >= 1 and ins[-1][0] == "192.10.1.9", ins

    def test_snapshot1_plain_stream(self):
        # SnapshotOutputRateLimit over a plain stream: periodic re-emission
        # of the latest row
        ins, _ = self._run_timed(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip output snapshot every 1 sec
        insert into uniqueIps ;""",
            [(1, "192.10.1.5"), (2, "192.10.1.3")], 1)
        assert len(ins) >= 1, ins

    def test_snapshot2_aggregation(self):
        # snapshot of a group-by aggregation re-emits every group's latest
        both = {("192.10.1.5", 2), ("192.10.1.3", 1)}
        ins, _ = self._run_timed(LOGIN + """@info(name = 'query1')
        from LoginEvents select ip, count() as total group by ip
        output snapshot every 1 sec
        insert into uniqueIps ;""",
            [(1, "192.10.1.5"), (2, "192.10.1.5"), (3, "192.10.1.3")], 2,
            # wait for a snapshot that saw ALL the sends: snapshots
            # re-emit every group's latest each period, so one period
            # after the last send processes, both rows appear
            until=lambda i, _r: both <= {tuple(r) for r in i})
        got = {tuple(r) for r in ins}
        assert both <= got, ins