"""Event substrate tests (analog of reference managment/EventTestCase.java unit suite)."""

import numpy as np

from siddhi_tpu.core.event import (
    KIND_CURRENT,
    KIND_EXPIRED,
    EventBatch,
    StreamSchema,
    concat_batches,
)
from siddhi_tpu.core.types import AttrType, InternTable


def make_schema():
    return StreamSchema(
        "StockStream",
        [("symbol", AttrType.STRING), ("price", AttrType.FLOAT), ("volume", AttrType.INT)],
    )


def test_round_trip():
    schema = make_schema()
    interner = InternTable()
    rows = [("WSO2", 55.6, 100), ("IBM", 75.6, 10)]
    batch = schema.to_batch([1000, 2000], rows, interner, capacity=4)
    assert batch.capacity == 4
    out = schema.from_batch(batch, interner)
    assert out == [
        (1000, KIND_CURRENT, ("WSO2", 55.599998474121094, 100)),
        (2000, KIND_CURRENT, ("IBM", 75.5999984741211, 10)),
    ] or [r[2][0] for r in out] == ["WSO2", "IBM"]
    assert len(out) == 2
    assert out[0][0] == 1000 and out[1][0] == 2000
    assert out[0][2][0] == "WSO2" and out[1][2][0] == "IBM"
    assert abs(out[0][2][1] - 55.6) < 1e-4
    assert out[0][2][2] == 100


def test_null_handling():
    schema = make_schema()
    interner = InternTable()
    batch = schema.to_batch([1], [(None, None, None)], interner, capacity=2)
    (ts, kind, row), = schema.from_batch(batch, interner)
    assert row == (None, None, None)


def test_kinds_and_padding():
    schema = make_schema()
    interner = InternTable()
    batch = schema.to_batch(
        [1, 2], [("A", 1.0, 1), ("B", 2.0, 2)], interner, capacity=8,
        kinds=[KIND_CURRENT, KIND_EXPIRED],
    )
    assert np.asarray(batch.valid).sum() == 2
    out = schema.from_batch(batch, interner)
    assert [k for _, k, _ in out] == [KIND_CURRENT, KIND_EXPIRED]


def test_intern_table_identity():
    t = InternTable()
    a, b = t.intern("x"), t.intern("x")
    assert a == b and t.intern("y") != a
    assert t.lookup(a) == "x"
    assert t.intern(None) == 0 and t.lookup(0) is None


def test_concat():
    schema = make_schema()
    interner = InternTable()
    a = schema.to_batch([1], [("A", 1.0, 1)], interner, capacity=2)
    b = schema.to_batch([2], [("B", 2.0, 2)], interner, capacity=2)
    c = concat_batches(a, b)
    assert c.capacity == 4
    out = schema.from_batch(c, interner)
    assert [r[2][0] for r in out] == ["A", "B"]


def test_pytree_registration():
    import jax

    schema = make_schema()
    interner = InternTable()
    batch = schema.to_batch([1], [("A", 1.0, 1)], interner, capacity=2)
    leaves = jax.tree_util.tree_leaves(batch)
    assert len(leaves) == 6  # ts, kind, valid + 3 cols
    mapped = jax.tree_util.tree_map(lambda x: x, batch)
    assert isinstance(mapped, EventBatch)
