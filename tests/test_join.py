"""Join query end-to-end tests.

Mirrors the reference's JoinTestCase / OuterJoinTestCase semantics
(reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/query/join/).
"""

import pytest

from siddhi_tpu import SiddhiManager


def run_app(ql, sends, callback_name="q"):
    """sends: list of (stream_id, row, ts). Returns (in_events, removed_events)."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    ins, removed = [], []

    def cb(ts, in_events, removed_events):
        if in_events:
            ins.extend(e.data for e in in_events)
        if removed_events:
            removed.extend(e.data for e in removed_events)

    rt.add_callback(callback_name, cb)
    rt.start()
    handlers = {}
    for stream_id, row, ts in sends:
        h = handlers.setdefault(stream_id, rt.get_input_handler(stream_id))
        h.send(row, timestamp=ts)
    rt.shutdown()
    mgr.shutdown()
    return ins, removed


BASE = """
define stream StockStream (sym string, price float);
define stream TwitterStream (user string, company string);
"""


class TestInnerJoin:
    def test_window_probe(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(10) join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select StockStream.sym as sym, TwitterStream.user as user, StockStream.price as price
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("StockStream", ("WSO2", 55.5), 100),
            ("TwitterStream", ("u1", "WSO2"), 200),
            ("StockStream", ("IBM", 75.5), 300),
            ("StockStream", ("WSO2", 57.0), 400),
        ])
        assert ins == [("WSO2", "u1", 55.5), ("WSO2", "u1", 57.0)]

    def test_multi_match_one_arrival(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(10) join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select TwitterStream.user as user, StockStream.price as price
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("TwitterStream", ("u1", "WSO2"), 100),
            ("TwitterStream", ("u2", "WSO2"), 200),
            ("StockStream", ("WSO2", 10.0), 300),
        ])
        # one stock arrival matches both tweets, window (insertion) order
        assert ins == [("u1", 10.0), ("u2", 10.0)]

    def test_join_condition_non_equi(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(10) as a join StockStream#window.length(10) as b
        on a.price < b.price
        select a.price as lo, b.price as hi
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("StockStream", ("WSO2", 10.0), 100),
            ("StockStream", ("WSO2", 20.0), 200),
        ])
        # arrival 20.0: left-side probe right window {10} -> no (20<10 false);
        # right-side probe left window {10,20} -> (10,20)
        assert ins == [(10.0, 20.0)]

    def test_filter_before_window(self):
        ql = BASE + """
        @info(name='q')
        from StockStream[price > 50]#window.length(10) join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select StockStream.price as price, TwitterStream.user as user
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("StockStream", ("WSO2", 10.0), 100),   # filtered out
            ("StockStream", ("WSO2", 60.0), 200),
            ("TwitterStream", ("u1", "WSO2"), 300),
        ])
        assert ins == [(60.0, "u1")]

    def test_unidirectional(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(10) unidirectional join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select StockStream.sym as sym, TwitterStream.user as user
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("StockStream", ("WSO2", 55.5), 100),
            ("TwitterStream", ("u1", "WSO2"), 200),   # right arrival: no output
            ("StockStream", ("WSO2", 57.0), 300),     # left arrival: match
        ])
        assert ins == [("WSO2", "u1")]


class TestOuterJoin:
    def test_left_outer(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(10) left outer join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select StockStream.sym as sym, TwitterStream.user as user
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("StockStream", ("WSO2", 55.5), 100),     # no match -> (WSO2, null)
            ("TwitterStream", ("u1", "WSO2"), 200),   # match -> (WSO2, u1)
            ("TwitterStream", ("u2", "IBM"), 300),    # right miss on left outer -> none
        ])
        assert ins == [("WSO2", None), ("WSO2", "u1")]

    def test_right_outer(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(10) right outer join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select StockStream.sym as sym, TwitterStream.user as user
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("TwitterStream", ("u1", "WSO2"), 100),   # no match -> (null, u1)
            ("StockStream", ("WSO2", 55.5), 200),     # match -> (WSO2, u1)
            ("StockStream", ("IBM", 75.5), 300),      # left miss on right outer -> none
        ])
        assert ins == [(None, "u1"), ("WSO2", "u1")]

    def test_full_outer(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(10) full outer join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select StockStream.sym as sym, TwitterStream.user as user
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("StockStream", ("WSO2", 55.5), 100),     # (WSO2, null)
            ("TwitterStream", ("u2", "IBM"), 200),    # (null, u2)
            ("TwitterStream", ("u1", "WSO2"), 300),   # (WSO2, u1)
        ])
        assert ins == [("WSO2", None), (None, "u2"), ("WSO2", "u1")]

    def test_null_numeric_fill(self):
        ql = BASE + """
        @info(name='q')
        from TwitterStream#window.length(10) left outer join StockStream#window.length(10)
        on TwitterStream.company == StockStream.sym
        select TwitterStream.user as user, StockStream.price as price
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("TwitterStream", ("u1", "WSO2"), 100),
        ])
        assert ins == [("u1", None)]


class TestJoinAggregation:
    def test_count_over_join(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(10) join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select StockStream.sym as sym, count() as c
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("TwitterStream", ("u1", "WSO2"), 100),
            ("StockStream", ("WSO2", 10.0), 200),
            ("StockStream", ("WSO2", 11.0), 300),
        ])
        assert ins == [("WSO2", 1), ("WSO2", 2)]

    def test_group_by_over_join(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(10) join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select TwitterStream.user as user, sum(StockStream.price) as total
        group by TwitterStream.user
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("TwitterStream", ("u1", "WSO2"), 100),
            ("TwitterStream", ("u2", "WSO2"), 150),
            ("StockStream", ("WSO2", 10.0), 200),
            ("StockStream", ("WSO2", 5.0), 300),
        ])
        assert ins == [("u1", 10.0), ("u2", 10.0), ("u1", 15.0), ("u2", 15.0)]


class TestJoinExpired:
    def test_all_events_expired_probe(self):
        ql = BASE + """
        @info(name='q')
        from StockStream#window.length(1) join TwitterStream#window.length(10)
        on StockStream.sym == TwitterStream.company
        select StockStream.price as price, TwitterStream.user as user
        insert all events into Out;
        """
        ins, removed = run_app(ql, [
            ("TwitterStream", ("u1", "WSO2"), 100),
            ("StockStream", ("WSO2", 10.0), 200),
            ("StockStream", ("WSO2", 11.0), 300),  # evicts 10.0 -> expired join
        ])
        assert ins == [(10.0, "u1"), (11.0, "u1")]
        assert removed == [(10.0, "u1")]
