"""Golden corpus: within-bounded patterns, translated from the reference test
data (reference: siddhi-core/src/test/java/org/wso2/siddhi/core/query/pattern/
WithinPatternTestCase.java — data-level translation, Thread.sleep gaps turned
into explicit event timestamps)."""

from tests.test_golden_count import assert_rows
from tests.test_golden_logical import run_ts

S12 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""


class TestWithinPatternGolden:
    def test_query1(self):
        # the WSO2 chain expires at 1 sec; GOOG's chain is inside the bound
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream1[price>20] -> e2=Stream2[price>e1.price] within 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream ;
        """
        got = run_ts(ql, [
            ("Stream1", ("WSO2", 55.6, 100), 1_000),
            ("Stream1", ("GOOG", 54.0, 100), 2_500),
            ("Stream2", ("IBM", 55.7, 100), 3_000),
        ])
        assert_rows(got, [("GOOG", "IBM")])

    def test_query2(self):
        # parenthesized pattern with within outside
        ql = S12 + """
        @info(name = 'query1')
        from (every e1=Stream1[price>20]-> e2=Stream2[price>e1.price])
         within 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream ;
        """
        got = run_ts(ql, [
            ("Stream1", ("WSO2", 55.6, 100), 1_000),
            ("Stream1", ("GOOG", 54.0, 100), 2_500),
            ("Stream2", ("IBM", 55.7, 100), 3_000),
        ])
        assert_rows(got, [("GOOG", "IBM")])

    def test_query3(self):
        # every block + within 2 sec: only the second (fresh) block instance
        # is within bound when e2 arrives
        ql = S12 + """
        @info(name = 'query1')
        from (every (e1=Stream1[price>20] -> e3=Stream1[price>20]) -> e2=Stream2[price>e1.price]) within 2 sec
        select e1.price as price1, e3.price as price3, e2.price as price2
        insert into OutputStream ;
        """
        got = run_ts(ql, [
            ("Stream1", ("WSO2", 55.6, 100), 1_000),
            ("Stream1", ("GOOG", 54.0, 100), 1_600),
            ("Stream1", ("WSO2", 53.6, 100), 2_200),
            ("Stream1", ("GOOG", 53.0, 100), 2_800),
            ("Stream2", ("IBM", 57.7, 100), 3_400),
        ])
        assert_rows(got, [(53.6, 53.0, 57.7)])
