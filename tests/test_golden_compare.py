"""Golden corpus: comparison typing + null checks, translated from the
reference test data (reference: siddhi-core/src/test/.../query/
StringCompareTestCase.java — all 30 string-vs-numeric comparisons must be
rejected at app creation — and IsNullTestCase.java)."""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError

OPS = ["x > y", "x < y", "x >= y", "x <= y", "x == y", "x != y"]
DEFS = [
    "x string, y int",
    "x int, y string",
    "x long, y string",
    "x float, y string",
    "x double, y string",
]


@pytest.mark.parametrize("fields", DEFS)
@pytest.mark.parametrize("cond", OPS)
def test_string_numeric_compare_rejected(cond, fields):
    mgr = SiddhiManager()
    with pytest.raises((SiddhiAppCreationError, TypeError)):
        mgr.create_siddhi_app_runtime(f"""
        define stream cseEventStream ({fields});
        @info(name = 'query1')
        from cseEventStream[{cond}]
        select x insert into outputStream;
        """)


class TestIsNullGolden:
    def test_is_null_filter(self):
        # IsNullTestCase.testIsNullStreamConditionCase1
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume long);
        @info(name = 'query1')
        from cseEventStream[symbol is null]
        select symbol, price
        insert into outputStream;
        """)
        got = []
        rt.add_callback("query1", lambda ts, i, r: got.extend(tuple(e.data) for e in i or []))
        rt.start()
        h = rt.get_input_handler("cseEventStream")
        h.send(("IBM", 700.0, 100))
        h.send((None, 60.5, 200))
        h.send(("WSO2", 60.5, 200))
        rt.shutdown()
        assert len(got) == 1 and got[0][0] is None, got

    def test_is_not_null_filter(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream cseEventStream (symbol string, price float, volume long);
        @info(name = 'query1')
        from cseEventStream[not (symbol is null)]
        select symbol, price
        insert into outputStream;
        """)
        got = []
        rt.add_callback("query1", lambda ts, i, r: got.extend(tuple(e.data) for e in i or []))
        rt.start()
        h = rt.get_input_handler("cseEventStream")
        h.send(("IBM", 700.0, 100))
        h.send((None, 60.5, 200))
        rt.shutdown()
        assert len(got) == 1 and got[0][0] == "IBM", got
