"""Observability layer tests: histogram math, reporters, tracing, toggling,
per-subscriber error attribution, device budget, and the no-overhead guard.

Reference: modules/siddhi-core/src/test/java/.../managment/StatisticsTestCase
plus the engine-specific additions (siddhi_tpu/observability/)."""

import json
import re
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.observability.metrics import (
    EWMA,
    LatencyTracker,
    LogHistogram,
    ThroughputTracker,
)
from siddhi_tpu.observability.reporters import render_prometheus
from siddhi_tpu.observability.tracing import Tracer


# ---------------------------------------------------------------------------
# histogram percentile math
# ---------------------------------------------------------------------------


class TestLogHistogram:
    def test_quantiles_uniform(self):
        h = LogHistogram()
        for v in range(1, 10_001):  # 1..10000, uniform
            h.record(v)
        assert h.count == 10_000
        for q, expect in ((0.5, 5_000), (0.95, 9_500), (0.99, 9_900)):
            got = h.quantile(q)
            assert abs(got - expect) / expect < 0.05, (q, got)

    def test_quantiles_bimodal_tail(self):
        # 99% fast (~1k ns), 1% slow (~1M ns): p99 must see the slow mode —
        # the whole point of histograms over a mean (BENCH p99 motivation)
        h = LogHistogram()
        for _ in range(990):
            h.record(1_000)
        for _ in range(10):
            h.record(1_000_000)
        assert h.quantile(0.5) < 2_000
        assert h.quantile(0.999) > 900_000
        assert abs(h.mean - (990 * 1_000 + 10 * 1_000_000) / 1000) < 1e-6

    def test_exact_small_values_and_bounds(self):
        h = LogHistogram()
        h.record(0)
        h.record(7)
        h.record(63)
        assert h.min == 0 and h.max == 63 and h.count == 3
        assert h.quantile(0.0) == 0.0
        # one-pass multi-quantile agrees with single reads
        a = h.quantiles([0.1, 0.9])
        assert a == [h.quantile(0.1), h.quantile(0.9)]

    def test_relative_error_bound(self):
        h = LogHistogram()
        for v in (100, 10_000, 123_456_789, 10**12):
            h2 = LogHistogram()
            h2.record(v)
            got = h2.quantile(0.5)
            assert abs(got - v) / v < 1 / 16, (v, got)
        del h

    def test_ewma_decays_when_idle(self):
        e = EWMA(60.0, now=0.0)
        e.update(600, now=0.0)
        r1 = e.rate(now=5.0)  # one tick: 600 events over 5 s
        assert r1 == pytest.approx(120.0)
        r2 = e.rate(now=600.0)  # ten minutes idle: decayed hard
        assert r2 < r1 * 0.01


# ---------------------------------------------------------------------------
# latency tracker nesting semantics (the pre-histogram TLS-t0 bug)
# ---------------------------------------------------------------------------


class TestLatencyTrackerNesting:
    def test_nested_marks_record_both_spans(self):
        lt = LatencyTracker("t")
        lt.mark_in()
        time.sleep(0.002)
        lt.mark_in()  # nested: must NOT overwrite the outer mark
        time.sleep(0.002)
        lt.mark_out()  # closes the inner span (~2 ms)
        time.sleep(0.002)
        lt.mark_out()  # closes the outer span (~6 ms)
        assert lt.samples == 2
        assert lt.hist.max >= 2 * lt.hist.min  # outer strictly contains inner
        assert lt.avg_ms > 0

    def test_stray_mark_out_is_ignored(self):
        lt = LatencyTracker("t")
        lt.mark_out()  # no open mark: must not record garbage
        assert lt.samples == 0
        lt.mark_in()
        lt.mark_out()
        lt.mark_out()  # second out with empty stack: still nothing
        assert lt.samples == 1

    def test_toggle_mid_span_never_records_garbage(self):
        # the gate decision is made at mark_in: disabling between a mark pair
        # must neither leak stack entries nor pair a stale t0 later
        class Gate:
            enabled = True

        g = Gate()
        lt = LatencyTracker("t", gate=g)
        lt.mark_in()
        g.enabled = False
        lt.mark_out()  # popped but not recorded (disabled at out)
        lt.mark_in()   # disabled: pushes a sentinel
        g.enabled = True
        lt.mark_out()  # pops the sentinel — records nothing
        assert lt.samples == 0
        lt.mark_in()
        lt.mark_out()
        assert lt.samples == 1
        assert lt.hist.max < 10**9  # no stale multi-second garbage sample

    def test_timed_context_manager(self):
        from siddhi_tpu.observability.metrics import timed

        lt = LatencyTracker("t")
        with timed(lt):
            pass
        with pytest.raises(ValueError):
            with timed(lt):  # exception-safe: mark_out still runs
                raise ValueError("x")
        assert lt.samples == 2
        with timed(None):  # None tracker is a no-op
            pass


# ---------------------------------------------------------------------------
# reporters: Prometheus text + JSON lines
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+]?[0-9.eE+-]+$"
)


def _assert_prometheus_wellformed(text: str) -> dict:
    """Every non-comment line must parse; returns family -> sample count."""
    families: dict = {}
    typed = set()
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            typed.add(line.split()[2])
            continue
        if line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), f"malformed exposition line: {line!r}"
        name = re.split(r"[{ ]", line, 1)[0]
        base = re.sub(r"_(sum|count)$", "", name)
        assert base in typed or name in typed, f"untyped family: {name}"
        families[base] = families.get(base, 0) + 1
    return families


class TestReporters:
    def test_prometheus_rendering_from_registry(self):
        from siddhi_tpu.observability.registry import StatisticsManager

        sm = StatisticsManager("App1", reporter="none")
        sm.throughput_tracker("stream.S").add(5)
        sm.latency_tracker("query.q").record_ns(1_500_000)
        sm.error_tracker("stream.S").add(1)
        sm.error_tracker("stream.S", subscriber="query.q").add(1)
        sm.device_time_tracker("query.q", "step").record_ns(2_000_000)
        sm.device_counter("stream.S", "h2d_bytes").add(4096)
        text = render_prometheus([sm.report()])
        fams = _assert_prometheus_wellformed(text)
        assert fams["siddhi_events_total"] == 1
        assert fams["siddhi_latency_ms"] >= 6  # 4 quantiles + sum + count
        assert 'subscriber="query.q"' in text
        assert "siddhi_device_time_ms" in fams
        assert "siddhi_h2d_bytes_total" in fams
        # label escaping never produces an unparseable line
        sm.throughput_tracker('we"ird\\n').add(1)
        _assert_prometheus_wellformed(render_prometheus([sm.report()]))

    def test_jsonl_reporter_writes_parseable_lines(self, tmp_path):
        from siddhi_tpu.observability.registry import StatisticsManager

        path = str(tmp_path / "m.jsonl")
        sm = StatisticsManager(
            "App1", reporter="jsonl", interval_s=0.05, options={"file": path}
        )
        sm.throughput_tracker("stream.S").add(3)
        sm.start_reporting()
        t0 = time.time()
        while time.time() - t0 < 5.0:
            with open(path) as f:
                lines = [ln for ln in f.read().splitlines() if ln]
            if len(lines) >= 2:
                break
            time.sleep(0.05)
        sm.stop_reporting()
        assert len(lines) >= 2
        for ln in lines:
            rep = json.loads(ln)
            assert rep["app"] == "App1"
            assert rep["throughput"]["stream.S"] == 3

    def test_custom_reporter_spi(self):
        from siddhi_tpu.observability.registry import StatisticsManager
        from siddhi_tpu.observability.reporters import (
            Reporter,
            register_reporter,
        )

        got = []

        class Capture(Reporter):
            def emit(self, report):
                got.append(report)

        register_reporter("capture_test", lambda app, opts: Capture())
        sm = StatisticsManager("A", reporter="capture_test", interval_s=0.05)
        sm.start_reporting()
        t0 = time.time()
        while not got and time.time() - t0 < 5.0:
            time.sleep(0.02)
        sm.stop_reporting()
        assert got and got[0]["app"] == "A"


# ---------------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------------


class TestTracer:
    def test_sampling_deterministic_under_seed(self):
        def run():
            tr = Tracer(0.3, capacity=1000, seed=1234)
            picks = []
            for _ in range(200):
                tok = tr.start_span("stream.S")
                # a sampled span token is a list; the skip sentinel is not
                picks.append(isinstance(tok, list))
                tr.end_span(tok)
            return picks, tr.sampled_count

        p1, n1 = run()
        p2, n2 = run()
        assert p1 == p2
        assert n1 == n2
        assert 20 < n1 < 120  # ~60 expected at p=0.3

    def test_nested_spans_and_ring_bound(self):
        tr = Tracer(1.0, capacity=4)
        for i in range(10):
            a = tr.start_span("stream.S", 1)
            b = tr.start_span("query.q", 1)
            tr.end_span(b)
            tr.end_span(a)
        traces = tr.traces()
        assert len(traces) == 4  # bounded ring keeps the newest
        spans = traces[-1]["spans"]
        assert [s["component"] for s in spans] == ["stream.S", "query.q"]
        assert spans[0]["depth"] == 0 and spans[1]["depth"] == 1
        assert spans[1]["duration_us"] <= spans[0]["duration_us"]
        json.dumps(traces)  # dumpable as JSON

    def test_unsampled_root_suppresses_children(self):
        tr = Tracer(0.0)
        a = tr.start_span("stream.S")
        b = tr.start_span("query.q")
        tr.end_span(b)
        tr.end_span(a)
        assert tr.traces() == []
        assert tr.sampled_count == 0


# ---------------------------------------------------------------------------
# end-to-end: engine wiring, exposition endpoint, traces across the pipeline
# ---------------------------------------------------------------------------


def _mk_app(mgr, extra=""):
    return mgr.create_siddhi_app_runtime(f"""
    @app:statistics(reporter='none', trace.sample='1.0', trace.seed='7'{extra})
    define stream S (symbol string, price float);
    @sink(type='inMemory', topic='stats_e2e_out')
    define stream Egress (symbol string);
    @info(name='q') from S[price > 10] select symbol insert into Egress;
    """)


class TestEngineWiring:
    def test_report_shape_and_histogram_latency(self):
        mgr = SiddhiManager()
        rt = _mk_app(mgr)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(20):
            h.send(("A", float(i)))
        rep = rt.statistics_manager.report()
        assert rep["throughput"]["stream.S"] == 20
        assert rep["throughput"]["stream.Egress"] == 9  # price in 11..19
        assert rep["throughput"]["sink.Egress"] == 9
        lat = rep["latency_ms"]["query.q"]
        assert lat["count"] == 20
        assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"]
        # back-compat keys survive (pre-histogram report shape)
        assert rep["latency_avg_ms"]["query.q"] > 0
        # device budget: per-query step time is collected live
        assert rep["device"]["time_ms"]["query.q.step"]["summary"]["count"] == 20
        assert "rates" in rep and "m1" in rep["rates"]["stream.S"]
        mgr.shutdown()

    def test_traces_cross_ingress_query_sink(self):
        mgr = SiddhiManager()
        rt = _mk_app(mgr)
        rt.start()
        rt.get_input_handler("S").send(("A", 99.0))
        traces = rt.traces()
        assert len(traces) == 1
        comps = [s["component"] for s in traces[0]["spans"]]
        depths = [s["depth"] for s in traces[0]["spans"]]
        assert comps == [
            "stream.S", "query.q", "stream.Egress", "sink.Egress[0]"
        ]
        assert depths == [0, 1, 2, 3]
        assert all(s["duration_us"] >= 0 for s in traces[0]["spans"])
        # dump_traces round-trips through JSON
        assert json.loads(rt.dump_traces())[0]["spans"][0]["component"] == "stream.S"
        mgr.shutdown()

    def test_trace_sampling_e2e_deterministic(self):
        counts = []
        for _ in range(2):
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime("""
            @app:statistics(reporter='none', trace.sample='0.25',
                            trace.seed='99')
            define stream S (v long);
            @info(name='q') from S select v insert into Out;
            """)
            rt.start()
            h = rt.get_input_handler("S")
            for i in range(80):
                h.send((i,))
            counts.append(len(rt.traces()))
            mgr.shutdown()
        assert counts[0] == counts[1]
        assert 0 < counts[0] < 80

    def test_per_subscriber_error_attribution(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:statistics(reporter='none')
        @OnError(action='LOG')
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)

        def boom(batch, now):
            raise ValueError("poison")

        rt.junctions["S"].subscribe(boom, name="custom.boom")
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send((i,))
        rep = rt.statistics_manager.report()
        assert rep["errors"]["stream.S"] == 3  # aggregate (back-compat)
        assert rep["errors"]["stream.S.subscriber.custom.boom"] == 3
        ent = rep["errors_detail"]["stream.S.subscriber.custom.boom"]
        assert ent["component"] == "stream.S"
        assert ent["subscriber"] == "custom.boom"
        text = mgr.prometheus_text()
        assert (
            'siddhi_errors_total{app="SiddhiApp",component="stream.S",'
            'subscriber="custom.boom"} 3' in text
        )
        mgr.shutdown()

    def test_enable_disable_toggling(self):
        mgr = SiddhiManager()
        rt = _mk_app(mgr)
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("A", 50.0))
        assert rt.statistics_manager.report()["throughput"]["stream.S"] == 1
        rt.enable_stats(False)
        for i in range(5):
            h.send(("A", 50.0))
        rep = rt.statistics_manager.report()
        assert rep["throughput"]["stream.S"] == 1  # collection stopped
        assert len(rt.traces()) == 1  # tracing stopped too
        rt.enable_stats(True)
        h.send(("A", 50.0))
        assert rt.statistics_manager.report()["throughput"]["stream.S"] == 2
        mgr.shutdown()

    def test_fused_ingest_stays_engaged_and_records_budget(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:statistics(reporter='none')
        @app:batch(size='32')
        define stream S (k long, v long);
        @info(name='q') from S select k, sum(v) as t group by k insert into Out;
        """)
        rt.start()
        j = rt.junctions["S"]
        n = 32 * 8
        rt.get_input_handler("S").send_columns(
            np.arange(n, dtype=np.int64),
            {
                "k": np.arange(n, dtype=np.int64) % 4,
                "v": np.ones(n, dtype=np.int64),
            },
        )
        assert j.fused_ingest is not None and j.fused_ingest.eligible()
        rep = rt.statistics_manager.report()
        dev = rep["device"]
        assert dev["counters"]["stream.S.h2d_chunks"]["count"] >= 1
        assert dev["counters"]["stream.S.h2d_bytes"]["count"] > 0
        assert dev["time_ms"]["stream.S.fused_step"]["summary"]["count"] >= 1
        # the query latency histogram records CHUNK dispatch time in fused mode
        assert rep["latency_ms"]["query.q"]["count"] >= 1
        assert rep["throughput"]["stream.S"] == n
        mgr.shutdown()


class TestSinkThroughputSemantics:
    def test_sink_counts_only_delivered_events(self):
        from siddhi_tpu.core.errors import ConnectionUnavailableError
        from siddhi_tpu.core.event import Event
        from siddhi_tpu.core.io import Sink

        class DownSink(Sink):
            def publish(self, payload):
                raise ConnectionUnavailableError("down")

        s = DownSink()
        s.init("S", {"on.error": "LOG"}, None)
        counts = []
        s.on_publish_stats = counts.append
        s.on_events([Event(0, ("a",))])
        assert counts == []  # dropped payloads are not "published events"

        class UpSink(Sink):
            def publish(self, payload):
                pass

        u = UpSink()
        u.init("S", {}, None)
        u.on_publish_stats = counts.append
        u.on_events([Event(0, ("a",)), Event(1, ("b",))])
        assert counts == [2]


class TestMetricsEndpoint:
    def test_serve_metrics_exposition(self):
        mgr = SiddhiManager()
        rt = _mk_app(mgr)

        def boom(batch, now):
            raise ValueError("poison")

        rt.junctions["S"].subscribe(boom, name="custom.boom")
        rt.set_exception_handler(lambda e: None)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(10):
            h.send(("A", float(i * 3)))
        port = mgr.serve_metrics(0)  # ephemeral port
        assert mgr.serve_metrics(0) == port  # idempotent
        base = f"http://127.0.0.1:{port}"
        text = urllib.request.urlopen(f"{base}/metrics", timeout=5).read().decode()
        fams = _assert_prometheus_wellformed(text)
        # acceptance: throughput, latency quantiles, buffered depth,
        # per-subscriber errors, device-time budget
        assert fams.get("siddhi_events_total", 0) >= 2
        for q in ('quantile="0.5"', 'quantile="0.95"', 'quantile="0.99"'):
            assert q in text
        assert "siddhi_buffered_events" in fams
        assert 'subscriber="custom.boom"' in text
        assert "siddhi_device_time_ms" in fams
        assert "siddhi_traces_sampled_total" in fams
        # JSON + traces endpoints
        rep = json.loads(
            urllib.request.urlopen(f"{base}/metrics.json", timeout=5).read()
        )
        assert rep[0]["app"] == "SiddhiApp"
        tr = json.loads(
            urllib.request.urlopen(f"{base}/traces", timeout=5).read()
        )
        assert tr["SiddhiApp"], "sampled traces must be served"
        mgr.shutdown()  # also stops the endpoint
        assert mgr.metrics_port is None

    def test_unknown_path_is_404(self):
        mgr = SiddhiManager()
        _mk_app(mgr).start()
        port = mgr.serve_metrics(0)
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/nope", timeout=5
            )
        assert ei.value.code == 404
        ei.value.read()  # framed body: the connection is not left hanging
        mgr.shutdown()

    def test_500_response_is_framed(self):
        # satellite: the old handler wrote a raw body after end_headers()
        # with no Content-Length, hanging keep-alive scrapers; send_error
        # frames it. Induce a handler fault by breaking report collection.
        mgr = SiddhiManager()
        _mk_app(mgr).start()
        port = mgr.serve_metrics(0)
        broken = mgr._metrics_server
        orig = broken._reports
        broken._reports = lambda: (_ for _ in ()).throw(RuntimeError("boom"))
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics.json", timeout=5
                )
            assert ei.value.code == 500
            assert ei.value.headers.get("Content-Length") is not None
            body = ei.value.read()
            assert b"boom" in body
        finally:
            broken._reports = orig
        # the server survives and keeps serving after the 500
        rep = json.loads(
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics.json", timeout=5
            ).read()
        )
        assert rep[0]["app"] == "SiddhiApp"
        mgr.shutdown()

    def test_concurrent_scrape_while_app_shutdown(self):
        # scrapes racing an app shutdown must always get well-formed 200s
        # (collection snapshots + manager-level iteration are copy-safe)
        import threading

        mgr = SiddhiManager()
        rt = _mk_app(mgr)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(10):
            h.send(("A", float(i * 3)))
        port = mgr.serve_metrics(0)
        base = f"http://127.0.0.1:{port}"
        errors: list = []
        stop = threading.Event()

        def scrape_loop():
            paths = ("/metrics", "/metrics.json", "/traces", "/status.json")
            i = 0
            while not stop.is_set():
                try:
                    resp = urllib.request.urlopen(
                        base + paths[i % len(paths)], timeout=5
                    )
                    assert resp.status == 200
                    resp.read()
                except Exception as e:  # pragma: no cover - failure detail
                    errors.append(e)
                    return
                i += 1

        threads = [threading.Thread(target=scrape_loop) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        assert mgr.shutdown_siddhi_app_runtime("SiddhiApp")
        time.sleep(0.1)  # keep scraping against the app-less manager
        stop.set()
        for t in threads:
            t.join(timeout=5)
        assert not errors, errors
        # app deregistered: endpoints still serve (empty) well-formed bodies
        assert json.loads(
            urllib.request.urlopen(f"{base}/metrics.json", timeout=5).read()
        ) == []
        mgr.shutdown()


# ---------------------------------------------------------------------------
# zero-cost-when-disabled guard
# ---------------------------------------------------------------------------


class TestNoOverheadWhenDisabled:
    def test_nothing_wired_without_annotation(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)
        rt.start()
        assert rt.statistics_manager is None
        assert rt.tracer is None
        j = rt.junctions["S"]
        assert j.on_publish_stats is None
        assert j.on_error_stats is None
        assert j.error_stats_factory is None
        assert j.device_stats is None
        assert j.tracer is None
        qr = rt.queries["q"]
        assert qr.device_step_tracker is None
        assert qr.sync_stall_tracker is None
        # profiler + compile telemetry ride the same wiring: without
        # @app:statistics the hot paths pay one `is None` check
        assert qr.compile_telemetry is None
        assert qr.profiler is None
        assert j.profiler is None
        assert j.compile_telemetry is None
        assert rt.traces() == []
        mgr.shutdown()

    def test_gated_trackers_are_cheap_when_disabled(self):
        # perf-regression assertion: a disabled tracker's mark_in/mark_out is
        # one gate check — it must run far faster than the enabled path that
        # takes timestamps and updates the histogram. Ratio-based with a wide
        # margin so CI jitter cannot flake it.
        class Gate:
            enabled = True

        gate = Gate()
        lt = LatencyTracker("t", gate=gate)
        tt = ThroughputTracker("t", gate=gate)
        n = 20_000

        def run():
            t0 = time.perf_counter()
            for _ in range(n):
                lt.mark_in()
                tt.add(1)
                lt.mark_out()
            return time.perf_counter() - t0

        run()  # warm
        enabled = min(run() for _ in range(3))
        gate.enabled = False
        base = lt.samples
        disabled = min(run() for _ in range(3))
        assert lt.samples == base  # nothing recorded while disabled
        assert disabled < enabled, (
            f"disabled path ({disabled:.4f}s) must be cheaper than enabled "
            f"({enabled:.4f}s)"
        )

    def test_profiler_hooks_are_single_gate_check_when_disabled(self):
        # the profiler/compile-telemetry contract matches the trackers':
        # `enable_stats(False)` stops collection at one gate check —
        # begin() returns None and observe() returns before touching the
        # jit cache or taking a lock's slow path
        from siddhi_tpu.observability.profiler import (
            CompileTelemetry,
            Profiler,
        )

        class Gate:
            enabled = True

        gate = Gate()
        prof = Profiler(gate=gate)
        ct = CompileTelemetry(gate=gate)

        class FakeProg:
            calls = 0

            def _cache_size(self):
                FakeProg.calls += 1
                return 1

        prog = FakeProg()
        gate.enabled = False
        assert prof.begin("S", 8) is None
        ct.observe("c", prog, (8,), 1000)
        assert FakeProg.calls == 0  # never reached the cache probe
        assert prof.report()["chunks"] == 0
        assert ct.report() == {}
        gate.enabled = True
        wf = prof.begin("S", 8)
        assert wf is not None
        prof.end(wf)
        ct.observe("c", prog, (8,), 1000)
        assert FakeProg.calls == 1
        assert prof.report()["chunks"] == 1
        assert ct.report()["c"]["compiles"] == 1
