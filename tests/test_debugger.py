"""First dedicated suite for the step debugger (core/debugger.py):
breakpoints at query IN/OUT terminals, next()/play() stepping, state
inspection while blocked — and the ISSUE 20 wiring: a SiddhiDebugger
attached to an incident replay (`replay_incident(..., debug=True)`), so
the exact query terminal that misbehaved can be breakpointed mid-replay
while the time machine re-feeds the recorded rings."""

import threading
import time

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.debugger import QueryTerminal
from siddhi_tpu.observability.blackbox import (
    attach_emission_collector,
    replay_incident,
)

APP = """
define stream S (symbol string, price float);
@info(name='q')
from S[price > 10.0]#window.length(4)
select symbol, sum(price) as total insert into Out;
"""


def _wait(pred, timeout=5.0):
    t0 = time.time()
    while not pred() and time.time() - t0 < timeout:
        time.sleep(0.02)
    return pred()


class TestBreakpoints:
    def test_in_breakpoint_blocks_then_next_steps(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(APP)
        got = []
        rt.add_callback(
            "Out", lambda evs: got.extend(tuple(e[1]) for e in evs)
        )
        dbg = rt.debug()
        hits = []
        dbg.set_debugger_callback(
            lambda events, qid, term, d: hits.append(
                (qid, term.value, [tuple(e[1]) for e in events])
            )
        )
        dbg.acquire_break_point("q", QueryTerminal.IN)
        rt.start()
        h = rt.get_input_handler("S")

        def sender():
            for i in range(3):
                h.send(("T", 20.0 + i))

        t = threading.Thread(target=sender)
        t.start()
        assert _wait(lambda: dbg._blocked.is_set())
        assert hits == [("q", "IN", [("T", 20.0)])]
        assert got == []  # blocked at IN: nothing processed yet
        dbg.next()  # step: runs until the NEXT event hits the breakpoint
        assert _wait(lambda: len(hits) == 2)
        assert got == [("T", 20.0)]
        dbg.next()
        assert _wait(lambda: len(hits) == 3)
        dbg.release_all_break_points()
        dbg.next()
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert got == [("T", 20.0), ("T", 41.0), ("T", 63.0)]
        mgr.shutdown()

    def test_out_breakpoint_sees_emitted_rows(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(APP)
        dbg = rt.debug()
        hits = []
        dbg.set_debugger_callback(
            lambda events, qid, term, d: hits.append((term.value, events))
        )
        dbg.acquire_break_point("q", QueryTerminal.OUT)
        rt.start()
        t = threading.Thread(
            target=lambda: rt.get_input_handler("S").send(("T", 50.0))
        )
        t.start()
        assert _wait(lambda: dbg._blocked.is_set())
        term, events = hits[0]
        assert term == "OUT"
        assert tuple(events[0][1]) == ("T", 50.0)  # sum over one event
        dbg.play()
        t.join(timeout=5.0)
        assert not t.is_alive()
        mgr.shutdown()

    def test_state_inspection_while_blocked(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(APP)
        dbg = rt.debug()
        dbg.acquire_break_point("q", QueryTerminal.OUT)
        rt.start()
        h = rt.get_input_handler("S")
        t = threading.Thread(target=lambda: [
            h.send(("T", 20.0)), h.send(("T", 30.0)),
        ])
        t.start()
        assert _wait(lambda: dbg._blocked.is_set())
        state = dbg.get_query_state("q")
        assert state is not None  # window state inspectable mid-block
        dbg.next()
        assert _wait(lambda: dbg._blocked.is_set())
        dbg.release_all_break_points()
        dbg.next()
        t.join(timeout=5.0)
        assert not t.is_alive()
        mgr.shutdown()


class TestReplayDebugging:
    def test_breakpoint_mid_incident_replay(self, tmp_path):
        # live run: record, freeze an incident
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(f"""
        @app:name('bbdbg')
        @app:blackbox(triggers='crash', keep='2', dir='{tmp_path}')
        {APP}
        """)
        live = attach_emission_collector(rt)
        rt.start()
        rt.get_input_handler("S").send_many(
            [("T", 20.0 + i) for i in range(6)],
            timestamps=[1_700_000_000_000 + i * 10 for i in range(6)],
        )
        iid = rt._blackbox.fire("crash", "debug replay")
        assert iid is not None
        path = rt.incidents()[-1]["path"]
        mgr.shutdown()

        # replay with the step debugger attached: NOT fed yet — arm
        # breakpoints, feed from a worker thread, step mid-replay
        replay = replay_incident(path, debug=True)
        dbg = replay.debugger
        assert dbg is not None
        t = threading.Thread(target=replay.feed, daemon=True)
        try:
            hits = []
            dbg.set_debugger_callback(
                lambda events, qid, term, d: hits.append(term.value)
            )
            dbg.acquire_break_point("q", QueryTerminal.IN)
            dbg.acquire_break_point("q", QueryTerminal.OUT)
            t.start()
            assert _wait(lambda: dbg._blocked.is_set())
            assert hits == ["IN"]  # replay paused at the query terminal
            assert replay.emissions["Out"] == []  # nothing emitted yet
            dbg.next()  # step IN -> OUT: the batch is processed, blocked
            assert _wait(lambda: len(hits) == 2 and dbg._blocked.is_set())
            assert hits == ["IN", "OUT"]
            # state inspection mid-replay, at the misbehaving terminal
            assert dbg.get_query_state("q") is not None
            dbg.release_all_break_points()
            dbg.next()
            t.join(timeout=10.0)
            assert not t.is_alive()
            # once released, the replay completes byte-identical
            assert replay.emissions == live
        finally:
            # unblock the feed thread even on assertion failure, or the
            # parked worker wedges interpreter shutdown
            dbg.release_all_break_points()
            dbg.next()
            t.join(timeout=5.0)
            replay.close()
