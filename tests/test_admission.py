"""Admission-control tests: per-app bounded ingress, overload policies, and
tenant isolation (`@app:admission`, core/admission.py).

The isolation contract (ISSUE 9): one bursting app degrades ITSELF — sheds
or blocks per its policy, counts metered — while a steady app on the same
manager keeps delivering every event.
"""

import time

import numpy as np
import pytest

from siddhi_tpu import AdmissionRejectedError, SiddhiManager
from siddhi_tpu.core.admission import (
    AdmissionConfig,
    AdmissionController,
    resolve_admission_annotation,
)
from siddhi_tpu.core.errors import SiddhiAppCreationError


def _wait_for(pred, timeout=30.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        v = pred()
        if v:
            return v
        time.sleep(0.01)
    return pred()


def _app(mgr, name, admission, collect="Out"):
    rt = mgr.create_siddhi_app_runtime(f"""
    @app:name('{name}')
    {admission}
    define stream S (v long);
    @info(name='q')
    from S select v insert into Out;
    """)
    got = []
    rt.add_callback(collect, lambda evs: got.extend(e.data for e in evs))
    rt.start()
    return rt, got


class TestAdmissionPolicies:
    def test_shed_newest_keeps_head(self):
        mgr = SiddhiManager()
        rt, got = _app(
            mgr, "ShedNew",
            "@app:admission(policy='shed_newest', rate.limit='100')",
        )
        rt.get_input_handler("S").send_many([(i,) for i in range(500)])
        st = rt.snapshot_status()["admission"]
        assert st["shed"] == 400 and st["admitted"] == 100
        assert got[0] == (0,) and got[-1] == (99,)
        mgr.shutdown()

    def test_shed_oldest_keeps_tail(self):
        mgr = SiddhiManager()
        rt, got = _app(
            mgr, "ShedOld",
            "@app:admission(policy='shed_oldest', rate.limit='100')",
        )
        rt.get_input_handler("S").send_many([(i,) for i in range(500)])
        st = rt.snapshot_status()["admission"]
        assert st["shed"] == 400
        assert got[0] == (400,) and got[-1] == (499,), (
            "shed_oldest must keep the freshest events"
        )
        mgr.shutdown()

    def test_shed_oldest_drains_async_queue(self):
        # a python-queue @async junction: admission drops QUEUED events
        # first, so the freshest data survives end to end
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('ShedQ')
        @app:admission(policy='shed_oldest', max.pending='4')
        define stream S (v long, pad string);
        @info(name='q')
        from S select v insert into Out;
        """)
        rt.start()
        j = rt.junctions["S"]
        j.enable_async(buffer_size=64, workers=1)
        # park the drain worker behind the junction lock so the queue fills
        with j.lock:
            h = rt.get_input_handler("S")
            for i in range(12):
                h.send((i, "x"))
            ctl = rt._admission
        assert ctl.shed > 0
        assert _wait_for(lambda: j.queued() == 0)
        mgr.shutdown()

    def test_error_policy_raises_and_refunds(self):
        mgr = SiddhiManager()
        rt, got = _app(
            mgr, "ErrPol",
            "@app:admission(policy='error', rate.limit='100')",
        )
        h = rt.get_input_handler("S")
        with pytest.raises(AdmissionRejectedError):
            h.send_many([(i,) for i in range(500)])
        assert not got, "a rejected send must deliver nothing"
        st = rt.snapshot_status()["admission"]
        assert st["rejected"] == 500 and st["shed"] == 0
        # the refunded tokens admit an in-quota send immediately
        h.send_many([(i,) for i in range(50)])
        assert len(got) == 50
        mgr.shutdown()

    def test_block_backpressures_then_sheds_at_timeout(self):
        mgr = SiddhiManager()
        rt, got = _app(
            mgr, "BlockPol",
            "@app:admission(policy='block', rate.limit='100', "
            "block.timeout='250 millisec')",
        )
        t0 = time.monotonic()
        rt.get_input_handler("S").send_many([(i,) for i in range(500)])
        wall = time.monotonic() - t0
        st = rt.snapshot_status()["admission"]
        assert wall >= 0.2, "block must back-pressure the sender"
        assert st["blocked_ms"] >= 200
        # ~25 more tokens refill during the wait; the rest sheds at timeout
        assert 100 <= st["admitted"] < 200
        assert st["shed"] == 500 - st["admitted"]
        mgr.shutdown()

    def test_send_columns_applies_admission(self):
        mgr = SiddhiManager()
        rt, got = _app(
            mgr, "Cols",
            "@app:admission(policy='shed_oldest', rate.limit='64')",
        )
        n = 256
        ts = np.arange(1, n + 1, dtype=np.int64)
        rt.get_input_handler("S").send_columns(
            ts, {"v": np.arange(n, dtype=np.int64)}
        )
        st = rt.snapshot_status()["admission"]
        assert st["admitted"] == 64 and st["shed"] == 192
        assert got[-1] == (255,), "tail survives under shed_oldest"
        mgr.shutdown()

    def test_burst_after_idle_refills(self):
        mgr = SiddhiManager()
        rt, got = _app(
            mgr, "Refill",
            "@app:admission(policy='shed_newest', rate.limit='200')",
        )
        h = rt.get_input_handler("S")
        h.send_many([(i,) for i in range(200)])
        assert len(got) == 200
        time.sleep(0.3)  # ~60 tokens refill
        h.send_many([(i,) for i in range(50)])
        assert len(got) == 250, "idle time must refill the bucket"
        mgr.shutdown()


class TestTenantIsolation:
    def test_burster_sheds_while_steady_app_delivers(self):
        """One manager, two tenants: the burster (tight quota, shed_newest)
        degrades itself; the steady app receives every event it sent, and
        the shed counts are metered in /status.json + Prometheus."""
        mgr = SiddhiManager()
        burst_rt, burst_got = _app(
            mgr, "Burster",
            "@app:admission(policy='shed_newest', rate.limit='500')",
        )
        steady_rt, steady_got = _app(mgr, "Steady", "")
        bh = burst_rt.get_input_handler("S")
        sh = steady_rt.get_input_handler("S")
        lat = []
        for round_ in range(5):
            bh.send_many([(i,) for i in range(2000)])  # 4x over quota
            t0 = time.perf_counter()
            sh.send((round_,))
            lat.append(time.perf_counter() - t0)
        assert len(steady_got) == 5, "steady tenant must lose nothing"
        bst = burst_rt.snapshot_status()["admission"]
        assert bst["shed"] >= 2000 * 5 - 500 * 5 - 1000  # quota + refill slop
        assert len(burst_got) == bst["admitted"]
        # metered: /status.json carries the counts, Prometheus the family
        assert "admission" in mgr.snapshot_status()["apps"]["Burster"]
        text = mgr.prometheus_text()
        assert 'siddhi_admission_shed_total{app="Burster"' in text
        # steady sends never waited on the burster's gate (no admission on
        # the steady app, and the burster's shed path does no sleeping)
        assert max(lat) < 1.0, lat
        mgr.shutdown()


class TestAdmissionAnnotation:
    def test_requires_a_bound(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime(
                "@app:admission(policy='block')\n"
                "define stream S (v long);\n"
                "from S select v insert into Out;"
            )
        mgr.shutdown()

    def test_resolver_full_options(self):
        class _Ann:
            elements = [
                ("policy", "block"), ("rate.limit", "1000.5"),
                ("max.pending", "64"), ("block.timeout", "2 sec"),
            ]

            def element(self, k, default=None):
                for kk, v in self.elements:
                    if kk == k:
                        return v
                return default

        cfg = resolve_admission_annotation(_Ann())
        assert cfg.policy == "block"
        assert cfg.rate_eps == 1000.5
        assert cfg.max_pending == 64
        assert cfg.block_timeout_ms == 2000

    def test_controller_without_rate_is_pending_only(self):
        class _J:
            def queued(self):
                return 10

        ctl = AdmissionController("x", AdmissionConfig(
            policy="shed_newest", max_pending=12,
        ))
        lo, hi = ctl.admit(8, _J())  # room for 2 of 8
        assert (lo, hi) == (0, 2)
        assert ctl.shed == 6

    def test_pending_bound_overflow_refunds_quota_tokens(self):
        """Tokens drained for events the pending bound then refused must go
        back to the bucket: a full queue must not quota-starve the sender
        once the queue frees."""
        class _J:
            full = True

            def queued(self):
                return 10 if self.full else 0

        j = _J()
        ctl = AdmissionController("x", AdmissionConfig(
            policy="shed_newest", rate_eps=100.0, max_pending=10,
        ))
        lo, hi = ctl.admit(50, j)  # room 0: all shed, 50 tokens refunded
        assert (lo, hi) == (0, 0) and ctl.shed == 50
        j.full = False
        lo, hi = ctl.admit(10, j)  # the refunded quota is still there
        assert (lo, hi) == (0, 10), "bucket was drained by refused events"
        assert ctl.admitted == 10

    def test_replay_bypasses_the_admission_gate(self):
        """Stored entries were admitted once already: replay must not ride
        the admission gate, or a quota-starved gate silently sheds the
        replay while the caller purges the entry (permanent loss)."""
        from siddhi_tpu.core.error_store import ORIGIN_STREAM, make_entry

        mgr = SiddhiManager()
        rt, got = _app(
            mgr, "ReplayAdm",
            "@app:admission(policy='shed_newest', rate.limit='100')",
        )
        # drain the whole quota so live traffic holds the bucket at zero
        rt.get_input_handler("S").send_many([(i,) for i in range(200)])
        assert rt._admission.shed > 0
        n_live = len(got)
        entry = make_entry(
            "ReplayAdm", ORIGIN_STREAM, "S", RuntimeError("boom"),
            events=[(1, (777,))],
        )
        mgr.error_store.store(entry)
        assert mgr.replay_errors() == 1
        assert _wait_for(lambda: (777,) in got[n_live:]), (
            "replayed entry was shed by the admission gate"
        )
        mgr.shutdown()

    def test_stable_handler_survives_restart(self, tmp_path):
        # admission wiring is annotation-carried: the supervisor's rebuilt
        # runtime re-applies it, and the restart-stable handler keeps
        # gating (supervision + admission compose)
        from siddhi_tpu.core.persistence import FileSystemPersistenceStore
        from siddhi_tpu.testing import InjectedFault, faults

        mgr = SiddhiManager()
        mgr.set_persistence_store(FileSystemPersistenceStore(str(tmp_path)))
        sup = mgr.supervise(poll_interval_s=0.05)
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('AdmSup')
        @app:admission(policy='shed_newest', rate.limit='100')
        @app:restart(max.attempts='2')
        define stream S (v long);
        @info(name='q')
        from S select v insert into Out;
        """)
        rt.start()
        h = sup.input_handler("AdmSup", "S")
        h.send_many([(i,) for i in range(50)])
        faults.install(faults.parse_plan("junction_dispatch@S:times=1"))
        try:
            h.send((99,))
        except InjectedFault:
            pass
        assert _wait_for(lambda: sup.restarts.get("AdmSup", 0) >= 1)
        faults.uninstall()
        rt2 = mgr.get_siddhi_app_runtime("AdmSup")
        assert rt2 is not rt and rt2._admission is not None
        rt2._admission.admitted = 0
        h.send_many([(i,) for i in range(500)])
        assert rt2._admission.shed > 0, "rebuilt app still gates ingress"
        mgr.shutdown()
