"""SiddhiQL parser tests — grammar -> AST round trips.

Mirrors the reference's siddhi-query-compiler test strategy (grammar -> AST
assertions) over the SiddhiQL surface in SiddhiQL.g4.
"""

import pytest

from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
from siddhi_tpu.core.errors import SiddhiParserError
from siddhi_tpu.core.types import AttrType
from siddhi_tpu.query_api.definition import Duration
from siddhi_tpu.query_api.execution import (
    AbsentStreamStateElement,
    CountStateElement,
    DeleteStream,
    EventOutputRate,
    EveryStateElement,
    Filter,
    InsertIntoStream,
    JoinInputStream,
    JoinType,
    LogicalStateElement,
    LogicalType,
    NextStateElement,
    OutputEventsFor,
    OutputRateType,
    Partition,
    RangePartitionType,
    SingleInputStream,
    SnapshotOutputRate,
    StateInputStream,
    StateStreamType,
    StreamStateElement,
    TimeOutputRate,
    UpdateOrInsertStream,
    UpdateStream,
    ValuePartitionType,
    WindowHandler,
)
from siddhi_tpu.query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    CompareOp,
    Constant,
    In,
    IsNull,
    Multiply,
    Or,
    Variable,
)


def parse(s):
    return SiddhiCompiler.parse(s)


def test_define_stream():
    app = parse("define stream StockStream (symbol string, price float, volume long);")
    d = app.stream_definitions["StockStream"]
    assert [(a.name, a.type) for a in d.attributes] == [
        ("symbol", AttrType.STRING),
        ("price", AttrType.FLOAT),
        ("volume", AttrType.LONG),
    ]


def test_case_insensitive_keywords_and_comments():
    app = parse(
        """
        -- line comment
        DEFINE STREAM S (a INT, b BOOL); /* block
        comment */
        FROM S SELECT a INSERT INTO Out;
        """
    )
    assert "S" in app.stream_definitions
    assert len(app.execution_elements) == 1


def test_app_annotations_and_info():
    app = parse(
        """
        @app:name('MyApp') @app:statistics('true')
        define stream S (a int);
        @info(name = 'query1')
        from S select a insert into Out;
        """
    )
    assert app.name == "MyApp"
    q = app.execution_elements[0]
    assert q.annotations[0].name == "info"
    assert q.annotations[0].element("name") == "query1"


def test_filter_query_structure():
    app = parse(
        """
        define stream cseEventStream (symbol string, price float, volume long);
        from cseEventStream[volume < 150] select symbol, price insert into outputStream;
        """
    )
    q = app.execution_elements[0]
    s = q.input_stream
    assert isinstance(s, SingleInputStream)
    assert isinstance(s.handlers[0], Filter)
    cond = s.handlers[0].expression
    assert isinstance(cond, Compare) and cond.op is CompareOp.LT
    assert q.selector.selection_list[0].name == "symbol"
    out = q.output_stream
    assert isinstance(out, InsertIntoStream) and out.target == "outputStream"


def test_window_and_stream_function_handlers():
    app = parse(
        """
        define stream S (a int, b string);
        from S[a > 10]#window.length(5) select a, sum(a) as total insert into O;
        """
    )
    s = app.execution_elements[0].input_stream
    assert isinstance(s.handlers[0], Filter)
    w = s.handlers[1]
    assert isinstance(w, WindowHandler)
    assert w.window.name == "length"
    assert w.window.parameters[0].value == 5
    agg = app.execution_elements[0].selector.selection_list[1]
    assert agg.rename == "total"
    assert isinstance(agg.expression, AttributeFunction)


def test_time_constants():
    assert SiddhiCompiler.parse_time_constant("1 min 30 sec") == 90_000
    assert SiddhiCompiler.parse_time_constant("2 hours") == 7_200_000
    assert SiddhiCompiler.parse_time_constant("500 milliseconds") == 500
    e = SiddhiCompiler.parse_expression("1 min")
    assert isinstance(e, Constant) and e.value == 60_000 and e.type is AttrType.LONG


def test_expression_precedence():
    e = SiddhiCompiler.parse_expression("a + b * 2 > 5 and c == 'x' or not d")
    assert isinstance(e, Or)
    assert isinstance(e.left, And)
    gt = e.left.left
    assert isinstance(gt, Compare) and gt.op is CompareOp.GT
    assert isinstance(gt.left, Add) and isinstance(gt.left.right, Multiply)


def test_literals():
    cases = {
        "42": (42, AttrType.INT),
        "42L": (42, AttrType.LONG),
        "4.2f": (4.2, AttrType.FLOAT),
        "4.2": (4.2, AttrType.DOUBLE),
        "4.2d": (4.2, AttrType.DOUBLE),
        "-7": (-7, AttrType.INT),
        "true": (True, AttrType.BOOL),
        "'str'": ("str", AttrType.STRING),
    }
    for src, (val, t) in cases.items():
        e = SiddhiCompiler.parse_expression(src)
        assert isinstance(e, Constant) and e.value == val and e.type is t, src


def test_is_null_and_in():
    e = SiddhiCompiler.parse_expression("price is null")
    assert isinstance(e, IsNull) and isinstance(e.expression, Variable)
    e2 = SiddhiCompiler.parse_expression("symbol == 'x' in MyTable")
    assert isinstance(e2, In) and e2.source_id == "MyTable"


def test_join_query():
    app = parse(
        """
        define stream A (symbol string, price float);
        define stream B (symbol string, qty int);
        from A#window.length(10) as l join B#window.time(1 min) as r
            on l.symbol == r.symbol
        select l.symbol as s, r.qty insert into J;
        """
    )
    j = app.execution_elements[0].input_stream
    assert isinstance(j, JoinInputStream)
    assert j.join_type is JoinType.JOIN
    assert j.left.alias == "l" and j.right.alias == "r"
    assert isinstance(j.on, Compare)
    v = j.on.left
    assert isinstance(v, Variable) and v.stream_id == "l" and v.attribute == "symbol"


def test_outer_joins_and_unidirectional():
    app = parse(
        """
        define stream A (x int); define stream B (x int);
        from A#window.length(2) unidirectional left outer join B#window.length(2)
            on A.x == B.x select A.x insert into O;
        """
    )
    j = app.execution_elements[0].input_stream
    assert j.join_type is JoinType.LEFT_OUTER
    assert j.unidirectional == "left"


def test_pattern_every_within():
    app = parse(
        """
        define stream A (v int); define stream B (v int);
        from every e1=A[v > 10] -> e2=B[v > e1.v] within 1 min
        select e1.v as v1, e2.v as v2 insert into O;
        """
    )
    st = app.execution_elements[0].input_stream
    assert isinstance(st, StateInputStream)
    assert st.type is StateStreamType.PATTERN
    chain = st.state
    assert isinstance(chain, NextStateElement)
    assert isinstance(chain.state, EveryStateElement)
    first = chain.state.state
    assert isinstance(first, StreamStateElement)
    assert first.stream.alias == "e1"
    second = chain.next
    # within attaches to the last term
    assert second.within_ms == 60_000
    # filter referencing e1.v
    f = second.stream.handlers[0]
    assert isinstance(f.expression, Compare)
    assert f.expression.right.stream_id == "e1"


def test_pattern_count_and_collect():
    app = parse(
        """
        define stream A (v int); define stream B (v int);
        from e1=A[v>0]<2:5> -> e2=B select e1[0].v as f, e1[last].v as l insert into O;
        """
    )
    st = app.execution_elements[0].input_stream
    cnt = st.state.state
    assert isinstance(cnt, CountStateElement)
    assert (cnt.min_count, cnt.max_count) == (2, 5)
    sel = app.execution_elements[0].selector
    v0 = sel.selection_list[0].expression
    assert v0.stream_index == 0
    vl = sel.selection_list[1].expression
    assert vl.stream_index == Variable.LAST


def test_pattern_logical_and_absent():
    app = parse(
        """
        define stream A (v int); define stream B (v int); define stream C (v int);
        from e1=A and e2=B -> not C for 2 sec select e1.v insert into O;
        """
    )
    st = app.execution_elements[0].input_stream
    chain = st.state
    logical = chain.state
    assert isinstance(logical, LogicalStateElement)
    assert logical.type is LogicalType.AND
    absent = chain.next
    assert isinstance(absent, AbsentStreamStateElement)
    assert absent.waiting_time_ms == 2000


def test_sequence_with_kleene():
    app = parse(
        """
        define stream A (v int); define stream B (v int);
        from every e1=A, e2=A[v > e1.v]+, e3=B select e1.v insert into O;
        """
    )
    st = app.execution_elements[0].input_stream
    assert st.type is StateStreamType.SEQUENCE
    # chain: Next(Next(Every(e1), Count(e2,1,ANY)), e3)
    inner = st.state.state
    assert isinstance(inner.next, CountStateElement)
    assert inner.next.min_count == 1
    assert inner.next.max_count == CountStateElement.ANY


def test_output_rates():
    app = parse(
        """
        define stream S (a int);
        from S select a output last every 3 events insert into O1;
        from S select a output every 2 sec insert into O2;
        from S select a output snapshot every 1 sec insert into O3;
        """
    )
    r1, r2, r3 = [q.output_rate for q in app.execution_elements]
    assert isinstance(r1, EventOutputRate) and r1.events == 3 and r1.type is OutputRateType.LAST
    assert isinstance(r2, TimeOutputRate) and r2.millis == 2000
    assert isinstance(r3, SnapshotOutputRate) and r3.millis == 1000


def test_group_by_having_order_limit():
    app = parse(
        """
        define stream S (sym string, p float, v int);
        from S#window.lengthBatch(4)
        select sym, avg(p) as ap group by sym, v having ap > 10
        order by sym desc limit 5 offset 1
        insert all events into O;
        """
    )
    sel = app.execution_elements[0].selector
    assert [g.attribute for g in sel.group_by] == ["sym", "v"]
    assert sel.having is not None
    assert sel.order_by[0].variable.attribute == "sym"
    assert sel.order_by[0].order.value == "desc"
    assert sel.limit == 5 and sel.offset == 1
    assert app.execution_elements[0].output_stream.output_events is OutputEventsFor.ALL


def test_table_crud_outputs():
    app = parse(
        """
        define stream S (sym string, p float);
        define table T (sym string, p float);
        from S select sym, p insert into T;
        from S delete T on T.sym == sym;
        from S update T set T.p = p on T.sym == sym;
        from S update or insert into T set T.p = p on T.sym == sym;
        """
    )
    outs = [q.output_stream for q in app.execution_elements]
    assert isinstance(outs[1], DeleteStream) and outs[1].target == "T"
    assert isinstance(outs[2], UpdateStream)
    assert outs[2].set_attributes[0].table_variable.stream_id == "T"
    assert isinstance(outs[3], UpdateOrInsertStream)


def test_partition():
    app = parse(
        """
        define stream S (sym string, p float);
        partition with (sym of S)
        begin
            from S select sym, sum(p) as t insert into #inner;
            from #inner select sym, t insert into Out;
        end;
        """
    )
    part = app.execution_elements[0]
    assert isinstance(part, Partition)
    assert isinstance(part.partition_types[0], ValuePartitionType)
    assert len(part.queries) == 2
    assert part.queries[0].output_stream.is_inner
    assert part.queries[1].input_stream.is_inner


def test_range_partition():
    app = parse(
        """
        define stream S (p float);
        partition with (p < 10 as 'low' or p >= 10 as 'high' of S)
        begin from S select p insert into O; end;
        """
    )
    pt = app.execution_elements[0].partition_types[0]
    assert isinstance(pt, RangePartitionType)
    assert [r.partition_key for r in pt.ranges] == ["low", "high"]


def test_definitions_window_trigger_function_aggregation():
    app = parse(
        """
        define window W (a int) length(5) output all events;
        define trigger T at every 5 sec;
        define trigger T2 at 'start';
        define function f[javascript] return int { return 1; };
        define stream S (sym string, p float, ts long);
        define aggregation Agg from S select sym, avg(p) as ap group by sym
            aggregate by ts every sec ... year;
        """
    )
    assert app.window_definitions["W"].window.name == "length"
    assert app.trigger_definitions["T"].at_every_ms == 5000
    assert app.trigger_definitions["T2"].at_start
    assert app.function_definitions["f"].language == "javascript"
    agg = app.aggregation_definitions["Agg"]
    assert agg.time_period.durations[0] is Duration.SECONDS
    assert agg.time_period.durations[-1] is Duration.YEARS
    assert agg.aggregate_attribute.attribute == "ts"


def test_store_query():
    sq = SiddhiCompiler.parse_store_query(
        "from T on p > 5 select sym, p order by p desc limit 2"
    )
    assert sq.input_store.store_id == "T"
    assert isinstance(sq.input_store.on, Compare)
    assert sq.selector.limit == 2


def test_parse_errors_have_location():
    with pytest.raises(SiddhiParserError) as ei:
        parse("define stream S (a int)\nfrom S select ^ insert into O;")
    assert "line" in str(ei.value)


def test_select_star_passthrough():
    app = parse(
        "define stream S (a int); from S insert into O; from S select * insert into P;"
    )
    assert app.execution_elements[0].selector.select_all
    assert app.execution_elements[1].selector.select_all


def test_triple_quoted_string_annotation():
    app = parse(
        '''
        @sink(type='log', @map(type='json', @payload("""{"v":{{a}}}""")))
        define stream S (a int);
        '''
    )
    sink = app.stream_definitions["S"].annotations[0]
    assert sink.name == "sink"
    m = sink.annotations[0]
    assert m.name == "map"
    assert m.annotations[0].elements[0][1] == '{"v":{{a}}}'
