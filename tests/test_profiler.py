"""Continuous profiler + EXPLAIN ANALYZE (observability/profiler.py,
observability/explain.py, /profile + /explain endpoints).

Covers: compile telemetry (count/cause/wall per program, cache-hit
accounting, the recompile-cause taxonomy), per-chunk stage waterfalls on
the fused (serial + pipelined, deliver and non-deliver) and per-batch
paths, the top-K slowest ring bound, `runtime.explain()` live annotations
on a multi-query app, the HTTP endpoints, and the zero-overhead-when-off
contract (companion to the gating tests in tests/test_statistics.py).
"""

from __future__ import annotations

import json
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.observability.profiler import (
    CAUSE_FIRST,
    CAUSE_TAIL_K,
    CompileTelemetry,
    Profiler,
)


class _Gate:
    enabled = True


def _mk(mgr, extra=""):
    rt = mgr.create_siddhi_app_runtime(f"""
    @app:statistics(reporter='none')
    @app:batch(size='32')
    define stream S (symbol string, price float);
    @info(name='q')
    from S[price > 10]#window.length(8)
    select symbol, avg(price) as ap insert into Out;
    {extra}
    """)
    rt.start()
    return rt


def _feed_columns(mgr, rt, n, start=0):
    h = rt.get_input_handler("S")
    sym = np.full((n,), mgr.interner.intern("A"), dtype=np.int32)
    h.send_columns(
        np.arange(n, dtype=np.int64) + start,
        {"symbol": sym, "price": np.linspace(0, 99, n, dtype=np.float32)},
    )


class TestCompileTelemetryUnit:
    def test_cache_growth_is_a_compile_and_hits_count(self):
        import jax
        import jax.numpy as jnp

        ct = CompileTelemetry(gate=_Gate())
        f = jax.jit(lambda x: x + 1)
        f(jnp.zeros(3))
        ct.observe("c", f, (3,), 1_000_000)
        f(jnp.zeros(3))
        ct.observe("c", f, (3,), 1_000)
        f(jnp.zeros(4))
        ct.observe("c", f, (4,), 2_000_000)
        rep = ct.report()["c"]
        assert rep["compiles"] == 2
        assert rep["cache_hits"] == 1
        assert rep["causes"] == {"first_compile": 1, "shape_change": 1}
        assert rep["signatures"] == 2
        assert rep["wall_ms_total"] == pytest.approx(3.0, abs=0.01)
        assert len(rep["recent"]) == 2
        assert rep["recent"][0]["cause"] == CAUSE_FIRST

    def test_tail_hint_on_first_compile_reads_first_compile(self):
        import jax
        import jax.numpy as jnp

        ct = CompileTelemetry(gate=_Gate())
        f = jax.jit(lambda x: x * 2)
        f(jnp.zeros(2))
        ct.observe("c", f, (2,), 1000, cause_hint=CAUSE_TAIL_K)
        f(jnp.zeros(5))
        ct.observe("c", f, (5,), 1000, cause_hint=CAUSE_TAIL_K)
        causes = ct.report()["c"]["causes"]
        assert causes == {"first_compile": 1, "tail_variant_k": 1}

    def test_gate_off_is_a_noop(self):
        g = _Gate()
        g.enabled = False
        ct = CompileTelemetry(gate=g)
        ct.observe("c", object(), (1,), 1000)
        assert ct.report() == {}
        assert ct.component("c") is None


class TestProfilerUnit:
    def test_top_k_keeps_slowest(self):
        import time

        prof = Profiler(gate=_Gate(), top_k=2)
        for i, dt in enumerate((0.003, 0.001, 0.006)):
            wf = prof.begin("S", 10)
            wf.stage("encode", int(dt * 1e9))
            time.sleep(dt)
            prof.end(wf)
        rep = prof.report()
        assert rep["chunks"] == 3 and rep["events"] == 30
        tops = [w["seq"] for w in rep["slowest"]]
        assert len(tops) == 2 and 2 not in tops  # the fast one evicted
        assert rep["slowest"][0]["total_ms"] >= rep["slowest"][1]["total_ms"]

    def test_gate_off_returns_none_and_records_nothing(self):
        g = _Gate()
        g.enabled = False
        prof = Profiler(gate=g)
        assert prof.begin("S", 1) is None
        prof.end(None)  # must not raise
        prof.tls_stage("device", 123)  # no active wf: no-op
        assert prof.report() == {"chunks": 0, "events": 0, "slowest": []}


class TestEngineProfile:
    def test_fused_ingest_records_compiles_and_waterfalls(self):
        mgr = SiddhiManager()
        rt = _mk(mgr)
        _feed_columns(mgr, rt, 1024)  # full chunk, fused deliverless
        _feed_columns(mgr, rt, 256)   # short tail -> tail-variant compile
        prof = rt.profile_report()
        comp = prof["compile"]
        fused = [k for k in comp if k.startswith("stream.S.fused")]
        assert fused, comp
        ledger = comp[fused[0]]
        assert ledger["compiles"] >= 2
        assert CAUSE_FIRST in ledger["causes"]
        assert CAUSE_TAIL_K in ledger["causes"]
        assert ledger["wall_ms_total"] > 0
        ev = ledger["recent"][0]
        assert ev["wall_ms"] > 0 and ev["cause"] == CAUSE_FIRST
        wfs = prof["waterfalls"]
        assert wfs["chunks"] >= 2 and wfs["events"] >= 1024
        stages = wfs["slowest"][0]["stages_ms"]
        assert "encode" in stages and "dispatch" in stages
        mgr.shutdown()

    def test_deliver_mode_waterfall_has_drain_stages(self):
        mgr = SiddhiManager()
        rt = _mk(mgr)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(i or []))
        _feed_columns(mgr, rt, 1024)
        prof = rt.profile_report()
        assert got, "callbacks must deliver"
        stages = prof["waterfalls"]["slowest"][0]["stages_ms"]
        for s in ("encode", "dispatch", "device", "deliver"):
            assert s in stages, stages

    def test_per_batch_waterfall_has_device_and_readback(self):
        mgr = SiddhiManager()
        rt = _mk(mgr)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(i or []))
        _feed_columns(mgr, rt, 32)  # single micro-batch: per-batch path
        prof = rt.profile_report()
        wfs = prof["waterfalls"]["slowest"]
        assert wfs, prof
        stages = wfs[0]["stages_ms"]
        for s in ("encode", "dispatch", "device", "readback"):
            assert s in stages, stages
        mgr.shutdown()

    def test_per_query_step_compile_ledger(self):
        mgr = SiddhiManager()
        rt = _mk(mgr)
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send(("A", float(40 + i)))
        comp = rt.profile_report()["compile"]["query.q"]
        assert comp["compiles"] == 1
        assert comp["causes"] == {"first_compile": 1}
        assert comp["cache_hits"] == 2
        mgr.shutdown()

    def test_high_quantiles_include_p9999(self):
        mgr = SiddhiManager()
        rt = _mk(mgr)
        h = rt.get_input_handler("S")
        for i in range(4):
            h.send(("A", float(i)))
        prof = rt.profile_report()
        lat = prof["latency_high_ms"]["query.q"]
        assert set(lat) == {"count", "p99", "p999", "p9999"}
        assert lat["p9999"] >= lat["p99"] > 0
        # the full report summaries carry p9999 too (Prometheus 0.9999)
        summ = rt.statistics_manager.report()["latency_ms"]["query.q"]
        assert "p9999" in summ
        text = mgr.prometheus_text()
        assert 'quantile="0.9999"' in text
        mgr.shutdown()


class TestExplain:
    def test_explain_multi_query_live_counters(self):
        mgr = SiddhiManager()
        rt = _mk(mgr, extra="""
        @info(name='q2') from S select symbol, price insert into Out2;
        """)
        _feed_columns(mgr, rt, 320)
        # one per-batch send so query.q's own step program compiles too
        # (fused sends run the impls inside the chunk program, whose
        # ledger sits on the stream node)
        rt.get_input_handler("S").send(("A", 50.0))
        plan = rt.explain(fmt="dict")
        assert plan["live"] and plan["analyzed"]
        nodes = {n["id"]: n for n in plan["nodes"]}
        assert "query:q" in nodes and "query:q2" in nodes
        qc = nodes["query:q"]["counters"]
        assert qc["dispatches"] >= 1
        assert qc["events_in"] == 321
        assert "latency_ms" in qc and "compile" in qc
        assert "compile" in nodes["stream:S"]["counters"]
        sc = nodes["stream:S"]["counters"]
        assert sc["events"] == 321
        assert sc["fused"] in ("pipelined", "serial")
        # edges connect S to both queries
        froms = [
            e for e in plan["edges"]
            if e["from"] == "stream:S" and e["to"].startswith("query:")
        ]
        assert len(froms) == 2
        text = rt.explain()
        assert "EXPLAIN ANALYZE" in text and "query q2" in text
        mgr.shutdown()

    def test_explain_without_statistics_is_topology_only(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (a int);
        @info(name='q') from S select a insert into Out;
        """)
        rt.start()
        plan = rt.explain(fmt="dict")
        assert not plan["live"]
        assert any(n["id"] == "query:q" for n in plan["nodes"])
        assert "EXPLAIN —" in rt.explain()
        mgr.shutdown()

    def test_explain_partitioned_app_renders(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:statistics(reporter='none')
        define stream S (symbol string, price float);
        partition with (symbol of S) begin
        @info(name='pq') from S[price > 20] select symbol, price as ap
        insert into #tmp;
        @info(name='pq2') from #tmp select symbol insert into Out2;
        end;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(4):
            h.send(("A", float(10 + i * 20)))
        text = rt.explain()
        assert "query pq" in text and "#tmp" in text
        mgr.shutdown()


class TestProfileEndpoints:
    def test_profile_and_explain_served(self):
        mgr = SiddhiManager()
        rt = _mk(mgr)
        _feed_columns(mgr, rt, 256)
        port = mgr.serve_metrics(0)

        def get(path):
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ).read().decode()

        prof = json.loads(get("/profile"))
        assert len(prof) == 1 and prof[0]["app"] == "SiddhiApp"
        assert any(
            ent["compiles"] >= 1 and ent["recent"][0]["wall_ms"] > 0
            for ent in prof[0]["compile"].values()
        )
        assert prof[0]["waterfalls"]["chunks"] >= 1
        text = get("/explain")
        assert "EXPLAIN ANALYZE" in text and "query q" in text
        plan = json.loads(get("/explain.json"))["SiddhiApp"]
        assert plan["nodes"] and plan["edges"]
        mgr.shutdown()


class TestZeroOverheadWhenOff:
    def test_no_statistics_annotation_wires_nothing(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (a int);
        @info(name='q') from S select a insert into Out;
        """)
        rt.start()
        qr = rt.queries["q"]
        assert qr.compile_telemetry is None and qr.profiler is None
        j = rt.junctions["S"]
        assert j.profiler is None and j.compile_telemetry is None
        assert rt.profile_report() is None
        mgr.shutdown()

    def test_enable_stats_false_gates_profiler_and_telemetry(self):
        mgr = SiddhiManager()
        rt = _mk(mgr)
        _feed_columns(mgr, rt, 256)
        before = rt.profile_report()
        assert before["waterfalls"]["chunks"] >= 1
        compiles_before = {
            k: v["compiles"] for k, v in before["compile"].items()
        }
        hits_before = {
            k: v["cache_hits"] for k, v in before["compile"].items()
        }
        rt.enable_stats(False)
        _feed_columns(mgr, rt, 256, start=10_000)
        h = rt.get_input_handler("S")
        for i in range(3):
            h.send(("A", 50.0))
        after = rt.profile_report()
        assert after["waterfalls"]["chunks"] == before["waterfalls"]["chunks"]
        assert {
            k: v["compiles"] for k, v in after["compile"].items()
        } == compiles_before
        assert {
            k: v["cache_hits"] for k, v in after["compile"].items()
        } == hits_before  # not even hit-counting while off
        rt.enable_stats(True)
        _feed_columns(mgr, rt, 256, start=20_000)
        assert (
            rt.profile_report()["waterfalls"]["chunks"]
            > before["waterfalls"]["chunks"]
        )
        mgr.shutdown()
