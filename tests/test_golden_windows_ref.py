"""Golden corpus: window / rate-limit / join behaviors translated from the
reference's own test DATA (query strings, event sequences, expected outputs):

- query/window/LengthWindowTestCase.java (tests 1-3)
- query/window/LengthBatchWindowTestCase.java (tests 1-6)
- query/window/SortWindowTestCase.java (test 1)
- query/window/FrequentWindowTestCase.java (test 1)
- query/ratelimit/EventOutputRateLimitTestCase.java (tests 1-5)
- query/join/JoinTestCase.java (tests 1, 10) — reference timings kept
  (1 sec windows; jit compiles happen in a warm-up phase).

The harness records each QueryCallback delivery as (ins, removed) data
tuples, mirroring how the reference asserts counts and per-position values.
"""

from __future__ import annotations

import time

from siddhi_tpu import SiddhiManager


def run(ql, sends, settle=0.0, query_name="query1", warm=()):
    """sends: list of (stream, row) or ('sleep', seconds).

    `warm`: inert (stream, row) pairs sent before the timed phase so each
    per-stream jit compile happens outside any wall-clock window under test
    (first compile takes seconds)."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    deliveries = []
    rt.add_callback(
        query_name,
        lambda ts, ins, rem: deliveries.append(
            (
                [tuple(e.data) for e in ins] if ins else [],
                [tuple(e.data) for e in rem] if rem else [],
            )
        ),
    )
    rt.start()
    handlers = {}
    for stream, row in warm:
        handlers.setdefault(stream, rt.get_input_handler(stream)).send(row)
    if warm:
        time.sleep(0.5)  # let warm rows age out of any time windows
        deliveries.clear()
    for step in sends:
        if step[0] == "sleep":
            time.sleep(step[1])
            continue
        stream, row = step
        handlers.setdefault(stream, rt.get_input_handler(stream)).send(row)
    if settle:
        time.sleep(settle)
    rt.shutdown()
    mgr.shutdown()
    return deliveries


def totals(deliveries):
    ins = sum(len(i) for i, _ in deliveries)
    rem = sum(len(r) for _, r in deliveries)
    return ins, rem


CSE = "define stream cseEventStream (symbol string, price float, volume int);\n"


class TestLengthWindowGolden:
    def test1_current_only(self):
        d = run(CSE + """@info(name = 'query1')
            from cseEventStream#window.length(4)
            select symbol,price,volume insert into outputStream ;""",
            [("cseEventStream", ("IBM", 700.0, 0)),
             ("cseEventStream", ("WSO2", 60.5, 1))])
        assert totals(d) == (2, 0)
        assert [i[0][2] for i, _ in d] == [0, 1]  # message order

    def test2_all_events_interleave(self):
        d = run(CSE + """@info(name = 'query1')
            from cseEventStream#window.length(4)
            select symbol,price,volume insert all events into outputStream ;""",
            [("cseEventStream", ("IBM", 700.0, i + 1)) for i in range(6)])
        assert totals(d) == (6, 2)
        # expired event i fires exactly when event i+length arrives
        assert [i[0][2] for i, _ in d] == [1, 2, 3, 4, 5, 6]
        assert [r[0][2] for _, r in d if r] == [1, 2]
        # the expired row rides the SAME delivery as its displacing current
        assert [i[0][2] for i, r in d if r] == [5, 6]

    def test3_query_callback_counts(self):
        d = run(CSE + """@info(name = 'query1')
            from cseEventStream#window.length(4)
            select symbol,price,volume insert all events into outputStream ;""",
            [("cseEventStream", ("WSO2", 60.5, i + 1)) for i in range(6)])
        assert totals(d) == (6, 2)


class TestLengthBatchWindowGolden:
    def test1_underfull_batch_stays_silent(self):
        d = run(CSE + """@info(name = 'query1')
            from cseEventStream#window.lengthBatch(4)
            select symbol,price,volume insert into outputStream ;""",
            [("cseEventStream", ("IBM", 700.0, 0)),
             ("cseEventStream", ("WSO2", 60.5, 1))])
        assert totals(d) == (0, 0)

    def test2_flush_emits_batch_in_order(self):
        d = run(CSE + """@info(name = 'query1')
            from cseEventStream#window.lengthBatch(4)
            select symbol,price,volume insert into outputStream ;""",
            [("cseEventStream", ("IBM", 700.0, i + 1)) for i in range(6)])
        assert totals(d) == (4, 0)
        assert [r[2] for i, _ in d for r in i] == [1, 2, 3, 4]

    def test3_all_events_expired_at_next_flush(self):
        d = run(CSE + """@info(name = 'query1')
            from cseEventStream#window.lengthBatch(2)
            select symbol,price,volume insert all events into outputStream ;""",
            [("cseEventStream", ("IBM", 700.0, i + 1)) for i in range(6)])
        assert totals(d) == (6, 4)
        flat_in = [r[2] for i, _ in d for r in i]
        flat_rm = [r[2] for _, rm in d for r in rm]
        assert flat_in == [1, 2, 3, 4, 5, 6]
        assert flat_rm == [1, 2, 3, 4]

    def test4_aggregated_flush_single_row(self):
        d = run(CSE + """@info(name = 'query1')
            from cseEventStream#window.lengthBatch(4)
            select symbol,sum(price) as sumPrice,volume
            insert into outputStream ;""",
            [("cseEventStream", ("IBM", 10.0, 0)),
             ("cseEventStream", ("WSO2", 20.0, 1)),
             ("cseEventStream", ("IBM", 30.0, 0)),
             ("cseEventStream", ("WSO2", 40.0, 1)),
             ("cseEventStream", ("IBM", 50.0, 0)),
             ("cseEventStream", ("WSO2", 60.0, 1))])
        rows = [r for i, _ in d for r in i]
        assert len(rows) == 1
        assert rows[0][1] == 100.0

    def test5_expired_events_only(self):
        d = run(CSE + """@info(name = 'query1')
            from cseEventStream#window.lengthBatch(2)
            select symbol,price,volume insert expired events into outputStream ;""",
            [("cseEventStream", ("IBM", 700.0, i + 1)) for i in range(6)])
        ins, rem = totals(d)
        assert ins == 0 and rem == 4
        assert [r[2] for _, rm in d for r in rm] == [1, 2, 3, 4]

    def test6_aggregated_sums_per_flush(self):
        d = run(CSE + """@info(name = 'query1')
            from cseEventStream#window.lengthBatch(4)
            select symbol,sum(price) as sumPrice,volume
            insert all events into outputStream ;""",
            [("cseEventStream", ("IBM", 10.0, 0)),
             ("cseEventStream", ("WSO2", 20.0, 1)),
             ("cseEventStream", ("IBM", 30.0, 0)),
             ("cseEventStream", ("WSO2", 40.0, 1)),
             ("cseEventStream", ("IBM", 50.0, 0)),
             ("cseEventStream", ("WSO2", 60.0, 1)),
             ("cseEventStream", ("WSO2", 60.0, 1)),
             ("cseEventStream", ("IBM", 70.0, 0)),
             ("cseEventStream", ("WSO2", 80.0, 1))])
        rows = [r for i, _ in d for r in i]
        assert [r[1] for r in rows] == [100.0, 240.0]


class TestSortWindowGolden:
    def test1_counts(self):
        ql = """define stream cseEventStream (symbol string, price float, volume long);
        @info(name = 'query1')
        from cseEventStream#window.sort(2,volume, 'asc')
        select volume insert all events into outputStream ;"""
        d = run(ql, [
            ("cseEventStream", ("WSO2", 55.6, 100)),
            ("cseEventStream", ("IBM", 75.6, 300)),
            ("cseEventStream", ("WSO2", 57.6, 200)),
            ("cseEventStream", ("WSO2", 55.6, 20)),
            ("cseEventStream", ("WSO2", 57.6, 40)),
        ])
        assert totals(d) == (5, 3)
        # the sort window keeps the 2 SMALLEST volumes: evictions are the
        # largest at each overflow: 300, then 200, then 100
        assert [r[0] for _, rm in d for r in rm] == [300, 200, 100]


class TestFrequentWindowGolden:
    def test1_whole_event_key(self):
        ql = """define stream purchase (cardNo string, price float);
        @info(name = 'query1')
        from purchase[price >= 30]#window.frequent(2)
        select cardNo, price insert all events into PotentialFraud ;"""
        sends = []
        for _ in range(2):
            sends += [
                ("purchase", ("3234-3244-2432-4124", 73.36)),
                ("purchase", ("1234-3244-2432-123", 46.36)),
                ("purchase", ("5768-3244-2432-5646", 48.36)),
                ("purchase", ("9853-3244-2432-4125", 78.36)),
            ]
        d = run(ql, sends)
        assert totals(d) == (8, 6)


class TestEventRateLimitGolden:
    LOGIN = "define stream LoginEvents (timestamp long, ip string);\n"
    IPS = ["192.10.1.3", "192.10.1.3", "192.10.1.4", "192.10.1.3", "192.10.1.5"]

    def _run(self, output_clause, ips):
        ql = self.LOGIN + f"""@info(name = 'query1')
        from LoginEvents select ip {output_clause} insert into uniqueIps ;"""
        return run(ql, [("LoginEvents", (1_700_000_000_000 + i, ip))
                        for i, ip in enumerate(ips)])

    def test1_all_every_2(self):
        d = self._run("output all every 2 events", self.IPS)
        assert totals(d) == (4, 0)

    def test2_default_every_2(self):
        d = self._run("output every 2 events", self.IPS)
        assert totals(d) == (4, 0)

    def test3_every_5_of_8(self):
        ips = ["192.10.1.5", "192.10.1.5", "192.10.1.3", "192.10.1.9",
               "192.10.1.4", "192.10.1.4", "192.10.1.4", "192.10.1.30"]
        d = self._run("output every 5 events", ips)
        assert totals(d) == (5, 0)

    def test4_first_every_2(self):
        ips = ["192.10.1.5", "192.10.1.3", "192.10.1.9", "192.10.1.4",
               "192.10.1.3"]
        d = self._run("output first every 2 events", ips)
        assert totals(d) == (3, 0)
        assert [r[0] for i, _ in d for r in i] == [
            "192.10.1.5", "192.10.1.9", "192.10.1.3"
        ]

    def test5_first_every_3(self):
        ips = ["192.10.1.5", "192.10.1.3", "192.10.1.9", "192.10.1.4",
               "192.10.1.3"]
        d = self._run("output first every 3 events", ips)
        assert totals(d) == (2, 0)
        assert [r[0] for i, _ in d for r in i] == ["192.10.1.5", "192.10.1.4"]


class TestJoinGolden:
    STREAMS = """define stream cseEventStream (symbol string, price float, volume int);
    define stream twitterStream (user string, tweet string, company string);
    """

    def test1_time_join_both_directions(self):
        # JoinTestCase.joinTest1, 1 sec window scaled to 300 ms
        ql = self.STREAMS + """@info(name = 'query1')
        from cseEventStream#window.time(1 sec) join twitterStream#window.time(1 sec)
        on cseEventStream.symbol== twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert all events into outputStream ;"""
        d = run(ql, [
            ("cseEventStream", ("WSO2", 55.6, 100)),
            ("twitterStream", ("User1", "Hello World", "WSO2")),
            ("cseEventStream", ("IBM", 75.6, 100)),
            ("sleep", 0.5),
            ("cseEventStream", ("WSO2", 57.6, 100)),
            ("sleep", 1.3),
        ], warm=[("cseEventStream", ("X", 1.0, 1)),
                 ("twitterStream", ("U", "t", "Y"))])
        ins, rem = totals(d)
        assert ins == 2 and rem == 2

    def test10_unidirectional(self):
        # JoinTestCase.joinTest10: only the left side drives the join
        ql = self.STREAMS + """@info(name = 'query1')
        from cseEventStream#window.time(1 sec) unidirectional
        join twitterStream#window.time(1 sec)
        on cseEventStream.symbol== twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert into outputStream ;"""
        d = run(ql, [
            ("twitterStream", ("User1", "Hello World", "WSO2")),
            ("cseEventStream", ("WSO2", 55.6, 100)),
            ("cseEventStream", ("WSO2", 57.6, 100)),
            ("sleep", 0.5),
        ], warm=[("cseEventStream", ("X", 1.0, 1)),
                 ("twitterStream", ("U", "t", "Y"))])
        ins, rem = totals(d)
        assert ins == 2 and rem == 0


class TestOuterJoinGolden:
    STREAMS = """define stream cseEventStream (symbol string, price float, volume int);
    define stream twitterStream (user string, tweet string, company string);
    """

    def test1_full_outer(self):
        # OuterJoinTestCase.joinTest1
        ql = self.STREAMS + """@info(name = 'query1')
        from cseEventStream#window.length(3) full outer join twitterStream#window.length(1)
        on cseEventStream.symbol== twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price
        insert all events into outputStream ;"""
        d = run(ql, [
            ("cseEventStream", ("WSO2", 55.6, 100)),
            ("twitterStream", ("User1", "Hello World", "WSO2")),
            ("cseEventStream", ("IBM", 75.6, 100)),
            ("cseEventStream", ("WSO2", 57.6, 100)),
        ])
        flat_in = [r for i, _ in d for r in i]
        assert [
            (r[0], r[1], round(r[2], 4) if r[2] is not None else None)
            for r in flat_in
        ] == [
            ("WSO2", None, round(55.6, 4)),
            ("WSO2", "Hello World", round(55.6, 4)),
            ("IBM", None, round(75.6, 4)),
            ("WSO2", "Hello World", round(57.6, 4)),
        ]

    def test2_right_outer(self):
        # OuterJoinTestCase.joinTest2
        ql = self.STREAMS + """@info(name = 'query1')
        from cseEventStream#window.length(1) right outer join twitterStream#window.length(2)
        on cseEventStream.symbol== twitterStream.company
        select cseEventStream.symbol as symbol, twitterStream.tweet, cseEventStream.price, twitterStream.company as company
        insert all events into outputStream ;"""
        d = run(ql, [
            ("twitterStream", ("User1", "Hello World", "WSO2")),
            ("cseEventStream", ("BMW", 57.6, 100)),
            ("twitterStream", ("User2", "Welcome", "IBM")),
            ("cseEventStream", ("WSO2", 57.6, 100)),
        ])
        flat_in = [r for i, _ in d for r in i]
        assert [(r[0], r[1], r[3]) for r in flat_in] == [
            (None, "Hello World", "WSO2"),
            (None, "Welcome", "IBM"),
            ("WSO2", "Hello World", "WSO2"),
        ]


class TestExternalTimeWindowGolden:
    def test1_event_time_expiry(self):
        # ExternalTimeWindowTestCase.externalTimeWindowTest1 — fully
        # event-time driven, no wall clock involved
        ql = """define stream LoginEvents (timestamp long, ip string) ;
        @info(name = 'query1')
        from LoginEvents#window.externalTime(timestamp,5 sec)
        select timestamp, ip
        insert all events into uniqueIps ;"""
        d = run(ql, [
            ("LoginEvents", (1366335804341, "192.10.1.3")),
            ("LoginEvents", (1366335804342, "192.10.1.4")),
            ("LoginEvents", (1366335814341, "192.10.1.5")),
            ("LoginEvents", (1366335814345, "192.10.1.6")),
            ("LoginEvents", (1366335824341, "192.10.1.7")),
        ])
        assert totals(d) == (5, 4)


class TestNullCompareGolden:
    def test_null_operand_fails_every_comparison(self):
        """Any comparison with a null operand is false, NEQ included
        (reference: CompareConditionExpressionExecutor.java:42)."""
        ql = CSE + """@info(name = 'query1')
            from cseEventStream[volume < 150 or volume >= 150 or volume != 7]
            select symbol, volume insert into outputStream ;"""
        d = run(ql, [
            ("cseEventStream", ("IBM", 700.0, 100)),
            ("cseEventStream", ("CCC", 70.0, None)),
            ("cseEventStream", ("WSO2", 60.5, 200)),
        ])
        assert [r[0] for i, _ in d for r in i] == ["IBM", "WSO2"]


class TestExternalTimeBatchGolden:
    """query/window/ExternalTimeBatchWindowTestCase.java — event-time batch
    windows; fully deterministic (the clock is the timestamp attribute)."""

    QL = """define stream jmxMetric(cpu int, timestamp long);
    @info(name='query')
    from jmxMetric#window.externalTimeBatch(timestamp, 10 sec)
    select avg(cpu) as avgCpu, count() as c insert into tmp;"""

    def test03_no_flush_inside_first_window(self):
        # test03NoEdgeCase: 5 events spanning < 10 sec -> no output at all
        now = 1_700_000_000_000
        d = run(self.QL, [
            ("jmxMetric", (15, now + i * 1000)) for i in range(5)
        ], query_name="query")
        assert totals(d) == (0, 0)

    def test05_edge_case_two_flushes(self):
        # test05EdgeCase: two rounds of 3 events 10 sec apart + a trigger:
        # two flushes, avg 15 then 85, count 3 each
        now = 0
        sends = [("jmxMetric", (15, now + i * 10)) for i in range(3)]
        sends += [("jmxMetric", (85, now + 10000 + i * 10)) for i in range(3)]
        sends += [("jmxMetric", (10000, now + 10 * 10000))]
        d = run(self.QL, sends, query_name="query")
        flat_in = [r for i, _ in d for r in i]
        assert [(r[0], r[1]) for r in flat_in] == [(15.0, 3), (85.0, 3)]
