"""First-class sharded execution (parallel/shard.py): `@app:shard` /
SIDDHI_TPU_SHARD resolved at start().

Covers the runtime half of the mesh contract promoted out of the multichip
dryrun: annotation/env resolution (one SA129 rule set with the analyzer),
round-robin router key distribution and batch-order merge (byte-identical
delivery vs unsharded), the stateless-only eligibility gate, partition-axis
mesh placement parity over key churn, per-device dispatch counters in
`describe_state()`/`snapshot_status()`/Prometheus, and a verify-suite
parity sweep under SIDDHI_TPU_SHARD=8 vs off (the in-process slice of the
CI diff; conftest forces the 8-device CPU mesh)."""

from __future__ import annotations

import os
import sys

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.parallel.shard import (
    resolve_shard_annotation,
    router_eligible,
    shardable_stateless,
)
from siddhi_tpu.query_api.annotation import Annotation

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


SYMS = ["WSO2", "IBM", "GOOG", "MSFT", "ORCL", "AAPL", "AMZN", "NVDA"]

STATELESS_QL = """@app:batch(size='32')
{HEAD}define stream S (symbol string, price float, volume long);
@info(name='q') from S[price > 50] select symbol, price insert into Out;
@info(name='q2') from S select symbol, volume insert into Out2;
"""


def _feed_cols(n, seed=5):
    rng = np.random.default_rng(seed)
    ts = np.arange(n, dtype=np.int64) + 1_700_000_000_000
    cols = {
        "symbol": rng.integers(1, 9, size=n).astype(np.int32),
        "price": rng.uniform(0, 100, size=n).astype(np.float32),
        "volume": rng.integers(1, 1000, size=n).astype(np.int64),
    }
    return ts, cols


def _run_stateless(head, n=4096, qids=("q", "q2")):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(STATELESS_QL.replace("{HEAD}", head))
    for s in SYMS:
        mgr.interner.intern(s)
    got = {qid: [] for qid in qids}
    for qid in qids:
        rt.add_callback(
            qid,
            lambda ts, ins, rem, _q=qid: got[_q].extend(
                [tuple(e.data) for e in (ins or [])]
            ),
        )
    rt.start()
    ts, cols = _feed_cols(n)
    rt.get_input_handler("S").send_columns(ts, cols, now=int(ts[-1]))
    status = rt.snapshot_status()
    fi = rt.junctions["S"].fused_ingest
    router = getattr(fi, "shard_router", None) if fi is not None else None
    router_state = router.describe_state() if router is not None else None
    prom = (
        rt.statistics_manager.prometheus_text()
        if rt.statistics_manager is not None
        else ""
    )
    rt.shutdown()
    mgr.shutdown()
    return got, status, router_state, prom


# ---------------------------------------------------------------------------
# annotation / env resolution (SA129 rule set)
# ---------------------------------------------------------------------------


class TestShardResolution:
    def test_annotation_devices_and_axis(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_TPU_SHARD", raising=False)
        ann = Annotation("app:shard", [("devices", "8"), ("axis", "part")])
        assert resolve_shard_annotation(ann) == (8, "part")

    def test_sole_positional_devices(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_TPU_SHARD", raising=False)
        assert resolve_shard_annotation(
            Annotation("app:shard", [(None, "4")])
        ) == (4, "auto")

    def test_no_annotation_defaults_off(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_TPU_SHARD", raising=False)
        assert resolve_shard_annotation(None) == (0, "auto")

    def test_env_overrides_annotation_both_directions(self, monkeypatch):
        ann = Annotation("app:shard", [("devices", "8")])
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "0")
        assert resolve_shard_annotation(ann)[0] == 0
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "4")
        assert resolve_shard_annotation(None)[0] == 4

    @pytest.mark.parametrize(
        "elements",
        [
            [("devices", "0")],
            [("devices", "-3")],
            [("devices", "many")],
            [("devices", "8"), ("axis", "diagonal")],
            [("devices", "8"), ("turbo", "on")],
        ],
    )
    def test_malformed_annotation_raises(self, monkeypatch, elements):
        monkeypatch.delenv("SIDDHI_TPU_SHARD", raising=False)
        with pytest.raises(SiddhiAppCreationError):
            resolve_shard_annotation(Annotation("app:shard", elements))

    def test_runtime_creation_rejects_malformed(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_TPU_SHARD", raising=False)
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime(
                "@app:shard(devices='8', axis='diagonal')\n"
                "define stream S (a int);\n"
                "from S select a insert into Out;"
            )
        mgr.shutdown()

    def test_analyzer_sa129_same_rule_set(self):
        from siddhi_tpu.analysis import analyze
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

        app = SiddhiCompiler.parse(
            "@app:shard(devices='0', axis='diagonal', turbo='on')\n"
            "define stream S (a int);\n"
            "from S select a insert into Out;"
        )
        codes = [d.code for d in analyze(app).diagnostics]
        assert codes.count("SA129") == 3, codes


# ---------------------------------------------------------------------------
# batch-axis router
# ---------------------------------------------------------------------------


class TestBatchRouter:
    def test_round_robin_distribution_and_counts(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        n = 4096  # 128 micro-batches of 32 -> 16 per device
        _got, status, router_state, _ = _run_stateless("", n=n)
        assert router_state is not None, "router did not arm"
        assert router_state["devices"] == 8
        assert sum(router_state["per_device_events"]) == n
        # round-robin over equal-size batches: every device gets an equal
        # share, so every occupancy is 1.0
        assert len(set(router_state["per_device_events"])) == 1
        assert all(d >= 1 for d in router_state["per_device_dispatches"])
        assert router_state["occupancy"] == [1.0] * 8
        # surfaced through snapshot_status too
        shard = status["shard"]
        assert shard["devices"] == 8
        assert shard["streams"]["S"]["per_device_events"] == (
            router_state["per_device_events"]
        )

    def test_merge_preserves_delivery_order_byte_identically(
        self, monkeypatch
    ):
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        sharded, _s, router_state, _ = _run_stateless("", n=4096)
        assert router_state is not None
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "0")
        unsharded, _s2, no_router, _ = _run_stateless("", n=4096)
        assert no_router is None
        assert sharded == unsharded
        assert len(sharded["q"]) > 500  # the filter actually selected rows
        assert len(sharded["q2"]) == 4096

    def test_multi_chunk_per_device_stays_byte_identical(self, monkeypatch):
        """More than two chunks per device in one send: every chunk's wire
        is staged before any dispatch, so staging must never reuse a buffer
        an earlier chunk still occupies (a pooled slot would be re-acquired
        ungated and overwrite staged bytes — duplicated/lost events)."""
        # @app:ingestChunk(size='4'): 3072 events / batch 32 = 96 batches,
        # 12 per device = THREE K=4 chunks each
        head = "@app:ingestChunk(size='4')\n"
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        sharded, _s, router_state, _ = _run_stateless(head, n=3072)
        assert router_state is not None
        assert min(router_state["per_device_dispatches"]) >= 3
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "0")
        unsharded, _s2, _r, _ = _run_stateless(head, n=3072)
        assert sharded == unsharded
        assert len(sharded["q2"]) == 3072

    def test_guarded_junction_owns_sharded_drain_failures(self, monkeypatch):
        """A poison query callback on a junction with an exception handler:
        the sharded merge drain must route the error through the junction's
        failure machinery (like every single-device drain), not abort the
        send — behavior may not diverge between shard on and off."""
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            STATELESS_QL.replace("{HEAD}", "")
        )
        for s in SYMS:
            mgr.interner.intern(s)
        caught = []
        rt.set_exception_handler(caught.append)
        delivered = []
        rt.add_callback("q2", lambda ts, ins, rem: delivered.extend(ins or []))

        def poison(ts, ins, rem):
            raise RuntimeError("poison callback")

        rt.add_callback("q", poison)
        rt.start()
        assert getattr(
            rt.junctions["S"].fused_ingest, "shard_router", None
        ) is not None
        ts, cols = _feed_cols(2048)
        # must not raise: the handler owns the failure (like the
        # single-device _drain_guarded, whose drain also aborts the
        # remaining endpoints of the failed drain call — healthy-endpoint
        # delivery after a poison is not promised on either path)
        rt.get_input_handler("S").send_columns(ts, cols, now=int(ts[-1]))
        assert caught and "poison" in str(caught[0])
        # the engine survives: a later send still reaches the router
        sends_before = rt.junctions["S"].fused_ingest.shard_router.sends
        rt.get_input_handler("S").send_columns(ts, cols, now=int(ts[-1]))
        assert rt.junctions["S"].fused_ingest.shard_router.sends > sends_before
        rt.shutdown()
        mgr.shutdown()

    def test_short_sends_fall_back_to_single_device(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        # one micro-batch: M=1 < 2 devices — router declines, single-device
        # path owns the call, rows still delivered
        got, _s, router_state, _ = _run_stateless("", n=32)
        assert len(got["q2"]) == 32
        assert router_state["sends"] == 0

    def test_stateful_endpoints_not_routed(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:batch(size='32')\n"
            "define stream S (symbol string, price float, volume long);\n"
            "@info(name='q') from S#window.length(8) "
            "select symbol, avg(price) as ap insert into Out;"
        )
        rt.start()
        fi = rt.junctions["S"].fused_ingest
        assert fi is None or getattr(fi, "shard_router", None) is None
        rt.shutdown()
        mgr.shutdown()

    def test_shardable_stateless_predicate(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "@app:batch(size='32')\n"
            "define stream S (symbol string, price float, volume long);\n"
            "@info(name='stateless') from S[price > 1] "
            "select symbol insert into Out1;\n"
            "@info(name='windowed') from S#window.length(4) "
            "select symbol insert into Out2;\n"
            "@info(name='agg') from S "
            "select sum(volume) as tv insert into Out3;\n"
            "@info(name='limited') from S select symbol "
            "output every 5 events insert into Out4;"
        )
        assert shardable_stateless(rt.queries["stateless"])
        assert not shardable_stateless(rt.queries["windowed"])
        assert not shardable_stateless(rt.queries["agg"])
        assert not shardable_stateless(rt.queries["limited"])
        mgr.shutdown()

    def test_prometheus_shard_families(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        _got, _s, router_state, prom = _run_stateless(
            "@app:statistics(reporter='none')\n", n=4096
        )
        assert router_state is not None
        assert "siddhi_shard_device_dispatches_total" in prom
        assert "siddhi_shard_device_events_total" in prom
        assert "siddhi_shard_device_occupancy" in prom
        assert 'device="7"' in prom

    def test_explain_renders_shard_counters(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            STATELESS_QL.replace("{HEAD}", "@app:statistics(reporter='none')\n")
        )
        for s in SYMS:
            mgr.interner.intern(s)
        rt.start()
        ts, cols = _feed_cols(4096)
        rt.get_input_handler("S").send_columns(ts, cols, now=int(ts[-1]))
        plan = rt.explain(fmt="dict")
        snode = next(n for n in plan["nodes"] if n["id"] == "stream:S")
        assert "shard" in snode.get("counters", {}), snode
        text = rt.explain()
        assert "shard[devices=8]" in text
        rt.shutdown()
        mgr.shutdown()


# ---------------------------------------------------------------------------
# partition-axis mesh placement
# ---------------------------------------------------------------------------

PARTITION_QL = """@app:batch(size='64')
@app:partitionCapacity(size='32')
{HEAD}define stream S (symbol string, price float, volume long);
partition with (symbol of S)
begin
    @info(name='q')
    from S[price > 0]#window.length(8)
    select symbol, sum(volume) as total, avg(price) as ap
    insert into Out;
end;
"""


def _run_partitioned(head, steps=30, bsz=64):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(PARTITION_QL.replace("{HEAD}", head))
    for i in range(24):
        mgr.interner.intern(f"SYM{i}")
    got = []
    rt.add_callback(
        "q", lambda ts, ins, rem: got.extend(
            [tuple(e.data) for e in (ins or [])]
        )
    )
    rt.start()
    rng = np.random.default_rng(11)
    h = rt.get_input_handler("S")
    for s in range(steps):
        pool = np.arange(1, 7) if s < 10 else np.arange(1, 21)
        ts = np.arange(bsz, dtype=np.int64) + 1_700_000_000_000 + s * bsz
        cols = {
            "symbol": rng.choice(pool, size=bsz).astype(np.int32),
            "price": rng.uniform(1, 100, size=bsz).astype(np.float32),
            "volume": rng.integers(1, 100, size=bsz).astype(np.int64),
        }
        h.send_columns(ts, cols, now=int(ts[-1]))
    status = rt.snapshot_status()
    rt.shutdown()
    mgr.shutdown()
    return got, status


class TestPartitionMesh:
    def test_parity_over_key_churn(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        sharded, status = _run_partitioned("")
        placed = status["shard"]["partitioned"]["q"]
        assert placed == {
            "sharded": True, "devices": 8, "axis": "part", "local_slots": 4,
        }
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "0")
        unsharded, status2 = _run_partitioned("")
        assert "shard" not in status2
        assert len(sharded) > 800
        assert sharded == unsharded

    def test_indivisible_capacity_pads_to_mesh(self, monkeypatch):
        # 32 % 6 != 0: the [P] axis is padded to 36 (6 local slots per
        # device) with dead slots that no key ever hashes to a live
        # position of — results byte-match the unsharded run
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "6")
        sharded, status = _run_partitioned("", steps=8)
        placed = status["shard"]["partitioned"]["q"]
        assert placed == {
            "sharded": True, "devices": 6, "axis": "part",
            "local_slots": 6, "padded_slots": 4,
        }
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "0")
        unsharded, _ = _run_partitioned("", steps=8)
        assert sharded == unsharded

    def test_annotation_axis_part_only_skips_batch_router(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_TPU_SHARD", raising=False)
        _got, _s, router_state, _ = _run_stateless(
            "@app:shard(devices='8', axis='part')\n", n=2048
        )
        assert router_state is None  # batch axis not requested


# ---------------------------------------------------------------------------
# verify-suite parity sweep (the in-process slice of the CI diff)
# ---------------------------------------------------------------------------


class TestVerifyParity:
    def test_verify_cases_byte_identical_shard8_vs_off(self, monkeypatch):
        import bench

        monkeypatch.setenv("SIDDHI_TPU_VERIFY_COLUMNAR", "1")
        results = {}
        for mode in ("8", "0"):
            monkeypatch.setenv("SIDDHI_TPU_SHARD", mode)
            results[mode] = bench._leg_verify()["cases"]
        errors = {
            k: v
            for m in results
            for k, v in results[m].items()
            if isinstance(v, str)
        }
        assert not errors, errors
        bad = [
            k for k in sorted(set(results["8"]) | set(results["0"]))
            if results["8"].get(k) != results["0"].get(k)
        ]
        assert not bad, bad
