"""Playback / @async / statistics / debugger tests.

Reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/managment/
PlaybackTestCase, AsyncTestCase, StatisticsTestCase and
debugger/TestDebugger.java.
"""

import threading
import time

from siddhi_tpu import SiddhiManager


class TestPlayback:
    def test_event_time_window_expiry(self):
        # time window driven by EVENT time, not wall time
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:playback
        define stream S (symbol string, price float);
        @info(name='q')
        from S#window.time(1 sec) select sum(price) as total insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(e.data for e in i or []))
        rt.start()
        h = rt.get_input_handler("S")
        base = 1_500_000_000_000
        h.send(("A", 10.0), timestamp=base)
        h.send(("B", 20.0), timestamp=base + 100)
        # jump event time past the window: A and B expire on arrival
        h.send(("C", 5.0), timestamp=base + 2_000)
        assert got == [(10.0,), (30.0,), (5.0,)]
        rt.shutdown()
        mgr.shutdown()

    def test_heartbeat_advances_idle_clock(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:playback(idle.time='50 millisec', increment='2 sec')
        define stream S (symbol string, price float);
        @info(name='q')
        from S#window.time(1 sec) select sum(price) as total
        insert all events into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, i, r: (
            got.extend(e.data for e in i or []),
            got.extend(e.data for e in r or []),
        ))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("A", 10.0), timestamp=1_500_000_000_000)
        # no more events: the idle heartbeat advances the virtual clock by 2s,
        # expiring A from the 1s window via the event-time scheduler
        t0 = time.time()
        while len(got) < 2 and time.time() - t0 < 10.0:
            time.sleep(0.05)
        assert len(got) >= 2  # the expiry fired without any new event
        rt.shutdown()
        mgr.shutdown()


class TestAsync:
    def test_async_ingress_delivers_everything(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @async(buffer.size='256', workers='1', batch.size.max='32')
        define stream S (symbol string, volume long);
        @info(name='q')
        from S select count() as n insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(e.data for e in i or []))
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(100):
            h.send(("A", i))
        t0 = time.time()
        while (not got or got[-1][0] < 100) and time.time() - t0 < 10.0:
            time.sleep(0.05)
        assert got[-1][0] == 100  # every event arrived exactly once, in order
        rt.shutdown()
        mgr.shutdown()


class TestStatistics:
    def test_trackers_collect(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:statistics(reporter='log', interval='3600')
        define stream S (symbol string, volume long);
        @info(name='q')
        from S select symbol insert into Out;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(5):
            h.send(("A", i))
        rep = rt.statistics_manager.report()
        assert rep["throughput"]["stream.S"] == 5
        assert rep["latency_avg_ms"]["query.q"] > 0
        rt.shutdown()
        mgr.shutdown()


class TestDebugger:
    def test_breakpoint_blocks_and_steps(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (symbol string);
        @info(name='q')
        from S select symbol insert into Out;
        """)
        got = []
        rt.add_callback("q", lambda ts, i, r: got.extend(e.data for e in i or []))
        from siddhi_tpu.core.debugger import QueryTerminal

        dbg = rt.debug()
        hits = []
        dbg.set_debugger_callback(
            lambda events, qid, term, d: hits.append((qid, term.value, len(events)))
        )
        dbg.acquire_break_point("q", QueryTerminal.IN)
        rt.start()

        def sender():
            rt.get_input_handler("S").send(("WSO2",))

        t = threading.Thread(target=sender)
        t.start()
        t0 = time.time()
        while not hits and time.time() - t0 < 5.0:
            time.sleep(0.02)
        assert hits == [("q", "IN", 1)]
        assert got == []  # blocked before processing
        dbg.play()
        t.join(timeout=5.0)
        assert got == [("WSO2",)]
        state = dbg.get_query_state("q")
        assert state is not None
        rt.shutdown()
        mgr.shutdown()


def test_statistics_report_includes_memory():
    # TPU-native analog of the reference's ObjectSizeCalculator memory metric:
    # per-component device-buffer bytes in the stats report
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime("""
    @app:statistics(reporter='none')
    define stream S (v long);
    define table T (v long);
    @info(name='q') from S#window.length(4) select sum(v) as s insert into Out;
    """)
    rt.start()
    rt.get_input_handler("S").send((1,))
    rep = rt.statistics_manager.report()
    assert "memory_bytes" in rep
    assert rep["memory_bytes"].get("query.q", 0) > 0, rep
    assert "table.T" in rep["memory_bytes"], rep
    rt.shutdown()
    mgr.shutdown()
