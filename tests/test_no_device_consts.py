"""Regression guard: compiled step programs must capture NO jax.Array consts.

On tunneled PJRT backends, lowering a jaxpr that holds a concrete jax.Array
constant (scalar or array) reads the buffer back to the host to embed it —
and the first device->host transfer permanently flips the relay out of its
speculative fast mode, degrading EVERY subsequent dispatch in the process
from ~0.02 ms to ~2.5 ms (measured on TPU v5e behind the axon relay; 330x
on the end-to-end filter step). Constants must therefore be numpy (embedded
as HLO literals with no readback) or built inside the trace via lax
primitives.

These tests trace representative query programs and assert the invariant
deterministically — no timing, no TPU needed.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from siddhi_tpu import SiddhiManager


def _batch_for(rt, mgr, stream, n=64):
    rng = np.random.default_rng(0)
    jn = rt.junctions[stream]
    ts = np.arange(n, dtype=np.int64) + 1_700_000_000_000
    cols = {}
    for name, t in jn.schema.attrs:
        from siddhi_tpu.core.types import AttrType

        if t is AttrType.STRING:
            cols[name] = rng.integers(1, 5, size=n).astype(np.int32)
        elif t in (AttrType.FLOAT, AttrType.DOUBLE):
            cols[name] = rng.uniform(0.0, 100.0, size=n).astype(np.float32)
        elif t is AttrType.BOOL:
            cols[name] = rng.integers(0, 2, size=n).astype(bool)
        else:
            cols[name] = rng.integers(1, 1000, size=n).astype(np.int64)
    return jn.schema.to_batch_cols(ts, cols, mgr.interner, capacity=n)


def _assert_no_device_consts(tag, fn, *args):
    closed = jax.make_jaxpr(fn)(*args)
    bad = [
        f"shape={c.shape} dtype={c.dtype}"
        for c in closed.consts
        if isinstance(c, jax.Array)
    ]
    assert not bad, f"{tag}: jax.Array consts captured: {bad}"


APPS = {
    "filter_const": """
        define stream S (symbol string, price float, volume long);
        @info(name='q') from S[price > 50 and symbol == 'WSO2']
        select symbol, price * 2 as p2 insert into Out;
    """,
    "window_agg": """
        define stream S (symbol string, price float, volume long);
        @info(name='q') from S#window.length(16)
        select symbol, avg(price) as ap, min(price) as mn, max(volume) as mx
        insert into Out;
    """,
    "batch_groupby": """
        define stream S (symbol string, price float, volume long);
        @info(name='q') from S#window.lengthBatch(8)
        select symbol, sum(volume) as tv, count() as c group by symbol
        having tv > 0 insert into Out;
    """,
    "time_window": """
        define stream S (symbol string, price float, volume long);
        @info(name='q') from S#window.time(1 sec)
        select symbol, sum(price) as sp insert into Out;
    """,
    "isnull_cast": """
        define stream S (symbol string, price float, volume long);
        @info(name='q') from S[not (volume is null)]
        select symbol, cast(price, 'double') as pd,
               ifThenElse(price > 50, 'hi', 'lo') as tag
        insert into Out;
    """,
}


@pytest.mark.parametrize("name", sorted(APPS))
def test_single_stream_steps_capture_no_device_consts(name):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime("@app:batch(size='64')\n" + APPS[name])
    rt.start()
    try:
        qr = rt.queries["q"]
        b = _batch_for(rt, mgr, "S")
        st = qr._fresh(qr.init_state())
        tst = qr._collect_table_states()
        now = np.int64(1_700_000_000_100)
        _assert_no_device_consts(
            name, lambda s, bb: qr._step_impl(s, tst, bb, now), st, b
        )
    finally:
        rt.shutdown()
        mgr.shutdown()


def test_join_step_captures_no_device_consts():
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime("""
        @app:batch(size='64') @app:joinCapacity(size='128')
        define stream S (symbol string, price float, volume long);
        @info(name='q')
        from S#window.length(8) as a join S#window.length(8) as b
        on a.volume == b.volume
        select a.symbol as s1, b.symbol as s2 insert into Out;
    """)
    rt.start()
    try:
        qr = rt.queries["q"]
        b = _batch_for(rt, mgr, "S")
        st = qr._fresh(qr.init_state())
        tst = qr._collect_table_states()
        now = np.int64(1_700_000_000_100)
        _assert_no_device_consts(
            "join", lambda s, bb: qr._step_impl(s, tst, bb, now, "l"), st, b
        )
    finally:
        rt.shutdown()
        mgr.shutdown()


def test_pattern_step_captures_no_device_consts():
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime("""
        @app:batch(size='64') @app:patternCapacity(size='64')
        define stream S (symbol string, price float, volume long);
        @info(name='q')
        from every a=S[price > 90] -> b=S[price < 10] within 1 sec
        select a.symbol as s1, b.symbol as s2 insert into Out;
    """)
    rt.start()
    try:
        qr = rt.queries["q"]
        b = _batch_for(rt, mgr, "S")
        st = qr._fresh(qr.init_state(1_700_000_000_000))
        step = qr._steps["S"]
        impl = getattr(step, "__wrapped__", step)
        now = np.int64(1_700_000_000_100)
        _assert_no_device_consts(
            "pattern", lambda s, bb: impl(s, {}, bb, now), st, b
        )
    finally:
        rt.shutdown()
        mgr.shutdown()
