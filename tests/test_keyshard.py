"""Key-sharded stateful scale-out (`@app:shard(axis='keys')`).

Non-partitioned group-by aggregation state is hashed across the mesh so
each device owns a DISJOINT key range; join window rings shard via
explicit GSPMD in/out shardings. The contract under test throughout:
keyed-shard emissions are byte-identical to the unsharded run — the
key-routed pre-pass masks rows to their owner, the positional psum fold
(floats bitcast to integer lanes first) reconstructs the exact output.

Reference: the cloud-native deployment framework's key-hash sharding of
detection state (PAPERS.md, arxiv 2401.09960).
"""

from __future__ import annotations

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.analysis import build_fusion_plan, compute_costs
from siddhi_tpu.analysis.fusion import H_KEYSHARD
from siddhi_tpu.parallel.keyshard import keyed_shardable, mix64, owner_of

SYMS = ["WSO2", "IBM", "GOOG", "MSFT", "ORCL", "AAPL", "AMZN", "NVDA"]

GB_QL = """@app:batch(size='64')
{HEAD}define stream S (symbol string, price float, volume long);
@info(name='q') from S select symbol, sum(volume) as sv, count() as c,
 min(volume) as mn group by symbol insert into Out;
"""

KEYS8 = "@app:shard(devices='8', axis='keys')\n"


def _mgr():
    mgr = SiddhiManager()
    for s in SYMS:
        mgr.interner.intern(s)
    return mgr


def _feed(h, n, seed, base=1_700_000_000_000):
    rng = np.random.default_rng(seed)
    ts = np.arange(n, dtype=np.int64) + base
    cols = {
        "symbol": rng.integers(1, 9, size=n).astype(np.int32),
        "price": rng.uniform(0, 100, size=n).astype(np.float32),
        "volume": rng.integers(1, 1000, size=n).astype(np.int64),
    }
    h.send_columns(ts, cols, now=int(ts[-1]))


def _run(ql, names=("q",), feeds=1, shard=None, monkeypatch=None):
    if monkeypatch is not None:
        monkeypatch.setenv("SIDDHI_TPU_SHARD", shard or "0")
    mgr = _mgr()
    rt = mgr.create_siddhi_app_runtime(ql)
    got = {n: [] for n in names}
    for n in names:
        rt.add_callback(
            n,
            lambda ts, i, r, _n=n: got[_n].extend(
                tuple(e.data) for e in (i or [])
            ),
        )
    rt.start()
    for f in range(feeds):
        _feed(
            rt.get_input_handler("S"), 256, 5 + f,
            base=1_700_000_000_000 + f * 1_000,
        )
    return mgr, rt, got


class TestOwnerHash:
    def test_mix64_host_device_agree(self):
        import jax.numpy as jnp

        keys = np.arange(1, 257, dtype=np.uint64) * np.uint64(7919)
        host = mix64(keys)
        dev = np.asarray(mix64(jnp.asarray(keys)))
        assert (host == dev).all()

    def test_owner_partition_is_total_and_disjoint(self):
        keys = np.arange(4096, dtype=np.int64)
        own = owner_of(keys, 8)
        assert own.min() >= 0 and own.max() < 8
        # splitmix64 scrambles sequential ids off a single stripe
        counts = np.bincount(own, minlength=8)
        assert (counts > 0).all()


class TestEligibility:
    CASES = {
        "exact_ints": (
            "from S select symbol, sum(volume) as v, count() as c, "
            "max(volume) as hi group by symbol insert into Out;",
            True,
        ),
        "extreme_float": (
            "from S select symbol, min(price) as lo "
            "group by symbol insert into Out;",
            True,
        ),
        "avg_float": (
            "from S select symbol, avg(price) as ap "
            "group by symbol insert into Out;",
            False,
        ),
        "stddev_float": (
            "from S select symbol, stddev(price) as sd "
            "group by symbol insert into Out;",
            False,
        ),
        "sum_float": (
            "from S select symbol, sum(price) as sp "
            "group by symbol insert into Out;",
            False,
        ),
        "no_group": (
            "from S select symbol, sum(volume) as v insert into Out;",
            False,
        ),
        "windowed": (
            "from S#window.length(8) select symbol, sum(volume) as v "
            "group by symbol insert into Out;",
            False,
        ),
        "ordered": (
            "from S select symbol, sum(volume) as v group by symbol "
            "order by v insert into Out;",
            False,
        ),
    }

    @pytest.mark.parametrize("case", sorted(CASES))
    def test_predicate(self, case):
        body, want = self.CASES[case]
        mgr = _mgr()
        rt = mgr.create_siddhi_app_runtime(
            "define stream S (symbol string, price float, volume long);\n"
            f"@info(name='q') {body}"
        )
        ok, why = keyed_shardable(rt.queries["q"])
        assert ok is want, (case, why)
        if not ok:
            assert why
        mgr.shutdown()

    def test_float_aggregators_reported_with_reason(self, monkeypatch):
        # reassociation-sensitive float arithmetic falls back single-device
        # AND still matches the unsharded run trivially
        ql = GB_QL.replace("{HEAD}", KEYS8).replace(
            "min(volume) as mn", "avg(price) as ap"
        )
        mgr, rt, got = _run(
            ql, shard="8", monkeypatch=monkeypatch
        )
        assert rt.queries["q"]._keyshard is None
        ks = rt.snapshot_status()["shard"]["keyshard"]["q"]
        assert ks["sharded"] is False
        assert "reassociation-sensitive" in ks["reason"]
        rt.shutdown()
        mgr.shutdown()


class TestGroupByParity:
    def test_byte_parity_and_occupancy(self, monkeypatch):
        mgr, rt, got = _run(
            GB_QL.replace("{HEAD}", KEYS8), feeds=4, shard="8",
            monkeypatch=monkeypatch,
        )
        qr = rt.queries["q"]
        assert qr._keyshard is not None
        desc = qr._keyshard.describe_state()
        status = rt.snapshot_status()
        rt.shutdown()
        mgr.shutdown()

        mgr2, rt2, got2 = _run(
            GB_QL.replace("{HEAD}", ""), feeds=4, shard="0",
            monkeypatch=monkeypatch,
        )
        rt2.shutdown()
        mgr2.shutdown()

        assert got["q"] and got["q"] == got2["q"]
        # per-device key ownership sums to the total key count
        assert desc["devices"] == 8 and desc["axis"] == "keys"
        assert sum(desc["per_device_keys"]) == desc["total_keys"] == 8
        assert len(desc["occupancy"]) == 8 and desc["skew"] >= 1.0
        placed = status["shard"]["keyshard"]["q"]
        assert placed["sharded"] is True and placed["devices"] == 8

    def test_prometheus_keyshard_families(self, monkeypatch):
        mgr, rt, _ = _run(
            GB_QL.replace(
                "{HEAD}", KEYS8 + "@app:statistics(reporter='none')\n"
            ),
            shard="8", monkeypatch=monkeypatch,
        )
        rt.snapshot_status()
        prom = mgr.prometheus_text()
        rt.shutdown()
        mgr.shutdown()
        assert "siddhi_keyshard_device_keys" in prom
        assert "siddhi_keyshard_occupancy" in prom
        assert "siddhi_keyshard_skew" in prom
        assert 'device="7"' in prom

    def test_explain_renders_keyshard(self, monkeypatch):
        mgr, rt, _ = _run(
            GB_QL.replace("{HEAD}", KEYS8), shard="8",
            monkeypatch=monkeypatch,
        )
        text = rt.explain()
        plan = rt.explain(fmt="dict")
        rt.shutdown()
        mgr.shutdown()
        assert "keyshard[devices=8 axis=keys" in text
        qnode = next(n for n in plan["nodes"] if n["id"] == "query:q")
        assert qnode["counters"]["keyshard"]["sharded"] is True


JOIN_QL = """@app:batch(size='64')
{HEAD}define stream S (symbol string, price float, volume long);
define stream B (symbol string, price float, volume long);
@info(name='j')
from S#window.length(8) join B#window.length(8)
 on S.symbol == B.symbol
select S.symbol as s, S.volume as av, B.volume as bv
insert into JOut;
"""


class TestJoinMesh:
    def test_join_parity_and_placement(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")

        def run(head):
            mgr = _mgr()
            rt = mgr.create_siddhi_app_runtime(
                JOIN_QL.replace("{HEAD}", head)
            )
            got = []
            rt.add_callback(
                "j",
                lambda ts, i, r: got.extend(
                    tuple(e.data) for e in (i or [])
                ),
            )
            rt.start()
            _feed(rt.get_input_handler("S"), 256, 3)
            _feed(rt.get_input_handler("B"), 256, 4,
                  base=1_700_000_000_300)
            armed = bool(getattr(rt.queries["j"], "_joinshard", False))
            status = rt.snapshot_status()
            rt.shutdown()
            mgr.shutdown()
            return got, armed, status

        sharded, armed, status = run(KEYS8)
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "0")
        plain, armed0, _ = run("")
        assert armed and not armed0
        assert sharded and sharded == plain
        placed = status["shard"]["joins"]["j"]
        assert placed["sharded"] is True
        assert placed["sharded_leaves"] > 0


class TestSnapshotRebalance:
    @pytest.mark.parametrize("route", ["8->4", "8->0", "0->8", "8->8"])
    def test_restore_across_mesh_sizes(self, route, monkeypatch):
        src, dst = route.split("->")

        def run(shard, snap=None):
            monkeypatch.setenv("SIDDHI_TPU_SHARD", shard)
            head = (
                f"@app:shard(devices='{shard}', axis='keys')\n"
                if shard != "0" else ""
            )
            mgr, rt, got = _run(GB_QL.replace("{HEAD}", head), feeds=0)
            if snap is None:
                _feed(rt.get_input_handler("S"), 256, 5)
                out = rt.snapshot()
            else:
                rt.restore(snap)
                got["q"].clear()
                _feed(rt.get_input_handler("S"), 256, 6,
                      base=1_700_000_001_000)
                out = None
            res = list(got["q"])
            rt.shutdown()
            mgr.shutdown()
            return res, out

        _, snap = run(src)
        _, snap0 = run("0")
        control, _ = run("0", snap=snap0)
        cont, _ = run(dst, snap=snap)
        assert cont and cont == control, route


FUSE_QL = """@app:batch(size='64')
{HEAD}define stream S (symbol string, price float, volume long);
@info(name='f1') from S[price > 10] select symbol, volume insert into F1;
@info(name='q') from S select symbol, sum(volume) as sv
 group by symbol insert into Out;
"""


class TestFusionVeto:
    def test_planner_names_the_hazard(self):
        plan = build_fusion_plan(FUSE_QL.replace("{HEAD}", KEYS8))
        hazards = {(b["query"], b["hazard"]) for b in plan.blockers}
        assert ("q", H_KEYSHARD) in hazards
        b = next(x for x in plan.blockers if x["query"] == "q")
        assert "key-shards" in b["why"]
        # without the keys axis the same query has no keyshard hazard
        plan2 = build_fusion_plan(FUSE_QL.replace("{HEAD}", ""))
        assert H_KEYSHARD not in {b["hazard"] for b in plan2.blockers}

    def test_fused_run_keeps_query_sharded_with_parity(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_FUSE", "1")
        mgr, rt, got = _run(
            FUSE_QL.replace("{HEAD}", KEYS8), names=("f1", "q"),
            feeds=2, shard="8", monkeypatch=monkeypatch,
        )
        assert rt.queries["q"]._keyshard is not None
        rt.shutdown()
        mgr.shutdown()

        monkeypatch.setenv("SIDDHI_TPU_FUSE", "0")
        mgr2, rt2, got2 = _run(
            FUSE_QL.replace("{HEAD}", ""), names=("f1", "q"),
            feeds=2, shard="0", monkeypatch=monkeypatch,
        )
        rt2.shutdown()
        mgr2.shutdown()
        assert got == got2


PAD_QL = """@app:batch(size='64')
@app:partitionCapacity(size='6')
{HEAD}define stream S (symbol string, price float, volume long);
partition with (symbol of S)
begin
    @info(name='p')
    from S[price > 0]#window.length(8)
    select symbol, sum(volume) as total
    insert into POut;
end;
"""


class TestPartitionPadding:
    def test_capacity_6_on_8_device_mesh(self, monkeypatch):
        # 6 % 8 != 0: the [P] axis pads to 8 with dead slots; overflow
        # drops (8 live symbols > 6 logical slots) behave IDENTICALLY to
        # the unsharded run because padded lanes never receive a key
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        mgr, rt, got = _run(PAD_QL.replace("{HEAD}", KEYS8), names=("p",))
        placed = rt.snapshot_status()["shard"]["partitioned"]["p"]
        rt.shutdown()
        mgr.shutdown()

        monkeypatch.setenv("SIDDHI_TPU_SHARD", "0")
        mgr2, rt2, got2 = _run(PAD_QL.replace("{HEAD}", ""), names=("p",))
        rt2.shutdown()
        mgr2.shutdown()

        # the placed record names the partition mesh's own axis ("part")
        # even when the app requested keys — keys = partition mesh + keyed
        # state arming
        assert placed == {
            "sharded": True, "devices": 8, "axis": "part",
            "local_slots": 1, "padded_slots": 2,
        }
        assert got["p"] == got2["p"]


class TestWireHintCosts:
    def test_declared_range_narrows_state_and_wire(self):
        # satellite: with NO value analysis, declared @app:wire range
        # hints size window state lanes and wire rows at proven widths
        base = """
        define stream S (sym string, vol long);
        @info(name='q') from S[vol > 1000]#window.length(64)
        select sym, sum(vol) as v insert into Out;
        """
        hinted = "@app:wire(range.S.vol='0..30000')\n" + base
        m0 = compute_costs(base)
        m1 = compute_costs(hinted)
        # wire row narrows by 6 bytes (int64 -> int16 vol lane: 0..30000
        # fits the declared 16-bit range encoding)
        assert m1.streams["S"].wire_row_bytes == \
            m0.streams["S"].wire_row_bytes - 6
        win = {
            o.op: o for o in m1.queries["q"].operators
        }.get("window:length")
        lanes = {t.lane: t for t in win.tensors}
        vol = next(v for k, v in lanes.items() if k.endswith(".vol"))
        assert vol.dtype == "int32"
        # filter selectivity refines off the declared interval: vol > 1000
        # over [0, 30000] keeps ~29/30 of rows, not the flat default
        f1 = next(o for o in m1.queries["q"].operators if o.op == "filter")
        f0 = next(o for o in m0.queries["q"].operators if o.op == "filter")
        assert f1.est_selectivity != f0.est_selectivity
        assert f1.est_selectivity > 0.9
