"""Pipelined fused ingest (core/pipeline.py) must be observationally
identical to the serial fused path: byte-identical outputs, identical
delivery order and per-chunk callback grouping, identical failure-policy
semantics when delivery fails on the drain worker.

Each parity case runs the same columnar feed twice — pipelined (the
default) and serial (`@pipeline(disable='true')`) — plus configuration,
error-routing, and observability coverage.
"""

from __future__ import annotations

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager

@pytest.fixture(autouse=True)
def _isolate_pipeline_env(monkeypatch):
    """CI runs part of the suite under SIDDHI_TPU_PIPELINE=1; these tests
    assert annotation-level behavior, so the outer override must not leak
    in (tests that want the env toggle set it themselves)."""
    monkeypatch.delenv("SIDDHI_TPU_PIPELINE", raising=False)


HEAD = "@app:batch(size='64')\ndefine stream S (symbol string, price float, volume long);\n"
SERIAL_HEAD = (
    "@app:batch(size='64')\n@pipeline(disable='true')\n"
    "define stream S (symbol string, price float, volume long);\n"
)


def _feed(n, seed=42):
    rng = np.random.default_rng(seed)
    return (
        np.arange(n, dtype=np.int64) + 1_700_000_000_000,
        {
            "symbol": rng.integers(1, 5, size=n).astype(np.int32),
            "price": rng.uniform(0.0, 100.0, size=n).astype(np.float32),
            "volume": rng.integers(1, 100, size=n).astype(np.int64),
        },
    )


def _boot(ql, callback=None):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    if callback is not None:
        rt.add_callback("q", callback)
    for s in ["A", "B", "C", "D"]:
        mgr.interner.intern(s)
    rt.start()
    return mgr, rt


def _run_rows(ql, n, store_q="from T select *"):
    mgr, rt = _boot(ql)
    ts, cols = _feed(n)
    rt.get_input_handler("S").send_columns(ts, cols)
    rows = sorted(map(repr, rt.query(store_q)))
    rt.shutdown()
    mgr.shutdown()
    return rows


TABLE_BODY = """
    @capacity(size='4096') define table T (symbol string, total long);
    @info(name='q') from S[price > 10]#window.lengthBatch(32)
    select symbol, sum(volume) as total group by symbol insert into T;
"""

CB_BODY = """@info(name='q') from S#window.length(16)
    select symbol, avg(price) as ap insert into Out;"""


def test_pipelined_matches_serial_table():
    n = 64 * 40
    assert _run_rows(HEAD + TABLE_BODY, n) == _run_rows(
        SERIAL_HEAD + TABLE_BODY, n
    )


def _run_cb(ql, n):
    got = []
    mgr, rt = _boot(
        ql,
        callback=lambda ts, ins, rem: got.append(
            (
                ts,
                [tuple(e.data) for e in (ins or [])],
                [tuple(e.data) for e in (rem or [])],
            )
        ),
    )
    ts, cols = _feed(n)
    rt.get_input_handler("S").send_columns(ts, cols)
    rt.shutdown()
    mgr.shutdown()
    return got


def test_pipelined_delivery_matches_serial():
    """Drain-worker delivery: identical events, identical per-micro-batch
    grouping, identical order."""
    n = 64 * 40
    pipelined = _run_cb(HEAD + CB_BODY, n)
    serial = _run_cb(SERIAL_HEAD + CB_BODY, n)
    assert pipelined == serial
    assert sum(len(i) for _t, i, _r in pipelined) > 50


def test_callbacks_complete_before_send_returns():
    """try_send barriers on the drain, so a per-row send AFTER a pipelined
    send_columns observes every pipelined callback already delivered."""
    order = []
    mgr, rt = _boot(
        HEAD + "@info(name='q') from S[price >= 0] select symbol, price "
        "insert into Out;",
        callback=lambda ts, ins, rem: order.extend(
            p for _s, p in (e.data for e in (ins or []))
        ),
    )
    h = rt.get_input_handler("S")
    ts, cols = _feed(64 * 8)
    cols["price"] = np.arange(64 * 8, dtype=np.float32)
    h.send_columns(ts, cols)
    n_before = len(order)
    assert n_before == 64 * 8  # everything drained before send returned
    h.send(("A", 1e6, 1))
    assert order[-1] == 1e6 and len(order) == n_before + 1
    rt.shutdown()
    mgr.shutdown()


# ---------------------------------------------------------------------------
# configuration
# ---------------------------------------------------------------------------


def _fused(rt):
    fi = rt.junctions["S"].fused_ingest
    assert fi is not None
    return fi


def test_pipeline_annotation_depth_and_disable():
    mgr, rt = _boot(
        "@app:batch(size='64')\n@pipeline(depth='3')\n"
        "define stream S (symbol string, price float, volume long);\n"
        + CB_BODY
    )
    fi = _fused(rt)
    assert fi.pipeline_enabled and fi.pipeline_depth == 3
    rt.shutdown()
    mgr.shutdown()

    mgr, rt = _boot(SERIAL_HEAD + CB_BODY)
    assert not _fused(rt).pipeline_enabled
    rt.shutdown()
    mgr.shutdown()


def test_pipeline_annotation_rejects_bad_options():
    from siddhi_tpu.core.errors import SiddhiAppCreationError

    for ann in ("@pipeline(depth='x')", "@pipeline(depth='0')",
                "@pipeline(depth='64')", "@pipeline(disable='maybe')",
                "@pipeline(bogus='1')"):
        with pytest.raises(SiddhiAppCreationError):
            SiddhiManager().create_siddhi_app_runtime(
                f"@app:batch(size='64')\n{ann}\n"
                "define stream S (symbol string, price float, volume long);\n"
                + CB_BODY
            )


def test_pipeline_env_override(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_PIPELINE", "0")
    mgr, rt = _boot(HEAD + CB_BODY)
    assert not _fused(rt).pipeline_enabled
    rt.shutdown()
    mgr.shutdown()

    monkeypatch.setenv("SIDDHI_TPU_PIPELINE", "1")
    mgr, rt = _boot(SERIAL_HEAD + CB_BODY)  # env wins over disable='true'
    assert _fused(rt).pipeline_enabled
    rt.shutdown()
    mgr.shutdown()


def test_prewarm_env_compiles_tail_variant(monkeypatch):
    monkeypatch.setenv("SIDDHI_TPU_PREWARM_TAIL", "1")
    got = _run_cb(HEAD + CB_BODY, 64 * 8)
    monkeypatch.delenv("SIDDHI_TPU_PREWARM_TAIL")
    assert got == _run_cb(HEAD + CB_BODY, 64 * 8)


def test_wire_slot_reuse_gated_per_shipment():
    """device_put may alias the host buffer (size/alignment-dependent on
    CPU): an aliased slot must be gated on the consuming dispatch
    (retire), a copied one on its transfer (ship)."""
    import numpy as np

    import jax

    from siddhi_tpu.core.pipeline import IngestPipeline

    class _Schema:
        stream_id = "S"

    class _Junction:
        schema = _Schema()
        exception_handler = None
        fault_policy = None

    pl = IngestPipeline(_Junction(), depth=2)
    for wire_bytes in (64, 1 << 20):  # small: alias candidate; big: copied
        slot = pl.acquire(2, wire_bytes)
        dev = pl.ship(slot)
        want_alias = dev.unsafe_buffer_pointer() == slot.buf.ctypes.data
        assert slot.aliased == want_alias
        assert slot.ref is dev  # transfer gate until retired
        completion = jax.numpy.zeros(())
        pl.retire(slot, completion)
        if want_alias:
            assert slot.ref is completion  # program gate replaced it
        else:
            assert slot.ref is dev  # copy: transfer gate suffices
    # no safe gate at all (only-donated-outputs dispatch): an aliased slot
    # must abandon its buffer rather than ever reuse it
    slot = pl.acquire(2, 64)
    old_buf = slot.buf
    pl.ship(slot)
    was_aliased = slot.aliased
    pl.retire(slot, None)
    if was_aliased:
        assert slot.buf is not old_buf and slot.ref is None
    pl.close()


# ---------------------------------------------------------------------------
# drain-worker failure semantics
# ---------------------------------------------------------------------------


def _boom(ts, ins, rem):
    raise RuntimeError("poisoned callback")


def test_drain_error_routes_to_exception_handler():
    """A delivery failure on the drain worker goes through the junction's
    failure machinery (mirroring @async drain workers): the sender never
    sees it once a handler owns the stream."""
    mgr, rt = _boot(HEAD + CB_BODY, callback=_boom)
    seen = []
    rt.set_exception_handler(seen.append)
    ts, cols = _feed(64 * 8)
    rt.get_input_handler("S").send_columns(ts, cols)  # must not raise
    assert seen and isinstance(seen[0], RuntimeError)
    rt.shutdown()
    mgr.shutdown()


def test_drain_error_with_onerror_policy_spares_sender():
    """A stream-level @OnError policy owns drain-worker delivery failures:
    the sender keeps sending, the junction's error counter ticks."""
    mgr, rt = _boot(
        "@app:statistics(reporter='none')\n@app:batch(size='64')\n"
        "@OnError(action='LOG')\n"
        "define stream S (symbol string, price float, volume long);\n"
        + CB_BODY,
        callback=_boom,
    )
    ts, cols = _feed(64 * 8)
    rt.get_input_handler("S").send_columns(ts, cols)  # must not raise
    assert rt.statistics_manager.error_tracker("stream.S").count > 0
    rt.shutdown()
    mgr.shutdown()


def test_drain_error_propagates_without_handler():
    """No handler, no @OnError policy: the failure surfaces to the sender
    at the end of the call, like the serial path's in-line drain."""
    mgr, rt = _boot(HEAD + CB_BODY, callback=_boom)
    ts, cols = _feed(64 * 8)
    with pytest.raises(RuntimeError, match="poisoned callback"):
        rt.get_input_handler("S").send_columns(ts, cols)
    rt.shutdown()
    mgr.shutdown()


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------


def test_pipeline_stage_metrics_and_occupancy():
    mgr, rt = _boot(
        "@app:statistics(reporter='none')\n" + HEAD + CB_BODY,
        callback=lambda ts, ins, rem: None,  # deliver mode: drain runs
    )
    ts, cols = _feed(64 * 16)
    rt.get_input_handler("S").send_columns(ts, cols)
    sm = rt.statistics_manager
    rep = sm.report()
    ent = rep["pipeline"]["stream.S"]
    assert ent["depth"] == 2  # default
    assert ent["occupancy"] > 0.0
    for op in ("encode", "h2d", "dispatch", "drain"):
        assert sm.device_time[f"stream.S.pipeline.{op}"].samples > 0, op
    text = sm.prometheus_text()
    assert "siddhi_pipeline_occupancy" in text
    assert "siddhi_pipeline_depth" in text
    assert 'op="pipeline.encode"' in text
    rt.shutdown()
    mgr.shutdown()


def test_stats_off_pays_one_gate_check():
    """With statistics never configured the pipelined hot path must not
    touch any tracker (junction.pipeline_stats stays None)."""
    mgr, rt = _boot(HEAD + CB_BODY)
    assert rt.junctions["S"].pipeline_stats is None
    fi = _fused(rt)
    ts, cols = _feed(64 * 8)
    rt.get_input_handler("S").send_columns(ts, cols)
    assert fi.pipeline is not None and fi.pipeline.stats is None
    rt.shutdown()
    mgr.shutdown()
