"""Sort / frequent / lossyFrequent / cron window tests.

Reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/query/window/
SortWindowTestCase, FrequentWindowTestCase, LossyFrequentWindowTestCase,
CronWindowTestCase.
"""

import time

from siddhi_tpu import SiddhiManager


def run_app(ql, sends, callback_name="q"):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    ins, removed = [], []

    def cb(ts, in_events, removed_events):
        if in_events:
            ins.extend(e.data for e in in_events)
        if removed_events:
            removed.extend(e.data for e in removed_events)

    rt.add_callback(callback_name, cb)
    rt.start()
    h = {}
    for stream_id, row, ts in sends:
        h.setdefault(stream_id, rt.get_input_handler(stream_id)).send(row, timestamp=ts)
    rt.shutdown()
    mgr.shutdown()
    return ins, removed


class TestSortWindow:
    def test_keeps_n_smallest(self):
        ql = """
        define stream S (symbol string, price float, volume long);
        @info(name='q')
        from S#window.sort(2, volume)
        select symbol, volume
        insert all events into Out;
        """
        ins, removed = run_app(ql, [
            ("S", ("A", 10.0, 50), 1),
            ("S", ("B", 20.0, 20), 2),
            ("S", ("C", 30.0, 40), 3),   # evicts A (volume 50 is greatest)
            ("S", ("D", 40.0, 100), 4),  # D itself evicted immediately
        ])
        assert ins == [("A", 50), ("B", 20), ("C", 40), ("D", 100)]
        assert removed == [("A", 50), ("D", 100)]

    def test_desc_order(self):
        ql = """
        define stream S (symbol string, price float, volume long);
        @info(name='q')
        from S#window.sort(2, volume, 'desc')
        select symbol, volume
        insert expired events into Out;
        """
        # desc: keeps the 2 LARGEST volumes; smallest evicted
        ins, removed = run_app(ql, [
            ("S", ("A", 1.0, 50), 1),
            ("S", ("B", 1.0, 20), 2),
            ("S", ("C", 1.0, 40), 3),  # evicts B (20 smallest)
        ])
        assert removed == [("B", 20)]

    def test_sum_over_sort_window(self):
        ql = """
        define stream S (symbol string, price float, volume long);
        @info(name='q')
        from S#window.sort(2, volume)
        select sum(volume) as total
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("S", ("A", 1.0, 50), 1),
            ("S", ("B", 1.0, 20), 2),
            ("S", ("C", 1.0, 40), 3),
        ])
        # 50; 50+20=70; +40=110 (the eviction of A is emitted AFTER the
        # arrival — reference: SortWindowProcessor.java:159-166 appends the
        # current event first — so C's current row sees the pre-evict sum)
        assert ins == [(50,), (70,), (110,)]


class TestFrequentWindow:
    def test_top2_keys(self):
        ql = """
        define stream S (cardNo string, price float);
        @info(name='q')
        from S#window.frequent(2, cardNo)
        select cardNo, price
        insert all events into Out;
        """
        ins, removed = run_app(ql, [
            ("S", ("X", 1.0), 1),
            ("S", ("Y", 2.0), 2),
            ("S", ("X", 3.0), 3),   # X count 2
            ("S", ("Z", 4.0), 4),   # full: decrement X->1, Y->0: Y evicted; Z in
            ("S", ("X", 5.0), 5),
        ])
        assert ins == [("X", 1.0), ("Y", 2.0), ("X", 3.0), ("Z", 4.0), ("X", 5.0)]
        assert removed == [("Y", 2.0)]

    def test_dropped_when_no_space(self):
        ql = """
        define stream S (cardNo string, price float);
        @info(name='q')
        from S#window.frequent(1, cardNo)
        select cardNo
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("S", ("X", 1.0), 1),
            ("S", ("X", 2.0), 2),   # X count 2
            ("S", ("Y", 3.0), 3),   # decrement X->1, still no space: Y dropped
            ("S", ("X", 4.0), 4),
        ])
        assert ins == [("X",), ("X",), ("X",)]


class TestLossyFrequentWindow:
    def test_support_threshold(self):
        ql = """
        define stream S (cardNo string, price float);
        @info(name='q')
        from S#window.lossyFrequent(0.5, 0.1, cardNo)
        select cardNo
        insert into Out;
        """
        # every arrival whose key count >= (0.5-0.1)*total passes
        ins, _ = run_app(ql, [
            ("S", ("X", 1.0), 1),   # X:1 >= 0.4*1 -> pass
            ("S", ("X", 2.0), 2),   # X:2 >= 0.4*2 -> pass
            ("S", ("Y", 3.0), 3),   # Y:1 >= 0.4*3=1.2? no
            ("S", ("X", 4.0), 4),   # X:3 >= 1.6 -> pass
        ])
        assert ins == [("X",), ("X",), ("X",)]


class TestCronWindow:
    def test_cron_flush(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (symbol string, price float);
        @info(name='q')
        from S#window.cron('*/1 * * * * ?')
        select symbol
        insert all events into Out;
        """)
        ins, removed = [], []
        rt.add_callback("q", lambda ts, i, r: (
            ins.extend(e.data for e in i or []),
            removed.extend(e.data for e in r or []),
        ))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("A", 1.0))
        h.send(("B", 2.0))
        t0 = time.time()
        while len(ins) < 2 and time.time() - t0 < 10.0:
            time.sleep(0.1)
        assert sorted(ins) == [("A",), ("B",)]  # flushed at the cron fire
        # the NEXT fire expires them (only after new events arrive per the
        # reference's dispatch guard, so send another)
        h.send(("C", 3.0))
        t0 = time.time()
        while len(removed) < 2 and time.time() - t0 < 10.0:
            time.sleep(0.1)
        assert sorted(removed) == [("A",), ("B",)]
        rt.shutdown()
        mgr.shutdown()


class TestBatchWindowMembership:
    def test_min_max_over_length_batch(self):
        # regression: bucket elements' membership interval was empty (death at
        # the bucket's own reset, which PRECEDES its currents in flush order)
        ql = """
        define stream S (symbol string, price float, volume long);
        @info(name='q')
        from S#window.lengthBatch(2)
        select min(price) as lo, max(price) as hi
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("S", ("A", 10.0, 1), 1), ("S", ("B", 5.0, 2), 2),
            ("S", ("C", 30.0, 3), 3), ("S", ("D", 8.0, 4), 4),
        ])
        # one output per flush chunk (processInBatchNoGroupBy lastEvent),
        # carrying the bucket's final min/max
        assert [tuple(r) for r in ins] == [(5.0, 10.0), (8.0, 30.0)]

    def test_grouped_min_max_over_length_batch(self):
        ql = """
        define stream S (symbol string, price float, volume long);
        @info(name='q')
        from S#window.lengthBatch(4)
        select symbol, sum(volume) as total, min(price) as lo, max(price) as hi
        group by symbol
        insert into Out;
        """
        ins, _ = run_app(ql, [
            ("S", ("A", 10.0, 1), 1), ("S", ("B", 20.0, 2), 2),
            ("S", ("A", 30.0, 3), 3), ("S", ("B", 40.0, 4), 4),
            ("S", ("A", 50.0, 5), 5), ("S", ("A", 60.0, 6), 6),
            ("S", ("B", 70.0, 7), 7), ("S", ("A", 80.0, 8), 8),
        ])
        rows = {tuple(r) for r in ins}
        assert ("A", 4, 10.0, 30.0) in rows and ("B", 6, 20.0, 40.0) in rows
        assert ("A", 19, 50.0, 80.0) in rows and ("B", 7, 70.0, 70.0) in rows
        assert len(ins) == 4
