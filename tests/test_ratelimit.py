"""Output rate limiter tests.

Reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/query/
ratelimit/ (EventOutputRateLimitTestCase, TimeOutputRateLimitTestCase,
SnapshotOutputRateLimitTestCase).
"""

import time

from siddhi_tpu import SiddhiManager


def build(ql):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    got = []
    rt.add_callback("q", lambda ts, ins, rem: got.extend(e.data for e in ins or []))
    rt.start()
    return mgr, rt, got


BASE = "define stream S (symbol string, price float);\n"


class TestEventRate:
    def test_all_every_3_events(self):
        mgr, rt, got = build(BASE + """
        @info(name='q')
        from S select symbol, price output all every 3 events insert into Out;
        """)
        h = rt.get_input_handler("S")
        for i in range(5):
            h.send((f"E{i}", float(i)), timestamp=i)
        # released in a chunk of 3; 2 still buffered
        assert got == [("E0", 0.0), ("E1", 1.0), ("E2", 2.0)]
        h.send(("E5", 5.0), timestamp=5)
        assert len(got) == 6
        rt.shutdown()
        mgr.shutdown()

    def test_first_every_3_events(self):
        mgr, rt, got = build(BASE + """
        @info(name='q')
        from S select symbol output first every 3 events insert into Out;
        """)
        h = rt.get_input_handler("S")
        for i in range(7):
            h.send((f"E{i}", float(i)), timestamp=i)
        assert got == [("E0",), ("E3",), ("E6",)]
        rt.shutdown()
        mgr.shutdown()

    def test_last_every_3_events(self):
        mgr, rt, got = build(BASE + """
        @info(name='q')
        from S select symbol output last every 3 events insert into Out;
        """)
        h = rt.get_input_handler("S")
        for i in range(6):
            h.send((f"E{i}", float(i)), timestamp=i)
        assert got == [("E2",), ("E5",)]
        rt.shutdown()
        mgr.shutdown()

    def test_last_per_group_every_3_events(self):
        mgr, rt, got = build(BASE + """
        @info(name='q')
        from S select symbol, sum(price) as total group by symbol
        output last every 3 events insert into Out;
        """)
        h = rt.get_input_handler("S")
        h.send(("A", 1.0), timestamp=1)
        h.send(("B", 2.0), timestamp=2)
        h.send(("A", 3.0), timestamp=3)
        # chunk of 3 closes: last row per key — A's total 4.0, B's total 2.0
        assert sorted(got) == [("A", 4.0), ("B", 2.0)]
        rt.shutdown()
        mgr.shutdown()


class TestTimeRate:
    def test_all_every_period(self):
        # period must exceed first-send jit-compile time: the flush timer
        # runs on wall clock, and a flush firing between the two sends would
        # legitimately deliver A early
        mgr, rt, got = build(BASE + """
        @info(name='q')
        from S select symbol output all every 2 sec insert into Out;
        """)
        h = rt.get_input_handler("S")
        h.send(("A", 1.0))
        h.send(("B", 2.0))
        assert got == []  # buffered until the period boundary
        t0 = time.time()
        while len(got) < 2 and time.time() - t0 < 5.0:
            time.sleep(0.05)
        assert sorted(got) == [("A",), ("B",)]
        rt.shutdown()
        mgr.shutdown()

    def test_snapshot(self):
        mgr, rt, got = build(BASE + """
        @info(name='q')
        from S select symbol, sum(price) as total group by symbol
        output snapshot every 100 milliseconds insert into Out;
        """)
        h = rt.get_input_handler("S")
        h.send(("A", 1.0))
        h.send(("A", 2.0))
        h.send(("B", 5.0))
        t0 = time.time()
        while ("B", 5.0) not in got and time.time() - t0 < 10.0:
            time.sleep(0.05)
        # snapshot re-emits the latest aggregate per key
        assert ("A", 3.0) in got and ("B", 5.0) in got
        rt.shutdown()
        mgr.shutdown()
