"""SLO burn-rate engine (observability/slo.py): `@app:slo` option
validation (runtime raise + analyzer SA139 share one rule set), the
injected SloAlertStream subscribed from ordinary SiddhiQL, multi-window
burn math, and the /slo surfaces."""

import json
import time
import urllib.request

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.observability.slo import (
    DEFAULT_BURN_FAST,
    DEFAULT_BURN_SLOW,
    DEFAULT_WINDOW_MS,
    SloEngine,
    iter_slo_annotation_problems,
    resolve_slo_annotation,
)
from siddhi_tpu.query_api.annotation import Annotation


def _ann(**opts):
    a = Annotation("app:slo")
    for k, v in opts.items():
        a.elements.append((k.replace("_", "."), v))
    return a


class TestAnnotationRules:
    def test_defaults(self):
        cfg = resolve_slo_annotation(_ann(**{"p99_latency_ms": "50"}))
        assert cfg.objectives == {"p99.latency.ms": 50.0}
        assert cfg.window_ms == DEFAULT_WINDOW_MS
        assert cfg.burn_fast == DEFAULT_BURN_FAST
        assert cfg.burn_slow == DEFAULT_BURN_SLOW
        assert cfg.fast_window_ms == DEFAULT_WINDOW_MS // 12

    def test_full_config(self):
        cfg = resolve_slo_annotation(_ann(
            p99_latency_ms="5", error_rate="0.01", shed_rate="0.05",
            window="10 min", **{"burn_fast": "10", "burn_slow": "1.5",
                                "interval": "500 millisec"},
        ))
        assert cfg.objectives == {
            "p99.latency.ms": 5.0, "error.rate": 0.01, "shed.rate": 0.05,
        }
        assert cfg.window_ms == 600_000
        assert cfg.interval_ms == 500
        assert (cfg.burn_fast, cfg.burn_slow) == (10.0, 1.5)

    @pytest.mark.parametrize("opts", [
        {"p99_latency_ms": "-1"},
        {"error_rate": "2"},
        {"shed_rate": "0"},
        {"p99_latency_ms": "50", "window": "soon"},
        {"p99_latency_ms": "50", "window": "10 millisec"},  # below 1 sec
        {"p99_latency_ms": "50", "burn_fast": "x"},
        {"p99_latency_ms": "50", "interval": "1 millisec"},
        {"p99_latency_ms": "50", "bogus": "1"},
        {"window": "1 hour"},  # no objective at all
    ])
    def test_each_malformed_option_raises(self, opts):
        with pytest.raises(SiddhiAppCreationError):
            resolve_slo_annotation(_ann(**opts))

    def test_reserved_stream_name(self):
        problems = list(iter_slo_annotation_problems(
            _ann(p99_latency_ms="50"),
            defined_streams=("SloAlertStream",),
        ))
        assert any("reserves the stream name" in p for p in problems)

    def test_analyzer_reports_every_problem(self):
        # one rule set: the analyzer yields them ALL (SA139), the resolver
        # raises on the first — counts must agree
        bad = _ann(p99_latency_ms="-1", error_rate="2", bogus="1")
        assert len(list(iter_slo_annotation_problems(bad))) == 3


class TestBurnMath:
    def test_window_burn_is_windowed_not_lifetime(self):
        # an early bad burst followed by clean traffic: the fast window
        # must read 0 while the full window still charges the burst
        ring = [(0, 0, 0), (5_000, 100, 100), (11_000, 1100, 100)]
        recent = SloEngine._window_burn(
            ring, now_ms=11_000, window_ms=2_000, allowed=0.01
        )
        assert recent == pytest.approx(0.0)
        full = SloEngine._window_burn(
            ring, now_ms=11_000, window_ms=100_000, allowed=0.01
        )
        assert full == pytest.approx((100 / 1100) / 0.01)

    def test_empty_window_is_none(self):
        assert SloEngine._window_burn(
            [(0, 5, 0), (100, 5, 0)], 100, 50, 0.01
        ) is None


SLO_APP = """@app:statistics(reporter='none')
@app:slo(p99.latency.ms='0.0001', window='2 sec',
         burn.fast='1', burn.slow='1', interval='25 millisec')
define stream S (v long);
@info(name='q') from S select v insert into Out;
@info(name='watch')
from SloAlertStream[objective == 'p99.latency.ms']
select component, objective, burn_rate, budget_left insert into Watched;
"""


class TestAlertStreamEndToEnd:
    def test_burning_slo_fires_siddhiql_subscriber(self):
        # acceptance: a 100 ns latency objective is in breach on any real
        # dispatch, so the burn engine must emit SloAlertStream rows a
        # plain SiddhiQL query consumes
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(SLO_APP)
        alerts = []
        rt.add_callback(
            "watch", lambda ts, ins, rem: alerts.extend(ins or [])
        )
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(8):
            h.send((i,))
        t0 = time.time()
        while not alerts and time.time() - t0 < 10:
            time.sleep(0.02)
            h.send((99,))  # keep latency samples flowing
        assert alerts, "slo burn alert must fire through SiddhiQL"
        ev = alerts[0]
        comps = {e.data[0] for e in alerts}
        assert any(c.startswith("query.") for c in comps)
        assert ev.data[1] == "p99.latency.ms"
        assert ev.data[2] >= 1.0  # burn_rate at/above the breach threshold
        assert 0.0 <= ev.data[3] <= 1.0  # budget_left
        status = rt.snapshot_status()
        assert status["slo"]["ticks"] >= 1
        assert status["slo"]["alerts"] >= 1
        mgr.shutdown()

    def test_slo_http_and_prometheus_surfaces(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(SLO_APP)
        alerts = []
        rt.add_callback(
            "watch", lambda ts, ins, rem: alerts.extend(ins or [])
        )
        rt.start()
        h = rt.get_input_handler("S")
        t0 = time.time()
        while not alerts and time.time() - t0 < 10:
            h.send((1,))
            time.sleep(0.02)
        port = mgr.serve_metrics(0)

        def get(path):
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ).read().decode()

        rep = json.loads(get("/slo.json"))["SiddhiApp"]
        assert rep["objectives"] == {"p99.latency.ms": 0.0001}
        assert rep["window_ms"] == 2000
        assert any(
            b["slow"] is not None and b["slow"] >= 1.0
            for b in rep["burn"]
        )
        text = get("/slo")
        assert "p99.latency.ms" in text and "budget_left" in text
        prom = mgr.prometheus_text()
        assert "siddhi_slo_burn_rate{" in prom
        mgr.shutdown()

    def test_runtime_rejects_malformed_annotation(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            @app:slo(window='1 hour')
            define stream S (v long);
            from S select v insert into Out;
            """)
        mgr.shutdown()

    def test_no_annotation_no_engine(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (v long);
        @info(name='q') from S select v insert into Out;
        """)
        assert rt._slo is None
        assert rt.slo_report() is None
        assert "no slo-enabled apps" in mgr.slo_text()
        mgr.shutdown()


class TestAnalyzerIntegration:
    def test_slo_app_lints_clean(self):
        from siddhi_tpu.analysis.analyzer import analyze
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

        res = analyze(SiddhiCompiler.parse(SLO_APP))
        assert not res.errors, [d.message for d in res.errors]

    def test_sa139_reported_per_problem(self):
        from siddhi_tpu.analysis.analyzer import analyze
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

        res = analyze(SiddhiCompiler.parse("""
        @app:slo(p99.latency.ms='-1', bogus='1')
        define stream S (v long);
        from S select v insert into Out;
        """))
        codes = [d.code for d in res.errors]
        assert codes.count("SA139") == 2
