"""Source/sink/mapper/broker tests.

Reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/transport/
InMemoryTransportTestCase (broker topics), TestFailingInMemorySource/Sink
(retry on ConnectionUnavailableException), MultiClientDistributedSinkTestCase
(round-robin/partitioned/broadcast egress).
"""

import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.event import Event
from siddhi_tpu.core.extension import extension
from siddhi_tpu.core.io import (
    ConnectionUnavailableError,
    InMemoryBroker,
    InMemorySink,
)


class _Collector:
    def __init__(self, topic):
        self.topic = topic
        self.got = []

    def on_message(self, payload):
        self.got.append(payload)


class TestInMemoryTransport:
    def test_source_sink_roundtrip(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @source(type='inMemory', topic='in_t', @map(type='passThrough'))
        define stream S (symbol string, price float);
        @sink(type='inMemory', topic='out_t', @map(type='passThrough'))
        define stream Out (symbol string, price float);
        from S[price > 10] select symbol, price insert into Out;
        """)
        col = _Collector("out_t")
        InMemoryBroker.subscribe(col)
        rt.start()
        InMemoryBroker.publish("in_t", ("WSO2", 55.5))
        InMemoryBroker.publish("in_t", ("IBM", 5.0))
        InMemoryBroker.publish("in_t", ("GOOG", 20.0))
        events = [e for batch in col.got for e in batch]
        assert [tuple(e.data) for e in events] == [("WSO2", 55.5), ("GOOG", 20.0)]
        InMemoryBroker.unsubscribe(col)
        rt.shutdown()
        mgr.shutdown()

    def test_json_mappers(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @source(type='inMemory', topic='jin', @map(type='json'))
        define stream S (symbol string, price float);
        @sink(type='inMemory', topic='jout', @map(type='json'))
        define stream Out (symbol string, price float);
        from S select symbol, price insert into Out;
        """)
        col = _Collector("jout")
        InMemoryBroker.subscribe(col)
        rt.start()
        InMemoryBroker.publish("jin", '{"event": {"symbol": "WSO2", "price": 55.5}}')
        import json

        assert json.loads(col.got[0]) == [
            {"event": {"symbol": "WSO2", "price": 55.5}}
        ]
        InMemoryBroker.unsubscribe(col)
        rt.shutdown()
        mgr.shutdown()


class TestFailingSink:
    def test_sink_reconnects_with_backoff(self):
        fails = {"n": 2}

        @extension("sink", "testFailing")
        class FailingSink(InMemorySink):
            def connect(self):
                super().connect()
                if fails["n"] > 0:
                    fails["n"] -= 1
                    raise ConnectionUnavailableError("down")

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (symbol string);
        @sink(type='testFailing', topic='ft', @map(type='passThrough'))
        define stream Out (symbol string);
        from S select symbol insert into Out;
        """)
        col = _Collector("ft")
        InMemoryBroker.subscribe(col)
        rt.start()
        sink = rt.sinks[0]
        t0 = time.time()
        while not sink.connected and time.time() - t0 < 5.0:
            time.sleep(0.05)
        assert sink.connected and fails["n"] == 0  # retried through backoff
        rt.get_input_handler("S").send(("WSO2",))
        assert [tuple(e.data) for b in col.got for e in b] == [("WSO2",)]
        InMemoryBroker.unsubscribe(col)
        rt.shutdown()
        mgr.shutdown()


class TestDistributedSink:
    def _run(self, strategy_clause, sends):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(f"""
        define stream S (symbol string, volume long);
        @sink(type='inMemory', @map(type='passThrough'),
              @distribution(strategy='{strategy_clause}',
                            @destination(topic='d1'), @destination(topic='d2')))
        define stream Out (symbol string, volume long);
        from S select symbol, volume insert into Out;
        """)
        c1, c2 = _Collector("d1"), _Collector("d2")
        InMemoryBroker.subscribe(c1)
        InMemoryBroker.subscribe(c2)
        rt.start()
        h = rt.get_input_handler("S")
        for row in sends:
            h.send(row)
        rt.shutdown()
        mgr.shutdown()
        InMemoryBroker.unsubscribe(c1)
        InMemoryBroker.unsubscribe(c2)
        flat1 = [tuple(e.data) for b in c1.got for e in b]
        flat2 = [tuple(e.data) for b in c2.got for e in b]
        return flat1, flat2

    def test_round_robin(self):
        f1, f2 = self._run("roundRobin", [("A", 1), ("B", 2), ("C", 3), ("D", 4)])
        assert f1 == [("A", 1), ("C", 3)]
        assert f2 == [("B", 2), ("D", 4)]

    def test_broadcast(self):
        f1, f2 = self._run("broadcast", [("A", 1), ("B", 2)])
        assert f1 == f2 == [("A", 1), ("B", 2)]

    def test_partitioned(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (symbol string, volume long);
        @sink(type='inMemory', @map(type='passThrough'),
              @distribution(strategy='partitioned', partitionKey='symbol',
                            @destination(topic='p1'), @destination(topic='p2')))
        define stream Out (symbol string, volume long);
        from S select symbol, volume insert into Out;
        """)
        c1, c2 = _Collector("p1"), _Collector("p2")
        InMemoryBroker.subscribe(c1)
        InMemoryBroker.subscribe(c2)
        rt.start()
        h = rt.get_input_handler("S")
        for row in [("A", 1), ("B", 2), ("A", 3), ("B", 4)]:
            h.send(row)
        rt.shutdown()
        mgr.shutdown()
        InMemoryBroker.unsubscribe(c1)
        InMemoryBroker.unsubscribe(c2)
        flat1 = [tuple(e.data) for b in c1.got for e in b]
        flat2 = [tuple(e.data) for b in c2.got for e in b]
        # same key always lands on the same destination
        keys1 = {s for s, _ in flat1}
        keys2 = {s for s, _ in flat2}
        assert keys1.isdisjoint(keys2)
        assert sorted(flat1 + flat2) == [("A", 1), ("A", 3), ("B", 2), ("B", 4)]
