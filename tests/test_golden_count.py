"""Golden corpus: count patterns, translated from the reference test data
(reference: siddhi-core/src/test/java/org/wso2/siddhi/core/query/pattern/
CountPatternTestCase.java — query strings, input events, and expected outputs
are the reference's observable contract; the assertions here are data-level
translations, not code translations)."""

import pytest

from siddhi_tpu import SiddhiManager


def run_app(ql, sends, query_name="query1"):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    got = []

    def cb(ts, ins, removed):
        for e in ins or []:
            got.append(tuple(e.data))

    rt.add_callback(query_name, cb)
    rt.start()
    handlers = {}
    for stream, row in sends:
        h = handlers.setdefault(stream, rt.get_input_handler(stream))
        h.send(row)
    rt.shutdown()
    return got


def assert_rows(got, expected):
    assert len(got) == len(expected), f"got {got}, expected {expected}"
    for g, e in zip(got, expected):
        assert len(g) == len(e), f"row {g} vs {e}"
        for gv, ev in zip(g, e):
            if ev is None:
                assert gv is None, f"row {g} vs {e}"
            elif isinstance(ev, float):
                assert gv == pytest.approx(ev, rel=1e-6), f"row {g} vs {e}"
            else:
                assert gv == ev, f"row {g} vs {e}"


S12 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""

SE = """
define stream EventStream (symbol string, price float, volume int);
"""

Q_2_5 = S12 + """
@info(name = 'query1')
from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>20]
select e1[0].price as price1_0, e1[1].price as price1_1, e1[2].price as price1_2,
   e1[3].price as price1_3, e2.price as price2
insert into OutputStream ;
"""


class TestCountPatternGolden:
    def test_query1(self):
        # CountPatternTestCase.testQuery1: a non-matching event between count
        # absorptions does not reset a pattern-type count state
        got = run_app(Q_2_5, [
            ("Stream1", ("WSO2", 25.6, 100)),
            ("Stream1", ("GOOG", 47.6, 100)),
            ("Stream1", ("GOOG", 13.7, 100)),
            ("Stream1", ("GOOG", 47.8, 100)),
            ("Stream2", ("IBM", 45.7, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
        ])
        assert_rows(got, [(25.6, 47.6, 47.8, None, 45.7)])

    def test_query2(self):
        # testQuery2: the e2 match freezes the captures; later Stream1 events
        # are not retroactively absorbed, and the token is consumed
        got = run_app(Q_2_5, [
            ("Stream1", ("WSO2", 25.6, 100)),
            ("Stream1", ("GOOG", 47.6, 100)),
            ("Stream1", ("GOOG", 13.7, 100)),
            ("Stream2", ("IBM", 45.7, 100)),
            ("Stream1", ("GOOG", 47.8, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
        ])
        assert_rows(got, [(25.6, 47.6, None, None, 45.7)])

    def test_query3(self):
        # testQuery3: an e2 event before min is reached does not match; the
        # count keeps absorbing across it
        got = run_app(Q_2_5, [
            ("Stream1", ("WSO2", 25.6, 100)),
            ("Stream2", ("IBM", 45.7, 100)),
            ("Stream1", ("GOOG", 47.8, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
        ])
        assert_rows(got, [(25.6, 47.8, None, None, 55.7)])

    def test_query4(self):
        # testQuery4: min not reached -> no output
        got = run_app(Q_2_5, [
            ("Stream1", ("WSO2", 25.6, 100)),
            ("Stream2", ("IBM", 45.7, 100)),
        ])
        assert_rows(got, [])

    def test_query5(self):
        # testQuery5: absorption stops at max (5); the sixth matching event
        # is not captured; emission uses the first five
        got = run_app(Q_2_5, [
            ("Stream1", ("WSO2", 25.6, 100)),
            ("Stream1", ("GOOG", 47.6, 100)),
            ("Stream1", ("GOOG", 23.7, 100)),
            ("Stream1", ("GOOG", 24.7, 100)),
            ("Stream1", ("GOOG", 25.7, 100)),
            ("Stream1", ("WSO2", 27.6, 100)),
            ("Stream2", ("IBM", 45.7, 100)),
            ("Stream1", ("GOOG", 47.8, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
        ])
        assert_rows(got, [(25.6, 47.6, 23.7, 24.7, 45.7)])

    def test_query6(self):
        # testQuery6: next-state condition referencing a count capture
        # (e2[price > e1[1].price])
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price>20] <2:5> -> e2=Stream2[price>e1[1].price]
        select e1[0].price as price1_0, e1[1].price as price1_1, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 25.6, 100)),
            ("Stream1", ("GOOG", 47.6, 100)),
            ("Stream2", ("IBM", 45.7, 100)),
            ("Stream2", ("IBM", 55.7, 100)),
        ])
        assert_rows(got, [(25.6, 47.6, 55.7)])

    def test_query7(self):
        # testQuery7: min=0 count at the start — the very first e2 event
        # emits with empty captures
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price>20] <0:5> -> e2=Stream2[price>20]
        select e1[0].price as price1_0, e1[1].price as price1_1, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream2", ("IBM", 45.7, 100)),
        ])
        assert_rows(got, [(None, None, 45.7)])

    def test_query8(self):
        # testQuery8: min=0 with a condition on e1[0] — null-tolerant compare
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price>20] <0:5> -> e2=Stream2[price>e1[0].price]
        select e1[0].price as price1_0, e1[1].price as price1_1, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 25.6, 100)),
            ("Stream1", ("GOOG", 7.6, 100)),
            ("Stream2", ("IBM", 45.7, 100)),
        ])
        assert_rows(got, [(25.6, None, 45.7)])

    def test_query9(self):
        # testQuery9: count in the middle of a single-stream chain
        ql = SE + """
        @info(name = 'query1')
        from e1 = EventStream [price >= 50 and volume > 100] -> e2 = EventStream [price <= 40] <0:5>
           -> e3 = EventStream [volume <= 70]
        select e1.symbol as symbol1, e2[0].symbol as symbol2, e3.symbol as symbol3
        insert into StockQuote;
        """
        got = run_app(ql, [
            ("EventStream", ("IBM", 75.6, 105)),
            ("EventStream", ("GOOG", 21.0, 81)),
            ("EventStream", ("WSO2", 176.6, 65)),
        ])
        assert_rows(got, [("IBM", "GOOG", "WSO2")])

    def test_query10(self):
        # testQuery10: <:5> == <0:5>; e2 and e3 both match the second event —
        # descending state order lets e3 win and e2 stays empty
        ql = SE + """
        @info(name = 'query1')
        from e1 = EventStream [price >= 50 and volume > 100] -> e2 = EventStream [price <= 40] <:5>
           -> e3 = EventStream [volume <= 70]
        select e1.symbol as symbol1, e2[0].symbol as symbol2, e3.symbol as symbol3
        insert into StockQuote;
        """
        got = run_app(ql, [
            ("EventStream", ("IBM", 75.6, 105)),
            ("EventStream", ("GOOG", 21.0, 61)),
            ("EventStream", ("WSO2", 21.0, 61)),
        ])
        assert_rows(got, [("IBM", None, "GOOG")])

    def test_query11(self):
        # testQuery11: e2[last] on an empty capture set is null
        ql = SE + """
        @info(name = 'query1')
        from e1 = EventStream [price >= 50 and volume > 100] -> e2 = EventStream [price <= 40] <:5>
           -> e3 = EventStream [volume <= 70]
        select e1.symbol as symbol1, e2[last].symbol as symbol2, e3.symbol as symbol3
        insert into StockQuote;
        """
        got = run_app(ql, [
            ("EventStream", ("IBM", 75.6, 105)),
            ("EventStream", ("GOOG", 21.0, 61)),
            ("EventStream", ("WSO2", 21.0, 61)),
        ])
        assert_rows(got, [("IBM", None, "GOOG")])

    def test_query12(self):
        # testQuery12: e2[last] picks the final absorbed event
        ql = SE + """
        @info(name = 'query1')
        from e1 = EventStream [price >= 50 and volume > 100] -> e2 = EventStream [price <= 40] <:5>
           -> e3 = EventStream [volume <= 70]
        select e1.symbol as symbol1, e2[last].symbol as symbol2, e3.symbol as symbol3
        insert into StockQuote;
        """
        got = run_app(ql, [
            ("EventStream", ("IBM", 75.6, 105)),
            ("EventStream", ("GOOG", 21.0, 91)),
            ("EventStream", ("FB", 21.0, 81)),
            ("EventStream", ("WSO2", 21.0, 61)),
        ])
        assert_rows(got, [("IBM", "FB", "WSO2")])

    def test_query13(self):
        # testQuery13: every + trailing count state — each token emits at
        # exactly min occurrences and is consumed
        ql = SE + """
        @info(name = 'query1')
        from every e1 = EventStream ->
             e2 = EventStream [e1.symbol==e2.symbol]<4:6>
        select e1.volume as volume1, e2[0].volume as volume2, e2[1].volume as volume3,
          e2[2].volume as volume4, e2[3].volume as volume5, e2[4].volume as volume6,
          e2[5].volume as volume7
        insert into StockQuote;
        """
        got = run_app(ql, [
            ("EventStream", ("IBM", 75.6, 100)),
            ("EventStream", ("IBM", 75.6, 200)),
            ("EventStream", ("IBM", 75.6, 300)),
            ("EventStream", ("GOOG", 21.0, 91)),
            ("EventStream", ("IBM", 75.6, 400)),
            ("EventStream", ("IBM", 75.6, 500)),
            ("EventStream", ("GOOG", 21.0, 91)),
            ("EventStream", ("IBM", 75.6, 600)),
            ("EventStream", ("IBM", 75.6, 700)),
            ("EventStream", ("IBM", 75.6, 800)),
            ("EventStream", ("GOOG", 21.0, 91)),
            ("EventStream", ("IBM", 75.6, 900)),
        ])
        assert_rows(got, [
            (100, 200, 300, 400, 500, None, None),
            (200, 300, 400, 500, 600, None, None),
            (300, 400, 500, 600, 700, None, None),
            (400, 500, 600, 700, 800, None, None),
            (500, 600, 700, 800, 900, None, None),
        ])

    def test_query14(self):
        # testQuery14: instanceOf guards over absent captures in having
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price>20] <0:5> -> e2=Stream2[price>e1[0].price]
        select e1[0].price as price1_0, e1[1].price as price1_1, e1[2].price as price1_2, e2.price as price2
        having instanceOfFloat(e1[1].price) and not instanceOfFloat(e1[2].price) and instanceOfFloat(price1_1) and not instanceOfFloat(price1_2)
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 25.6, 100)),
            ("Stream1", ("WSO2", 23.6, 100)),
            ("Stream1", ("GOOG", 7.6, 100)),
            ("Stream2", ("IBM", 45.7, 100)),
        ])
        assert_rows(got, [(25.6, 23.6, None, 45.7)])

    def test_query15(self):
        # testQuery15: every -> exact count <2> -> absent-and-logical tail;
        # an arriving event on the absent side kills waiting tokens
        ql = S12 + """
        @info(name = 'query1')
        from every e1=Stream1[price>20] -> e2=Stream1[price>20]<2> -> not Stream1[price>20] and e3=Stream2
        select e1.price as price1_0, e2[0].price as price2_0, e2[1].price as price2_1,
        e2[2].price as price2_2, e3.price as price3_0
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 25.6, 100)),
            ("Stream1", ("WSO2", 23.6, 100)),
            ("Stream1", ("WSO2", 23.6, 100)),
            ("Stream1", ("GOOG", 27.6, 100)),
            ("Stream1", ("GOOG", 28.6, 100)),
            ("Stream2", ("IBM", 45.7, 100)),
        ])
        assert_rows(got, [(23.6, 27.6, 28.6, None, 45.7)])
