"""Partition tests.

Reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/query/
partition/PartitionTestCase1.java — per-key isolated query state, range
partitions, inner streams.
"""

from siddhi_tpu import SiddhiManager


def run_app(ql, sends, callback_name="q"):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    ins = []
    rt.add_callback(callback_name, lambda ts, i, r: ins.extend(e.data for e in i or []))
    rt.start()
    h = {}
    for sid, row, ts in sends:
        h.setdefault(sid, rt.get_input_handler(sid)).send(row, timestamp=ts)
    rt.shutdown()
    mgr.shutdown()
    return ins


class TestValuePartition:
    def test_per_key_aggregator_state(self):
        ql = """
        define stream S (symbol string, volume long);
        partition with (symbol of S)
        begin
            @info(name='q')
            from S select symbol, sum(volume) as total insert into Out;
        end;
        """
        ins = run_app(ql, [
            ("S", ("A", 10), 1),
            ("S", ("B", 5), 2),
            ("S", ("A", 20), 3),
            ("S", ("B", 7), 4),
        ])
        # each key has its OWN running sum (no group by needed)
        assert ins == [("A", 10), ("B", 5), ("A", 30), ("B", 12)]

    def test_per_key_window(self):
        ql = """
        define stream S (symbol string, volume long);
        partition with (symbol of S)
        begin
            @info(name='q')
            from S#window.length(2) select symbol, sum(volume) as total insert into Out;
        end;
        """
        ins = run_app(ql, [
            ("S", ("A", 1), 1),
            ("S", ("A", 2), 2),
            ("S", ("B", 10), 3),
            ("S", ("A", 4), 4),   # A's window evicts 1 -> 2+4
            ("S", ("B", 20), 5),
        ])
        assert ins == [("A", 1), ("A", 3), ("B", 10), ("A", 6), ("B", 30)]

    def test_filter_inside_partition(self):
        ql = """
        define stream S (symbol string, volume long);
        partition with (symbol of S)
        begin
            @info(name='q')
            from S[volume > 5] select symbol, count() as n insert into Out;
        end;
        """
        ins = run_app(ql, [
            ("S", ("A", 10), 1),
            ("S", ("A", 3), 2),
            ("S", ("A", 20), 3),
        ])
        assert ins == [("A", 1), ("A", 2)]


class TestRangePartition:
    def test_ranges(self):
        ql = """
        define stream S (symbol string, price float);
        partition with (price < 100 as 'cheap' or price >= 100 as 'expensive' of S)
        begin
            @info(name='q')
            from S select symbol, count() as n insert into Out;
        end;
        """
        ins = run_app(ql, [
            ("S", ("X", 50.0), 1),
            ("S", ("Y", 150.0), 2),
            ("S", ("Z", 60.0), 3),
        ])
        # cheap partition counts 1,2; expensive counts 1
        assert ins == [("X", 1), ("Y", 1), ("Z", 2)]


class TestInnerStream:
    def test_inner_stream_chaining(self):
        ql = """
        define stream S (symbol string, volume long);
        partition with (symbol of S)
        begin
            from S select symbol, sum(volume) as total insert into #T;
            @info(name='q')
            from #T[total > 10] select symbol, total insert into Out;
        end;
        """
        ins = run_app(ql, [
            ("S", ("A", 8), 1),
            ("S", ("B", 20), 2),
            ("S", ("A", 5), 3),   # A total 13 -> passes
        ])
        assert ins == [("B", 20), ("A", 13)]


class TestJoinInPartition:
    def test_per_key_join_windows(self):
        ql = """
        define stream A (symbol string, av long);
        define stream B (symbol string, bv long);
        partition with (symbol of A, symbol of B)
        begin
            @info(name='q')
            from A#window.length(2) join B#window.length(2)
            on A.av == B.bv
            select A.symbol as s, A.av as av
            insert into Out;
        end;
        """
        ins = run_app(ql, [
            ("A", ("K1", 7), 1),
            ("B", ("K2", 7), 2),   # same value, DIFFERENT key: must NOT join
            ("B", ("K1", 7), 3),   # same key: joins
            ("A", ("K2", 9), 4),
            ("B", ("K2", 9), 5),   # K2 joins within its own partition
        ])
        assert ins == [("K1", 7), ("K2", 9)]


class TestPatternInPartition:
    def test_per_key_pattern(self):
        ql = """
        define stream S (symbol string, price float);
        partition with (symbol of S)
        begin
            @info(name='q')
            from every e1=S[price > 90] -> e2=S[price < 10]
            select e1.symbol as s, e1.price as p1, e2.price as p2
            insert into Out;
        end;
        """
        ins = run_app(ql, [
            ("S", ("K1", 95.0), 1),
            ("S", ("K2", 5.0), 2),    # different key: must NOT complete K1's token
            ("S", ("K2", 96.0), 3),
            ("S", ("K1", 4.0), 4),    # completes K1
            ("S", ("K2", 3.0), 5),    # completes K2
        ])
        assert ins == [("K1", 95.0, 4.0), ("K2", 96.0, 3.0)]
