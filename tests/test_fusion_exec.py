"""Plan-driven whole-graph fusion (core/fusion_exec.py + the group mode of
core/ingest.py FusedJunctionIngest).

The FusionPlan's fusable groups run as ONE donated-state chunk program per
stream; SA124-blocked queries ride the residual per-batch path after each
fused commit; shared-state candidates reference one refcounted window ring.
Every case here holds the byte-parity contract: outputs under the group
engine must equal the same app run with fusion disabled
(@app:fuse(disable='true') / SIDDHI_TPU_FUSE=0)."""

from __future__ import annotations

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError


@pytest.fixture(autouse=True)
def _isolate_fuse_env(monkeypatch):
    """CI runs parts of the suite under SIDDHI_TPU_FUSE=1|0; these tests set
    the toggle explicitly per case."""
    monkeypatch.delenv("SIDDHI_TPU_FUSE", raising=False)


HEAD = "@app:batch(size='32')\ndefine stream S (symbol string, price float, volume long);\n"

# three fusable queries (two sharing an identical filter+window chain) plus
# one rate-limited query — the plan forms a group of three, shares one ring,
# and leaves q4 on the residual path (hazard: rate-limit)
GROUP_QL = HEAD + """
@info(name='q1') from S[price > 50]#window.length(16) select symbol, avg(price) as ap insert into Out1;
@info(name='q2') from S[price > 50]#window.length(16) select symbol, max(price) as mx insert into Out2;
@info(name='q3') from S#window.lengthBatch(8) select sum(volume) as tv insert into Out3;
@info(name='q4') from S[volume > 300] select symbol, volume output every 5 events insert into Out4;
"""


def _feed(n, seed=11):
    rng = np.random.default_rng(seed)
    return (
        np.arange(n, dtype=np.int64) + 1_700_000_000_000,
        {
            "symbol": rng.integers(1, 5, size=n).astype(np.int32),
            "price": rng.uniform(0.0, 100.0, size=n).astype(np.float32),
            "volume": rng.integers(1, 1000, size=n).astype(np.int64),
        },
    )


def _run(ql, n=96, sends=1, keep_runtime=False, seed=11):
    """Run `ql` on a columnar feed; returns ({qid: rows}, runtime-or-None).
    With keep_runtime the caller must shut the runtime down."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    for s in ("A", "B", "C", "D"):
        mgr.interner.intern(s)
    rows = {qid: [] for qid in rt.queries}
    for qid in rt.queries:
        rt.add_callback(
            qid,
            lambda ts, ins, rem, _q=qid: rows[_q].append(
                (
                    tuple(tuple(e.data) for e in (ins or [])),
                    tuple(tuple(e.data) for e in (rem or [])),
                )
            ),
        )
    rt.start()
    ts, cols = _feed(n, seed)
    for _ in range(sends):
        rt.get_input_handler("S").send_columns(ts, cols, now=int(ts[-1]))
    if keep_runtime:
        return rows, (mgr, rt)
    rt.shutdown()
    mgr.shutdown()
    return rows, None


class TestGroupEngine:
    def test_group_formed_with_residual_and_shared_ring(self):
        rows, (mgr, rt) = _run(GROUP_QL, keep_runtime=True)
        try:
            fi = rt.junctions["S"].fused_ingest
            assert fi is not None and fi.plan_group is not None
            rep = fi.group_report()
            assert rep["queries"] == ["q1", "q2", "q3"]
            assert rep["residual"] == ["query.q4"]
            assert rep["chunks"] >= 1  # the fused path actually engaged
            assert rep["dispatches_per_chunk_after"] == 1
            assert rep["shared_state"] == [
                {"queries": ["q1", "q2"], "refcount": 2}
            ]
            # achieved reduction: n*K per-batch dispatches became `chunks`
            assert 0 < rep["achieved_dispatch_reduction"] <= 1
            # surfaced through junction introspection too
            assert (
                rt.junctions["S"].describe_state()["pipeline"]["fusedgroup"]
                == rep
            )
            # ... and per query: one refcounted ring
            q1 = rt.queries["q1"].describe_state()
            assert q1["shared_ring"]["refcount"] == 2
            assert q1["shared_ring"]["leader"] == "q1"
            assert "shared_ring" not in rt.queries["q3"].describe_state()
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_group_outputs_match_unfused(self):
        fused, _ = _run(GROUP_QL, n=96, sends=2)
        unfused, _ = _run(
            "@app:fuse(disable='true')\n" + GROUP_QL, n=96, sends=2
        )
        assert set(fused) == set(unfused)
        for qid in fused:
            assert fused[qid] == unfused[qid], qid

    def test_env_force_off_beats_annotation(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_FUSE", "0")
        rows, (mgr, rt) = _run(GROUP_QL, keep_runtime=True)
        try:
            assert all(
                j.fused_ingest is None for j in rt.junctions.values()
            )
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_annotation_disable(self):
        _rows, (mgr, rt) = _run(
            "@app:fuse(disable='true')\n" + GROUP_QL, keep_runtime=True
        )
        try:
            assert all(
                j.fused_ingest is None for j in rt.junctions.values()
            )
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_env_force_on_beats_annotation(self, monkeypatch):
        monkeypatch.setenv("SIDDHI_TPU_FUSE", "1")
        _rows, (mgr, rt) = _run(
            "@app:fuse(disable='true')\n" + GROUP_QL, keep_runtime=True
        )
        try:
            assert rt.junctions["S"].fused_ingest is not None
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_single_query_junction_keeps_legacy_engine(self):
        ql = HEAD + (
            "@info(name='q') from S[price > 10] select symbol, price "
            "insert into Out;\n"
        )
        _rows, (mgr, rt) = _run(ql, keep_runtime=True)
        try:
            fi = rt.junctions["S"].fused_ingest
            assert fi is not None
            assert fi.plan_group is None  # legacy all-or-nothing engine
            assert fi.group_report() is None
        finally:
            rt.shutdown()
            mgr.shutdown()


class TestSharedState:
    def test_shared_chains_alias_after_fused_send(self):
        _rows, (mgr, rt) = _run(GROUP_QL, keep_runtime=True)
        try:
            import jax

            q1 = rt.queries["q1"]
            q2 = rt.queries["q2"]
            l1 = jax.tree_util.tree_leaves(q1.state["chain"])
            l2 = jax.tree_util.tree_leaves(q2.state["chain"])
            assert all(a is b for a, b in zip(l1, l2))  # ONE ring
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_unshare_on_per_batch_fallback_keeps_parity(self):
        """A short send (below the 2-batch fused threshold) after a fused
        send rides the per-batch path: the aliased chains must split first
        (independent donation) and the outputs must stay byte-identical to
        a never-fused run of the same sequence."""

        def run(ql):
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(ql)
            for s in ("A", "B", "C", "D"):
                mgr.interner.intern(s)
            rows = {qid: [] for qid in rt.queries}
            for qid in rt.queries:
                rt.add_callback(
                    qid,
                    lambda ts, ins, rem, _q=qid: rows[_q].append(
                        (
                            tuple(tuple(e.data) for e in (ins or [])),
                            tuple(tuple(e.data) for e in (rem or [])),
                        )
                    ),
                )
            rt.start()
            ts, cols = _feed(96)
            h = rt.get_input_handler("S")
            h.send_columns(ts, cols, now=int(ts[-1]))  # fused chunk
            short_ts, short_cols = _feed(8, seed=3)  # per-batch fallback
            h.send_columns(short_ts, short_cols, now=int(short_ts[-1]))
            h.send_columns(ts, cols, now=int(ts[-1]))  # re-fuses
            import jax

            q1, q2 = rt.queries["q1"], rt.queries["q2"]
            alias = [
                a is b
                for a, b in zip(
                    jax.tree_util.tree_leaves(q1.state["chain"]),
                    jax.tree_util.tree_leaves(q2.state["chain"]),
                )
            ]
            rt.shutdown()
            mgr.shutdown()
            return rows, alias

        fused_rows, alias = run(GROUP_QL)
        assert all(alias)  # the final fused send re-shared the ring
        unfused_rows, _ = run("@app:fuse(disable='true')\n" + GROUP_QL)
        for qid in fused_rows:
            assert fused_rows[qid] == unfused_rows[qid], qid

    def test_row_send_after_fused_send_keeps_parity(self):
        """Row-based send() events after a fused send reach the shared-ring
        queries through StreamJunction.send_rows -> publish_batch — a path
        that never consults try_send. The receive-side unshare guard
        (QueryRuntime._unshare_guard) must split the aliased chains before
        each per-batch step donates them: without it, q1's step donates the
        shared ring buffers and q2's step consumes freed device memory."""

        def run(ql):
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(ql)
            for s in ("A", "B", "C", "D"):
                mgr.interner.intern(s)
            rows = {qid: [] for qid in rt.queries}
            for qid in rt.queries:
                rt.add_callback(
                    qid,
                    lambda ts, ins, rem, _q=qid: rows[_q].append(
                        (
                            tuple(tuple(e.data) for e in (ins or [])),
                            tuple(tuple(e.data) for e in (rem or [])),
                        )
                    ),
                )
            rt.start()
            ts, cols = _feed(96)
            h = rt.get_input_handler("S")
            h.send_columns(ts, cols, now=int(ts[-1]))  # fused: aliases rings
            base = int(ts[-1]) + 1
            for k in range(6):  # row path: publish_batch, never try_send
                h.send(("A", 60.0 + k, 500), timestamp=base + k)
            h.send_columns(ts, cols, now=int(ts[-1]))  # re-fuses
            rt.shutdown()
            mgr.shutdown()
            return rows

        fused_rows = run(GROUP_QL)
        unfused_rows = run("@app:fuse(disable='true')\n" + GROUP_QL)
        for qid in fused_rows:
            assert fused_rows[qid] == unfused_rows[qid], qid


class TestFuseAnnotation:
    def test_malformed_disable_raises_at_creation(self):
        with pytest.raises(SiddhiAppCreationError, match="disable"):
            SiddhiManager().create_siddhi_app_runtime(
                "@app:fuse(disable='maybe')\n" + GROUP_QL
            )

    def test_unknown_option_raises_at_creation(self):
        with pytest.raises(SiddhiAppCreationError, match="turbo"):
            SiddhiManager().create_siddhi_app_runtime(
                "@app:fuse(turbo='on')\n" + GROUP_QL
            )

    def test_analyzer_sa125_same_rule_set(self):
        from siddhi_tpu.analysis import analyze

        r = analyze("@app:fuse(disable='maybe', turbo='on')\n" + GROUP_QL)
        codes = [d.code for d in r.diagnostics]
        assert codes.count("SA125") == 2

    def test_valid_annotation_lints_clean(self):
        from siddhi_tpu.analysis import analyze

        r = analyze("@app:fuse(disable='false')\n" + GROUP_QL)
        assert not [d for d in r.diagnostics if d.code == "SA125"]


class TestObservability:
    def test_explain_and_profile_surface_the_group(self):
        _rows, (mgr, rt) = _run(
            "@app:statistics(reporter='none')\n" + GROUP_QL,
            keep_runtime=True,
        )
        try:
            plan = rt.explain_plan()
            snode = next(
                n for n in plan["nodes"] if n["id"] == "stream:S"
            )
            g = snode["counters"]["fusedgroup"]
            assert g["component"] == "stream.S.fusedgroup.0"
            assert g["queries"] == ["q1", "q2", "q3"]
            assert g["dispatches_per_chunk_after"] == 1
            text = rt.explain()
            assert "fusedgroup[q1,q2,q3]" in text
            prof = rt.profile_report()
            groups = prof["fused_groups"]
            assert groups[0]["stream"] == "S"
            assert groups[0]["chunks"] >= 1
            # the chunk program's compile ledger rides the SAME component
            # name the cost model predicts (stream.<S>.fusedgroup.<g>)
            assert any(
                comp.startswith("stream.S.fusedgroup.0")
                for comp in prof["compile"]
            )
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_plan_component_matches_engine(self):
        from siddhi_tpu.analysis.fusion import build_fusion_plan

        _rows, (mgr, rt) = _run(GROUP_QL, keep_runtime=True)
        try:
            plan = build_fusion_plan(rt.app)
            fi = rt.junctions["S"].fused_ingest
            assert plan.groups[0]["component"] == fi.component
        finally:
            rt.shutdown()
            mgr.shutdown()


class TestResidualSafety:
    def test_feedback_into_fused_stream_vetoes_partial_fusion(self):
        """A rate-limited (blocked) query whose output re-enters S must NOT
        ride the residual path: post-chunk re-dispatch would reorder the
        group's input. The junction falls back to the legacy all-or-nothing
        path (which never engages here)."""
        ql = HEAD + """
        @info(name='q1') from S[price > 50]#window.length(16) select symbol, avg(price) as ap insert into Out1;
        @info(name='q2') from S#window.lengthBatch(8) select symbol, sum(volume) as tv group by symbol insert into Out2;
        @info(name='q4') from S select symbol, price, volume output every 5 events insert into Loop;
        @info(name='q5') from Loop select symbol, price, volume insert into S;
        """
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        rt.start()
        try:
            fi = rt.junctions["S"].fused_ingest
            assert fi is None or fi.plan_group is None
        finally:
            rt.shutdown()
            mgr.shutdown()

    def test_group_engine_respects_late_subscriber_count(self):
        """eligible() re-checks subscriber accounting every send: detaching
        nothing but adding a raw subscriber after start() must disengage the
        fused path (count mismatch), not corrupt it."""
        _rows, (mgr, rt) = _run(GROUP_QL, keep_runtime=True)
        try:
            j = rt.junctions["S"]
            fi = j.fused_ingest
            before = fi.chunks_dispatched
            j.subscribe(lambda b, now: None, name="late")
            ts, cols = _feed(96)
            rt.get_input_handler("S").send_columns(
                ts, cols, now=int(ts[-1])
            )
            assert fi.chunks_dispatched == before  # fell back per-batch
        finally:
            rt.shutdown()
            mgr.shutdown()
