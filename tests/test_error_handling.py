"""Fault-stream & error-handling subsystem tests.

Reference: modules/siddhi-core/src/test/java/.../core/stream/event/FaultStreamTestCase
(@OnError LOG/STREAM routing, `!stream` queries), util/error/handler tests
(error store capture + replay), and Sink.onError semantics from
InMemoryTransportTestCase (on.error retry/wait/store matrix).
"""

import threading
import time

import pytest

from siddhi_tpu import InMemoryErrorStore, SiddhiManager
from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler
from siddhi_tpu.core.errors import (
    DefinitionNotExistError,
    SiddhiAppCreationError,
)
from siddhi_tpu.core.io import (
    BackoffRetryCounter,
    ConnectionUnavailableError,
    SINKS,
    Sink,
)


def _wait_for(pred, timeout=30.0):
    """Poll until pred() is truthy (async drains + first-batch jit compiles
    make fixed sleeps racy); returns the last pred() value."""
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        v = pred()
        if v:
            return v
        time.sleep(0.01)
    return pred()


# ---------------------------------------------------------------------------
# parser: `!stream` syntax + @OnError validation
# ---------------------------------------------------------------------------


class TestFaultSyntax:
    def test_from_fault_stream_parses(self):
        app = SiddhiCompiler.parse("""
        @OnError(action='STREAM')
        define stream S (v int);
        from !S select v, _error insert into F;
        """)
        q = app.execution_elements[0]
        assert q.input_stream.stream_id == "!S"
        assert q.input_stream.is_fault

    def test_insert_into_fault_stream_parses(self):
        app = SiddhiCompiler.parse("""
        @OnError(action='STREAM')
        define stream S (v int);
        define stream T (v int, m string);
        from T select v, m as _error insert into !S;
        """)
        out = app.execution_elements[0].output_stream
        assert out.target == "!S"
        assert out.is_fault

    def test_bad_on_error_action_rejected(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime(
                "@OnError(action='EXPLODE') define stream S (v int);"
            )
        mgr.shutdown()

    def test_error_attribute_name_reserved(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime(
                "@OnError(action='STREAM') define stream S (_error string);"
            )
        mgr.shutdown()

    def test_insert_into_undeclared_fault_stream_rejected(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            define stream S (v int);
            define stream T (v int, m string);
            from T select v, m insert into !S;
            """)
        mgr.shutdown()

    def test_programmatic_fault_api(self):
        from siddhi_tpu.core.types import AttrType
        from siddhi_tpu.query_api.annotation import Annotation
        from siddhi_tpu.query_api.definition import StreamDefinition
        from siddhi_tpu.query_api.execution import (
            Query,
            Selector,
            SingleInputStream,
        )
        from siddhi_tpu.query_api.expression import Variable
        from siddhi_tpu.query_api.siddhi_app import SiddhiApp

        app = SiddhiApp.siddhi_app("Prog")
        sd = StreamDefinition("S").attribute("v", AttrType.INT)
        sd.annotation(Annotation("OnError", [("action", "STREAM")]))
        app.define_stream(sd)
        app.add_query(
            Query.query()
            .from_(SingleInputStream.fault_stream("S"))
            .select(
                Selector()
                .select(None, Variable("v"))
                .select(None, Variable("_error"))
            )
            .insert_into("FOut")
        )
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(app)
        faults = []
        rt.add_callback("FOut", lambda evs: faults.extend(evs))
        rt.junctions["S"].subscribe(_poison_subscriber("v", 3))
        rt.start()
        rt.get_input_handler("S").send((3,))
        assert [tuple(e.data) for e in faults] == [(3, "ValueError: poison 3")]
        rt.shutdown()
        mgr.shutdown()

    def test_from_undeclared_fault_stream_rejected(self):
        mgr = SiddhiManager()
        with pytest.raises(DefinitionNotExistError):
            mgr.create_siddhi_app_runtime("""
            define stream S (v int);
            from !S select v insert into F;
            """)
        mgr.shutdown()


# ---------------------------------------------------------------------------
# @OnError runtime semantics
# ---------------------------------------------------------------------------


def _poison_subscriber(attr, bad):
    """Subscriber raising when any valid row's `attr` equals `bad`."""
    import numpy as np

    def fn(batch, now):
        vals = np.asarray(batch.cols[attr])[np.asarray(batch.valid)]
        if (vals == bad).any():
            raise ValueError(f"poison {bad}")

    return fn


class TestOnErrorStream:
    def test_fault_events_carry_attrs_and_error(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('F1')
        @OnError(action='STREAM')
        define stream S (symbol string, price float);
        from S select symbol, price insert into Out;
        from !S select symbol, price, _error insert into FOut;
        """)
        got, faults = [], []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        rt.add_callback("FOut", lambda evs: faults.extend(evs))
        rt.junctions["S"].subscribe(_poison_subscriber("price", -1.0))
        rt.start()
        h = rt.get_input_handler("S")
        h.send(("WSO2", 10.0))
        h.send(("BAD", -1.0))
        h.send(("IBM", 20.0))
        # the healthy query keeps processing every batch
        assert [tuple(e.data) for e in got] == [
            ("WSO2", 10.0), ("BAD", -1.0), ("IBM", 20.0)
        ]
        # only the failing batch lands on !S, original attrs + _error
        assert [tuple(e.data) for e in faults] == [
            ("BAD", -1.0, "ValueError: poison -1.0")
        ]
        rt.shutdown()
        mgr.shutdown()

    def test_real_query_failure_routes_to_fault_stream(self):
        # the query itself (not a synthetic subscriber) throws while
        # processing a batch: its script function body explodes at trace time
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('F5')
        @OnError(action='STREAM')
        define stream S (v int);
        define function bad[python] return int { nonexistent_name(data[0]) };
        from S select bad(v) as w insert into Out;
        from !S select v, _error insert into FOut;
        """)
        faults = []
        rt.add_callback("FOut", lambda evs: faults.extend(evs))
        rt.start()
        rt.get_input_handler("S").send((5,))
        assert len(faults) == 1
        assert faults[0].data[0] == 5
        assert "nonexistent_name" in faults[0].data[1]
        rt.shutdown()
        mgr.shutdown()

    def test_fault_stream_filterable_by_normal_query(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('F2')
        @OnError(action='STREAM')
        define stream S (v int);
        from !S[v > 5] select v, _error insert into Big;
        """)
        big = []
        rt.add_callback("Big", lambda evs: big.extend(evs))
        rt.junctions["S"].subscribe(_poison_subscriber("v", 0))
        rt.start()
        h = rt.get_input_handler("S")
        h.send((0,))
        h.send((9,))  # no failure: never reaches !S
        assert [tuple(e.data) for e in big] == []
        rt.junctions["S"].subscribers[0] = _poison_subscriber("v", 9)
        h.send((9,))
        assert [tuple(e.data) for e in big] == [(9, "ValueError: poison 9")]
        rt.shutdown()
        mgr.shutdown()

    def test_query_can_insert_into_fault_stream(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('F3')
        @OnError(action='STREAM')
        define stream S (v int);
        define stream Quarantine (v int, reason string);
        from Quarantine select v, reason as _error insert into !S;
        from !S select v, _error insert into FOut;
        """)
        faults = []
        rt.add_callback("FOut", lambda evs: faults.extend(evs))
        rt.start()
        rt.get_input_handler("Quarantine").send((3, "manual"))
        assert [tuple(e.data) for e in faults] == [(3, "manual")]
        rt.shutdown()
        mgr.shutdown()

    def test_positional_on_error_form(self):
        # @OnError('STREAM') without the action= key must not silently
        # degrade to LOG
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('F7')
        @OnError('STREAM')
        define stream S (v int);
        from !S select v, _error insert into FOut;
        """)
        faults = []
        rt.add_callback("FOut", lambda evs: faults.extend(evs))
        rt.junctions["S"].subscribe(_poison_subscriber("v", 1))
        rt.start()
        rt.get_input_handler("S").send((1,))
        assert len(faults) == 1
        rt.shutdown()
        mgr.shutdown()

    def test_fault_routing_preserves_event_kind(self):
        # an EXPIRED row in a failed batch must stay EXPIRED on !S
        import numpy as np

        from siddhi_tpu.core.event import KIND_CURRENT, KIND_EXPIRED

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('F8')
        @OnError(action='STREAM')
        define stream S (v int);
        @info(name='fq')
        from !S select v, _error insert all events into FOut;
        """)
        kinds_seen = []
        rt.add_callback(
            "fq", lambda ts, ins, removed: kinds_seen.append(
                (len(ins or []), len(removed or []))
            )
        )
        rt.start()
        j = rt.junctions["S"]

        def boom(batch, now):
            raise ValueError("always")

        j.subscribe(boom)
        batch = j.schema.to_batch(
            [1, 2], [(10,), (20,)], j.interner,
            capacity=j.batch_size, kinds=[KIND_CURRENT, KIND_EXPIRED],
        )
        j.publish_batch(batch, 2)
        # one current + one removed event reached the fault-stream query
        assert kinds_seen == [(1, 1)]
        rt.shutdown()
        mgr.shutdown()

    def test_multiple_failing_subscribers_route_batch_once(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('F6')
        @OnError(action='STREAM')
        define stream S (v int);
        from !S select v, _error insert into FOut;
        """)
        faults = []
        rt.add_callback("FOut", lambda evs: faults.extend(evs))
        rt.junctions["S"].subscribe(_poison_subscriber("v", 4))
        rt.junctions["S"].subscribe(_poison_subscriber("v", 4))
        rt.start()
        rt.get_input_handler("S").send((4,))
        # two subscribers failed on the same batch: ONE fault emission
        assert len(faults) == 1
        rt.shutdown()
        mgr.shutdown()

    def test_log_action_swallows_and_continues(self, caplog):
        import logging

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('F4')
        @OnError(action='LOG')
        define stream S (v int);
        from S select v insert into Out;
        """)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        rt.junctions["S"].subscribe(_poison_subscriber("v", 13))
        rt.start()
        h = rt.get_input_handler("S")
        with caplog.at_level(logging.ERROR, logger="siddhi_tpu.core.stream_junction"):
            h.send((13,))  # must NOT propagate to the sender
        h.send((14,))
        assert [tuple(e.data) for e in got] == [(13,), (14,)]
        assert any("LOG" in r.message for r in caplog.records)
        rt.shutdown()
        mgr.shutdown()

    def test_no_policy_still_propagates_to_sender(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("define stream S (v int);")
        rt._junction("S").subscribe(_poison_subscriber("v", 1))
        rt.start()
        with pytest.raises(ValueError):
            rt.get_input_handler("S").send((1,))
        rt.shutdown()
        mgr.shutdown()


class TestOnErrorStore:
    def test_store_query_replay_purge(self):
        mgr = SiddhiManager()
        store = InMemoryErrorStore(capacity=8)
        mgr.set_error_store(store)
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('ES1')
        @OnError(action='STORE')
        define stream S (v int);
        from S select v insert into Out;
        """)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        boom = _poison_subscriber("v", 5)
        rt.junctions["S"].subscribe(boom)
        rt.start()
        h = rt.get_input_handler("S")
        h.send((5,), timestamp=111)
        entries = store.load(app_name="ES1", stream_id="S")
        assert len(entries) == 1
        assert entries[0].events == [(111, (5,))]
        assert "poison 5" in entries[0].error
        # replay after removing the poison subscriber: events re-enter S
        rt.junctions["S"].subscribers.remove(boom)
        assert mgr.replay_errors() == 1
        assert (5,) in [tuple(e.data) for e in got]
        assert store.size() == 0  # replayed entries are purged
        rt.shutdown()
        mgr.shutdown()

    def test_undispatchable_entry_stays_stored(self):
        # an entry whose origin is gone must NOT be purged by replay
        mgr = SiddhiManager()
        store = InMemoryErrorStore()
        mgr.set_error_store(store)
        from siddhi_tpu.core.error_store import ORIGIN_STREAM, make_entry

        store.store(
            make_entry("NoSuchApp", ORIGIN_STREAM, "S", "gone", events=[(1, (1,))])
        )
        assert mgr.replay_errors() == 0
        assert store.size() == 1
        mgr.shutdown()

    def test_capacity_bound_evicts_oldest(self):
        store = InMemoryErrorStore(capacity=2)
        from siddhi_tpu.core.error_store import ORIGIN_STREAM, make_entry

        for v in range(3):
            store.store(make_entry("A", ORIGIN_STREAM, "S", f"e{v}"))
        assert store.size() == 2
        assert store.dropped == 1
        assert [e.error for e in store.load()] == ["e1", "e2"]
        assert store.purge() == 2
        assert store.size() == 0


# ---------------------------------------------------------------------------
# set_exception_handler / async drain survival
# ---------------------------------------------------------------------------


class TestExceptionHandler:
    def test_handler_receives_and_processing_continues(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('EH1')
        define stream S (v int);
        from S select v insert into Out;
        """)
        got, errors = [], []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        rt.set_exception_handler(errors.append)
        rt.junctions["S"].subscribe(_poison_subscriber("v", 2))
        rt.start()
        h = rt.get_input_handler("S")
        h.send((1,))
        h.send((2,))  # swallowed by the handler, sender unaffected
        h.send((3,))
        assert [tuple(e.data) for e in got] == [(1,), (2,), (3,)]
        assert len(errors) == 1 and isinstance(errors[0], ValueError)
        rt.shutdown()
        mgr.shutdown()

    def test_async_junction_survives_poison_event(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('EH2')
        @async(buffer.size='64')
        define stream S (v int);
        from S select v insert into Out;
        """)
        got, errors = [], []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        rt.set_exception_handler(errors.append)
        rt.junctions["S"].subscribe(_poison_subscriber("v", 7))
        rt.start()
        j = rt.junctions["S"]
        assert j.is_async
        h = rt.get_input_handler("S")
        h.send((7,))
        _wait_for(lambda: errors)
        assert all(t.is_alive() for t in j._workers)  # worker survived
        h.send((8,))
        _wait_for(lambda: len(got) >= 2)
        assert (8,) in [tuple(e.data) for e in got]
        assert any(isinstance(e, ValueError) for e in errors)
        rt.shutdown()
        mgr.shutdown()

    def test_async_worker_survives_unpackable_row(self):
        # object columns force the python-queue drain path, where the worker
        # itself packs rows: a wrong-arity row raises inside the worker
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('EH3')
        @async(buffer.size='64')
        define stream S (v object);
        """)
        rows, errors = [], []
        rt.add_callback("S", lambda evs: rows.extend(evs))
        rt.set_exception_handler(errors.append)
        rt.start()
        j = rt.junctions["S"]
        h = rt.get_input_handler("S")
        h.send(("a", "extra"))  # poison: arity 2 into a 1-attribute stream
        _wait_for(lambda: errors)
        assert all(t.is_alive() for t in j._workers)
        h.send(("b",))
        _wait_for(lambda: rows)
        assert [tuple(e.data) for e in rows] == [("b",)]
        assert len(errors) == 1
        rt.shutdown()
        mgr.shutdown()


# ---------------------------------------------------------------------------
# sink on.error
# ---------------------------------------------------------------------------


class _FlakySink(Sink):
    """Publish raises until `down` clears; connect honors `conn_down`."""

    def __init__(self):
        self.delivered = []
        self.down = False
        self.conn_down = False
        self.publish_attempts = 0

    def connect(self):
        if self.conn_down:
            raise ConnectionUnavailableError("connect refused")

    def publish(self, payload):
        self.publish_attempts += 1
        if self.down:
            raise ConnectionUnavailableError("transport down")
        self.delivered.append(payload)


def _sink_app(on_error, extra=""):
    mgr = SiddhiManager()
    instances = []

    class _Impl(_FlakySink):
        def __init__(self):
            super().__init__()
            instances.append(self)

    SINKS["flakytest"] = _Impl
    try:
        rt = mgr.create_siddhi_app_runtime(f"""
        @app:name('SK_{on_error}')
        define stream In (v int);
        @sink(type='flakytest', on.error='{on_error}'{extra},
              @map(type='passThrough'))
        define stream Out (v int);
        from In select v insert into Out;
        """)
    finally:
        del SINKS["flakytest"]
    rt.start()
    return mgr, rt, instances[0]


class TestSinkOnError:
    def test_invalid_on_error_rejected(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            _FlakySink().init("S", {"on.error": "PANIC"}, None)
        mgr.shutdown()

    def test_retry_reconnects_and_delivers(self):
        mgr, rt, sink = _sink_app("RETRY")
        rt.get_input_handler("In").send((0,))  # warm up: first batch compiles
        assert len(sink.delivered) == 1
        sink.down = True
        attempts_before = sink.publish_attempts

        def recover():
            time.sleep(0.12)  # past the first 50ms+100ms backoff steps
            sink.down = False

        threading.Thread(target=recover, daemon=True).start()
        rt.get_input_handler("In").send((1,))  # blocks in the retry ladder
        assert [tuple(e.data) for p in sink.delivered for e in p] == [(0,), (1,)]
        assert sink.publish_attempts - attempts_before >= 2
        rt.shutdown()
        mgr.shutdown()

    def test_retry_exhaustion_drops(self):
        mgr, rt, sink = _sink_app("RETRY", extra=", retry.count='2'")
        sink.down = True
        sink.conn_down = True
        rt.get_input_handler("In").send((1,))  # 2 attempts, then dropped
        assert sink.delivered == []
        sink.down = False
        sink.conn_down = False
        rt.get_input_handler("In").send((2,))
        assert [tuple(e.data) for p in sink.delivered for e in p] == [(2,)]
        rt.shutdown()
        mgr.shutdown()

    def test_wait_blocks_then_delivers(self):
        mgr, rt, sink = _sink_app("WAIT")
        sink.down = True
        sink.conn_down = True
        done = threading.Event()

        def send():
            rt.get_input_handler("In").send((1,))
            done.set()

        t = threading.Thread(target=send, daemon=True)
        t.start()
        assert not done.wait(0.2)  # caller is blocked while the link is down
        sink.down = False
        sink.conn_down = False
        assert done.wait(5.0)  # reconnect chain lands, payload delivered
        assert [tuple(e.data) for p in sink.delivered for e in p] == [(1,)]
        rt.shutdown()
        mgr.shutdown()

    def test_wait_shutdown_spills_to_error_store(self):
        mgr, rt, sink = _sink_app("WAIT")
        sink.down = True
        sink.conn_down = True
        done = threading.Event()

        def send():
            rt.get_input_handler("In").send((1,))
            done.set()

        threading.Thread(target=send, daemon=True).start()
        assert not done.wait(0.2)
        rt.shutdown()  # stops sinks: the WAIT loop must exit, not drop silently
        assert done.wait(5.0)
        assert sink.delivered == []
        entries = mgr.error_store.load(origin="sink")
        assert len(entries) == 1 and entries[0].stream_id == "Out"
        mgr.shutdown()

    def test_store_spills_and_replay_republishes(self):
        mgr, rt, sink = _sink_app("STORE")
        sink.down = True
        rt.get_input_handler("In").send((1,))
        assert sink.delivered == []
        entries = mgr.error_store.load(origin="sink")
        assert len(entries) == 1 and entries[0].stream_id == "Out"
        sink.down = False
        assert mgr.replay_errors() == 1
        assert [tuple(e.data) for p in sink.delivered for e in p] == [(1,)]
        assert mgr.error_store.size() == 0
        rt.shutdown()
        mgr.shutdown()

    def test_failed_replay_against_log_sink_keeps_entry(self):
        # an entry replayed into a still-down LOG sink is dropped by the
        # sink's policy, so the store must keep it for a later attempt
        mgr, rt, sink = _sink_app("LOG")
        from siddhi_tpu.core.error_store import ORIGIN_SINK, make_entry

        mgr.error_store.store(make_entry(
            "SK_LOG", ORIGIN_SINK, "Out", "old failure", payload=[],
        ))
        sink.down = True
        assert mgr.replay_errors() == 0
        assert mgr.error_store.size() == 1
        sink.down = False
        assert mgr.replay_errors() == 1
        assert mgr.error_store.size() == 0
        rt.shutdown()
        mgr.shutdown()

    def test_invalid_retry_options_rejected_at_creation(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            @sink(type='log', retry.jitter='2.5', @map(type='text'))
            define stream Out (v int);
            """)
        mgr.shutdown()

    def test_log_drops_and_recovers(self):
        mgr, rt, sink = _sink_app("LOG")
        sink.down = True
        rt.get_input_handler("In").send((1,))
        assert sink.delivered == []  # dropped
        sink.down = False
        time.sleep(0.12)  # background reconnect backoff
        rt.get_input_handler("In").send((2,))
        assert [tuple(e.data) for p in sink.delivered for e in p] == [(2,)]
        rt.shutdown()
        mgr.shutdown()


# ---------------------------------------------------------------------------
# backoff counter
# ---------------------------------------------------------------------------


class TestBackoffRetryCounter:
    def test_default_sequence_unchanged(self):
        c = BackoffRetryCounter()
        seq = [c.next_interval_ms() for _ in range(10)]
        assert seq == [50, 100, 500, 1000, 5000, 10000, 30000, 60000, 60000, 60000]
        c.reset()
        assert c.next_interval_ms() == 50

    def test_interval_cap(self):
        c = BackoffRetryCounter(max_interval_ms=750)
        assert [c.next_interval_ms() for _ in range(5)] == [50, 100, 500, 750, 750]

    def test_jitter_bounded(self):
        import random

        c = BackoffRetryCounter(jitter=0.5, rand=random.Random(42))
        for base in [50, 100, 500, 1000]:
            iv = c.next_interval_ms()
            assert base <= iv <= int(base * 1.5)

    def test_jitter_never_exceeds_cap(self):
        import random

        c = BackoffRetryCounter(max_interval_ms=100, jitter=1.0, rand=random.Random(7))
        for _ in range(6):
            assert c.next_interval_ms() <= 100  # the cap is a hard ceiling

    def test_invalid_jitter_rejected(self):
        with pytest.raises(ValueError):
            BackoffRetryCounter(jitter=1.5)


# ---------------------------------------------------------------------------
# statistics: dispatch failures are counted
# ---------------------------------------------------------------------------


class TestErrorStatistics:
    def test_error_counter_in_report(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('ST1')
        @app:statistics(reporter='log', interval='3600')
        @OnError(action='LOG')
        define stream S (v int);
        from S select v insert into Out;
        """)
        rt.junctions["S"].subscribe(_poison_subscriber("v", 1))
        rt.start()
        h = rt.get_input_handler("S")
        h.send((1,))
        h.send((0,))
        rep = rt.statistics_manager.report()
        assert rep["errors"]["stream.S"] == 1
        assert rep["throughput"]["stream.S"] == 2
        rt.shutdown()
        mgr.shutdown()


# ---------------------------------------------------------------------------
# source-side on.error (ingress transports get the same policies)
# ---------------------------------------------------------------------------


def _source_app(on_error, stream_extra="", topic="src-err-topic"):
    """App with an inMemory JSON source; malformed JSON published to the
    broker exercises the map-failure path."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(f"""
    @app:name('SRC_{on_error or "none"}')
    {stream_extra}
    @source(type='inMemory', topic='{topic}'
            {", on.error='" + on_error + "'" if on_error else ""},
            @map(type='json'))
    define stream S (v int);
    @info(name='q')
    from S select v insert into Out;
    """)
    got = []
    rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
    rt.start()
    return mgr, rt, got


class TestSourceOnError:
    def test_default_propagates_to_publisher(self):
        from siddhi_tpu.core.io import InMemoryBroker

        mgr, rt, got = _source_app(None, topic="t-none")
        with pytest.raises(Exception):
            InMemoryBroker.publish("t-none", "{not json")
        mgr.shutdown()

    def test_log_drops_and_continues(self):
        from siddhi_tpu.core.io import InMemoryBroker

        mgr, rt, got = _source_app("LOG", topic="t-log")
        InMemoryBroker.publish("t-log", "{not json")  # dropped, no raise
        InMemoryBroker.publish("t-log", '{"v": 7}')
        assert _wait_for(lambda: got)
        assert got == [(7,)]
        mgr.shutdown()

    def test_store_spills_payload_and_replay_redelivers(self):
        from siddhi_tpu.core.error_store import ORIGIN_SOURCE
        from siddhi_tpu.core.io import InMemoryBroker

        mgr, rt, got = _source_app("STORE", topic="t-store")
        InMemoryBroker.publish("t-store", "{not json")
        entries = mgr.error_store.load(origin=ORIGIN_SOURCE)
        assert len(entries) == 1
        assert entries[0].payload == "{not json"
        assert entries[0].stream_id == "S"
        # replay with the payload still unmappable: the entry re-stores
        # (zero loss), THEN a fixed mapper path drains it
        assert mgr.replay_errors() == 1
        assert len(mgr.error_store.load(origin=ORIGIN_SOURCE)) == 1
        e = mgr.error_store.load(origin=ORIGIN_SOURCE)[0]
        e.payload = '{"v": 9}'  # operator fixed the payload
        mgr.error_store.purge()
        mgr.error_store.store(e)
        assert mgr.replay_errors() == 1
        assert _wait_for(lambda: (9,) in got)
        assert not mgr.error_store.load()
        mgr.shutdown()

    def test_stream_routes_mapped_rows_to_fault_stream(self):
        from siddhi_tpu.core.io import InMemoryBroker

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('SRC_STREAM')
        @OnError(action='STREAM')
        @source(type='inMemory', topic='t-fs', on.error='STREAM',
                @map(type='json'))
        define stream S (v int);
        @info(name='qf')
        from !S select v, _error insert into F;
        """)
        fgot = []
        rt.add_callback("F", lambda evs: fgot.extend(e.data for e in evs))
        rt.start()
        # mapped rows whose delivery fails: poison the junction so
        # send_many raises AFTER mapping succeeded
        rt.junctions["S"].subscribe(_poison_subscriber("v", 13))
        InMemoryBroker.publish("t-fs", '{"v": 13}')
        assert _wait_for(lambda: fgot)
        assert fgot[0][0] == 13 and "poison" in fgot[0][1].lower()
        mgr.shutdown()

    def test_stream_policy_requires_fault_stream(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            @source(type='inMemory', topic='t-bad', on.error='STREAM',
                    @map(type='json'))
            define stream S (v int);
            from S select v insert into Out;
            """)
        mgr.shutdown()

    def test_invalid_on_error_rejected(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            @source(type='inMemory', topic='t-bad2', on.error='PANIC',
                    @map(type='json'))
            define stream S (v int);
            from S select v insert into Out;
            """)
        mgr.shutdown()


# ---------------------------------------------------------------------------
# @OnError on named windows and tables
# ---------------------------------------------------------------------------


class TestWindowOnError:
    def _window_app(self, action):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(f"""
        @app:name('WOE_{action}')
        define stream S (v int);
        @OnError(action='{action}')
        define window W (v int) length(3);
        from S select v insert into W;
        """)
        rt.start()
        return mgr, rt

    def test_store_captures_window_mutation_failure(self):
        mgr, rt = self._window_app("STORE")
        rt.junctions["W"].subscribe(_poison_subscriber("v", 5))
        h = rt.get_input_handler("S")
        h.send((1,))  # healthy
        h.send((5,))  # poison: the window junction's STORE policy owns it
        entries = mgr.error_store.load(stream_id="W")
        assert len(entries) == 1
        assert entries[0].events[0][1] == (5,)
        h.send((2,))  # the app keeps processing
        mgr.shutdown()

    def test_stream_routes_to_window_fault_stream(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('WOE_STREAM')
        define stream S (v int);
        @OnError(action='STREAM')
        define window W (v int) length(3);
        from S select v insert into W;
        @info(name='qf')
        from !W select v, _error insert into WF;
        """)
        fgot = []
        rt.add_callback("WF", lambda evs: fgot.extend(e.data for e in evs))
        rt.start()
        rt.junctions["W"].subscribe(_poison_subscriber("v", 5))
        rt.get_input_handler("S").send((5,))
        assert _wait_for(lambda: fgot)
        assert fgot[0][0] == 5
        mgr.shutdown()

    def test_no_policy_propagates(self):
        mgr, rt = self._window_app("LOG")
        # LOG: swallowed. Now check a policy-free window propagates.
        mgr2 = SiddhiManager()
        rt2 = mgr2.create_siddhi_app_runtime("""
        define stream S (v int);
        define window W2 (v int) length(3);
        from S select v insert into W2;
        """)
        rt2.start()
        rt2.junctions["W2"].subscribe(_poison_subscriber("v", 5))
        with pytest.raises(Exception):
            rt2.get_input_handler("S").send((5,))
        mgr.shutdown()
        mgr2.shutdown()

    def test_reserved_error_attribute_rejected(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            define stream S (v int, _error string);
            @OnError(action='STREAM')
            define window W (v int, _error string) length(3);
            from S select v, _error insert into W;
            """)
        mgr.shutdown()


class TestTableOnError:
    def test_store_captures_mutating_query_failure(self):
        from siddhi_tpu.core.error_store import ORIGIN_TABLE

        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('TOE')
        define stream S (v int);
        @OnError(action='STORE')
        define table T (v int);
        from S select v insert into T;
        """)
        rt.start()
        qr = next(iter(rt.queries.values()))
        orig = qr.receive
        calls = []

        def exploding(batch, now, *a, **kw):
            calls.append(1)
            raise RuntimeError("table mutation exploded")

        qr.receive = exploding
        try:
            rt.get_input_handler("S").send((3,))  # must NOT propagate
        finally:
            qr.receive = orig
        assert calls
        entries = mgr.error_store.load(origin=ORIGIN_TABLE)
        assert len(entries) == 1
        assert entries[0].stream_id == "T"
        assert entries[0].sink_ref == "S"  # replay re-drives through S
        # replay re-runs the (now healthy) mutating query
        assert mgr.replay_errors() == 1
        rows = rt.query("from T select v")
        assert [e.data for e in rows] == [(3,)]
        mgr.shutdown()

    def test_stream_action_rejected_for_tables(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError):
            mgr.create_siddhi_app_runtime("""
            define stream S (v int);
            @OnError(action='STREAM')
            define table T (v int);
            from S select v insert into T;
            """)
        mgr.shutdown()

    def test_record_store_flush_failure_owned(self):
        from siddhi_tpu.core.record_table import RECORD_STORES, RecordStore

        flushes = []

        class _FlakyStore(RecordStore):
            def init(self, table_id, schema, options):
                self.fail = False

            def load(self):
                return []

            def on_change(self, rows):
                flushes.append(len(rows))
                if self.fail:
                    raise IOError("store down")

        RECORD_STORES["flakyrec"] = _FlakyStore
        try:
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime("""
            @app:name('TOF')
            define stream S (v int);
            @OnError(action='LOG')
            @store(type='flakyrec')
            define table T (v int);
            from S select v insert into T;
            """)
            rt.start()
            t = rt.tables["T"]
            store_impl = t.record_store
            store_impl.fail = True
            rt.get_input_handler("S").send((1,))  # flush fails, owned
            assert t._dirty, "failed flush keeps the table dirty"
            store_impl.fail = False
            t.flush_record_store()  # retry succeeds
            assert not t._dirty
            mgr.shutdown()
        finally:
            del RECORD_STORES["flakyrec"]


# ---------------------------------------------------------------------------
# SqliteErrorStore (DB-backed SPI)
# ---------------------------------------------------------------------------


class TestSqliteErrorStore:
    def _entry(self, app="DB", v=1):
        from siddhi_tpu.core.error_store import ORIGIN_STREAM, make_entry

        return make_entry(app, ORIGIN_STREAM, "S", "boom", events=[(v, (v,))])

    def test_store_load_purge_roundtrip(self, tmp_path):
        from siddhi_tpu import SqliteErrorStore

        store = SqliteErrorStore(str(tmp_path / "err.db"))
        for v in range(3):
            store.store(self._entry(v=v))
        assert store.size() == 3
        loaded = store.load(app_name="DB")
        assert [e.events[0][1] for e in loaded] == [(0,), (1,), (2,)]
        assert loaded[0].events[0] == (0, (0,))  # tuples re-tupled
        assert store.purge([loaded[0].id]) == 1
        assert store.size() == 2
        assert store.purge() == 2
        assert store.size() == 0
        store.close()

    def test_ids_unique_across_restarts(self, tmp_path):
        from siddhi_tpu import SqliteErrorStore

        path = str(tmp_path / "err.db")
        s1 = SqliteErrorStore(path)
        s1.store(self._entry(v=1))
        s1.store(self._entry(v=2))
        ids1 = {e.id for e in s1.load()}
        s1.purge()  # empty the table, then restart
        s1.close()
        s2 = SqliteErrorStore(path)
        s2.store(self._entry(v=3))
        ids2 = {e.id for e in s2.load()}
        assert not ids1 & ids2, "AUTOINCREMENT must never reuse ids"
        s2.close()

    def test_capacity_evicts_oldest(self, tmp_path):
        from siddhi_tpu import SqliteErrorStore

        store = SqliteErrorStore(str(tmp_path / "err.db"), capacity=3)
        for v in range(5):
            store.store(self._entry(v=v))
        assert store.size() == 3 and store.dropped == 2
        assert [e.events[0][1] for e in store.load()] == [(2,), (3,), (4,)]
        st = store.describe_state()
        assert st["depth"] == 3 and st["by_app"] == {"DB": 3}
        store.close()

    def test_rides_manager_replay(self, tmp_path):
        from siddhi_tpu import SqliteErrorStore

        mgr = SiddhiManager()
        mgr.set_error_store(SqliteErrorStore(str(tmp_path / "err.db")))
        rt = mgr.create_siddhi_app_runtime("""
        @app:name('DBApp')
        @OnError(action='STORE')
        define stream S (v int);
        @info(name='q')
        from S select v insert into Out;
        """)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(e.data for e in evs))
        rt.junctions["S"].subscribe(_poison_subscriber("v", 5))
        rt.start()
        rt.get_input_handler("S").send((5,))
        assert mgr.error_store.size() == 1
        # un-poison (times out naturally: the poison fires on v==5 forever;
        # replace subscriber list minus the poison instead)
        j = rt.junctions["S"]
        idx = len(j.subscribers) - 1
        j.subscribers.pop(idx)
        j.subscriber_names.pop(idx)
        assert mgr.replay_errors() == 1
        assert (5,) in got
        assert mgr.error_store.size() == 0
        mgr.shutdown()

    def test_non_json_payload_stringified(self, tmp_path):
        from siddhi_tpu import SqliteErrorStore
        from siddhi_tpu.core.error_store import ORIGIN_SINK, make_entry

        store = SqliteErrorStore(str(tmp_path / "err.db"))
        store.store(make_entry(
            "DB", ORIGIN_SINK, "Out", "boom", payload=object(),
        ))
        e = store.load()[0]
        assert "object" in e.payload
        store.close()
