"""Golden corpus: logical (and/or) patterns, translated from the reference
test data (reference: siddhi-core/src/test/java/org/wso2/siddhi/core/query/
pattern/LogicalPatternTestCase.java — data-level translation)."""

from siddhi_tpu import SiddhiManager

from tests.test_golden_count import assert_rows, run_app

S12 = """
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
"""

S123 = S12 + """
define stream Stream3 (symbol string, price float, volume int);
"""

Q_OR = S12 + """
@info(name = 'query1')
from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]
select e1.symbol as symbol1, e2.symbol as symbol2
insert into OutputStream ;
"""

Q_AND = S12 + """
@info(name = 'query1')
from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] and e3=Stream2['IBM' == symbol]
select e1.symbol as symbol1, e2.price as price2, e3.price as price3
insert into OutputStream ;
"""


def run_ts(ql, sends, query_name="query1"):
    """sends: (stream, row, timestamp_ms) — event-time-exact within tests."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    got = []
    rt.add_callback(
        query_name,
        lambda ts, i, r: got.extend(tuple(e.data) for e in i or []),
    )
    rt.start()
    handlers = {}
    for stream, row, ts in sends:
        h = handlers.setdefault(stream, rt.get_input_handler(stream))
        h.send(row, timestamp=ts)
    rt.shutdown()
    return got


class TestLogicalPatternGolden:
    def test_query1(self):
        got = run_app(Q_OR, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("GOOG", 59.6, 100)),
        ])
        assert_rows(got, [("WSO2", "GOOG")])

    def test_query2(self):
        # the or's OTHER side fires: e2 stays null
        got = run_app(Q_OR, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 10.7, 100)),
        ])
        assert_rows(got, [("WSO2", None)])

    def test_query3(self):
        # or completes on first arrival; second event doesn't re-fire
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 72.7, 100)),
            ("Stream2", ("IBM", 75.7, 100)),
        ])
        assert_rows(got, [("WSO2", 72.7, None)])

    def test_query4(self):
        # and: waits for both sides
        got = run_app(Q_AND, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("GOOG", 72.7, 100)),
            ("Stream2", ("IBM", 4.7, 100)),
        ])
        assert_rows(got, [("WSO2", 72.7, 4.7)])

    def test_query5(self):
        # one event can satisfy both sides of an and
        got = run_app(Q_AND, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 72.7, 100)),
            ("Stream2", ("IBM", 75.7, 100)),
        ])
        assert_rows(got, [("WSO2", 72.7, 72.7)])

    def test_query6(self):
        # and across two different streams
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] and e3=Stream1['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 72.7, 100)),
            ("Stream1", ("IBM", 75.7, 100)),
        ])
        assert_rows(got, [("WSO2", 72.7, 75.7)])

    def test_query7(self):
        # and as the FIRST state
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] and e2=Stream2[price >30] -> e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("GOOG", 72.7, 100)),
            ("Stream2", ("IBM", 4.7, 100)),
        ])
        assert_rows(got, [("WSO2", 72.7, 4.7)])

    def test_query8(self):
        # or as the FIRST state — left side fires
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] or e2=Stream2[price >30] -> e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("GOOG", 72.7, 100)),
            ("Stream2", ("IBM", 4.7, 100)),
        ])
        assert_rows(got, [("WSO2", None, 4.7)])

    def test_query9(self):
        # or as the FIRST state — right side fires
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] or e2=Stream2[price >30] -> e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream2", ("GOOG", 72.7, 100)),
            ("Stream2", ("IBM", 4.7, 100)),
        ])
        assert_rows(got, [(None, 72.7, 4.7)])

    def test_query10(self):
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] or e2=Stream2[price >30] -> e3=Stream2['IBM' == symbol]
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 55.6, 100)),
            ("Stream2", ("IBM", 4.7, 100)),
        ])
        assert_rows(got, [("WSO2", None, 4.7)])

    def test_query11(self):
        # every -> and over two other streams; two chains share completions
        ql = S123 + """
        @info(name = 'query1')
        from every e1=Stream1[price >20] -> e2=Stream2['IBM' == symbol] and e3=Stream3['WSO2' == symbol]
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("IBM", 25.5, 100)),
            ("Stream1", ("IBM", 59.65, 100)),
            ("Stream2", ("IBM", 45.5, 100)),
            ("Stream3", ("WSO2", 46.56, 100)),
        ])
        assert len(got) == 2, got
        assert_rows(sorted(got), sorted([(25.5, 45.5, 46.56), (59.65, 45.5, 46.56)]))

    def test_query12(self):
        # every -> or: completes on the first side
        ql = S123 + """
        @info(name = 'query1')
        from every e1=Stream1[price >20] -> e2=Stream2['IBM' == symbol] or e3=Stream3['WSO2' == symbol]
        select e1.price as price1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("IBM", 25.5, 100)),
            ("Stream1", ("IBM", 59.65, 100)),
            ("Stream2", ("IBM", 45.5, 100)),
        ])
        assert len(got) == 2, got
        assert_rows(sorted(got), sorted([(25.5, 45.5, None), (59.65, 45.5, None)]))

    def test_query13(self):
        # whole pattern = one and
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] and e2=Stream2[price >30]
        select e1.symbol as symbol1, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 25.0, 100)),
            ("Stream2", ("IBM", 35.0, 100)),
            ("Stream1", ("GOOGLE", 45.0, 100)),
            ("Stream2", ("ORACLE", 55.0, 100)),
        ])
        assert_rows(got, [("WSO2", 35.0)])

    def test_query14(self):
        # whole pattern = one or
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] or e2=Stream2[price >30]
        select e1.symbol as symbol1, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 25.0, 100)),
            ("Stream2", ("IBM", 35.0, 100)),
            ("Stream2", ("ORACLE", 45.0, 100)),
        ])
        assert_rows(got, [("WSO2", None)])

    def test_query15(self):
        # every (and): re-fires per completed pair
        ql = S12 + """
        @info(name = 'query1')
        from every (e1=Stream1[price > 20] and e2=Stream2[price >30])
        select e1.symbol as symbol1, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 25.0, 100)),
            ("Stream2", ("IBM", 35.0, 100)),
            ("Stream1", ("GOOGLE", 45.0, 100)),
            ("Stream2", ("ORACLE", 55.0, 100)),
        ])
        assert_rows(got, [("WSO2", 35.0), ("GOOGLE", 55.0)])

    def test_query16(self):
        # every (or): each satisfying event completes and re-arms
        ql = S12 + """
        @info(name = 'query1')
        from every (e1=Stream1[price > 20] or e2=Stream2[price >30])
        select e1.symbol as symbol1, e2.price as price2
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("WSO2", 25.0, 100)),
            ("Stream2", ("IBM", 35.0, 100)),
            ("Stream2", ("ORACLE", 45.0, 100)),
        ])
        assert_rows(got, [("WSO2", None), (None, 35.0), (None, 45.0)])

    def test_query17(self):
        # within expires the or target
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] or e3=Stream2['IBM' == symbol]
         within 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2
        insert into OutputStream ;
        """
        got = run_ts(ql, [
            ("Stream1", ("WSO2", 55.6, 100), 1_000),
            ("Stream2", ("GOOG", 59.6, 100), 2_200),
        ])
        assert_rows(got, [])

    def test_query18(self):
        # within expires a half-satisfied and
        ql = S12 + """
        @info(name = 'query1')
        from e1=Stream1[price > 20] -> e2=Stream2[price > e1.price] and e3=Stream2['IBM' == symbol]
         within 1 sec
        select e1.symbol as symbol1, e2.price as price2, e3.price as price3
        insert into OutputStream ;
        """
        got = run_ts(ql, [
            ("Stream1", ("WSO2", 55.6, 100), 1_000),
            ("Stream2", ("GOOG", 72.7, 100), 1_100),
            ("Stream2", ("IBM", 4.7, 100), 2_300),
        ])
        assert_rows(got, [])

    def test_query19(self):
        # every (and) -> e3: two pending pairs both fire on e3
        ql = S123 + """
        @info(name = 'query1')
        from every (e1=Stream1[price>10] and e2=Stream2[price>20]) -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e2.symbol as symbol2, e3.symbol as symbol3
        insert into OutputStream ;
        """
        got = run_app(ql, [
            ("Stream1", ("ORACLE", 15.0, 100)),
            ("Stream2", ("MICROSOFT", 45.0, 100)),
            ("Stream1", ("IBM", 55.0, 100)),
            ("Stream2", ("WSO2", 65.0, 100)),
            ("Stream3", ("GOOGLE", 75.0, 100)),
        ])
        assert len(got) == 2, got
        assert_rows(sorted(got), sorted([
            ("ORACLE", "MICROSOFT", "GOOGLE"), ("IBM", "WSO2", "GOOGLE")]))
