"""Black-box incident recorder + deterministic replay
(observability/blackbox.py; ISSUE 20).

Covers the acceptance gates: zero overhead with the annotation absent
(one is-None gate per site), trigger -> frozen bundle with a coherent
ring + checkpoint interval, byte-identical replay (exact rows and
checksums, including from a mid-feed checkpoint pin), oldest-first
`keep` eviction with bounded disk, debounce suppression, unarmed
triggers as no-ops, and the observability surfaces (snapshot_status,
explain, Prometheus families, manager.incidents / incident_detail).
"""

import glob
import os
import time

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.observability.blackbox import (
    attach_emission_collector,
    emissions_checksum,
    load_bundle,
    replay_incident,
)
from siddhi_tpu.testing import faults

APP = """
@app:name('bb')
@app:blackbox(window='30 sec',
              triggers='slo,crash,dispatch_error,calibration,admission',
              keep='4', dir='{d}')
@OnError(action='LOG')
define stream S (symbol string, price float, volume int);
@info(name='q')
from S[price > 10.0]#window.length(8)
select symbol, sum(volume) as v, avg(price) as ap insert into Out;
"""


def _boot(tmp_path, app=APP):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(app.format(d=tmp_path))
    return mgr, rt


def _feed(rt, n=24, t0=1_700_000_000_000):
    h = rt.get_input_handler("S")
    rows = [("ABC" if i % 2 else "XYZ", 5.0 + i * 1.5, i + 1)
            for i in range(n)]
    h.send_many(rows, timestamps=[t0 + i * 20 for i in range(n)])
    return rows


class TestZeroOverhead:
    def test_no_annotation_means_none_everywhere(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime("""
        define stream S (symbol string, price float);
        @info(name='q') from S select symbol insert into Out;
        """)
        rt.start()
        assert rt._blackbox is None
        for j in rt.junctions.values():
            assert j.blackbox is None
            assert j.on_incident is None
        assert rt.incidents() == []
        assert "blackbox" not in rt.snapshot_status()
        mgr.shutdown()

    def test_bad_annotation_rejected(self):
        mgr = SiddhiManager()
        for bad in ("window='soon'", "triggers='meteor'", "keep='0'",
                    "ring='x'", "bogus='1'"):
            with pytest.raises(SiddhiAppCreationError):
                mgr.create_siddhi_app_runtime(f"""
                @app:blackbox({bad})
                define stream S (symbol string);
                from S select symbol insert into Out;
                """)
        mgr.shutdown()


class TestTriggers:
    def test_dispatch_fault_freezes_bundle(self, tmp_path):
        mgr, rt = _boot(tmp_path)
        rt.start()
        _feed(rt)
        faults.install(
            faults.parse_plan("seed=5;junction_dispatch@S:times=1")
        )
        try:
            rt.get_input_handler("S").send(
                ("POISON", 1.0, 0), timestamp=1_700_000_001_000
            )
        finally:
            faults.uninstall()
        idx = rt.incidents()
        assert len(idx) == 1
        inc = idx[0]
        assert inc["trigger"] == "dispatch_error"
        assert inc["app"] == "bb"
        assert "InjectedFault" in inc["detail"]
        assert os.path.isfile(inc["path"])
        assert inc["events"] == 25  # full S ring captured since the pin
        bundle = load_bundle(inc["path"])
        assert bundle["id"] == inc["id"]
        assert bundle["checkpoint"]["seq_mark"] == 0
        assert len(bundle["rings"]["S"]["events"]) == 25
        assert bundle["surfaces"]["status"]["app"] == "bb"
        mgr.shutdown()

    def test_unarmed_trigger_is_noop_and_debounce_suppresses(self, tmp_path):
        mgr, rt = _boot(tmp_path, APP.replace(
            "triggers='slo,crash,dispatch_error,calibration,admission'",
            "triggers='crash'",
        ))
        rt.start()
        _feed(rt, n=4)
        bb = rt._blackbox
        assert bb.fire("slo", "not armed") is None  # unarmed trigger
        assert rt.incidents() == []
        assert bb.fire("crash", "first") is not None
        assert bb.fire("crash", "inside debounce") is None
        assert bb.suppressed == 1
        assert len(rt.incidents()) == 1
        mgr.shutdown()

    def test_admission_shed_fires_incident(self, tmp_path):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(f"""
        @app:name('bbadm')
        @app:blackbox(triggers='admission', keep='2', dir='{tmp_path}')
        @app:admission(policy='shed_newest', rate.limit='100')
        define stream S (v int);
        @info(name='q') from S select v insert into Out;
        """)
        rt.start()
        h = rt.get_input_handler("S")
        h.send_many([(i,) for i in range(500)])
        idx = rt.incidents()
        assert idx and idx[0]["trigger"] == "admission"
        assert "shed" in idx[0]["detail"]
        mgr.shutdown()


class TestReplay:
    def test_replay_byte_identical(self, tmp_path):
        mgr, rt = _boot(tmp_path)
        live = attach_emission_collector(rt)
        rt.start()
        _feed(rt, n=32)
        assert rt._blackbox.fire("crash", "synthetic") is not None
        inc = rt.incidents()[-1]
        mgr.shutdown()

        replay = replay_incident(inc["path"])
        assert replay.events_fed == 32
        assert replay.emissions == live
        assert replay.checksum() == emissions_checksum(live)

    def test_replay_from_midfeed_pin_restores_state(self, tmp_path):
        # re-pin the checkpoint mid-feed: the bundle then carries only the
        # post-pin ring rows plus the pinned state, and the replay must
        # regenerate exactly the live run's post-pin emissions — sums and
        # averages over a window SPANNING the pin prove the restore
        mgr, rt = _boot(tmp_path)
        live = attach_emission_collector(rt)
        rt.start()
        _feed(rt, n=20)
        pre_out = len(live["Out"])
        rt._blackbox.pin_checkpoint()
        assert rt._blackbox.pins == 2  # start() + manual
        _feed(rt, n=20, t0=1_700_000_100_000)
        assert rt._blackbox.fire("crash", "post-pin") is not None
        inc = rt.incidents()[-1]
        # only post-pin rows in the bundle: 20 source rows (plus the Out
        # rows the collector subscription makes the Out junction publish)
        assert len(load_bundle(inc["path"])["rings"]["S"]["events"]) == 20
        tail = {
            "S": live["S"][20:],
            "Out": live["Out"][pre_out:],
        }
        mgr.shutdown()

        replay = replay_incident(inc["path"])
        assert replay.events_fed == 20
        assert replay.emissions == tail
        assert replay.checksum() == emissions_checksum(tail)


class TestRetention:
    def test_keep_evicts_oldest_first(self, tmp_path):
        mgr, rt = _boot(tmp_path, APP.replace("keep='4'", "keep='2'"))
        rt.start()
        _feed(rt, n=4)
        bb = rt._blackbox
        # distinct triggers sidestep the per-trigger debounce
        ids = [bb.fire(t, "evict me") for t in
               ("crash", "slo", "calibration")]
        assert all(ids)
        on_disk = sorted(glob.glob(str(tmp_path / "incident_bb_*.pkl")))
        assert len(on_disk) == 2, on_disk
        assert not any(ids[0] in p for p in on_disk)  # oldest gone
        assert [r["id"] for r in rt.incidents()] == ids[1:]
        mgr.shutdown()


class TestSurfaces:
    def test_status_explain_prometheus_and_manager_routes(self, tmp_path):
        mgr, rt = _boot(tmp_path)
        rt.start()
        _feed(rt, n=6)
        iid = rt._blackbox.fire("crash", "surface check")
        status = rt.snapshot_status()["blackbox"]
        assert status["incidents"]["crash"] == 1
        assert status["pins"] >= 1
        assert status["bundles"][0]["id"] == iid

        plan = rt.explain_plan()
        s_node = next(
            n for n in plan["nodes"] if n["id"] == "stream:S"
        )
        assert s_node["counters"]["blackbox"]["incidents"] == 1
        assert "blackbox[window=30s" in rt.explain()

        text = mgr.prometheus_text()
        assert 'siddhi_incidents_total{app="bb",trigger="crash"} 1' in text
        assert 'siddhi_blackbox_ring_events{app="bb",stream="S"} 6' in text

        inc = mgr.incidents()["bb"]
        assert inc["incidents"]["crash"] == 1
        assert inc["bundles"][0]["id"] == iid
        detail = mgr.incident_detail(iid)
        assert detail["trigger"] == "crash"
        assert detail["rings"]["S"]["events"] == 6
        assert detail["checkpoint"]["bytes"] > 0
        assert mgr.incident_detail("nope") is None
        mgr.shutdown()

    def test_supervisor_restart_record_carries_incident_id(self, tmp_path):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(f"""
        @app:name('bbsup')
        @app:blackbox(triggers='crash', keep='2', dir='{tmp_path}')
        @app:restart(policy='on-failure', max.attempts='1',
                     backoff='10 millisec')
        define stream S (v int);
        @info(name='q') from S select v insert into Out;
        """)
        sup = mgr.supervise(poll_interval_s=0.05)
        rt.start()
        rt.get_input_handler("S").send_many([(i,) for i in range(4)])
        faults.install(
            faults.parse_plan("seed=9;junction_dispatch@S:times=1")
        )
        try:
            with pytest.raises(Exception):
                rt.get_input_handler("S").send((99,))
        finally:
            faults.uninstall()
        deadline = time.time() + 10
        while time.time() < deadline and not any(
            "restarted:" in what for _ts, _app, what in list(sup.events)
        ):
            time.sleep(0.05)
        restarts = [
            what for _ts, _app, what in list(sup.events)
            if "restarted:" in what
        ]
        assert restarts, list(sup.events)
        # the crash froze a bundle; its id rides the restart record so
        # /status.json links the crash to its post-mortem
        assert "[incident " in restarts[0], restarts
        mgr.shutdown()
