"""Event lineage & provenance (observability/lineage.py + @app:lineage).

Covers the acceptance contract of the lineage layer:

* `runtime.lineage()` returns the EXACT contributing input events
  (byte-compared against hand-computed expectations) for a sliding window
  emission, a pattern/sequence match, a join match, and a group-by
  aggregation bucket;
* identical lineage records under whole-graph fusion on/off and the
  8-device batch-shard router on/off;
* emissions byte-identical with lineage on vs off;
* zero overhead when off (no arenas, no recorders, no `__lin.*` lanes in
  the traced step — the profiler/tracing gating contract);
* annotation validation shared between runtime (raises) and analyzer
  (SA131), arena seq addressing + eviction, multi-hop resolution through
  insert-into chains, @OnError STORE seq ranges, trace-span annotation,
  explain fan-in, sample mode, and aggregation buckets.
"""

from __future__ import annotations

import json
import time
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError
from siddhi_tpu.core.event import StreamSchema
from siddhi_tpu.core.types import AttrType, InternTable
from siddhi_tpu.observability.lineage import (
    LineageArena,
    LineageConfig,
    iter_lineage_annotation_problems,
)
from siddhi_tpu.query_api.annotation import Annotation


def _drain():
    time.sleep(0.05)


def _mk(app_text):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(app_text)
    return mgr, rt


def _inputs(chain):
    """[(stream, [(seq, event tuple or None)...])] from a resolved record."""
    out = []
    for inp in chain["inputs"]:
        out.append((
            inp["stream"],
            [
                (e["seq"], tuple(e["event"]) if e.get("event") else None)
                for e in inp.get("events", ())
            ],
        ))
    return sorted(out)


# ---------------------------------------------------------------------------
# annotation validation (SA131 <-> runtime, one rule set)
# ---------------------------------------------------------------------------


class TestAnnotation:
    def test_malformed_capacity_raises_at_creation(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError, match="capacity"):
            mgr.create_siddhi_app_runtime(
                "@app:lineage(capacity='nope')\n"
                "define stream S (a int);\n"
                "from S select a insert into Out;"
            )

    def test_malformed_mode_raises_at_creation(self):
        mgr = SiddhiManager()
        with pytest.raises(SiddhiAppCreationError, match="mode"):
            mgr.create_siddhi_app_runtime(
                "@app:lineage(mode='firehose')\n"
                "define stream S (a int);\n"
                "from S select a insert into Out;"
            )

    def test_rule_set_shared_with_analyzer(self):
        ann = Annotation("app:lineage")
        ann.elements = [
            ("capacity", "0"), ("mode", "x"), ("turbo", "on"),
        ]
        assert len(list(iter_lineage_annotation_problems(ann))) == 3

    def test_sa131_from_analyzer(self):
        from siddhi_tpu.analysis import analyze
        from siddhi_tpu.compiler.siddhi_compiler import SiddhiCompiler

        app = SiddhiCompiler.parse(
            "@app:lineage(capacity='zero')\n"
            "define stream S (a int);\n"
            "from S select a insert into Out;"
        )
        res = analyze(app)
        assert any(d.code == "SA131" for d in res.diagnostics)


# ---------------------------------------------------------------------------
# zero overhead when off
# ---------------------------------------------------------------------------


class TestZeroOverheadOff:
    def test_no_recorders_no_arenas_no_lanes(self):
        mgr, rt = _mk(
            "define stream S (v long);\n"
            "@info(name='q') from S#window.length(3) "
            "select sum(v) as s insert into Out;"
        )
        rt.start()
        h = rt.get_input_handler("S")
        h.send([1], timestamp=1000)
        _drain()
        qr = rt.queries["q"]
        assert qr.lineage is None
        assert qr.chain.lineage_probe is None
        assert rt.junctions["S"].lineage is None
        assert rt.lineage_ledger is None
        # the traced step emits no __lin lanes: probe the aux structure
        # exactly like the fused engine does
        import jax

        batch = rt.stream_schemas["S"].empty_batch(rt.batch_size)
        closed = jax.eval_shape(
            lambda s, t, b: qr._step_impl(s, t, b, np.int64(0))[3],
            qr.init_state(), {}, batch,
        )
        assert not any(k.startswith("__lin") for k in closed)
        with pytest.raises(SiddhiAppCreationError, match="@app:lineage"):
            rt.lineage("q")
        assert rt.lineage_report() == {}
        mgr.shutdown()


# ---------------------------------------------------------------------------
# arena unit semantics
# ---------------------------------------------------------------------------


class TestArena:
    def _arena(self, size):
        schema = StreamSchema("S", [("k", AttrType.LONG)])
        return LineageArena(schema, InternTable(), size)

    def test_seq_addressing_and_eviction(self):
        ar = self._arena(4)
        for i in range(10):
            base, n = ar.record_columns(
                np.asarray([100 + i]), {"k": np.asarray([i])}, 1
            )
            assert (base, n) == (i, 1)
        assert ar.next_seq == 10
        evs = ar.events_for_seqs([0, 5, 6, 9, 42])
        assert evs[0] is None  # evicted (ring holds 6..9)
        assert evs[5] is None
        assert evs[6] == (106, (6,))
        assert evs[9] == (109, (9,))
        assert evs[42] is None  # never stamped
        assert ar.describe_state()["next_seq"] == 10

    def test_current_rows_only(self):
        from siddhi_tpu.core.event import KIND_EXPIRED

        schema = StreamSchema("S", [("k", AttrType.LONG)])
        ar = LineageArena(schema, InternTable(), 8)
        batch = schema.to_batch(
            [1, 2], [(7,), (8,)], InternTable(), capacity=4,
            kinds=[0, KIND_EXPIRED],
        )
        base, n = ar.record_batch(batch)
        assert (base, n) == (0, 1)  # the EXPIRED row is not stamped
        assert ar.events_for_seqs([0])[0] == (1, (7,))

    def test_oversized_commit_keeps_seq_slot_mapping(self):
        # one commit larger than the ring: _write trims to the tail and
        # the head advances by size while the seq counter advances by n —
        # decode must follow the head, not seq % size (regression)
        ar = self._arena(4)
        n = 6
        ar.record_columns(
            np.arange(n) + 100, {"k": np.arange(n)}, n
        )
        assert ar.next_seq == 6
        evs = ar.events_for_seqs([0, 1, 2, 3, 4, 5])
        assert evs[0] is None and evs[1] is None  # trimmed away
        assert evs[2] == (102, (2,))
        assert evs[3] == (103, (3,))
        assert evs[4] == (104, (4,))
        assert evs[5] == (105, (5,))

    def test_zero_current_publish_updates_last_range(self):
        # a publish with no CURRENT rows must not leave the PREVIOUS
        # batch's range for the @OnError STORE path (regression)
        from siddhi_tpu.core.event import KIND_EXPIRED

        schema = StreamSchema("S", [("k", AttrType.LONG)])
        ar = LineageArena(schema, InternTable(), 8)
        ar.record_columns(np.asarray([1]), {"k": np.asarray([7])}, 1)
        assert ar.last_range == (0, 1)
        batch = schema.to_batch(
            [2], [(8,)], InternTable(), capacity=4, kinds=[KIND_EXPIRED],
        )
        assert ar.record_batch(batch) == (1, 0)
        assert ar.last_range == (1, 0)
        assert ar.record_columns(np.asarray([]), {"k": np.asarray([])}, 0) \
            == (1, 0)


# ---------------------------------------------------------------------------
# exact provenance goldens (hand-computed)
# ---------------------------------------------------------------------------


WINDOW_APP = """
@app:name('lw')
@app:lineage(capacity='64')
define stream S (v int);
@info(name='q') from S[v > 0]#window.length(3)
select sum(v) as s insert into Out;
"""


class TestSlidingWindowGolden:
    def test_exact_window_contents_with_filter(self):
        mgr, rt = _mk(WINDOW_APP)
        got = []
        rt.add_callback("Out", lambda evs: got.extend(evs))
        rt.start()
        h = rt.get_input_handler("S")
        # seqs:        0  1   2  3  4   (seq 2 fails the filter)
        for i, v in enumerate([1, 2, -5, 3, 4]):
            h.send([v], timestamp=1000 + i)
        _drain()
        assert [(e.timestamp, e.data) for e in got] == [
            (1000, (1,)), (1001, (3,)), (1003, (6,)), (1004, (9,)),
        ]
        # emission 3 (4th CURRENT): window holds the last 3 admitted =
        # seqs 1, 3, 4 — events (2,), (3,), (4,); seq 0 was evicted and
        # seq 2 never admitted
        cur = [
            r for i in range(rt.queries["q"].lineage.out_count)
            for r in [rt.lineage("q", i)] if r["kind"] == "CURRENT"
        ]
        assert _inputs(cur[0]) == [("S", [(0, (1,))])]
        assert _inputs(cur[1]) == [("S", [(0, (1,)), (1, (2,))])]
        assert _inputs(cur[2]) == [("S", [(0, (1,)), (1, (2,)), (3, (3,))])]
        assert _inputs(cur[3]) == [("S", [(1, (2,)), (3, (3,)), (4, (4,))])]
        assert all(not r["approx"] for r in cur)
        assert cur[3]["trigger"] == {"stream": "S", "seq": 4}
        # the eviction emission (EXPIRED) recorded the post-evict window
        exp = [
            r for i in range(rt.queries["q"].lineage.out_count)
            for r in [rt.lineage("q", i)] if r["kind"] == "EXPIRED"
        ]
        assert len(exp) == 1
        mgr.shutdown()

    def test_time_window_contents(self):
        # playback clock: explicit past timestamps drive expiry, not the
        # wall-clock scheduler (which would expire the ring mid-test)
        mgr, rt = _mk(
            "@app:playback\n"
            "@app:lineage(capacity='64')\n"
            "define stream S (v int);\n"
            "@info(name='q') from S#window.time(100)\n"
            "select sum(v) as s insert into Out;"
        )
        rt.start()
        h = rt.get_input_handler("S")
        h.send([1], timestamp=1000)  # seq 0
        h.send([2], timestamp=1050)  # seq 1
        h.send([4], timestamp=1200)  # seq 2: 0 and 1 have expired
        _drain()
        recs = [
            rt.lineage("q", i)
            for i in range(rt.queries["q"].lineage.out_count)
        ]
        cur = [r for r in recs if r["kind"] == "CURRENT"]
        assert _inputs(cur[0]) == [("S", [(0, (1,))])]
        assert _inputs(cur[1]) == [("S", [(0, (1,)), (1, (2,))])]
        assert _inputs(cur[2]) == [("S", [(2, (4,))])]
        mgr.shutdown()


PATTERN_APP = """
@app:name('lp')
@app:lineage(capacity='64')
define stream A (x int);
define stream B (y int);
@info(name='pq') from every e1=A[x > 10] -> e2=B[y > e1.x] within 1 sec
select e1.x as ax, e2.y as by2 insert into M;
"""


class TestPatternGolden:
    def test_sequence_returns_exactly_the_two_contributing_events(self):
        mgr, rt = _mk(PATTERN_APP)
        got = []
        rt.add_callback("M", lambda evs: got.extend(evs))
        rt.start()
        ha, hb = rt.get_input_handler("A"), rt.get_input_handler("B")
        ha.send([5], timestamp=1000)   # A seq 0: fails the e1 filter
        ha.send([20], timestamp=1100)  # A seq 1: arms e1
        hb.send([15], timestamp=1200)  # B seq 0: fails y > 20
        hb.send([25], timestamp=1300)  # B seq 1: completes the match
        _drain()
        assert [(e.timestamp, e.data) for e in got] == [(1300, (20, 25))]
        chain = rt.lineage("pq", 0)
        assert chain["kind"] == "CURRENT" and not chain["approx"]
        assert _inputs(chain) == [
            ("A", [(1, (20,))]),
            ("B", [(1, (25,))]),
        ]
        mgr.shutdown()


JOIN_APP = """
@app:name('lj')
@app:lineage(capacity='64')
define stream L (k int, v int);
define stream R (k int, w int);
@info(name='jq') from L#window.length(4) join R#window.length(4)
on L.k == R.k select L.k as k, L.v as v, R.w as w insert into J;
"""


class TestJoinGolden:
    def test_left_right_seq_pair_per_match(self):
        mgr, rt = _mk(JOIN_APP)
        got = []
        rt.add_callback("J", lambda evs: got.extend(evs))
        rt.start()
        hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
        hl.send([1, 100], timestamp=2000)  # L seq 0
        hl.send([2, 200], timestamp=2001)  # L seq 1
        hr.send([2, 999], timestamp=2002)  # R seq 0: matches L seq 1
        hl.send([2, 300], timestamp=2003)  # L seq 2: matches R seq 0
        _drain()
        assert [(e.timestamp, e.data) for e in got] == [
            (2002, (2, 200, 999)), (2003, (2, 300, 999)),
        ]
        c0 = rt.lineage("jq", 0)
        assert _inputs(c0) == [
            ("L", [(1, (2, 200))]),
            ("R", [(0, (2, 999))]),
        ]
        assert c0["trigger"] == {"stream": "R", "seq": 0}
        c1 = rt.lineage("jq", 1)
        assert _inputs(c1) == [
            ("L", [(2, (2, 300))]),
            ("R", [(0, (2, 999))]),
        ]
        assert c1["trigger"] == {"stream": "L", "seq": 2}
        assert not c0["approx"] and not c1["approx"]
        mgr.shutdown()

    def test_partner_without_admission_order_is_flagged(self):
        # a lengthBatch partner window carries no seq lane: the matched
        # partner cannot be resolved, and the record must say so
        # (approx=True) instead of presenting a one-sided chain as exact
        mgr, rt = _mk(
            "@app:lineage(capacity='64')\n"
            "define stream L (k int);\n"
            "define stream R (k int);\n"
            "@info(name='jq') from L#window.length(4) join "
            "R#window.lengthBatch(4)\n"
            "on L.k == R.k select L.k as k insert into J;"
        )
        rt.start()
        hl, hr = rt.get_input_handler("L"), rt.get_input_handler("R")
        hr.send([1], timestamp=5000)  # open R bucket (view shows it)
        hr.send([1], timestamp=5001)
        hl.send([1], timestamp=5010)  # probes the open R bucket
        _drain()
        lin = rt.queries["jq"].lineage
        assert lin.out_count > 0
        rec = rt.lineage("jq", 0)
        assert rec["approx"] is True
        assert rec["trigger"]["stream"] == "L"  # the probe side is exact
        mgr.shutdown()


GROUPBY_APP = """
@app:name('lg')
@app:lineage(capacity='64')
define stream S (sym string, px int);
@info(name='g') from S#window.lengthBatch(4)
select sym, sum(px) as total group by sym insert into G;
"""


class TestGroupByGolden:
    def test_per_key_bucket_members(self):
        mgr, rt = _mk(GROUPBY_APP)
        got = []
        rt.add_callback("G", lambda evs: got.extend(evs))
        rt.start()
        h = rt.get_input_handler("S")
        for i, r in enumerate([("a", 1), ("b", 2), ("a", 3), ("b", 4)]):
            h.send(list(r), timestamp=3000 + i)
        _drain()
        assert sorted(e.data for e in got) == [("a", 4), ("b", 6)]
        ra = rt.lineage("g", 0)
        rb = rt.lineage("g", 1)
        assert _inputs(ra) == [("S", [(0, ("a", 1)), (2, ("a", 3))])]
        assert _inputs(rb) == [("S", [(1, ("b", 2)), (3, ("b", 4))])]
        assert not ra["approx"] and not rb["approx"]
        mgr.shutdown()


# ---------------------------------------------------------------------------
# multi-hop + stream-indexed resolution
# ---------------------------------------------------------------------------


CHAIN_APP = """
@app:name('lc')
@app:lineage(capacity='64')
define stream S (v int);
@info(name='q1') from S[v > 0] select v * 10 as w insert into Mid;
@info(name='q2') from Mid#window.length(2) select sum(w) as t insert into Out;
"""


class TestMultiHop:
    def test_walks_back_to_ingress(self):
        mgr, rt = _mk(CHAIN_APP)
        rt.start()
        h = rt.get_input_handler("S")
        for i, v in enumerate([3, -1, 5]):  # seq 1 filtered out by q1
            h.send([v], timestamp=4000 + i)
        _drain()
        # Out seq 1 = q2's 2nd CURRENT = window {Mid seq 0, Mid seq 1}
        node = rt.lineage("Out", 1)
        assert node["stream"] == "Out" and node["event"] == [80]
        via = node["via"]
        assert via["query"] == "q2"
        (mid,) = via["inputs"]
        assert mid["stream"] == "Mid" and mid["n"] == 2
        # each Mid seq resolves further back to the exact S event
        ups = {u["out_index"]: u for u in mid["via"]}
        s_events = sorted(
            e["seq"] for u in ups.values() for e in u["inputs"][0]["events"]
        )
        assert s_events == [0, 2]  # S seq 1 (v=-1) contributed nowhere
        mgr.shutdown()

    def test_stream_index_accounts_for_expired_records(self):
        mgr, rt = _mk(WINDOW_APP)
        rt.start()
        h = rt.get_input_handler("S")
        for i, v in enumerate([1, 2, -5, 3, 4]):
            h.send([v], timestamp=1000 + i)
        _drain()
        # Out carries only the CURRENT emissions; seq 3 on Out = the 4th
        # CURRENT record even though an EXPIRED record sits between them
        node = rt.lineage("Out", 3)
        assert node["event"] == [9]
        assert node["via"]["kind"] == "CURRENT"
        assert _inputs(node["via"]) == [
            ("S", [(1, (2,)), (3, (3,)), (4, (4,))])
        ]
        mgr.shutdown()

    def test_externally_co_fed_stream_is_not_walked(self):
        # q1 inserts into Mid AND the host sends into Mid directly: the
        # junction seqs interleave both, so attributing seq k to q1's
        # k-th record would be a guess — the walk must decline (regression)
        mgr, rt = _mk(
            "@app:lineage(capacity='64')\n"
            "define stream S (v int);\n"
            "define stream Mid (w int);\n"
            "@info(name='q1') from S select v * 10 as w insert into Mid;\n"
            "@info(name='q2') from Mid select w insert into Out;"
        )
        rt.start()
        rt.get_input_handler("S").send([1], timestamp=1000)
        rt.get_input_handler("Mid").send([999], timestamp=1001)  # external
        rt.get_input_handler("S").send([2], timestamp=1002)
        _drain()
        node = rt.lineage("Mid", 1)
        assert node["event"] == [999]
        assert "via" not in node
        assert node.get("mixed") is True and node["producers"] == ["q1"]
        mgr.shutdown()


# ---------------------------------------------------------------------------
# parity: lineage on/off emissions; fused/sharded record equality
# ---------------------------------------------------------------------------


PARITY_APP = """
@app:name('par')
{LINEAGE}
define stream S (v long, k long);
@info(name='w') from S[v % 3 != 0]#window.length(5)
select sum(v) as s insert into Out;
@info(name='g') from S#window.lengthBatch(8)
select sum(v) as t group by k insert into G;
"""


def _drive_parity(head, n=256):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(
        PARITY_APP.replace("{LINEAGE}", head)
    )
    got = {"w": [], "g": []}
    for qid in ("w", "g"):
        rt.add_callback(
            qid,
            lambda ts, ins, removed, _q=qid: got[_q].extend(ins or []),
        )
    rt.start()
    h = rt.get_input_handler("S")
    ts = np.arange(n, dtype=np.int64) + 10_000
    vs = (np.arange(n, dtype=np.int64) * 7) % 23
    h.send_columns(ts, {"v": vs, "k": vs % 4}, now=int(ts[-1]))
    time.sleep(0.2)
    out = {
        k: [(e.timestamp, tuple(e.data)) for e in v] for k, v in got.items()
    }
    recs = {}
    for qid in ("w", "g"):
        lin = rt.queries[qid].lineage
        if lin is None:
            continue
        recs[qid] = [
            (
                r["out_index"], r["ts"], r["kind"], r["approx"],
                tuple(
                    (i["stream"], tuple(map(tuple, i["ranges"])), i["n"])
                    for i in r["inputs"]
                ),
            )
            for i_ in range(lin.out_count)
            for r in [rt.lineage(qid, i_)]
        ]
    engaged = rt.junctions["S"].fused_ingest
    chunks = engaged.chunks_dispatched if engaged is not None else 0
    mgr.shutdown()
    return out, recs, chunks


class TestParity:
    def test_emissions_byte_identical_lineage_on_vs_off(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_TPU_SHARD", raising=False)
        on, _r, _ = _drive_parity("@app:lineage(capacity='512')")
        off, _r2, _ = _drive_parity("")
        assert on == off

    def test_records_identical_fuse_on_vs_off(self, monkeypatch):
        monkeypatch.delenv("SIDDHI_TPU_SHARD", raising=False)
        monkeypatch.setenv("SIDDHI_TPU_FUSE", "1")
        out1, rec1, chunks1 = _drive_parity("@app:lineage(capacity='512')")
        monkeypatch.setenv("SIDDHI_TPU_FUSE", "0")
        out0, rec0, chunks0 = _drive_parity("@app:lineage(capacity='512')")
        assert chunks1 > 0 and chunks0 == 0  # the A/B really fused vs not
        assert out1 == out0
        assert rec1 == rec0

    def test_records_identical_shard_8_vs_0(self, monkeypatch):
        # stateless query: the batch-shard router's round-robin dispatch
        # must replay lineage observations in original batch order
        app = (
            "@app:lineage(capacity='4096')\n"
            "define stream S (v long);\n"
            "@info(name='f') from S[v % 2 == 0] select v * 10 as w "
            "insert into Out;"
        )

        def drive():
            mgr = SiddhiManager()
            rt = mgr.create_siddhi_app_runtime(app)
            got = []
            rt.add_callback(
                "f", lambda ts, ins, removed: got.extend(ins or [])
            )
            rt.start()
            h = rt.get_input_handler("S")
            n = 1024
            ts = np.arange(n, dtype=np.int64) + 50_000
            h.send_columns(
                ts, {"v": np.arange(n, dtype=np.int64)}, now=int(ts[-1])
            )
            time.sleep(0.2)
            lin = rt.queries["f"].lineage
            recs = [
                (
                    r["out_index"], r["ts"], r["approx"],
                    tuple(
                        (i["stream"], tuple(map(tuple, i["ranges"])))
                        for i in r["inputs"]
                    ),
                )
                for i_ in range(lin.out_count)
                for r in [rt.lineage("f", i_)]
            ]
            routed = (
                rt.junctions["S"].fused_ingest is not None
                and rt.junctions["S"].fused_ingest.shard_router is not None
            )
            out = [(e.timestamp, tuple(e.data)) for e in got]
            mgr.shutdown()
            return out, recs, routed

        monkeypatch.setenv("SIDDHI_TPU_SHARD", "8")
        out8, rec8, routed8 = drive()
        monkeypatch.setenv("SIDDHI_TPU_SHARD", "0")
        out0, rec0, routed0 = drive()
        assert routed8 and not routed0
        assert out8 == out0
        assert rec8 == rec0


# ---------------------------------------------------------------------------
# surfaces: STORE entries, traces, explain, endpoints, sampling, aggregation
# ---------------------------------------------------------------------------


class TestSurfaces:
    def test_store_entry_carries_seq_range(self):
        mgr, rt = _mk(
            "@app:lineage(capacity='64')\n"
            "@OnError(action='STORE')\n"
            "define stream S (v int);\n"
            "@info(name='q') from S select v insert into Out;"
        )
        boom = {"armed": False}

        def cb(evs):
            if boom["armed"]:
                raise RuntimeError("poison")

        rt.add_callback("S", cb)
        rt.start()
        h = rt.get_input_handler("S")
        h.send([1], timestamp=1000)  # seq 0 (clean)
        boom["armed"] = True
        h.send([2], timestamp=1001)  # seq 1 -> fails, STORE'd
        _drain()
        entries = mgr.error_store.load()
        assert entries, "the failing batch must be stored"
        ent = entries[-1]
        assert ent.lineage == {"stream": "S", "seq_lo": 1, "seq_hi": 1}
        mgr.shutdown()

    def test_trace_span_carries_seq_range(self):
        mgr, rt = _mk(
            "@app:statistics(reporter='none', trace.sample='1.0')\n"
            "@app:lineage(capacity='64')\n"
            "define stream S (v int);\n"
            "@info(name='q') from S select v insert into Out;"
        )
        rt.start()
        h = rt.get_input_handler("S")
        h.send([1], timestamp=1000)
        h.send([2], timestamp=1001)
        _drain()
        spans = [s for t in rt.traces() for s in t["spans"]]
        stamped = [s for s in spans if "lineage_seq" in s]
        assert stamped, spans
        assert stamped[0]["lineage_seq"] == [0, 1]
        mgr.shutdown()

    def test_explain_renders_fan_in(self):
        mgr, rt = _mk(WINDOW_APP)
        rt.start()
        h = rt.get_input_handler("S")
        for i, v in enumerate([1, 2, 3, 4]):
            h.send([v], timestamp=1000 + i)
        _drain()
        text = rt.explain()
        assert "lineage[fan-in avg=" in text
        mgr.shutdown()

    def test_http_endpoints(self):
        mgr, rt = _mk(WINDOW_APP)
        rt.start()
        h = rt.get_input_handler("S")
        for i, v in enumerate([1, 2, 3]):
            h.send([v], timestamp=1000 + i)
        _drain()
        port = mgr.serve_metrics(port=0)
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/lineage.json", timeout=10
        ).read().decode()
        rep = json.loads(body)["lw"]
        assert rep["streams"]["S"]["next_seq"] == 3
        assert rep["queries"]["q"]["outputs"] >= 3
        assert rep["recent"]["q"][-1]["inputs"][0]["stream"] == "S"
        text = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/lineage", timeout=10
        ).read().decode()
        assert "query q" in text and "fan-in" in text
        mgr.shutdown()

    def test_sample_mode_records_every_kth(self):
        mgr, rt = _mk(
            "@app:lineage(capacity='64', mode='sample', sample.every='4')\n"
            "define stream S (v int);\n"
            "@info(name='q') from S select v insert into Out;"
        )
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(12):
            h.send([i], timestamp=1000 + i)
        _drain()
        lin = rt.queries["q"].lineage
        assert lin.out_count == 12  # fan-in counters always run
        assert [r["out_index"] for r in lin.records] == [0, 4, 8]
        assert rt.lineage("q", 1)["error"]  # sampled out
        mgr.shutdown()

    def test_aggregation_buckets(self):
        mgr, rt = _mk(
            "@app:lineage(capacity='64')\n"
            "define stream S (v int, ts long);\n"
            "define aggregation ag\n"
            "from S\n"
            "select sum(v) as total\n"
            "aggregate by ts every sec;"
        )
        rt.start()
        h = rt.get_input_handler("S")
        h.send([1, 1_000], timestamp=1_000)   # seq 0, bucket 1000
        h.send([2, 1_500], timestamp=1_500)   # seq 1, bucket 1000
        h.send([3, 2_200], timestamp=2_200)   # seq 2, bucket 2000
        _drain()
        rep = rt.lineage_report()
        buckets = rep["aggregations"]["ag"]["buckets"]
        assert buckets["1000"] == {"seq_lo": 0, "seq_hi": 1, "count": 2}
        assert buckets["2000"] == {"seq_lo": 2, "seq_hi": 2, "count": 1}
        mgr.shutdown()

    def test_describe_state_surfaces(self):
        mgr, rt = _mk(WINDOW_APP)
        rt.start()
        h = rt.get_input_handler("S")
        h.send([1], timestamp=1000)
        _drain()
        st = rt.snapshot_status()
        assert st["streams"]["S"]["lineage"]["next_seq"] == 1
        assert st["queries"]["q"]["lineage"]["outputs"] >= 1
        mgr.shutdown()


MULTI_PRODUCER_APP = """
@app:lineage(capacity='256')
define stream S (a int);
define stream Mid (a int, tag int);
@info(name='pA') from S[a % 2 == 0] select a, 100 as tag insert into Mid;
@info(name='pB') from S[a % 2 == 1] select a, 200 as tag insert into Mid;
@info(name='c') from Mid#window.length(4) select a, tag insert into Out;
"""


class TestMultiProducer:
    """Per-publish producer capture (LineageArena.pub_log): a stream fed by
    TWO recorded queries resolves each seq to the producer whose publish
    stamped it, instead of listing candidates (the PR 12 carried-forward)."""

    def test_seq_resolves_to_actual_producer(self):
        mgr, rt = _mk(MULTI_PRODUCER_APP)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(10):
            h.send([i], timestamp=1000 + i)
        _drain()
        arena = rt.junctions["Mid"].lineage
        assert arena.next_seq == 10
        for s in range(10):
            node = rt.lineage("Mid", s)
            a, tag = node["event"]
            want = "pA" if a % 2 == 0 else "pB"
            assert node.get("producer") == want, node
            via = node["via"]
            assert via["query"] == want
            # the producer's record walks back to the exact S event
            (inp,) = via["inputs"]
            assert inp["stream"] == "S"
            assert [e["event"] for e in inp["events"]] == [[a]]
        mgr.shutdown()

    def test_consumer_inputs_walk_through_producers(self):
        mgr, rt = _mk(MULTI_PRODUCER_APP)
        rt.start()
        h = rt.get_input_handler("S")
        for i in range(6):
            h.send([i], timestamp=1000 + i)
        _drain()
        # the window consumer's record on Mid resolves each contributing
        # seq to ITS producer (pA for evens, pB for odds)
        node = rt.lineage("c")
        (mid,) = node["inputs"]
        assert mid["stream"] == "Mid"
        ups = mid.get("via")
        assert ups, node
        for up in ups:
            a = up["inputs"][0]["events"][0]["event"][0]
            assert up["query"] == ("pA" if a % 2 == 0 else "pB"), up
        mgr.shutdown()

    def test_external_interleaved_writer_stays_mixed(self):
        # an input handler ALSO feeds Mid: unlogged seqs must not be
        # mis-attributed — they fall back to the candidate listing
        mgr, rt = _mk(MULTI_PRODUCER_APP)
        rt.start()
        h = rt.get_input_handler("S")
        hm = rt.get_input_handler("Mid")
        h.send([2], timestamp=1000)     # seq 0 <- pA
        hm.send([9, 900], timestamp=1001)  # seq 1 <- external writer
        h.send([3], timestamp=1002)     # seq 2 <- pB
        _drain()
        assert rt.lineage("Mid", 0).get("producer") == "pA"
        ext = rt.lineage("Mid", 1)
        assert "producer" not in ext and ext.get("mixed"), ext
        assert sorted(ext["producers"]) == ["pA", "pB"]
        assert rt.lineage("Mid", 2).get("producer") == "pB"
        mgr.shutdown()
