"""Bench driver resilience: the final JSON line must print on EVERY exit
path (ROADMAP: round 5 shipped rc=124 with no JSON at all when the
harness's outer `timeout -k` killed the driver).

These tests run `bench.py` as a real subprocess — the same shape the
harness uses — and assert the one-line contract:

* deadline path: a too-small `--deadline` skips every leg and still emits;
* SIGTERM path: the outer-timeout analog (`timeout -k` sends TERM first)
  emits the final line from the signal handler via a direct fd-1 write,
  BEFORE attempting any cleanup that could block.

Also covers the p99 leg's new keys offline (no accelerator required): the
leg function itself runs in-process on CPU in the slow marker-free suite
would be too costly, so the key contract is asserted on the driver level.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(__file__)), "bench.py")


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SIDDHI_TPU_AUX_DRAIN_S"] = "0"
    return env


def _last_json_line(text: str) -> dict:
    lines = [ln for ln in text.strip().splitlines() if ln.strip()]
    assert lines, f"no output at all: {text!r}"
    return json.loads(lines[-1])


class TestBenchDriverExitPaths:
    def test_deadline_skips_all_legs_and_emits_final_json(self):
        """--deadline smaller than the 60 s per-leg floor: every leg is
        skipped, the driver exits 0, and the final line is valid JSON with
        the skip reasons recorded."""
        proc = subprocess.run(
            [sys.executable, BENCH, "--deadline", "5"],
            capture_output=True, text=True, timeout=120, env=_env(),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        got = _last_json_line(proc.stdout)
        assert got["metric"] == "engine_throughput_geomean"
        failed = got["detail"].get("failed_legs", [])
        assert failed and all(
            f["error"] == "skipped(deadline)" for f in failed
        ), failed

    def test_bench_budget_env_trims_and_emits_final_json(self):
        """SIDDHI_TPU_BENCH_BUDGET=<seconds> (no --deadline flag at all —
        the harness shape): a tiny budget caps the overall deadline AND the
        per-leg subprocess timeouts; every leg is skip-recorded and the
        final line is parseable JSON."""
        env = _env()
        env["SIDDHI_TPU_BENCH_BUDGET"] = "12"
        proc = subprocess.run(
            [sys.executable, BENCH],
            capture_output=True, text=True, timeout=120, env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        got = _last_json_line(proc.stdout)
        assert got["metric"] == "engine_throughput_geomean"
        failed = got["detail"].get("failed_legs", [])
        assert failed and all(
            f["error"] == "skipped(deadline)" for f in failed
        ), failed
        # the snapshot-line tail contract under the budget knob (the
        # harness shape that shipped BENCH_r05 rc=124 with an EMPTY tail):
        # every line on stdout — per-leg snapshots AND the final line —
        # must parse, so a SIGKILL at any point leaves a consumable tail
        lines = [
            ln for ln in proc.stdout.strip().splitlines() if ln.strip()
        ]
        assert len(lines) >= 2, lines
        for ln in lines[:-1]:
            snap = json.loads(ln)
            assert snap["detail"].get("partial_through_leg"), snap
        assert "partial_through_leg" not in got["detail"]

    def test_per_leg_snapshot_lines_are_parseable(self):
        """Every completed leg prints a snapshot JSON line (the SIGKILL
        defense: a hard kill mid-suite still leaves a parseable tail).
        With a sub-floor deadline no legs run, but each skip still updates
        detail — assert every non-final line parses and carries the
        partial marker."""
        proc = subprocess.run(
            [sys.executable, BENCH, "--deadline", "5"],
            capture_output=True, text=True, timeout=120, env=_env(),
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        lines = [
            ln for ln in proc.stdout.strip().splitlines() if ln.strip()
        ]
        assert len(lines) >= 2  # snapshots + the final line
        for ln in lines[:-1]:
            snap = json.loads(ln)
            assert snap["metric"] == "engine_throughput_geomean"
            assert snap["detail"].get("partial_through_leg")
        assert "partial_through_leg" not in json.loads(lines[-1])["detail"]

    def test_sigterm_mid_leg_emits_final_json(self):
        """SIGTERM while a leg subprocess is running (what `timeout -k`
        sends first): the handler must emit the final JSON line before the
        kill grace window can expire."""
        proc = subprocess.Popen(
            [sys.executable, BENCH, "--deadline", "0"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, env=_env(),
        )
        try:
            # give the driver time to spawn its first leg subprocess (the
            # leg imports jax; the driver itself is up within a second)
            time.sleep(6.0)
            proc.send_signal(signal.SIGTERM)
            out, _err = proc.communicate(timeout=30)
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.communicate()
        got = _last_json_line(out)
        assert got["metric"] == "engine_throughput_geomean"
        # the interrupted leg is recorded, not silently dropped
        failed = got["detail"].get("failed_legs", [])
        assert any(
            f["error"] == f"signal{int(signal.SIGTERM)}" for f in failed
        ), failed
