"""Golden corpus: reference query/table/PrimaryKeyTableTestCase.java (data-level
translation: queries, event sequences, expected rows). Tests 28/29/31/32/33 are
definition-error tests (asserted as creation/parse errors here); test 30 does
not exist in the reference; test 35 is a wall-clock performance race (asserts
indexed sends are faster than unindexed — not a behavioral contract) and is
not translated."""

from __future__ import annotations

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError, SiddhiParserError

S3 = (
    "define stream StockStream (symbol string, price float, volume long); "
    "define stream CheckStockStream (symbol string, volume long); "
    "define stream UpdateStockStream (symbol string, price float, volume long);"
)
S3D = (
    "define stream StockStream (symbol string, price float, volume long); "
    "define stream CheckStockStream (symbol string, volume long); "
    "define stream DeleteStockStream (symbol string, price float, volume long);"
)


def run(ql, sends, query_name):
    """sends: [(stream, row), ...] in order; returns (ins, removed_count)."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    ins, rem = [], []
    rt.add_callback(
        query_name,
        lambda ts, i, r: (
            ins.extend(tuple(e.data) for e in i or []),
            rem.extend(tuple(e.data) for e in r or []),
        ),
    )
    rt.start()
    hs = {}
    for stream, row in sends:
        hs.setdefault(stream, rt.get_input_handler(stream)).send(row)
    rt.shutdown()
    mgr.shutdown()
    return ins, len(rem)


def eq(got, expected):
    assert len(got) == len(expected), (got, expected)
    for g, e in zip(got, expected):
        assert len(g) == len(e), (g, e)
        for a, b in zip(g, e):
            if isinstance(b, float):
                assert a is not None and abs(a - b) < 1e-3, (got, expected)
            else:
                assert a == b, (got, expected)


def eq_unsorted(got, expected):
    eq(sorted(got, key=str), sorted(expected, key=str))


class TestPrimaryKeyTableGolden:
    def test1_pk_join_equality(self):
        ql = S3 + """@PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, nrem = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("StockStream", ("IBM", 56.6, 200)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq(ins, [("IBM", 100), ("WSO2", 100)])
        assert nrem == 0

    def test2_pk_join_not_equal(self):
        ql = S3 + """@PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol!=StockTable.symbol
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, nrem = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("GOOG", 100)),
        ], "query2")
        eq_unsorted(ins, [("GOOG", "IBM", 100), ("GOOG", "WSO2", 100)])
        assert nrem == 0

    def test3_pk_join_greater(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.volume > StockTable.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, nrem = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("FOO", 60)),
        ], "query2")
        eq_unsorted(ins[:2], [("IBM", "GOOG", 50), ("IBM", "ABC", 70)])
        eq_unsorted(ins[2:], [("FOO", "GOOG", 50)])
        assert nrem == 0

    def test4_pk_join_less(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume < CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 200)),
        ], "query2")
        eq_unsorted(ins, [("IBM", "ABC", 70), ("IBM", "GOOG", 50)])

    def test5_pk_join_less_equal(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume <= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 70)),
        ], "query2")
        eq_unsorted(ins, [("IBM", "ABC", 70), ("IBM", "GOOG", 50)])

    def test6_pk_join_table_greater(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume > CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 50)),
        ], "query2")
        eq_unsorted(ins, [("IBM", "WSO2", 200), ("IBM", "ABC", 70)])

    def test7_pk_join_table_greater_equal(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 70)),
        ], "query2")
        eq_unsorted(ins, [("IBM", "ABC", 70), ("IBM", "WSO2", 200)])

    def test8_pk_update_or_insert_overwrites(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream
        update or insert into StockTable
        on volume == StockTable.volume ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("FOO", 50.6, 200)),
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 70)),
        ], "query2")
        eq_unsorted(ins, [("IBM", "ABC", 70), ("IBM", "WSO2", 200)])

    def test9_pk_update_equality(self):
        ql = S3 + """@PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        update StockTable on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("UpdateStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query3")
        eq(ins, [("IBM", 100), ("WSO2", 100), ("IBM", 200), ("WSO2", 100)])

    def test10_pk_update_not_equal(self):
        ql = S3 + """@PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        update StockTable on StockTable.symbol!=symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol!=StockTable.symbol
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("UpdateStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query3")
        # update on symbol != "IBM" sets WSO2's row to (WSO2?, ...) — the
        # update writes price/volume from the update stream; volume becomes
        # 200 for WSO2. Reference expects [WSO2 100, IBM 100, IBM 100]:
        # the first two from the pre-update checks, the last from the
        # post-update check (WSO2's row was updated to volume 200? no — the
        # reference updates ALL attrs incl. symbol=IBM: WSO2 row becomes IBM
        # 200; check !=WSO2 then matches IBM rows only; order: IBM(orig).
        eq(ins, [("WSO2", 100), ("IBM", 100), ("IBM", 100)])

    def test11_pk_update_le_nonkey_select(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume <= volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume >= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 200)),
            ("UpdateStockStream", ("FOO", 77.6, 200)),
            ("CheckStockStream", ("BAR", 200)),
        ], "query3")
        # update selects only (price, volume): both rows get price 77.6?
        # No — reference expected keeps 55.6 for both checks: the update's
        # condition params are non-updatable (see reference //Todo) and the
        # update matched rows get price 77.6 and volume 200 — but expected2
        # still shows 55.6: the reference treats this shape as a no-op.
        eq_unsorted(ins[:2], [(55.6, 200), (55.6, 100)])
        eq_unsorted(ins[2:], [(55.6, 200), (55.6, 100)])

    def test12_pk_update_lt_nonkey_select(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume < volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume >= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 200)),
            ("UpdateStockStream", ("FOO", 77.6, 200)),
            ("CheckStockStream", ("BAR", 200)),
        ], "query3")
        eq_unsorted(ins[:2], [(55.6, 200), (55.6, 100)])
        eq_unsorted(ins[2:], [(55.6, 200), (55.6, 100)])

    def test13_pk_update_ge(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume >= volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume <= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 200)),
            ("UpdateStockStream", ("FOO", 77.6, 200)),
            ("CheckStockStream", ("BAR", 200)),
        ], "query3")
        eq(ins, [(55.6, 200), (77.6, 200)])

    def test14_pk_update_gt(self):
        ql = S3 + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume > volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume <= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 150)),
            ("UpdateStockStream", ("FOO", 77.6, 150)),
            ("CheckStockStream", ("BAR", 150)),
        ], "query3")
        eq(ins, [(55.6, 200), (77.6, 150)])

    def test15_pk_delete_equality(self):
        ql = S3D + """@PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 100)])
        eq(ins[2:], [("WSO2", 100)])

    def test16_pk_delete_not_equal(self):
        ql = S3D + """@PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.symbol!=symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 100)])
        eq(ins[2:], [("IBM", 100)])

    def test17_pk_delete_table_gt(self):
        ql = S3D + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume>volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 150)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 200)])
        eq(ins[2:], [("IBM", 100)])

    def test18_pk_delete_table_ge(self):
        ql = S3D + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume>=volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 200)])
        eq(ins[2:], [("IBM", 100)])

    def test19_pk_delete_table_lt(self):
        ql = S3D + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume < volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 150)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 200)])
        eq(ins[2:], [("WSO2", 200)])

    def test20_pk_delete_table_le(self):
        ql = S3D + """@PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume <= volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 150)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:3], [("IBM", 100), ("BAR", 150), ("WSO2", 200)])
        eq(ins[3:], [("WSO2", 200)])

    def test21_pk_in_condition_eq(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(symbol==StockTable.symbol) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq_unsorted(ins, [("WSO2", 100)])

    def test22_pk_in_condition_ne(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(symbol!=StockTable.symbol) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 100), ("WSO2", 100)])

    def test23_pk_in_condition_gt(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(volume > StockTable.volume) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 170)),
            ("CheckStockStream", ("FOO", 500)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 170), ("FOO", 500)])

    def test24_pk_in_condition_lt(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(volume < StockTable.volume) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 170)),
            ("CheckStockStream", ("FOO", 500)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 170)])

    def test25_pk_in_condition_le(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(volume <= StockTable.volume) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 170)),
            ("CheckStockStream", ("FOO", 200)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 170), ("FOO", 200)])

    def test26_pk_in_condition_ge(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @PrimaryKey('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(volume >= StockTable.volume) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 170)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 170), ("FOO", 100)])

    def test27_pk_left_outer_join_upsert(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long, price float);
        define stream UpdateStockStream (comp string, vol long);
        @PrimaryKey('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from UpdateStockStream left outer join StockTable
        on UpdateStockStream.comp == StockTable.symbol
        select comp as symbol, ifThenElse(price is null,0f,price) as price, vol as volume
        update or insert into StockTable
        on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol==StockTable.symbol and volume==StockTable.volume
         and price==StockTable.price) in StockTable]
        insert into OutStream;"""
        ins, nrem = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("CheckStockStream", ("IBM", 100, 155.6)),
            ("CheckStockStream", ("WSO2", 100, 155.6)),
            ("UpdateStockStream", ("IBM", 200)),
            ("UpdateStockStream", ("WSO2", 300)),
            ("CheckStockStream", ("IBM", 200, 0.0)),
            ("CheckStockStream", ("WSO2", 300, 55.6)),
        ], "query3")
        eq(ins, [("IBM", 200, 0.0), ("WSO2", 300, 55.6)])
        assert nrem == 0

    def test28_pk_unknown_attribute_rejected(self):
        with pytest.raises(SiddhiAppCreationError):
            mgr = SiddhiManager()
            mgr.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey('symbol1')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable ;
            """)

    def test29_pk_empty_annotation_rejected(self):
        with pytest.raises((SiddhiAppCreationError, SiddhiParserError)):
            mgr = SiddhiManager()
            mgr.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey()
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable ;
            """)

    def test31_pk_duplicate_annotation_rejected(self):
        with pytest.raises((SiddhiAppCreationError, SiddhiParserError)):
            mgr = SiddhiManager()
            mgr.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey('symbol')
            @PrimaryKey('price')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable ;
            """)

    def test32_pk_malformed_annotation_rejected(self):
        with pytest.raises((SiddhiAppCreationError, SiddhiParserError)):
            mgr = SiddhiManager()
            mgr.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey'symbol'
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable ;
            """)

    def test33_pk_case_sensitive_attribute_rejected(self):
        with pytest.raises((SiddhiAppCreationError, SiddhiParserError)):
            mgr = SiddhiManager()
            mgr.create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @PrimaryKey ('Symbol')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable ;
            """)

    def test36_composite_pk_join_both_keys(self):
        ql = S3 + """@PrimaryKey('symbol','volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol and CheckStockStream.volume==StockTable.volume
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("StockStream", ("IBM", 56.6, 200)),
            ("CheckStockStream", ("IBM", 200)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq(ins, [("IBM", 200), ("WSO2", 100)])

    def test37_composite_pk_join_one_key(self):
        ql = S3 + """@PrimaryKey('symbol','volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("StockStream", ("IBM", 56.6, 200)),
            ("CheckStockStream", ("IBM", 200)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq(ins, [("IBM", 100), ("IBM", 200), ("WSO2", 100)])

    def test38_composite_pk_join_with_constant(self):
        ql = S3 + """@PrimaryKey('symbol','volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on (CheckStockStream.symbol==StockTable.symbol and CheckStockStream.volume==StockTable.volume) and
         55.6f == StockTable.price
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 101)),
            ("StockStream", ("IBM", 55.6, 102)),
            ("StockStream", ("IBM", 55.6, 200)),
            ("CheckStockStream", ("IBM", 200)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq(ins, [("IBM", 200), ("WSO2", 100)])

    def test39_composite_pk_join_three_conditions(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, price float, volume long);
        @PrimaryKey('symbol','volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol and CheckStockStream.volume==StockTable.volume and
         CheckStockStream.price == StockTable.price
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 101)),
            ("StockStream", ("IBM", 55.6, 102)),
            ("StockStream", ("IBM", 55.6, 200)),
            ("CheckStockStream", ("IBM", 55.6, 200)),
            ("CheckStockStream", ("WSO2", 55.6, 100)),
        ], "query2")
        eq(ins, [("IBM", 200), ("WSO2", 100)])

    def test47_pk_table_side_join_group_by(self):
        # reference persistenceTest47 (same file): table-side join driving a
        # group-by with PK dedupe — WSO2-1/IBM-1 rows keep their PK'd values
        ql = """define stream StockStream (symbol2 string, price float, volume long);
        define stream CheckStockStream (symbol1 string);
        @PrimaryKey('symbol2')
        define table StockTable (symbol2 string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from StockTable join CheckStockStream
        on symbol2 == symbol1
        select symbol2 as symbol1, volume as TB
        group by symbol2
        insert all events into OutStream;"""
        sends = []
        for i in range(10):
            sends.append(("StockStream", (f"WSO2-{i}", 55.6, 180 + i)))
        for i in range(10):
            sends.append(("StockStream", (f"IBM-{i}", 55.6, 100 + i)))
        sends += [
            ("StockStream", ("WSO2-11", 100.6, 180)),
            ("StockStream", ("IBM-11", 100.6, 100)),
            ("StockStream", ("WSO2-12", 8.6, 13)),
            ("StockStream", ("IBM-12", 7.6, 14)),
            ("CheckStockStream", ("IBM-1",)),
            ("CheckStockStream", ("WSO2-1",)),
        ]
        ins, _ = run(ql, sends, "query2")
        eq(ins, [("IBM-1", 101), ("WSO2-1", 181)])
