"""Snapshot / persistence tests.

Reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/managment/
PersistenceTestCase.java and IncrementalPersistenceTestCase.java — snapshot,
shutdown, recreate the app, restore, continue exactly where it left off.
"""

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.persistence import (
    FileSystemPersistenceStore,
    IncrementalFileSystemPersistenceStore,
    InMemoryPersistenceStore,
)

APP = """
@app:name('PersistApp')
define stream S (symbol string, price float, volume long);
define table T (symbol string, volume long);
@info(name='q')
from S#window.length(3) select symbol, sum(volume) as total insert into Out;
from S select symbol, volume insert into T;
"""


def make(store=None):
    mgr = SiddhiManager()
    if store is not None:
        mgr.set_persistence_store(store)
    rt = mgr.create_siddhi_app_runtime(APP)
    got = []
    rt.add_callback("q", lambda ts, i, r: got.extend(e.data for e in i or []))
    rt.start()
    return mgr, rt, got


class TestSnapshotRestore:
    def test_full_snapshot_bytes_roundtrip(self):
        mgr, rt, got = make()
        h = rt.get_input_handler("S")
        h.send(("A", 1.0, 10), timestamp=1)
        h.send(("A", 1.0, 20), timestamp=2)
        snap = rt.snapshot()
        rt.shutdown()

        mgr2, rt2, got2 = make()
        rt2.restore(snap)
        # the window carry continues: next event sums with restored state
        rt2.get_input_handler("S").send(("A", 1.0, 5), timestamp=3)
        assert got2 == [("A", 35)]
        # table contents restored too
        rows = rt2.query("from T select symbol, volume")
        assert [e.data for e in rows][:2] == [("A", 10), ("A", 20)]
        rt2.shutdown()
        mgr.shutdown()
        mgr2.shutdown()

    def test_in_memory_store_revisions(self):
        store = InMemoryPersistenceStore()
        mgr, rt, got = make(store)
        h = rt.get_input_handler("S")
        h.send(("A", 1.0, 10), timestamp=1)
        rev = rt.persist()
        assert rev.endswith("_PersistApp")
        rt.shutdown()

        mgr2, rt2, got2 = make(store)
        rt2.restore_last_revision()
        rt2.get_input_handler("S").send(("A", 1.0, 7), timestamp=2)
        assert got2 == [("A", 17)]
        rt2.shutdown()
        mgr.shutdown()
        mgr2.shutdown()

    def test_filesystem_store(self, tmp_path):
        store = FileSystemPersistenceStore(str(tmp_path))
        mgr, rt, got = make(store)
        rt.get_input_handler("S").send(("B", 2.0, 100), timestamp=1)
        rt.persist()
        rt.shutdown()

        mgr2, rt2, got2 = make(store)
        rt2.restore_last_revision()
        rt2.get_input_handler("S").send(("B", 2.0, 1), timestamp=2)
        assert got2 == [("B", 101)]
        rt2.shutdown()
        mgr.shutdown()
        mgr2.shutdown()

    def test_incremental_store(self, tmp_path):
        store = IncrementalFileSystemPersistenceStore(str(tmp_path))
        mgr, rt, got = make(store)
        h = rt.get_input_handler("S")
        h.send(("A", 1.0, 10), timestamp=1)
        rt.persist()  # full (first)
        h.send(("A", 1.0, 20), timestamp=2)
        rt.persist()  # delta
        rt.shutdown()

        mgr2, rt2, got2 = make(store)
        rt2.restore_last_revision()
        rt2.get_input_handler("S").send(("A", 1.0, 5), timestamp=3)
        assert got2 == [("A", 35)]
        rt2.shutdown()
        mgr.shutdown()
        mgr2.shutdown()

    def test_interner_conflict_detected(self):
        mgr, rt, got = make()
        rt.get_input_handler("S").send(("A", 1.0, 10), timestamp=1)
        snap = rt.snapshot()
        rt.shutdown()

        mgr2, rt2, got2 = make()
        # divergent interning order: 'ZZZ' now takes the id 'A' had
        mgr2.interner.intern("ZZZ")
        with pytest.raises(ValueError, match="intern table conflict"):
            rt2.restore(snap)
        rt2.shutdown()
        mgr.shutdown()
        mgr2.shutdown()
