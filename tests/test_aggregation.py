"""Incremental aggregation tests.

Reference: modules/siddhi-core/src/test/java/org/wso2/siddhi/core/aggregation/
AggregationTestCase.java (45 tests) — event-time bucket rollup sec..year and
store-query reads with within/per.
"""

import pytest

from siddhi_tpu import SiddhiManager

BASE_TS = 1_496_289_720_000  # 2017-06-01 04:05:20 GMT (reference test epoch)


def build(ql):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    rt.start()
    return mgr, rt


APP = """
define stream TradeStream (symbol string, price float, volume long, ts long);
define aggregation TradeAgg
from TradeStream
select symbol, avg(price) as avgPrice, sum(volume) as total
group by symbol
aggregate by ts every sec, min;
"""


class TestIncrementalAggregation:
    def test_rollup_and_store_query(self):
        mgr, rt = build(APP)
        h = rt.get_input_handler("TradeStream")
        # two events in second 0, one in second 1, one in second 2
        h.send(("WSO2", 50.0, 10, BASE_TS), timestamp=1)
        h.send(("WSO2", 70.0, 20, BASE_TS + 500), timestamp=2)
        h.send(("WSO2", 60.0, 5, BASE_TS + 1000), timestamp=3)
        h.send(("IBM", 100.0, 1, BASE_TS + 2000), timestamp=4)

        rows = rt.query("from TradeAgg per 'sec' select AGG_TIMESTAMP, symbol, avgPrice, total")
        got = sorted(e.data for e in rows)
        assert got == [
            (BASE_TS, "WSO2", 60.0, 30),          # closed bucket (spilled)
            (BASE_TS + 1000, "WSO2", 60.0, 5),    # closed by the IBM event
            (BASE_TS + 2000, "IBM", 100.0, 1),    # in-flight bucket
        ]
        rt.shutdown()
        mgr.shutdown()

    def test_minute_rollup(self):
        mgr, rt = build(APP)
        h = rt.get_input_handler("TradeStream")
        h.send(("WSO2", 50.0, 10, BASE_TS), timestamp=1)
        h.send(("WSO2", 70.0, 30, BASE_TS + 30_000), timestamp=2)   # same minute
        h.send(("WSO2", 10.0, 100, BASE_TS + 65_000), timestamp=3)  # next minute
        rows = rt.query("from TradeAgg per 'min' select AGG_TIMESTAMP, symbol, total")
        got = sorted(e.data for e in rows)
        minute0 = BASE_TS - (BASE_TS % 60_000)
        assert got == [
            (minute0, "WSO2", 40),           # closed minute bucket
            (minute0 + 60_000, "WSO2", 100),  # in-flight minute
        ]
        rt.shutdown()
        mgr.shutdown()

    def test_within_filter(self):
        mgr, rt = build(APP)
        h = rt.get_input_handler("TradeStream")
        h.send(("WSO2", 50.0, 10, BASE_TS), timestamp=1)
        h.send(("WSO2", 70.0, 20, BASE_TS + 10_000), timestamp=2)
        rows = rt.query(
            f"from TradeAgg within {BASE_TS}L, {BASE_TS + 5_000}L per 'sec' "
            "select symbol, total"
        )
        assert [e.data for e in rows] == [("WSO2", 10)]
        rt.shutdown()
        mgr.shutdown()

    def test_group_by_store_query_aggregation(self):
        mgr, rt = build(APP)
        h = rt.get_input_handler("TradeStream")
        h.send(("WSO2", 50.0, 10, BASE_TS), timestamp=1)
        h.send(("IBM", 20.0, 5, BASE_TS + 100), timestamp=2)
        h.send(("WSO2", 70.0, 20, BASE_TS + 1_100), timestamp=3)
        # sum over all buckets per symbol via the store-query selector
        rows = rt.query(
            "from TradeAgg per 'sec' select symbol, sum(total) as t group by symbol"
        )
        assert sorted(e.data for e in rows) == [("IBM", 5), ("WSO2", 30)]
        rt.shutdown()
        mgr.shutdown()


class TestAggregationJoin:
    def test_stream_join_aggregation(self):
        mgr, rt = build(APP + """
        define stream Query (symbol string);
        @info(name='j')
        from Query join TradeAgg
        on Query.symbol == TradeAgg.symbol
        within 1496289720000L, 1496289730000L
        per 'sec'
        select Query.symbol as s, TradeAgg.total as total
        insert into JOut;
        """)
        h = rt.get_input_handler("TradeStream")
        h.send(("WSO2", 50.0, 10, BASE_TS), timestamp=1)
        h.send(("WSO2", 70.0, 20, BASE_TS + 100), timestamp=2)
        h.send(("IBM", 30.0, 5, BASE_TS + 200), timestamp=3)
        got = []
        rt.add_callback("j", lambda ts, i, r: got.extend(e.data for e in i or []))
        rt.get_input_handler("Query").send(("WSO2",), timestamp=4)
        # the in-flight second bucket for WSO2 joins: total 30
        assert got == [("WSO2", 30)]
        rt.shutdown()
        mgr.shutdown()


class TestAggregationRestartRebuild:
    def test_store_backed_restart_rebuilds_inflight(self):
        # reference: aggregation/RecreateInMemoryData.java — a @store-backed
        # aggregation restarting WITHOUT a snapshot rebuilds its open coarse
        # buckets from the persisted finer duration tables
        from siddhi_tpu.core.record_table import InMemoryRecordStore

        InMemoryRecordStore.clear_all()
        app = """
        define stream S (symbol string, volume long, ts long);
        @store(type='memory', store.id='agg-rb')
        define aggregation A
        from S
        select symbol, sum(volume) as total
        group by symbol
        aggregate by ts every sec, min;
        """
        mgr, rt = build(app)
        h = rt.get_input_handler("S")
        h.send(("WSO2", 1, BASE_TS), timestamp=1)
        h.send(("WSO2", 2, BASE_TS + 1000), timestamp=2)  # closes sec bucket 0
        h.send(("WSO2", 4, BASE_TS + 2000), timestamp=3)  # closes sec bucket 1
        rows = rt.query("from A per 'min' select AGG_TIMESTAMP, symbol, total")
        pre = [(e.data[1], e.data[2]) for e in rows]
        rt.shutdown()
        mgr.shutdown()
        # the live minute view covers all three events (closed seconds 1+2
        # plus the still-open second 4)
        assert ("WSO2", 7) in pre, pre

        # restart WITHOUT snapshots: the seconds table reloads from the record
        # store; the open minute bucket must be rebuilt from it
        mgr2, rt2 = build(app)
        rows2 = rt2.query("from A per 'min' select AGG_TIMESTAMP, symbol, total")
        post = [(e.data[1], e.data[2]) for e in rows2]
        rt2.shutdown()
        mgr2.shutdown()
        InMemoryRecordStore.clear_all()
        # the two spilled seconds are recovered; the open second (4) was
        # never spilled and is irrecoverable (same as the reference)
        assert ("WSO2", 3) in post, post
