"""Golden corpus: the reference's LogicalAbsentPatternTestCase, full file.

Data-level translation of all 68 tests in
siddhi-core/src/test/java/org/wso2/siddhi/core/query/pattern/absent/
LogicalAbsentPatternTestCase.java — query strings, event sequences and
expected outputs are the reference's own; wall-clock sleeps become explicit
`@app:playback` timestamps (cumulative ms, identical durations), and where a
trailing sleep lets a deadline fire, an inert clock-advance event (matching
no condition) stands in for the passage of time.
"""

from __future__ import annotations

import pytest

from siddhi_tpu import SiddhiManager

HEAD = """@app:playback @app:batch(size='8')
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
define stream Stream3 (symbol string, price float, volume int);
define stream Stream4 (symbol string, price float, volume int);
"""

S1, S2, S3, S4 = "Stream1", "Stream2", "Stream3", "Stream4"


def run_pb(ql, steps, query_name="query1"):
    """steps: (ts_ms, stream, (symbol, price, volume)) in timestamp order.
    'adv' stream = inert Stream1 row that matches no test condition but
    advances the playback clock so due deadlines fire."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(HEAD + ql)
    got = []
    rt.add_callback(
        query_name,
        lambda ts, i, r: got.extend(tuple(e.data) for e in i or []),
    )
    rt.start()
    hs = {}
    for ts, stream, row in steps:
        if stream == "adv":
            stream, row = S1, ("ZZZ", 1.0, 0)
        hs.setdefault(stream, rt.get_input_handler(stream)).send(
            row, timestamp=ts
        )
    rt.shutdown()
    mgr.shutdown()
    return got


# Each case: (query, steps, expected_prefix, total_count).
# expected_prefix lists the reference's asserted events in order; total_count
# is the reference's asserted inEventCount (None = len(expected_prefix)).
CASES = {
    "absent1": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S3, ("GOOGLE", 35.0, 100))],
        [("WSO2", "GOOGLE")], 1),
    "absent2": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("IBM", 25.0, 100)),
         (200, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent3": (
        """@info(name = 'query1')
        from not Stream1[price>10] and e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S2, ("IBM", 25.0, 100)), (100, S3, ("GOOGLE", 35.0, 100))],
        [("IBM", "GOOGLE")], 1),
    "absent4": (
        """@info(name = 'query1')
        from not Stream1[price>10] and e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("IBM", 25.0, 100)),
         (200, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent5": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (1100, S3, ("GOOGLE", 35.0, 100))],
        [("WSO2", "GOOGLE")], 1),
    "absent5_1": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (500, S3, ("GOOGLE", 35.0, 100)),
         (1100, "adv", None)],
        [("WSO2", "GOOGLE")], 1),
    "absent5_2": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(1100, S1, ("WSO2", 15.0, 100)), (1200, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent6": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent7": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec and e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("IBM", 25.0, 100)),
         (200, S3, ("GOOGLE", 35.0, 100)), (2300, "adv", None)],
        [], 0),
    "absent8": (
        """@info(name = 'query1')
        from not Stream1[price>10] for 1 sec and e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(1100, S2, ("IBM", 25.0, 100)), (1200, S3, ("GOOGLE", 35.0, 100))],
        [("IBM", "GOOGLE")], 1),
    "absent8_1": (
        """@info(name = 'query1')
        from not Stream1[price>10] for 1 sec and e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S2, ("IBM", 25.0, 100)), (1100, S3, ("GOOGLE", 35.0, 100))],
        [("IBM", "GOOGLE")], 1),
    "absent8_2": (
        """@info(name = 'query1')
        from not Stream1[price>10] for 1 sec and e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(500, S1, ("WSO2", 15.0, 100)), (1100, S2, ("IBM", 25.0, 100)),
         (1200, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent9": (
        """@info(name = 'query1')
        from not Stream1[price>10] for 1 sec and e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S2, ("IBM", 25.0, 100)), (100, S3, ("GOOGLE", 35.0, 100)),
         (1200, "adv", None)],
        [], 0),
    "absent10": (
        """@info(name = 'query1')
        from not Stream1[price>10] for 1 sec and e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (1100, S2, ("IBM", 25.0, 100)),
         (1200, S3, ("GOOGLE", 35.0, 100))],
        [("IBM", "GOOGLE")], 1),
    "absent11": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec or e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S3, ("GOOGLE", 35.0, 100))],
        [("WSO2", "GOOGLE")], 1),
    "absent12": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec or e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S3, ("GOOGLE", 35.0, 100)),
         (1200, "adv", None)],
        [("WSO2", "GOOGLE")], 1),
    "absent13": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec or e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (1100, "adv", None)],
        [("WSO2", None)], 1),
    "absent14": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec or e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100))],
        [], 0),
    "absent15": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec or e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("IBM", 25.0, 100)),
         (200, S3, ("GOOGLE", 35.0, 100)), (2300, "adv", None)],
        [("WSO2", "GOOGLE")], 1),
    "absent16": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec or e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("IBM", 25.0, 100)),
         (1200, "adv", None)],
        [], 0),
    "absent17": (
        """@info(name = 'query1')
        from not Stream1[price>10] for 1 sec or e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S2, ("WSO2", 25.0, 100)), (100, S3, ("GOOGLE", 35.0, 100))],
        [("WSO2", "GOOGLE")], 1),
    "absent18": (
        """@info(name = 'query1')
        from not Stream1[price>10] for 1 sec or e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(1100, S3, ("GOOGLE", 35.0, 100))],
        [(None, "GOOGLE")], 1),
    "absent19": (
        """@info(name = 'query1')
        from not Stream1[price>10] for 1 sec or e2=Stream2[price>20] -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent20": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] and e3=Stream3[price>30]) within 1 sec
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S3, ("GOOGLE", 35.0, 100))],
        [("WSO2", "GOOGLE")], 1),
    "absent21": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] and e3=Stream3[price>30]) within 1 sec
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (1100, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent22": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] and e3=Stream3[price>30]) within 1 sec
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (1100, S2, ("IBM", 25.0, 100)),
         (2200, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent23": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec and e3=Stream3[price>30]) within 2 sec
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (1100, S3, ("GOOGLE", 35.0, 100))],
        [("WSO2", "GOOGLE")], 1),
    "absent24": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec and e3=Stream3[price>30]) within 2 sec
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (2100, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent25": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec and not Stream3[price>30] for 1 sec) within 2 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (1100, "adv", None)],
        [("WSO2",)], 1),
    "absent26": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec and not Stream3[price>30] for 1 sec) within 2 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("IBM", 25.0, 101)),
         (1200, "adv", None)],
        [], 0),
    "absent27": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec and not Stream3[price>30] for 1 sec) within 2 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S3, ("IBM", 35.0, 102)),
         (1200, "adv", None)],
        [], 0),
    "absent28": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec and not Stream3[price>30] for 1 sec) within 2 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("IBM", 25.0, 101)),
         (200, S3, ("ORACLE", 35.0, 102)), (1300, "adv", None)],
        [], 0),
    "absent29": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec or not Stream3[price>30] for 1 sec) within 2 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (1100, "adv", None)],
        [("WSO2",)], 1),
    "absent30": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec or not Stream3[price>30] for 1 sec) within 2 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("IBM", 25.0, 101)),
         (1200, "adv", None)],
        [("WSO2",)], 1),
    "absent31": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec or not Stream3[price>30] for 1 sec) within 2 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S3, ("IBM", 35.0, 102)),
         (1200, "adv", None)],
        [("WSO2",)], 1),
    "absent32": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> (not Stream2[price>20] for 1 sec or not Stream3[price>30] for 1 sec) within 2 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("IBM", 25.0, 101)),
         (200, S3, ("ORACLE", 35.0, 102)), (1300, "adv", None)],
        [], 0),
    "absent33": (
        """@info(name = 'query1')
        from (not Stream1[price>10] for 1 sec or not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(1100, S3, ("WSO2", 35.0, 100)), (2200, S3, ("WSO2", 35.0, 100))],
        [("WSO2",)], 1),
    "absent34": (
        """@info(name = 'query1')
        from (not Stream1[price>10] for 1 sec or not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(500, S1, ("IBM", 15.0, 100)), (1100, S3, ("WSO2", 35.0, 100))],
        [("WSO2",)], 1),
    "absent35": (
        """@info(name = 'query1')
        from (not Stream1[price>10] for 1 sec or not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(500, S2, ("IBM", 25.0, 100)), (1100, S3, ("WSO2", 35.0, 100))],
        [("WSO2",)], 1),
    "absent36": (
        """@info(name = 'query1')
        from (not Stream1[price>10] for 1 sec or not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(0, S1, ("ORACLE", 15.0, 100)), (100, S2, ("IBM", 25.0, 100)),
         (200, S3, ("WSO2", 35.0, 100))],
        [], 0),
    "absent37": (
        """@info(name = 'query1')
        from (not Stream1[price>10] for 1 sec and not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(1100, S3, ("WSO2", 35.0, 100))],
        [("WSO2",)], 1),
    "absent38": (
        """@info(name = 'query1')
        from (not Stream1[price>10] for 1 sec and not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(500, S1, ("IBM", 15.0, 100)), (1100, S3, ("WSO2", 35.0, 100))],
        [], 0),
    "absent39": (
        """@info(name = 'query1')
        from (not Stream1[price>10] for 1 sec and not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(500, S2, ("IBM", 25.0, 100)), (1100, S3, ("WSO2", 35.0, 100))],
        [], 0),
    "absent40": (
        """@info(name = 'query1')
        from (not Stream1[price>10] for 1 sec and not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(0, S1, ("ORACLE", 15.0, 100)), (100, S2, ("IBM", 25.0, 100)),
         (200, S3, ("WSO2", 35.0, 100))],
        [], 0),
    "absent41": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> e2=Stream2[price>20] or not Stream3[price>30] for 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (100, S2, ("GOOGLE", 25.0, 100))],
        [("WSO2", "GOOGLE")], 1),
    "absent42": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] -> e2=Stream2[price>20] or not Stream3[price>30] for 1 sec
        select e1.symbol as symbol1, e2.symbol as symbol2 insert into OutputStream;""",
        [(0, S1, ("WSO2", 15.0, 100)), (1100, "adv", None)],
        [("WSO2", None)], 1),
    "absent43": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] or not Stream2[price>20] for 1 sec -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("WSO2", 25.0, 100)), (100, S3, ("GOOGLE", 35.0, 100))],
        [("WSO2", "GOOGLE")], 1),
    "absent44": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] or not Stream2[price>20] for 1 sec -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(1100, S3, ("GOOGLE", 35.0, 100))],
        [(None, "GOOGLE")], 1),
    "absent45": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] or not Stream2[price>20] for 1 sec -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(100, S3, ("GOOGLE", 35.0, 100))],
        [], 0),
    "absent46": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec or not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(500, S1, ("ORACLE", 15.0, 100)), (1100, S3, ("WSO2", 35.0, 100)),
         (1400, S2, ("MICROSOFT", 45.0, 100)), (2200, S3, ("IBM", 55.0, 100))],
        [("WSO2",), ("IBM",)], 2),
    "absent47": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec or not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(1200, S3, ("WSO2", 35.0, 100)), (2400, S3, ("IBM", 55.0, 100))],
        [("WSO2",), ("WSO2",), ("IBM",)], 4),
    "absent48": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec or not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(2100, S3, ("WSO2", 35.0, 100))],
        [("WSO2",), ("WSO2",), ("WSO2",)], 4),
    "absent49": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec and not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(1100, S3, ("WSO2", 35.0, 100)), (2200, S3, ("IBM", 55.0, 100))],
        [("WSO2",), ("IBM",)], 2),
    "absent50": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec and not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e3.symbol as symbol insert into OutputStream;""",
        [(2100, S3, ("WSO2", 35.0, 100))],
        [("WSO2",), ("WSO2",)], 2),
    "absent51": (
        """@info(name = 'query1')
        from every (e1=Stream1[price>10] and not Stream2[price>20] for 1 sec) -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(1100, S1, ("IBM", 25.0, 100)), (1200, S3, ("GOOGLE", 35.0, 100)),
         (2300, S1, ("ORACLE", 45.0, 100)), (2400, S3, ("MICROSOFT", 55.0, 100))],
        [("IBM", "GOOGLE"), ("ORACLE", "MICROSOFT")], 2),
    "absent52": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec or e2=Stream2[price>20]) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(500, S1, ("ORACLE", 15.0, 100)), (1100, S3, ("WSO2", 35.0, 100)),
         (1400, S2, ("MICROSOFT", 45.0, 100)), (2200, S3, ("IBM", 55.0, 100))],
        None, 1),
    "absent53": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec or e2=Stream2[price>20]) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(1200, S3, ("WSO2", 35.0, 100)), (2400, S3, ("IBM", 55.0, 100)),
         (2500, S2, ("ORACLE", 65.0, 100)), (2600, S3, ("GOOGLE", 75.0, 100))],
        [(None, "WSO2"), (None, "IBM"), ("ORACLE", "GOOGLE")], 3),
    "absent54": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec or e2=Stream2[price>20]) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(2100, S3, ("WSO2", 35.0, 100))],
        [(None, "WSO2"), (None, "WSO2")], 2),
    "absent55": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec and e2=Stream2[price>20]) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("ORACLE", 15.0, 100)), (100, S2, ("MICROSOFT", 45.0, 100)),
         (200, S3, ("IBM", 55.0, 100)), (2300, S2, ("WSO2", 45.0, 100)),
         (2400, S3, ("GOOGLE", 55.0, 100))],
        # both the MICROSOFT and WSO2 cycles complete and match GOOGLE; the
        # reference's newest-first pending list puts WSO2 first, our lane
        # order puts MICROSOFT first (documented same-event-order deviation,
        # core/pattern.py module docstring) — asserted order-insensitively
        {("WSO2", "GOOGLE"), ("MICROSOFT", "GOOGLE")}, 2),
    "absent56": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec and e2=Stream2[price>20]) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(1200, S3, ("WSO2", 35.0, 100)), (2400, S3, ("IBM", 55.0, 100)),
         (2500, S2, ("ORACLE", 65.0, 100)), (2600, S3, ("GOOGLE", 75.0, 100))],
        [("ORACLE", "GOOGLE")], 1),
    "absent57": (
        """@info(name = 'query1')
        from every (not Stream1[price>10] for 1 sec and e2=Stream2[price>20]) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(1100, S3, ("WSO2", 35.0, 100))],
        [], 0),
    "absent58": (
        """@info(name = 'query1')
        from every (e2=Stream2[price>20] or not Stream1[price>10] for 1 sec) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(500, S1, ("ORACLE", 15.0, 100)), (1100, S3, ("WSO2", 35.0, 100)),
         (1400, S2, ("MICROSOFT", 45.0, 100)), (2200, S3, ("IBM", 55.0, 100))],
        None, 1),
    "absent59": (
        """@info(name = 'query1')
        from every (e2=Stream2[price>20] or not Stream1[price>10] for 1 sec) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(1200, S3, ("WSO2", 35.0, 100)), (2400, S3, ("IBM", 55.0, 100)),
         (2500, S2, ("ORACLE", 65.0, 100)), (2600, S3, ("GOOGLE", 75.0, 100))],
        [(None, "WSO2"), (None, "IBM"), ("ORACLE", "GOOGLE")], 3),
    "absent60": (
        """@info(name = 'query1')
        from every (e2=Stream2[price>20] or not Stream1[price>10] for 1 sec) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(2100, S3, ("WSO2", 35.0, 100))],
        [(None, "WSO2"), (None, "WSO2")], 2),
    "absent61": (
        """@info(name = 'query1')
        from every (e2=Stream2[price>20] and not Stream1[price>10] for 1 sec) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("ORACLE", 15.0, 100)), (100, S2, ("MICROSOFT", 45.0, 100)),
         (200, S3, ("IBM", 55.0, 100)), (2300, S2, ("WSO2", 45.0, 100)),
         (2400, S3, ("GOOGLE", 55.0, 100))],
        # same-event emission order deviation as absent55
        {("WSO2", "GOOGLE"), ("MICROSOFT", "GOOGLE")}, 2),
    "absent62": (
        """@info(name = 'query1')
        from every (e2=Stream2[price>20] and not Stream1[price>10] for 1 sec) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(1200, S3, ("WSO2", 35.0, 100)), (2400, S3, ("IBM", 55.0, 100)),
         (2500, S2, ("ORACLE", 65.0, 100)), (2600, S3, ("GOOGLE", 75.0, 100))],
        [("ORACLE", "GOOGLE")], 1),
    "absent63": (
        """@info(name = 'query1')
        from every (e2=Stream2[price>20] and not Stream1[price>10] for 1 sec) -> e3=Stream3[price>30]
        select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
        [(1100, S3, ("WSO2", 35.0, 100))],
        [], 0),
    "absent64": (
        """@info(name = 'query1')
        from not Stream1[price>10] for 1 sec -> not Stream2[price>20] and e3=Stream3[price>30] -> e4=Stream4[price>40]
        select e3.symbol as symbol3, e4.symbol as symbol4 insert into OutputStream;""",
        [(1100, S3, ("GOOGLE", 35.0, 100)), (1200, S4, ("ORACLE", 45.0, 100))],
        [("GOOGLE", "ORACLE")], 1),
    "absent65": (
        """@info(name = 'query1')
        from e1=Stream1[price>10] and not Stream2[price>20] -> e3=Stream3[price>30]
        select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;""",
        [(0, S1, ("IBM", 15.0, 100)), (100, S3, ("GOOGLE", 35.0, 100))],
        [("IBM", "GOOGLE")], 1),
    "absent66": (
        """@info(name = 'query1')
        from not Stream1[price>50] and e2=Stream2[price>20]
        select e2.symbol as symbol2 insert into OutputStream;""",
        [(0, S2, ("IBM", 25.0, 100))],
        [("IBM",)], 1),
    "absent67": (
        """@info(name = 'query1')
        from not Stream1[price==50.0f] and e2=Stream1[price==20.0f]
        select e2.symbol as symbol2 insert into OutputStream;""",
        [(0, S1, ("WSO2", 50.0, 100)), (100, S1, ("IBM", 20.0, 100))],
        [], 0),
}


@pytest.mark.parametrize("name", sorted(CASES))
def test_logical_absent_golden(name):
    ql, steps, expected, total = CASES[name]
    got = run_pb(ql, steps)
    if total is not None:
        assert len(got) == total, (name, got)
    if isinstance(expected, set):
        assert set(got[: len(expected)]) == expected, (name, got)
    elif expected is not None:
        assert got[: len(expected)] == expected, (name, got)


def test_absent68_partitioned_both_absent():
    """Partitioned both-sides-absent (reference testQueryAbsent68)."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime("""@app:playback @app:batch(size='8')
    define stream Stream1 (symbol string, price float, volume int);
    partition with (symbol of Stream1) begin
    @info(name='query1')
    from e1=Stream1[price==10.0f] -> not Stream1[symbol == e1.symbol and price==20.0f] for 1 sec
         and not Stream1[symbol == e1.symbol and price==20.0f] for 1 sec
    select e1.symbol as symbol insert into OutputStream;
    end;
    """)
    got = []
    rt.add_callback(
        "OutputStream", lambda evs: got.extend(tuple(e.data) for e in evs)
    )
    rt.start()
    h = rt.get_input_handler("Stream1")
    h.send(("WSO2", 10.0, 20), timestamp=0)
    h.send(("IBM", 10.0, 21), timestamp=1)
    h.send(("IBM", 20.0, 15), timestamp=500)
    h.send(("ZZZ", 1.0, 0), timestamp=1200)  # clock advance
    rt.shutdown()
    mgr.shutdown()
    assert got == [("WSO2",)], got
