"""Differential tests: the batch pattern kernels vs the per-event scan oracle.

The scan path (`PatternProgram.apply_event` under `lax.scan`) is the semantic
oracle; `apply_batch_fast` / `apply_batch_count` must produce identical outputs
on the same inputs (reference analog: the golden corpus pins the interpreter,
here the interpreter pins the kernels)."""

import numpy as np
import pytest

import siddhi_tpu.core.pattern as pattern_mod
from siddhi_tpu import SiddhiManager

SCHEMA = "define stream S (sym string, price float, volume int);\n"


def run_columns(ql, data, batch):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(f"@app:batch(size='{batch}')\n" + ql)
    got = []

    def cb(ts, ins, removed):
        for e in ins or []:
            got.append((e.timestamp, tuple(e.data)))

    rt.add_callback("q", cb)
    rt.start()
    h = rt.get_input_handler("S")
    h.send_columns(data["ts"], {k: v for k, v in data.items() if k != "ts"})
    rt.shutdown()
    return got


def both_paths(ql, data, batch):
    """Outputs of the scan oracle and the batch kernel, each sorted within a
    timestamp: completions of the SAME event are emitted in lane order by the
    kernels and in pending order by the scan path (both approximations of the
    reference's pending-list age order), so intra-timestamp order is not part
    of the contract."""
    orig = pattern_mod.FORCE_SCAN
    try:
        pattern_mod.FORCE_SCAN = True
        slow = run_columns(ql, data, batch)
        pattern_mod.FORCE_SCAN = False
        fast = run_columns(ql, data, batch)
    finally:
        pattern_mod.FORCE_SCAN = orig

    def canon(rows):
        # stable: primary order by arrival (the list), ties by ts sorted data
        out, i = [], 0
        while i < len(rows):
            j = i
            while j < len(rows) and rows[j][0] == rows[i][0]:
                j += 1
            out.extend(sorted(rows[i:j], key=repr))
            i = j
        return out

    return canon(slow), canon(fast)


def make_data(n, seed, hi=90.0, lo=10.0):
    rng = np.random.default_rng(seed)
    return {
        "ts": np.arange(n, dtype=np.int64) + 1_000,
        "sym": rng.integers(1, 5, size=n).astype(np.int32),
        "price": rng.uniform(0.0, 100.0, size=n).astype(np.float32),
        "volume": rng.integers(1, 100, size=n).astype(np.int64),
    }


COUNT_QL = SCHEMA + """
@info(name='q')
from every a1=S[price > %s]<2:4> -> a2=S[price < %s]
select a1[0].volume as v0, a1[1].volume as v1, a1[2].volume as v2,
       a1[3].volume as v3, a2.volume as va
insert into Out;
"""


class TestCountKernelDifferential:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("batch", [1, 7, 64])
    def test_every_count_vs_scan(self, seed, batch):
        data = make_data(160, seed)
        slow, fast = both_paths(COUNT_QL % (90.0, 10.0), data, batch)
        assert fast == slow

    def test_dense_matches_vs_scan(self, seed=3):
        # high selectivity stresses the generation chain + lane pressure
        data = make_data(96, seed)
        slow, fast = both_paths(COUNT_QL % (30.0, 20.0), data, batch=32)
        assert fast == slow

    def test_no_every_count_vs_scan(self):
        ql = SCHEMA + """
        @info(name='q')
        from a1=S[price > 80]<2:3> -> a2=S[price < 20]
        select a1[0].volume as v0, a1[1].volume as v1, a2.volume as va
        insert into Out;
        """
        data = make_data(120, 5)
        slow, fast = both_paths(ql, data, batch=16)
        assert fast == slow

    def test_exact_count_vs_scan(self):
        ql = SCHEMA + """
        @info(name='q')
        from every a1=S[price > 70]<2> -> a2=S[price < 30]
        select a1[0].volume as v0, a1[1].volume as v1, a2.volume as va
        insert into Out;
        """
        data = make_data(120, 6)
        slow, fast = both_paths(ql, data, batch=24)
        assert fast == slow

    def test_cross_ref_advance_cond_vs_scan(self):
        # slot-1 condition reads e1's captures -> the row-only gate must
        # reject the kernel and both paths must agree (regression: per-cond
        # key sets were diffed against the cumulative root set)
        ql = SCHEMA + """
        @info(name='q')
        from every a1=S[price > 10]<2:5> -> a2=S[price > 10 and a1.price < price]
        select a1[0].volume as v0, a2.volume as va
        insert into Out;
        """
        data = make_data(96, 11)
        slow, fast = both_paths(ql, data, batch=32)
        assert fast == slow

    def test_min_above_capture_capacity_vs_scan(self):
        # min 10 > default countCapacity 8: the occurrence counter must keep
        # counting past the capture capacity (regression: kernel clamped the
        # counter to the capture room and never reached min)
        ql = SCHEMA + """
        @info(name='q')
        from every a1=S[price > 20]<10:> -> a2=S[price < 5]
        select a1[0].volume as v0, a1[last].volume as vl, a2.volume as va
        insert into Out;
        """
        data = make_data(200, 12)
        slow, fast = both_paths(ql, data, batch=40)
        assert fast == slow
        assert len(slow) > 0  # the scenario must actually fire

    def test_kleene_plus_unbounded_vs_scan(self):
        ql = SCHEMA + """
        @info(name='q')
        from every a1=S[price > 60]<1:> -> a2=S[price < 40]
        select a1[0].volume as v0, a1[last].volume as vl, a2.volume as va
        insert into Out;
        """
        data = make_data(120, 13)
        slow, fast = both_paths(ql, data, batch=24)
        assert fast == slow

    def test_three_slot_tail_vs_scan(self):
        ql = SCHEMA + """
        @info(name='q')
        from every a1=S[price > 85]<1:3> -> a2=S[price < 15] -> a3=S[volume > a2.volume]
        select a1[0].volume as v0, a2.volume as va, a3.volume as vb
        insert into Out;
        """
        data = make_data(160, 7)
        slow, fast = both_paths(ql, data, batch=32)
        assert fast == slow


class TestSimpleKernelDifferential:
    @pytest.mark.parametrize("seed", [0, 1])
    def test_every_two_state_vs_scan(self, seed):
        ql = SCHEMA + """
        @info(name='q')
        from every a1=S[price > 92] -> a2=S[price < 8]
        select a1.volume as v1, a2.volume as v2
        insert into Out;
        """
        data = make_data(160, seed)
        slow, fast = both_paths(ql, data, batch=32)
        assert fast == slow
