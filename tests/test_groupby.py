"""Group-by / order-by / limit tests.

Mirrors reference: core/src/test/java/.../query/GroupByTestCase.java,
OrderByLimitTestCase.java — SiddhiQL string -> runtime -> callback -> assert.
"""

import pytest

from siddhi_tpu import SiddhiManager


def make_runtime(ql):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    rt.start()
    return mgr, rt


def test_groupby_running_sum_no_window():
    mgr, rt = make_runtime(
        """
        define stream S (symbol string, price float, volume long);
        @info(name='q1')
        from S select symbol, sum(volume) as total group by symbol
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send(("IBM", 10.0, 5))
    h.send(("WSO2", 10.0, 7))
    h.send(("IBM", 10.0, 2))
    h.send(("WSO2", 10.0, 1))
    assert [e.data for e in got] == [
        ("IBM", 5), ("WSO2", 7), ("IBM", 7), ("WSO2", 8),
    ]
    mgr.shutdown()


def test_groupby_carry_across_batches():
    mgr, rt = make_runtime(
        """
        define stream S (k int, v int);
        @info(name='q1')
        from S select k, sum(v) as s, count() as c group by k insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    # separate sends => separate device batches; carries must persist per key
    h.send((1, 10))
    h.send((2, 100))
    h.send((1, 5))
    h.send((2, 50))
    h.send((3, 1))
    assert [e.data for e in got] == [
        (1, 10, 1), (2, 100, 1), (1, 15, 2), (2, 150, 2), (3, 1, 1),
    ]
    mgr.shutdown()


def test_groupby_with_length_window_expiry():
    # sliding length(2) per-key? No: window is per stream; expired events
    # subtract from their group's aggregate
    mgr, rt = make_runtime(
        """
        define stream S (sym string, v long);
        @info(name='q1')
        from S#window.length(2) select sym, sum(v) as s group by sym
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send(("A", 1))
    h.send(("A", 2))
    h.send(("B", 10))  # evicts A:1 -> A's sum drops to 2... via EXPIRED event
    h.send(("B", 20))  # evicts A:2
    # outputs: per CURRENT event the running group sum, and the EXPIRED rows
    # adjust state (callback receives CURRENT rows by default)
    assert [e.data for e in got] == [
        ("A", 1), ("A", 3), ("B", 10), ("B", 30),
    ]
    mgr.shutdown()


def test_groupby_lengthbatch_emits_one_per_key():
    mgr, rt = make_runtime(
        """
        define stream S (sym string, v long);
        @info(name='q1')
        from S#window.lengthBatch(4) select sym, sum(v) as s group by sym
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send_many([("A", 1), ("B", 10), ("A", 2), ("B", 20)])
    assert sorted(e.data for e in got) == [("A", 3), ("B", 30)]
    got.clear()
    # second bucket: group sums reset (batch window RESET clears group state)
    h.send_many([("A", 7), ("A", 1), ("C", 5), ("B", 2)])
    assert sorted(e.data for e in got) == [("A", 8), ("B", 2), ("C", 5)]
    mgr.shutdown()


def test_groupby_avg_min_max_with_window():
    mgr, rt = make_runtime(
        """
        define stream S (sym string, p float);
        @info(name='q1')
        from S#window.length(3)
        select sym, avg(p) as a, min(p) as lo, max(p) as hi group by sym
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send(("A", 10.0))
    h.send(("A", 20.0))
    h.send(("B", 100.0))
    h.send(("A", 30.0))  # evicts A:10 -> A holds {20,30}
    assert got[-1].data == ("A", 25.0, 20.0, 30.0)
    assert got[2].data == ("B", 100.0, 100.0, 100.0)
    mgr.shutdown()


def test_groupby_composite_key():
    mgr, rt = make_runtime(
        """
        define stream S (sym string, region string, v long);
        @info(name='q1')
        from S select sym, region, sum(v) as s group by sym, region
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send(("A", "us", 1))
    h.send(("A", "eu", 10))
    h.send(("A", "us", 2))
    assert [e.data for e in got] == [
        ("A", "us", 1), ("A", "eu", 10), ("A", "us", 3),
    ]
    mgr.shutdown()


def test_groupby_having():
    mgr, rt = make_runtime(
        """
        define stream S (sym string, v long);
        @info(name='q1')
        from S select sym, sum(v) as s group by sym having s > 10
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send(("A", 5))
    h.send(("A", 6))   # s=11 passes
    h.send(("B", 3))
    assert [e.data for e in got] == [("A", 11)]
    mgr.shutdown()


def test_order_by_desc_with_limit():
    mgr, rt = make_runtime(
        """
        define stream S (sym string, p float, v long);
        @info(name='q1')
        from S#window.lengthBatch(4)
        select sym, p order by p desc limit 2
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send_many([("A", 10.0, 1), ("B", 40.0, 1), ("C", 20.0, 1), ("D", 30.0, 1)])
    assert [e.data for e in got] == [("B", 40.0), ("D", 30.0)]
    mgr.shutdown()


def test_order_by_two_keys():
    mgr, rt = make_runtime(
        """
        define stream S (g int, p float);
        @info(name='q1')
        from S#window.lengthBatch(4)
        select g, p order by g, p desc
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send_many([(2, 1.0), (1, 5.0), (2, 9.0), (1, 7.0)])
    assert [e.data for e in got] == [(1, 7.0), (1, 5.0), (2, 9.0), (2, 1.0)]
    mgr.shutdown()


def test_limit_offset_arrival_order():
    mgr, rt = make_runtime(
        """
        define stream S (v int);
        @info(name='q1')
        from S#window.lengthBatch(5) select v limit 2 offset 1
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    rt.get_input_handler("S").send_many([(1,), (2,), (3,), (4,), (5,)])
    assert [e.data for e in got] == [(2,), (3,)]
    mgr.shutdown()


def test_groupby_capacity_annotation_and_bucket_reset_reclaims_slots():
    # tiny capacity 4; lengthBatch resets must clear the slot table so
    # cumulative cardinality beyond capacity works across buckets
    mgr, rt = make_runtime(
        """
        @app:groupCapacity(size='4')
        define stream S (k int, v long);
        @info(name='q1')
        from S#window.lengthBatch(3) select k, sum(v) as s group by k
        insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    h.send_many([(1, 1), (2, 2), (1, 3)])      # bucket 1: keys {1,2}
    h.send_many([(3, 5), (4, 6), (5, 7)])      # bucket 2: keys {3,4,5}
    h.send_many([(6, 8), (7, 9), (6, 1)])      # bucket 3: keys {6,7}
    assert sorted(e.data for e in got) == [
        (1, 4), (2, 2), (3, 5), (4, 6), (5, 7), (6, 9), (7, 9),
    ]
    mgr.shutdown()


def test_groupby_overflow_does_not_corrupt_existing_groups(caplog):
    import logging

    mgr, rt = make_runtime(
        """
        @app:groupCapacity(size='2')
        define stream S (k int, v long);
        @info(name='q1')
        from S select k, sum(v) as s group by k insert into Out;
        """
    )
    got = []
    rt.add_callback("q1", lambda ts, ins, removed: got.extend(ins or []))
    h = rt.get_input_handler("S")
    with caplog.at_level(logging.ERROR):
        h.send((1, 10))
        h.send((2, 20))
        h.send((3, 30))   # overflow: key 3 has no slot
        h.send((1, 5))    # key 1's carry must be intact
        for qr in rt.queries.values():
            qr.flush_aux_warnings()  # aux checks drain on a background thread
    assert got[0].data == (1, 10)
    assert got[1].data == (2, 20)
    assert got[2].data == (3, 30)   # within-batch value still exact
    assert got[3].data == (1, 15)   # not corrupted by key 3
    assert any("overflow" in r.message for r in caplog.records)
    mgr.shutdown()
