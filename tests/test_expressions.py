"""Expression / filter golden-behavior corpus.

Mirrors the breadth of the reference's FilterTestCase1/2 (81+ tests of
comparison operators across type pairs), math operator tests, and the
built-in function tests (reference: modules/siddhi-core/src/test/java/org/
wso2/siddhi/core/query/FilterTestCase1.java, function/*TestCase).
"""

import math

import pytest

from siddhi_tpu import SiddhiManager


def run(ql, rows, stream="S", name="q"):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    got = []
    rt.add_callback(name, lambda ts, i, r: got.extend(e.data for e in i or []))
    rt.start()
    h = rt.get_input_handler(stream)
    for i, row in enumerate(rows):
        h.send(row, timestamp=i + 1)
    rt.shutdown()
    mgr.shutdown()
    return got


STOCK = "define stream S (symbol string, price float, volume long, qty int);\n"
ROWS = [
    ("WSO2", 50.0, 60, 5),
    ("IBM", 70.0, 40, 10),
    ("GOOG", 50.0, 200, 5),
]


class TestComparisons:
    def _sel(self, cond):
        return STOCK + f"@info(name='q') from S[{cond}] select symbol insert into Out;"

    def test_gt_float_long(self):
        assert run(self._sel("price > volume"), ROWS) == [("IBM",)]

    def test_ge_int_float(self):
        assert run(self._sel("qty >= 10"), ROWS) == [("IBM",)]

    def test_lt_long_int(self):
        assert run(self._sel("volume < qty"), ROWS) == []

    def test_le(self):
        assert run(self._sel("price <= 50"), ROWS) == [("WSO2",), ("GOOG",)]

    def test_eq_string(self):
        assert run(self._sel("symbol == 'IBM'"), ROWS) == [("IBM",)]

    def test_neq_string(self):
        assert run(self._sel("symbol != 'IBM'"), ROWS) == [("WSO2",), ("GOOG",)]

    def test_eq_float_int(self):
        assert run(self._sel("price == 50"), ROWS) == [("WSO2",), ("GOOG",)]

    def test_and_or_not(self):
        assert run(self._sel("price == 50 and not (volume > 100)"), ROWS) == [("WSO2",)]
        assert run(self._sel("symbol == 'IBM' or volume > 100"), ROWS) == [
            ("IBM",), ("GOOG",)
        ]


class TestMath:
    def test_arithmetic_projection(self):
        ql = STOCK + """@info(name='q')
        from S select price + volume as a, price - qty as b,
                      price * 2 as c, volume / qty as d, volume % qty as e
        insert into Out;"""
        got = run(ql, [("A", 10.0, 7, 2)])
        assert got == [(17.0, 8.0, 20.0, 3, 1)]

    def test_integer_division_truncates(self):
        ql = STOCK + "@info(name='q') from S select volume / qty as d insert into Out;"
        assert run(ql, [("A", 1.0, 7, 2)]) == [(3,)]
        assert run(ql, [("A", 1.0, -7, 2)]) == [(-3,)]  # Java truncation

    def test_mod_sign_of_dividend(self):
        ql = STOCK + "@info(name='q') from S select volume % qty as m insert into Out;"
        assert run(ql, [("A", 1.0, -7, 2)]) == [(-1,)]

    def test_promotion_int_to_double(self):
        ql = STOCK + "@info(name='q') from S select qty / 2.0 as h insert into Out;"
        assert run(ql, [("A", 1.0, 1, 5)]) == [(2.5,)]


class TestBuiltins:
    def test_if_then_else(self):
        ql = STOCK + """@info(name='q')
        from S select ifThenElse(price > 60, 'high', 'low') as b insert into Out;"""
        assert run(ql, ROWS) == [("low",), ("high",), ("low",)]

    def test_coalesce_and_default(self):
        ql = """define stream S (a long, b long);
        @info(name='q') from S select coalesce(a, b) as c, default(a, 0L) as d
        insert into Out;"""
        assert run(ql, [(None, 7), (3, 9)]) == [(7, 0), (3, 3)]

    def test_cast_and_convert(self):
        ql = STOCK + """@info(name='q')
        from S select cast(qty, 'long') as l, convert(price, 'int') as i
        insert into Out;"""
        assert run(ql, [("A", 7.9, 1, 5)]) == [(5, 7)]

    def test_maximum_minimum(self):
        ql = STOCK + """@info(name='q')
        from S select maximum(price, volume, qty) as mx,
                      minimum(price, volume, qty) as mn insert into Out;"""
        assert run(ql, [("A", 50.0, 60, 5)]) == [(60.0, 5.0)]

    def test_event_timestamp(self):
        ql = STOCK + "@info(name='q') from S select eventTimestamp() as t insert into Out;"
        assert run(ql, [("A", 1.0, 1, 1)]) == [(1,)]

    def test_instance_of(self):
        ql = STOCK + """@info(name='q')
        from S select instanceOfFloat(price) as f, instanceOfString(symbol) as s,
                      instanceOfLong(price) as n insert into Out;"""
        assert run(ql, [("A", 1.0, 1, 1)]) == [(True, True, False)]

    def test_is_null(self):
        ql = """define stream S (a long, b string);
        @info(name='q') from S[a is null] select b insert into Out;"""
        assert run(ql, [(None, "x"), (1, "y")]) == [("x",)]


class TestAggregatorsCorpus:
    def test_stddev(self):
        ql = STOCK + """@info(name='q')
        from S select stdDev(price) as sd insert into Out;"""
        got = run(ql, [("A", 2.0, 1, 1), ("A", 4.0, 1, 1)])
        assert got[-1][0] == pytest.approx(1.0)

    def test_distinct_count_window(self):
        ql = STOCK + """@info(name='q')
        from S#window.length(3) select distinctCount(symbol) as d insert into Out;"""
        got = run(ql, [("A", 1.0, 1, 1), ("B", 1.0, 1, 1), ("A", 1.0, 1, 1),
                       ("C", 1.0, 1, 1)])
        assert [g[0] for g in got] == [1, 2, 2, 3]

    def test_min_forever(self):
        ql = STOCK + """@info(name='q')
        from S#window.length(1) select minForever(price) as m insert into Out;"""
        got = run(ql, [("A", 5.0, 1, 1), ("A", 2.0, 1, 1), ("A", 9.0, 1, 1)])
        assert [g[0] for g in got] == [5.0, 2.0, 2.0]

    def test_windowed_min_exact_on_expiry(self):
        ql = STOCK + """@info(name='q')
        from S#window.length(2) select min(price) as m insert into Out;"""
        got = run(ql, [("A", 5.0, 1, 1), ("A", 2.0, 1, 1), ("A", 9.0, 1, 1)])
        # window holds {5},{5,2},{2,9}: the min recovers after 5 expires
        assert [g[0] for g in got] == [5.0, 2.0, 2.0]


class TestStringConversion:
    def test_convert_numeric_to_string(self):
        from siddhi_tpu.utils.backend import host_callbacks_supported

        if not host_callbacks_supported():
            pytest.skip("backend lacks host callbacks")
        ql = """define stream S (v long, f double);
        @info(name='q')
        from S select convert(v, 'string') as sv, convert(f, 'string') as sf
        insert into Out;"""
        assert run(ql, [(42, 2.5)]) == [("42", "2.5")]
