"""Plan-vs-actual calibration ledger (observability/calibration.py): six
prediction kinds pairing live meters, mispricing reason codes end-to-end
(HTTP + Prometheus + explain), churn re-pairing that preserves cumulative
counters, the zero-overhead gate, and byte parity with the ledger armed."""

import json
import urllib.request

import numpy as np
import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.observability.calibration import (
    KIND_COMPILES,
    KIND_DISPATCH,
    KIND_SELECTIVITY,
    KIND_STATE_BYTES,
    KIND_WIRE_DECLARED,
    KIND_WIRE_INFERRED,
    REASON_WIRE_FALLBACK,
    _safe_ratio,
)

# the six-kind sentinel shape (mirrors bench.py --leg calibration): two
# shared filter+window queries, one externalTimeBatch query, a declared
# dict wire lane + an inferred delta lane, all fused under one group.
# batch 256: a 64-entry dictionary must amortize under the wide int32
# lane, which it cannot at small chunks (build_wire_spec drops it)
SENTINEL = """@app:statistics(reporter='none')
@app:batch(size='256')
@app:wire(dict.S.symbol='64')
define stream S (symbol string, price float, volume long);
@info(name='q1') from S[price > 50.0]#window.length(16)
select symbol, price insert into Out1;
@info(name='q2') from S[price > 50.0]#window.length(16)
select symbol, max(price) as mp insert into Out2;
@info(name='q3') from S#window.externalTimeBatch(volume, 1000)
select symbol, sum(price) as sp insert into Out3;
"""

ALL_KINDS = sorted((
    KIND_COMPILES, KIND_DISPATCH, KIND_SELECTIVITY,
    KIND_STATE_BYTES, KIND_WIRE_DECLARED, KIND_WIRE_INFERRED,
))


def _boot(ql):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    for q in ("q1", "q2", "q3"):
        rt.add_callback(q, lambda ts, ins, rem: None)
    rt.start()
    for s in ("A", "B", "C", "D"):
        mgr.interner.intern(s)
    return mgr, rt


def _feed(rt, chunks=4, n=1024, base=0):
    rng = np.random.default_rng(0)
    cols = {
        "symbol": rng.integers(1, 5, n).astype(np.int32),
        "price": rng.uniform(0, 100, n).astype(np.float32),
        "volume": (np.arange(n, dtype=np.int64) * 7) % 2000,
    }
    ts = np.arange(n, dtype=np.int64) + 1_700_000_000_000 + base
    h = rt.get_input_handler("S")
    for k in range(chunks):
        h.send_columns(ts + k * n, cols, now=int(ts[-1] + k * n))


class TestSafeRatio:
    def test_plain(self):
        assert _safe_ratio(2.0, 4.0) == 0.5

    def test_both_zero_is_perfectly_priced(self):
        assert _safe_ratio(0, 0) == 1.0

    def test_zero_prediction_saturates_finite(self):
        assert _safe_ratio(3.0, 0) == 4.0

    def test_none_and_nan_unpaired(self):
        assert _safe_ratio(None, 1.0) is None
        assert _safe_ratio(float("nan"), 1.0) is None


class TestZeroOverheadGate:
    def test_no_statistics_no_ledger(self):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(
            "define stream S (a int);\n"
            "@info(name='q') from S select a insert into Out;\n"
        )
        assert rt._calibration is None
        assert rt.calibration_report() is None
        assert "no calibration-enabled apps" in mgr.calibration_text()
        mgr.shutdown()


class TestSixKindsPairing:
    def test_all_six_kinds_pair_live(self):
        mgr, rt = _boot(SENTINEL)
        _feed(rt)
        rep = rt.calibration_report()
        mgr.shutdown()
        assert rep["generation"] >= 1
        assert rep["kinds_paired"] == ALL_KINDS
        by_key = {(p["kind"], p["component"]): p for p in rep["pairs"]}
        # every paired entry carries a finite ratio + EWMA
        for p in rep["pairs"]:
            if p["live"] is not None:
                assert p["ratio"] is not None and p["ratio"] >= 0
                assert p["ratio_ewma"] is not None
        # the fused group's compile + dispatch predictions join on the
        # group component name (cost model and telemetry share it by design)
        assert (KIND_COMPILES, "stream.S.fusedgroup.0") in by_key
        disp = by_key[(KIND_DISPATCH, "stream.S.fusedgroup.0")]
        assert 0.0 < disp["live"] <= 1.0
        # wire: declared dict lane and inferred delta lane, same live split
        decl = by_key[(KIND_WIRE_DECLARED, "stream.S")]
        inf = by_key[(KIND_WIRE_INFERRED, "stream.S")]
        assert decl["live"] == inf["live"] is not None
        assert decl["live"] < 24  # narrower than the 24 B/ev logical width

    def test_state_bytes_priced_close(self):
        mgr, rt = _boot(SENTINEL)
        _feed(rt)
        rep = rt.calibration_report()
        mgr.shutdown()
        ratios = [
            p["ratio"] for p in rep["pairs"]
            if p["kind"] == KIND_STATE_BYTES and p["ratio"] is not None
        ]
        assert ratios and all(0.5 < r < 2.0 for r in ratios)


class TestMispricedWireFallback:
    def test_reason_code_on_every_surface(self):
        mgr, rt = _boot(SENTINEL)
        _feed(rt, chunks=2)
        fi = rt.junctions["S"].fused_ingest
        assert fi is not None and fi._narrow  # encodings engaged
        fi.force_full_width()
        _feed(rt, chunks=2, base=1 << 20)
        rep = rt.calibration_report()
        assert REASON_WIRE_FALLBACK in rep["flags"]
        assert any(
            m["reason"] == REASON_WIRE_FALLBACK
            and m["component"] == "stream.S"
            for m in rep["mispriced"]
        )
        # HTTP surface
        port = mgr.serve_metrics(0)

        def get(path):
            return urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=10
            ).read().decode()

        blob = json.loads(get("/calibration.json"))["SiddhiApp"]
        assert REASON_WIRE_FALLBACK in blob["flags"]
        assert "mispriced" in get("/calibration")
        # Prometheus surface
        prom = mgr.prometheus_text()
        assert "siddhi_calibration_error_ratio" in prom
        assert (
            'siddhi_calibration_mispriced_total{'
            in prom and REASON_WIRE_FALLBACK in prom
        )
        assert "siddhi_compiles_total" in prom
        # explain surface: calib lines beside static lines
        text = rt.explain()
        assert "calib:" in text
        assert REASON_WIRE_FALLBACK in text
        mgr.shutdown()


class TestChurnRepairing:
    def test_generation_bumps_and_counters_survive(self):
        mgr, rt = _boot(SENTINEL)
        _feed(rt, chunks=2)
        fi = rt.junctions["S"].fused_ingest
        fi.force_full_width()
        _feed(rt, chunks=2, base=1 << 20)
        rep1 = rt.calibration_report()
        g1 = rep1["generation"]
        assert rep1["mispriced_total"] >= 1
        qid = rt.add_query(
            "@info(name='hot') from S[price < 0] "
            "select symbol insert into OutHot;"
        )
        rep2 = rt.calibration_report()
        # the splice rebuilt the fused engine -> the ledger re-paired
        # against the NEW AST, but cumulative mispricings survived
        assert rep2["generation"] > g1
        assert rep2["mispriced_total"] >= rep1["mispriced_total"]
        assert any(
            p["component"] == "query.hot" for p in rep2["pairs"]
        )
        rt.remove_query(qid)
        rep3 = rt.calibration_report()
        assert rep3["generation"] > rep2["generation"]
        assert not any(
            p["component"] == "query.hot" for p in rep3["pairs"]
        )
        assert rep3["mispriced_total"] >= rep1["mispriced_total"]
        mgr.shutdown()


class TestByteParity:
    def _collect(self, ql):
        mgr = SiddhiManager()
        rt = mgr.create_siddhi_app_runtime(ql)
        rows = {q: [] for q in ("q1", "q2", "q3")}
        for q, acc in rows.items():
            rt.add_callback(
                q,
                lambda ts, ins, rem, _a=acc: _a.extend(
                    tuple(e.data)
                    for e in tuple(ins or ()) + tuple(rem or ())
                ),
            )
        rt.start()
        for s in ("A", "B", "C", "D"):
            mgr.interner.intern(s)
        _feed(rt)
        mgr.shutdown()
        return rows

    def test_outputs_identical_with_ledger_on_and_off(self):
        armed = self._collect(SENTINEL)
        bare = self._collect(
            SENTINEL.replace("@app:statistics(reporter='none')\n", "")
        )
        assert armed == bare
        assert any(len(v) > 0 for v in armed.values())


class TestSnapshotStatus:
    def test_calibration_section_present(self):
        mgr, rt = _boot(SENTINEL)
        _feed(rt, chunks=2)
        status = rt.snapshot_status()
        assert "calibration" in status
        assert status["calibration"]["generation"] >= 1
        mgr.shutdown()
