"""Golden corpus: incremental aggregation behaviors translated from the
reference's aggregation/AggregationTestCase.java test DATA (queries, event
sequences with event-time timestamps, expected store-query rows)."""

from __future__ import annotations

from siddhi_tpu import SiddhiManager

STOCK = (
    "define stream stockStream (symbol string, price float, "
    "lastClosingPrice float, volume long , quantity int, timestamp long);"
)

SENDS = [
    ("WSO2", 50.0, 60.0, 90, 6, 1496289950000),
    ("WSO2", 70.0, None, 40, 10, 1496289950000),
    ("WSO2", 60.0, 44.0, 200, 56, 1496289952000),
    ("WSO2", 100.0, None, 200, 16, 1496289952000),
    ("IBM", 100.0, None, 200, 26, 1496289954000),
    ("IBM", 100.0, None, 200, 96, 1496289954000),
]


def test_aggregation_test5_seconds_within_wildcard():
    """incrementalStreamProcessorTest5: group-by sec...hour aggregation,
    store query with wildcard within + per seconds -> exact rows."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(STOCK + """
    define aggregation stockAggregation
    from stockStream
    select symbol, avg(price) as avgPrice, sum(price) as totalPrice,
           (price * quantity) as lastTradeValue
    group by symbol
    aggregate by timestamp every sec...hour ;
    """)
    rt.start()
    h = rt.get_input_handler("stockStream")
    for row in SENDS:
        h.send(row)
    events = rt.query(
        'from stockAggregation within "2017-06-** **:**:**" per "seconds"'
    )
    rows = sorted(tuple(e.data) for e in events)
    assert rows == sorted([
        (1496289952000, "WSO2", 80.0, 160.0, 1600.0),
        (1496289950000, "WSO2", 60.0, 120.0, 700.0),
        (1496289954000, "IBM", 100.0, 200.0, 9600.0),
    ])
    rt.shutdown()
    mgr.shutdown()


def test_aggregation_test6_join_within_per_variables():
    """incrementalStreamProcessorTest6 shape: a stream joins the aggregation
    with within/per taken from the driving event, ordered by AGG_TIMESTAMP."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(STOCK + """
    define aggregation stockAggregation
    from stockStream
    select symbol, avg(price) as avgPrice, sum(price) as totalPrice,
           (price * quantity) as lastTradeValue
    group by symbol
    aggregate by timestamp every sec...year ;
    define stream inputStream (symbol string, value int, startTime string,
    endTime string, perValue string);
    @info(name = 'query1')
    from inputStream as i join stockAggregation as s
    within "2017-06-01 04:05:50", "2017-06-01 05:07:57"
    per "seconds"
    select s.symbol, avgPrice, totalPrice as sumPrice, lastTradeValue
    order by sumPrice
    insert all events into outputStream;
    """)
    got = []
    rt.add_callback(
        "query1", lambda ts, ins, rem: got.extend(tuple(e.data) for e in ins or [])
    )
    rt.start()
    h = rt.get_input_handler("stockStream")
    for row in SENDS:
        h.send(row)
    rt.get_input_handler("inputStream").send(
        ("IBM", 1, "2017-06-01 04:05:50", "2017-06-01 05:07:57", "seconds")
    )
    rt.shutdown()
    mgr.shutdown()
    assert sorted(got) == sorted([
        ("WSO2", 80.0, 160.0, 1600.0),
        ("WSO2", 60.0, 120.0, 700.0),
        ("IBM", 100.0, 200.0, 9600.0),
    ])


def test_aggregation_minute_rollup():
    """Coarser-duration read (per minutes) rolls the three second-buckets up
    into one minute bucket per group (reference: test5 family with
    per 'minutes' reads — sums add, avgs re-derive, last wins)."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(STOCK + """
    define aggregation stockAggregation
    from stockStream
    select symbol, avg(price) as avgPrice, sum(price) as totalPrice
    group by symbol
    aggregate by timestamp every sec...hour ;
    """)
    rt.start()
    h = rt.get_input_handler("stockStream")
    for row in SENDS:
        h.send(row)
    events = rt.query(
        'from stockAggregation within "2017-06-** **:**:**" per "minutes"'
    )
    rows = sorted(tuple(e.data) for e in events)
    # 1496289950000 // 60000 * 60000 == 1496289900000 for every send
    assert rows == sorted([
        (1496289900000, "WSO2", 70.0, 280.0),
        (1496289900000, "IBM", 100.0, 200.0),
    ])
    rt.shutdown()
    mgr.shutdown()
