"""Golden corpus: incremental aggregation behaviors translated from the
reference's aggregation/AggregationTestCase.java test DATA (queries, event
sequences with event-time timestamps, expected store-query rows)."""

from __future__ import annotations

from siddhi_tpu import SiddhiManager

STOCK = (
    "define stream stockStream (symbol string, price float, "
    "lastClosingPrice float, volume long , quantity int, timestamp long);"
)

SENDS = [
    ("WSO2", 50.0, 60.0, 90, 6, 1496289950000),
    ("WSO2", 70.0, None, 40, 10, 1496289950000),
    ("WSO2", 60.0, 44.0, 200, 56, 1496289952000),
    ("WSO2", 100.0, None, 200, 16, 1496289952000),
    ("IBM", 100.0, None, 200, 26, 1496289954000),
    ("IBM", 100.0, None, 200, 96, 1496289954000),
]


def test_aggregation_test5_seconds_within_wildcard():
    """incrementalStreamProcessorTest5: group-by sec...hour aggregation,
    store query with wildcard within + per seconds -> exact rows."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(STOCK + """
    define aggregation stockAggregation
    from stockStream
    select symbol, avg(price) as avgPrice, sum(price) as totalPrice,
           (price * quantity) as lastTradeValue
    group by symbol
    aggregate by timestamp every sec...hour ;
    """)
    rt.start()
    h = rt.get_input_handler("stockStream")
    for row in SENDS:
        h.send(row)
    events = rt.query(
        'from stockAggregation within "2017-06-** **:**:**" per "seconds"'
    )
    rows = sorted(tuple(e.data) for e in events)
    assert rows == sorted([
        (1496289952000, "WSO2", 80.0, 160.0, 1600.0),
        (1496289950000, "WSO2", 60.0, 120.0, 700.0),
        (1496289954000, "IBM", 100.0, 200.0, 9600.0),
    ])
    rt.shutdown()
    mgr.shutdown()


def test_aggregation_test6_join_within_per_variables():
    """incrementalStreamProcessorTest6 shape: a stream joins the aggregation
    with within/per taken from the driving event, ordered by AGG_TIMESTAMP."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(STOCK + """
    define aggregation stockAggregation
    from stockStream
    select symbol, avg(price) as avgPrice, sum(price) as totalPrice,
           (price * quantity) as lastTradeValue
    group by symbol
    aggregate by timestamp every sec...year ;
    define stream inputStream (symbol string, value int, startTime string,
    endTime string, perValue string);
    @info(name = 'query1')
    from inputStream as i join stockAggregation as s
    within "2017-06-01 04:05:50", "2017-06-01 05:07:57"
    per "seconds"
    select s.symbol, avgPrice, totalPrice as sumPrice, lastTradeValue
    order by sumPrice
    insert all events into outputStream;
    """)
    got = []
    rt.add_callback(
        "query1", lambda ts, ins, rem: got.extend(tuple(e.data) for e in ins or [])
    )
    rt.start()
    h = rt.get_input_handler("stockStream")
    for row in SENDS:
        h.send(row)
    rt.get_input_handler("inputStream").send(
        ("IBM", 1, "2017-06-01 04:05:50", "2017-06-01 05:07:57", "seconds")
    )
    rt.shutdown()
    mgr.shutdown()
    assert sorted(got) == sorted([
        ("WSO2", 80.0, 160.0, 1600.0),
        ("WSO2", 60.0, 120.0, 700.0),
        ("IBM", 100.0, 200.0, 9600.0),
    ])


def test_aggregation_minute_rollup():
    """Coarser-duration read (per minutes) rolls the three second-buckets up
    into one minute bucket per group (reference: test5 family with
    per 'minutes' reads — sums add, avgs re-derive, last wins)."""
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(STOCK + """
    define aggregation stockAggregation
    from stockStream
    select symbol, avg(price) as avgPrice, sum(price) as totalPrice
    group by symbol
    aggregate by timestamp every sec...hour ;
    """)
    rt.start()
    h = rt.get_input_handler("stockStream")
    for row in SENDS:
        h.send(row)
    events = rt.query(
        'from stockAggregation within "2017-06-** **:**:**" per "minutes"'
    )
    rows = sorted(tuple(e.data) for e in events)
    # 1496289950000 // 60000 * 60000 == 1496289900000 for every send
    assert rows == sorted([
        (1496289900000, "WSO2", 70.0, 280.0),
        (1496289900000, "IBM", 100.0, 200.0),
    ])
    rt.shutdown()
    mgr.shutdown()


# --- round-5 additions: AggregationTestCase 1-4, 20, 23-24, 26-35 ----------

STOCK2 = (
    "define stream stockStream (symbol string, price float, "
    "lastClosingPrice float, volume long , quantity int, timestamp long);"
)

SENDS_CISCO = [
    ("WSO2", 50.0, 60.0, 90, 6, 1496289950000),
    ("WSO2", 70.0, None, 40, 10, 1496289950000),
    ("WSO2", 60.0, 44.0, 200, 56, 1496289952000),
    ("WSO2", 100.0, None, 200, 16, 1496289952000),
    ("IBM", 100.0, None, 200, 26, 1496289954000),
    ("IBM", 100.0, None, 200, 96, 1496289954000),
    ("CISCO", 100.0, None, 200, 26, 1513578087000),
    ("CISCO", 100.0, None, 200, 96, 1513578087000),
]

AGG_HOUR = STOCK2 + """
define aggregation stockAggregation
from stockStream
select symbol, avg(price) as avgPrice, sum(price) as totalPrice,
       (price * quantity) as lastTradeValue
group by symbol
aggregate by timestamp every sec...hour ;
"""


def _agg_runtime(ql=AGG_HOUR, sends=SENDS_CISCO):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    rt.start()
    h = rt.get_input_handler("stockStream")
    for row in sends:
        h.send(row)
    return mgr, rt


def test_agg1_creation_arrival_range():
    # incrementalStreamProcessorTest1: sec ... min by an explicit attribute
    mgr = SiddhiManager()
    mgr.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, price float,"
        " volume int); define aggregation stockAggregation from stockStream"
        " select sum(price) as sumPrice aggregate by arrival every sec ... min"
    )


def test_agg2_creation_event_time_range():
    # test2: range form without an explicit timestamp attribute
    mgr = SiddhiManager()
    mgr.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, price float,"
        " volume int); define aggregation stockAggregation from stockStream"
        " select sum(price) as sumPrice aggregate every sec ... min"
    )


def test_agg3_creation_duration_list():
    # test3: explicit duration list + group by
    mgr = SiddhiManager()
    mgr.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, price float,"
        " volume int); define aggregation stockAggregation from stockStream"
        " select sum(price) as sumPrice group by price"
        " aggregate every sec, min, hour, day"
    )


def test_agg4_creation_composite_group():
    # test4: composite group-by key
    mgr = SiddhiManager()
    mgr.create_siddhi_app_runtime(
        "define stream stockStream (arrival long, symbol string, price float,"
        " volume int); define aggregation stockAggregation from stockStream"
        " select sum(price) as sumPrice group by price, volume"
        " aggregate every sec, min, hour, day"
    )


def test_agg23_store_query_on_condition():
    # test23: on-filter + within wildcard + projection
    mgr, rt = _agg_runtime(sends=SENDS_CISCO[:6])
    events = rt.query(
        'from stockAggregation on symbol=="IBM" '
        'within "2017-06-** **:**:**" per "seconds" select symbol, avgPrice'
    )
    rows = [tuple(e.data) for e in events]
    assert rows == [("IBM", 100.0)], rows
    rt.shutdown()
    mgr.shutdown()


def test_agg24_store_query_all_groups():
    # test24: three second-buckets across the two symbols
    mgr, rt = _agg_runtime(sends=SENDS_CISCO[:6])
    events = rt.query(
        'from stockAggregation within "2017-06-** **:**:**" per "seconds"'
    )
    assert len(events) == 3, [tuple(e.data) for e in events]
    rt.shutdown()
    mgr.shutdown()


def test_agg27_numeric_per_rejected():
    # test27: `per 1000` is not a duration string
    import pytest

    mgr, rt = _agg_runtime(sends=[])
    with pytest.raises(Exception):
        rt.query('from stockAggregation within "2017-06-** **:**:**" per 1000')
    rt.shutdown()
    mgr.shutdown()


def test_agg28_inverted_within_rejected():
    # test28: start after end
    import pytest

    mgr, rt = _agg_runtime(sends=[])
    with pytest.raises(Exception):
        rt.query(
            'from stockAggregation within "2017-06-02 00:00:00", '
            '"2017-06-01 00:00:00" per "hours"'
        )
    rt.shutdown()
    mgr.shutdown()


def test_agg29_malformed_within_rejected():
    # test29: bad wildcard pattern
    import pytest

    mgr, rt = _agg_runtime(sends=[])
    with pytest.raises(Exception):
        rt.query(
            'from stockAggregation within "2017-06-** **:**:**:1000" '
            'per "hours"'
        )
    rt.shutdown()
    mgr.shutdown()


def test_agg30_partial_wildcard_rejected():
    # test30: wildcards below a fixed field
    import pytest

    mgr, rt = _agg_runtime(sends=[])
    with pytest.raises(Exception):
        rt.query(
            'from stockAggregation within "2017-06-** 12:**:**" per "hours"'
        )
    rt.shutdown()
    mgr.shutdown()


def test_agg31_select_star_four_buckets():
    # test31: select * over every second bucket (4 across 3 symbols)
    mgr, rt = _agg_runtime()
    events = rt.query(
        'from stockAggregation within "2017-**-** **:**:**" per "seconds" '
        "select *"
    )
    rows = sorted(tuple(e.data) for e in events)
    assert rows == sorted([
        (1496289950000, "WSO2", 60.0, 120.0, 700.0),
        (1496289952000, "WSO2", 80.0, 160.0, 1600.0),
        (1496289954000, "IBM", 100.0, 200.0, 9600.0),
        (1513578087000, "CISCO", 100.0, 200.0, 9600.0),
    ]), rows
    rt.shutdown()
    mgr.shutdown()


def test_agg32_day_wildcard():
    # test32: a whole-day wildcard matches only CISCO's bucket
    mgr, rt = _agg_runtime()
    events = rt.query(
        'from stockAggregation within "2017-12-18 **:**:**" per "seconds" '
        "select *"
    )
    rows = [tuple(e.data) for e in events]
    assert rows == [(1513578087000, "CISCO", 100.0, 200.0, 9600.0)], rows
    rt.shutdown()
    mgr.shutdown()


def test_agg33_hour_wildcard():
    # test33: hour-level wildcard (06 UTC == 11:51 +05:30)
    mgr, rt = _agg_runtime()
    events = rt.query(
        'from stockAggregation within "2017-12-18 06:**:**" per "seconds" '
        "select *"
    )
    rows = [tuple(e.data) for e in events]
    assert rows == [(1513578087000, "CISCO", 100.0, 200.0, 9600.0)], rows
    rt.shutdown()
    mgr.shutdown()


def test_agg34_minute_wildcard():
    # test34: minute-level wildcard
    mgr, rt = _agg_runtime()
    events = rt.query(
        'from stockAggregation within "2017-12-18 06:21:**" per "seconds" '
        "select *"
    )
    rows = [tuple(e.data) for e in events]
    assert rows == [(1513578087000, "CISCO", 100.0, 200.0, 9600.0)], rows
    rt.shutdown()
    mgr.shutdown()
