"""Golden corpus: filter queries, data-driven from the reference's filter test
corpus (see tests/golden_filter_data.py). Each case runs the reference's exact
condition over its exact input rows and checks the match count."""

import pytest

from siddhi_tpu import SiddhiManager

from tests.golden_filter_data import CASES


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_filter_golden(case):
    name, schema, cond, sel, rows, expected = case
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(f"""
    define stream cseEventStream ({schema});
    @info(name = 'query1')
    from cseEventStream[{cond}]
    select {sel}
    insert into outputStream;
    """)
    got = []
    rt.add_callback("query1", lambda ts, i, r: got.extend(i or []))
    rt.start()
    h = rt.get_input_handler("cseEventStream")
    for row in rows:
        h.send(row)
    rt.shutdown()
    mgr.shutdown()
    assert len(got) == expected, (name, cond, [tuple(e.data) for e in got])
