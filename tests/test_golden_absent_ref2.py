"""Golden corpus: the reference's AbsentPatternTestCase (tests 1-42) and
EveryAbsentPatternTestCase (tests 1-49), full files.

Data-level translation (query strings, event sequences, expected outputs are
the reference's own) from
siddhi-core/src/test/java/org/wso2/siddhi/core/query/pattern/absent/ —
wall-clock sleeps become explicit `@app:playback` timestamps; where a
trailing sleep lets a deadline fire, an inert clock-advance event stands in.
AbsentPatternTestCase test43 (partitioned) is covered by the partitioned
case in test_golden_logical_absent_ref.py.
"""

from __future__ import annotations

import pytest

from siddhi_tpu import SiddhiManager

HEAD = """@app:playback @app:batch(size='8')
define stream Stream1 (symbol string, price float, volume int);
define stream Stream2 (symbol string, price float, volume int);
define stream Stream3 (symbol string, price float, volume int);
define stream Stream4 (symbol string, price float, volume int);
"""

S1, S2, S3, S4 = "Stream1", "Stream2", "Stream3", "Stream4"


def run_pb(ql, steps, query_name="query1"):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(HEAD + ql)
    got = []
    rt.add_callback(
        query_name,
        lambda ts, i, r: got.extend(tuple(e.data) for e in i or []),
    )
    rt.start()
    hs = {}
    for ts, stream, row in steps:
        if stream == "adv":
            stream, row = S1, ("ZZZ", 1.0, 0)
        hs.setdefault(stream, rt.get_input_handler(stream)).send(
            row, timestamp=ts
        )
    rt.shutdown()
    mgr.shutdown()
    return got


Q_AP_A = """@info(name = 'query1')
from e1=Stream1[price>20] -> not Stream2[price>e1.price] for 1 sec
select e1.symbol as symbol1 insert into OutputStream;"""
Q_AP_B = """@info(name = 'query1')
from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
select e2.symbol as symbol insert into OutputStream;"""
Q_AP_C = """@info(name = 'query1')
from e1=Stream1[price>10] -> e2=Stream2[price>20] -> not Stream3[price>30] for 1 sec
select e1.symbol as symbol1, e2.symbol as symbol2 insert into OutputStream;"""
Q_AP_D = """@info(name = 'query1')
from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec -> e3=Stream3[price>30]
select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;"""
Q_AP_E = """@info(name = 'query1')
from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20] -> e3=Stream3[price>30]
select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""
Q_AP_F = """@info(name = 'query1')
from e1=Stream1[price>10] -> e2=Stream2[price>20] -> e3=Stream3[price>30] -> not Stream4[price>40] for 1 sec
select e1.symbol as symbol1, e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""
Q_AP_G = """@info(name = 'query1')
from e1=Stream1[price>10] -> e2=Stream2[price>20] -> not Stream3[price>30] for 1 sec -> e4=Stream4[price>40]
select e1.symbol as symbol1, e2.symbol as symbol2, e4.symbol as symbol4 insert into OutputStream;"""
Q_AP_H = """@info(name = 'query1')
from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20] -> e3=Stream3[price>30] -> e4=Stream4[price>40]
select e2.symbol as symbol2, e3.symbol as symbol3, e4.symbol as symbol4 insert into OutputStream;"""
Q_AP_I = """@info(name = 'query1')
from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20] -> not Stream3[price>30] for 1 sec -> e4=Stream4[price>40]
select e2.symbol as symbol2, e4.symbol as symbol4 insert into OutputStream;"""
Q_AP_AND = """@info(name = 'query1')
from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec -> e2=Stream3[price>30] and e3=Stream4[price>40]
select e1.symbol as symbol1, e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""
Q_AP_OR = """@info(name = 'query1')
from e1=Stream1[price>10] -> not Stream2[price>20] for 1 sec -> e2=Stream3[price>30] or e3=Stream4[price>40]
select e1.symbol as symbol1, e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""
Q_AP_CNT = """@info(name = 'query1')
from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]<2:5>
select e2[0].symbol as symbol0, e2[1].symbol as symbol1, e2[2].symbol as symbol2, e2[3].symbol as symbol3
insert into OutputStream;"""

AP = {
    "ap1": (Q_AP_A, [(0, S1, ("WSO2", 55.6, 100)), (1100, "adv", None)],
            [("WSO2",)], 1),
    "ap2": (Q_AP_A, [(0, S1, ("WSO2", 55.6, 100)),
                     (1100, S2, ("IBM", 58.7, 100))], [("WSO2",)], 1),
    "ap3": (Q_AP_A, [(0, S1, ("WSO2", 55.6, 100)),
                     (100, S2, ("IBM", 58.7, 100)), (1100, "adv", None)],
            [], 0),
    "ap4": (Q_AP_A, [(0, S1, ("WSO2", 55.6, 100)),
                     (100, S2, ("IBM", 50.7, 100)), (1200, "adv", None)],
            [("WSO2",)], 1),
    "ap5": (Q_AP_B, [(1100, S2, ("IBM", 58.7, 100))], [("IBM",)], 1),
    "ap6": (Q_AP_B, [(100, S1, ("WSO2", 59.6, 100)),
                     (2200, S2, ("IBM", 58.7, 100))], [("IBM",)], 1),
    "ap7": (Q_AP_B, [(0, S1, ("WSO2", 5.6, 100)),
                     (100, S2, ("IBM", 58.7, 100))], [], 0),
    "ap8": (Q_AP_B, [(0, S1, ("WSO2", 55.6, 100)),
                     (100, S2, ("IBM", 58.7, 100))], [], 0),
    "ap9": (Q_AP_C, [(0, S1, ("WSO2", 15.6, 100)),
                     (100, S2, ("IBM", 28.7, 100)),
                     (200, S3, ("GOOGLE", 55.7, 100)), (1300, "adv", None)],
            [], 0),
    "ap10": (Q_AP_C, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 25.7, 100)), (1300, "adv", None)],
             [("WSO2", "IBM")], 1),
    "ap11": (Q_AP_C, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)), (1200, "adv", None)],
             [("WSO2", "IBM")], 1),
    "ap12": (Q_AP_D, [(0, S1, ("WSO2", 15.6, 100)),
                      (1100, S3, ("GOOGLE", 55.7, 100))],
             [("WSO2", "GOOGLE")], 1),
    "ap13": (Q_AP_D, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 8.7, 100)),
                      (1200, S3, ("GOOGLE", 55.7, 100))],
             [("WSO2", "GOOGLE")], 1),
    "ap14": (Q_AP_D, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 55.7, 100))], [], 0),
    "ap15": (Q_AP_E, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 55.7, 100))], [], 0),
    "ap16": (Q_AP_E, [(2100, S2, ("IBM", 28.7, 100)),
                      (2200, S3, ("GOOGLE", 55.7, 100))],
             [("IBM", "GOOGLE")], 1),
    "ap17": (Q_AP_E, [(500, S1, ("WSO2", 5.6, 100)),
                      (1100, S2, ("IBM", 28.7, 100)),
                      (1200, S3, ("GOOGLE", 55.7, 100))],
             [("IBM", "GOOGLE")], 1),
    "ap18": (Q_AP_E, [(0, S1, ("WSO2", 25.6, 100)),
                      (1100, S2, ("IBM", 28.7, 100)),
                      (1200, S3, ("GOOGLE", 55.7, 100))],
             [("IBM", "GOOGLE")], 1),
    "ap19": (Q_AP_F, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 35.7, 100)), (1300, "adv", None)],
             [("WSO2", "IBM", "GOOGLE")], 1),
    "ap20": (Q_AP_F, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 35.7, 100)),
                      (300, S4, ("ORACLE", 44.7, 100)), (1400, "adv", None)],
             [], 0),
    "ap21": (Q_AP_G, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (1200, S4, ("ORACLE", 44.7, 100))],
             [("WSO2", "IBM", "ORACLE")], 1),
    "ap22": (Q_AP_G, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 38.7, 100)),
                      (1300, S4, ("ORACLE", 44.7, 100))], [], 0),
    "ap23": (Q_AP_H, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 38.7, 100)),
                      (300, S4, ("ORACLE", 44.7, 100))], [], 0),
    "ap24": (Q_AP_I, [(1100, S2, ("IBM", 28.7, 100)),
                      (2200, S4, ("ORACLE", 44.7, 100))],
             [("IBM", "ORACLE")], 1),
    "ap25": (Q_AP_I, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 38.7, 100)),
                      (300, S4, ("ORACLE", 44.7, 100))], [], 0),
    "ap26": (Q_AP_I, [(0, S2, ("IBM", 28.7, 100)),
                      (100, S3, ("GOOGLE", 38.7, 100)),
                      (200, S4, ("ORACLE", 44.7, 100))], [], 0),
    "ap27": (Q_AP_B, [(0, S2, ("IBM", 58.7, 100))], [], 0),
    "ap28": (Q_AP_AND, [(0, S1, ("IBM", 18.7, 100)),
                        (1100, S3, ("WSO2", 35.0, 100)),
                        (1200, S4, ("GOOGLE", 56.86, 100))],
             [("IBM", "WSO2", "GOOGLE")], 1),
    "ap29": (Q_AP_AND, [(0, S1, ("IBM", 18.7, 100)),
                        (100, S3, ("WSO2", 35.0, 100)),
                        (200, S4, ("GOOGLE", 56.86, 100))], [], 0),
    "ap30": (Q_AP_OR, [(0, S1, ("IBM", 18.7, 100)),
                       (1100, S3, ("WSO2", 35.0, 100))],
             [("IBM", "WSO2", None)], 1),
    "ap31": (Q_AP_OR, [(0, S1, ("IBM", 18.7, 100)),
                       (1100, S4, ("GOOGLE", 56.86, 100))],
             [("IBM", None, "GOOGLE")], 1),
    "ap32": (Q_AP_OR, [(0, S1, ("IBM", 18.7, 100)),
                       (100, S3, ("WSO2", 35.0, 100)),
                       (200, S4, ("GOOGLE", 56.86, 100))], [], 0),
    "ap33": (Q_AP_AND, [(0, S1, ("IBM", 18.7, 100)),
                        (100, S2, ("ORACLE", 25.0, 100)),
                        (200, S3, ("WSO2", 35.0, 100)),
                        (300, S4, ("GOOGLE", 56.86, 100))], [], 0),
    "ap34": (Q_AP_OR, [(0, S1, ("IBM", 18.7, 100)),
                       (100, S2, ("ORACLE", 25.0, 100)),
                       (200, S3, ("WSO2", 35.0, 100)),
                       (300, S4, ("GOOGLE", 56.86, 100))], [], 0),
    "ap35": (Q_AP_CNT, [(0, S1, ("WSO2", 15.0, 100)),
                        (100, S2, ("GOOGLE", 35.0, 100)),
                        (200, S2, ("ORACLE", 45.0, 100))], [], 0),
    "ap36": (Q_AP_CNT, [(1100, S2, ("WSO2", 35.0, 100)),
                        (1200, S2, ("IBM", 45.0, 100))],
             [("WSO2", "IBM", None, None)], 1),
    "ap37": (Q_AP_B.replace("price>30", "price>30"),
             [(2100, S2, ("WSO2", 35.0, 100)), (2200, S2, ("IBM", 45.0, 100))],
             [("WSO2",)], 1),
    "ap38": (Q_AP_D, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (1200, S3, ("GOOGLE", 55.7, 100))], [], 0),
    "ap39": (Q_AP_OR, [(0, S1, ("IBM", 18.7, 100)),
                       (100, S2, ("WSO2", 25.5, 100)),
                       (1200, S4, ("GOOGLE", 56.86, 100))], [], 0),
    "ap40": (Q_AP_B, [(1100, S2, ("IBM", 58.7, 100)),
                      (2300, S2, ("WSO2", 68.7, 100))], [("IBM",)], 1),
    "ap42": ("""@info(name = 'query1')
        from not Stream1[price>20] for 1 sec -> e2=Stream2[price>30] within 2 sec
        select e2.symbol as symbol insert into OutputStream;""",
             [(3100, S2, ("IBM", 58.7, 100))], [], 0),
}

Q_EA_A = """@info(name = 'query1')
from e1=Stream1[price>20] -> every not Stream2[price>e1.price] for 1 sec
select e1.symbol as symbol1 insert into OutputStream;"""
Q_EA_B = """@info(name = 'query1')
from every not Stream1[price>20] for 1 sec -> e2=Stream2[price>30]
select e2.symbol as symbol insert into OutputStream;"""
Q_EA_C = """@info(name = 'query1')
from e1=Stream1[price>10] -> e2=Stream2[price>20] -> every not Stream3[price>30] for 1 sec
select e1.symbol as symbol1, e2.symbol as symbol2 insert into OutputStream;"""
Q_EA_D = """@info(name = 'query1')
from e1=Stream1[price>10] -> every not Stream2[price>20] for 1 sec -> e3=Stream3[price>30]
select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;"""
Q_EA_E = """@info(name = 'query1')
from every not Stream1[price>10] for 1 sec -> e2=Stream2[price>20] -> e3=Stream3[price>30]
select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""
Q_EA_F = """@info(name = 'query1')
from e1=Stream1[price>10] -> e2=Stream2[price>20] -> e3=Stream3[price>30] -> every not Stream4[price>40] for 1 sec
select e1.symbol as symbol1, e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""
Q_EA_G = """@info(name = 'query1')
from e1=Stream1[price>10] -> e2=Stream2[price>20] -> every not Stream3[price>30] for 1 sec -> e4=Stream4[price>40]
select e1.symbol as symbol1, e2.symbol as symbol2, e4.symbol as symbol4 insert into OutputStream;"""
Q_EA_I = """@info(name = 'query1')
from not Stream1[price>10] for 1 sec -> e2=Stream2[price>20] -> every not Stream3[price>30] for 1 sec -> e4=Stream4[price>40]
select e2.symbol as symbol2, e4.symbol as symbol4 insert into OutputStream;"""
Q_EA_AND = """@info(name = 'query1')
from e1=Stream1[price>10] -> every not Stream2[price>20] for 1 sec -> e2=Stream3[price>30] and e3=Stream4[price>40]
select e1.symbol as symbol1, e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""
Q_EA_OR = """@info(name = 'query1')
from e1=Stream1[price>10] -> every not Stream2[price>20] for 1 sec -> e2=Stream3[price>30] or e3=Stream4[price>40]
select e1.symbol as symbol1, e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""
Q_EA_CNT = """@info(name = 'query1')
from every not Stream1[price>10] for 1 sec -> e2=Stream2[price>20]<2:5>
select e2[0].symbol as symbol0, e2[1].symbol as symbol1, e2[2].symbol as symbol2, e2[3].symbol as symbol3
insert into OutputStream;"""
Q_EA_LOG1 = """@info(name = 'query1')
from e1=Stream1[price>10] -> every (not Stream2[price>20] and e3=Stream3[price>30])
select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;"""
Q_EA_LOG2 = """@info(name = 'query1')
from every (not Stream1[price>10] and e2=Stream2[price>20]) -> e3=Stream3[price>30]
select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""
Q_EA_LOG3 = """@info(name = 'query1')
from e1=Stream1[price>10] -> every (not Stream2[price>20] for 1 sec and e3=Stream3[price>30])
select e1.symbol as symbol1, e3.symbol as symbol3 insert into OutputStream;"""
Q_EA_LOG4 = """@info(name = 'query1')
from every (not Stream1[price>10] for 1 sec and e2=Stream2[price>20]) -> e3=Stream3[price>30]
select e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;"""

EA = {
    "ea1": (Q_EA_A, [(0, S1, ("WSO2", 55.6, 100)), (3200, "adv", None)],
            [("WSO2",), ("WSO2",), ("WSO2",)], 3),
    "ea2": ("""@info(name = 'query1')
        from (e1=Stream1[price>20] -> every not Stream2[price>e1.price] for 900 milliseconds) within 2 sec
        select e1.symbol as symbol1 insert into OutputStream;""",
            [(0, S1, ("WSO2", 55.6, 100)), (3200, "adv", None)],
            [("WSO2",), ("WSO2",)], 2),
    "ea4": (Q_EA_A, [(0, S1, ("WSO2", 55.6, 100)),
                     (2100, S2, ("IBM", 58.7, 100)), (3200, "adv", None)],
            [("WSO2",), ("WSO2",)], None),
    "ea5": (Q_EA_B, [(2100, S2, ("IBM", 58.7, 100)), (3200, "adv", None)],
            [("IBM",), ("IBM",)], 2),
    "ea7": (Q_EA_A, [(0, S1, ("WSO2", 55.6, 100)),
                     (100, S2, ("IBM", 50.7, 100)), (2200, "adv", None)],
            [("WSO2",), ("WSO2",)], None),
    "ea8": (Q_EA_B, [(2200, S2, ("IBM", 58.7, 100)), (3300, "adv", None)],
            [("IBM",), ("IBM",)], 2),
    "ea9": (Q_EA_B, [(0, S1, ("WSO2", 59.6, 100)),
                     (2100, S2, ("IBM", 58.7, 100))],
            [("IBM",)], None),
    "ea10": (Q_EA_B, [(0, S1, ("WSO2", 25.6, 100)),
                      (500, S1, ("WSO2", 25.6, 100)),
                      (1000, S1, ("WSO2", 25.6, 100)),
                      (1500, S2, ("IBM", 58.7, 100))], [], 0),
    "ea11": (Q_EA_B, [(0, S1, ("WSO2", 55.6, 100)),
                      (100, S2, ("IBM", 58.7, 100))], [], 0),
    "ea13": (Q_EA_C, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (700, S3, ("GOOGLE", 25.7, 100)), (3200, "adv", None)],
             [("WSO2", "IBM")], None),
    "ea14": (Q_EA_C, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)), (2200, "adv", None)],
             [("WSO2", "IBM"), ("WSO2", "IBM")], 2),
    "ea15": (Q_EA_D, [(0, S1, ("WSO2", 15.6, 100)),
                      (2100, S3, ("GOOGLE", 55.7, 100)), (3200, "adv", None)],
             [("WSO2", "GOOGLE"), ("WSO2", "GOOGLE")], 2),
    "ea16": (Q_EA_D, [(0, S1, ("WSO2", 15.6, 100)),
                      (1000, S2, ("IBM", 8.7, 100)),
                      (2100, S3, ("GOOGLE", 55.7, 100))],
             [("WSO2", "GOOGLE"), ("WSO2", "GOOGLE")], 2),
    "ea18": (Q_EA_E, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 55.7, 100))], [], 0),
    "ea19": (Q_EA_E, [(2100, S2, ("IBM", 28.7, 100)),
                      (2200, S3, ("GOOGLE", 55.7, 100))],
             [("IBM", "GOOGLE"), ("IBM", "GOOGLE")], 2),
    "ea20": (Q_EA_E, [(500, S1, ("WSO2", 5.6, 100)),
                      (1100, S2, ("IBM", 28.7, 100)),
                      (1200, S3, ("GOOGLE", 55.7, 100))],
             [("IBM", "GOOGLE")], 1),
    "ea21": (Q_EA_E, [(0, S1, ("WSO2", 25.6, 100)),
                      (2100, S2, ("IBM", 28.7, 100)),
                      (2200, S3, ("GOOGLE", 55.7, 100))],
             [("IBM", "GOOGLE"), ("IBM", "GOOGLE")], 2),
    "ea22": (Q_EA_F, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 35.7, 100)), (2300, "adv", None)],
             [("WSO2", "IBM", "GOOGLE"), ("WSO2", "IBM", "GOOGLE")], 2),
    "ea23": ("""@info(name = 'query1')
        from (e1=Stream1[price>10] -> e2=Stream2[price>20] -> e3=Stream3[price>30] -> every not Stream4[price>40] for 1 sec) within 2 sec
        select e1.symbol as symbol1, e2.symbol as symbol2, e3.symbol as symbol3 insert into OutputStream;""",
             [(0, S1, ("WSO2", 15.6, 100)), (100, S2, ("IBM", 28.7, 100)),
              (1200, S3, ("GOOGLE", 35.7, 100)),
              (1300, S4, ("ORACLE", 44.7, 100)), (2400, "adv", None)],
             [], 0),
    "ea24": (Q_EA_G, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (2200, S4, ("ORACLE", 44.7, 100))],
             [("WSO2", "IBM", "ORACLE"), ("WSO2", "IBM", "ORACLE")], 2),
    "ea25": (Q_EA_G, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (1200, S3, ("GOOGLE", 38.7, 100)),
                      (2300, S4, ("ORACLE", 44.7, 100))],
             [("WSO2", "IBM", "ORACLE")], 1),
    "ea26": (Q_EA_E.replace(
        "-> e3=Stream3[price>30]",
        "-> e3=Stream3[price>30] -> e4=Stream4[price>40]").replace(
        "e3.symbol as symbol3",
        "e3.symbol as symbol3, e4.symbol as symbol4"),
        [(0, S1, ("WSO2", 15.6, 100)), (100, S2, ("IBM", 28.7, 100)),
         (200, S3, ("GOOGLE", 38.7, 100)), (300, S4, ("ORACLE", 44.7, 100))],
        [], 0),
    "ea27": (Q_EA_I, [(1100, S2, ("IBM", 28.7, 100)),
                      (3200, S4, ("ORACLE", 44.7, 100))],
             [("IBM", "ORACLE"), ("IBM", "ORACLE")], 2),
    "ea28": (Q_EA_I, [(0, S1, ("WSO2", 15.6, 100)),
                      (100, S2, ("IBM", 28.7, 100)),
                      (200, S3, ("GOOGLE", 38.7, 100)),
                      (300, S4, ("ORACLE", 44.7, 100))], [], 0),
    "ea29": (Q_EA_I, [(0, S2, ("IBM", 28.7, 100)),
                      (100, S3, ("GOOGLE", 38.7, 100)),
                      (200, S4, ("ORACLE", 44.7, 100))], [], 0),
    "ea30": (Q_EA_B, [(0, S2, ("IBM", 58.7, 100))], [], 0),
    "ea31": (Q_EA_CNT, [(0, S1, ("WSO2", 15.0, 100)),
                        (100, S2, ("GOOGLE", 35.0, 100)),
                        (200, S2, ("ORACLE", 45.0, 100))], [], 0),
    "ea32": (Q_EA_CNT, [(2100, S2, ("WSO2", 35.0, 100)),
                        (2200, S2, ("IBM", 45.0, 100))],
             [("WSO2", "IBM", None, None), ("WSO2", "IBM", None, None)], 2),
    "ea33": (Q_EA_B.replace("price>20", "price>10").replace(
        "price>30", "price>20"),
        [(2100, S2, ("WSO2", 35.0, 100)), (2200, S2, ("IBM", 45.0, 100))],
        [("WSO2",), ("WSO2",)], None),
    "ea34": (Q_EA_AND, [(0, S1, ("IBM", 18.7, 100)),
                        (2100, S3, ("WSO2", 35.0, 100)),
                        (2200, S4, ("GOOGLE", 56.86, 100))],
             [("IBM", "WSO2", "GOOGLE"), ("IBM", "WSO2", "GOOGLE")], 2),
    "ea36": (Q_EA_OR, [(0, S1, ("IBM", 18.7, 100)),
                       (2100, S3, ("WSO2", 35.0, 100))],
             [("IBM", "WSO2", None), ("IBM", "WSO2", None)], 2),
    "ea37": (Q_EA_OR, [(0, S1, ("IBM", 18.7, 100)),
                       (2100, S4, ("GOOGLE", 56.86, 100))],
             [("IBM", None, "GOOGLE"), ("IBM", None, "GOOGLE")], 2),
    "ea38": (Q_EA_OR, [(0, S1, ("IBM", 18.7, 100)),
                       (100, S3, ("WSO2", 35.0, 100)),
                       (200, S4, ("GOOGLE", 56.86, 100))], [], 0),
    "ea39": (Q_EA_AND, [(0, S1, ("IBM", 18.7, 100)),
                        (100, S2, ("ORACLE", 25.0, 100)),
                        (200, S3, ("WSO2", 35.0, 100)),
                        (300, S4, ("GOOGLE", 56.86, 100))], [], 0),
    "ea40": (Q_EA_OR, [(0, S1, ("IBM", 18.7, 100)),
                       (100, S2, ("ORACLE", 25.0, 100)),
                       (200, S3, ("WSO2", 35.0, 100)),
                       (300, S4, ("GOOGLE", 56.86, 100))], [], 0),
    "ea41": (Q_EA_LOG1, [(0, S1, ("WSO2", 15.0, 100)),
                         (100, S3, ("GOOGLE", 35.0, 100)),
                         (200, S3, ("ORACLE", 45.0, 100))],
             [("WSO2", "GOOGLE"), ("WSO2", "ORACLE")], 2),
    "ea42": (Q_EA_LOG1, [(0, S1, ("WSO2", 15.0, 100)),
                         (100, S2, ("IBM", 25.0, 100)),
                         (200, S3, ("GOOGLE", 35.0, 100))], [], 0),
    "ea43": (Q_EA_LOG2, [(0, S2, ("IBM", 25.0, 100)),
                         (100, S2, ("WSO2", 26.0, 100)),
                         (200, S3, ("GOOGLE", 35.0, 100))],
             [("IBM", "GOOGLE"), ("WSO2", "GOOGLE")], 2),
    "ea44": (Q_EA_LOG2, [(0, S1, ("WSO2", 15.0, 100)),
                         (100, S2, ("IBM", 25.0, 100)),
                         (200, S3, ("GOOGLE", 35.0, 100))], [], 0),
    "ea45": (Q_EA_LOG3, [(0, S1, ("WSO2", 15.0, 100)),
                         (1200, S3, ("GOOGLE", 35.0, 100)),
                         (2300, S3, ("ORACLE", 45.0, 100))],
             [("WSO2", "GOOGLE"), ("WSO2", "ORACLE")], 2),
    "ea46": (Q_EA_LOG3, [(0, S1, ("WSO2", 15.0, 100)),
                         (100, S2, ("IBM", 25.0, 100)),
                         (1200, S3, ("GOOGLE", 35.0, 100)),
                         (2300, "adv", None)], [], 0),
    "ea47": (Q_EA_LOG3, [(0, S1, ("WSO2", 15.0, 100)),
                         (1100, S2, ("IBM", 25.0, 100)),
                         (1200, S3, ("GOOGLE", 35.0, 100))],
             [("WSO2", "GOOGLE")], 1),
    "ea48": (Q_EA_LOG4, [(0, S1, ("WSO2", 15.0, 100)),
                         (1100, S2, ("IBM", 25.0, 100)),
                         (1200, S3, ("GOOGLE", 35.0, 100))],
             [("IBM", "GOOGLE")], 1),
}

EA_DEVIATIONS = {
    # reference testQueryAbsent49: after a violating Stream1 arrival kills
    # the `every (not A and e2)` element, the reference's lazy re-init skips
    # exactly ONE e2 (IBM) and completes with the second (ORACLE) — a
    # pending-list re-initialization artifact. Here the violation kills the
    # element permanently when the absent side has no waiting time
    # (matching testQueryAbsent44's suppression), so no completion occurs.
    "ea49": (Q_EA_LOG2, [(0, S1, ("WSO2", 15.0, 100)),
                         (100, S2, ("IBM", 25.0, 100)),
                         (200, S2, ("ORACLE", 35.0, 100)),
                         (300, S3, ("GOOGLE", 45.0, 100))],
             [("ORACLE", "GOOGLE")], 1),
}


@pytest.mark.xfail(reason="documented deviation: see EA_DEVIATIONS", strict=True)
@pytest.mark.parametrize("name", sorted(EA_DEVIATIONS))
def test_absent_golden_deviation(name):
    ql, steps, expected, total = EA_DEVIATIONS[name]
    got = run_pb(ql, steps)
    assert len(got) == total and got[: len(expected)] == expected, (name, got)

CASES = {**AP, **EA}


@pytest.mark.parametrize("name", sorted(CASES))
def test_absent_golden(name):
    ql, steps, expected, total = CASES[name]
    got = run_pb(ql, steps)
    if total is not None:
        assert len(got) == total, (name, got)
    if isinstance(expected, set):
        assert set(got[: len(expected)]) == expected, (name, got)
    elif expected is not None:
        assert got[: len(expected)] == expected, (name, got)


def test_late_timestamp_present_side_still_completes():
    """A present-side event whose explicit timestamp is at or before an
    already-processed absent deadline must still complete the element (the
    deadline elapsed in event time) — regression for the next_timer `after`
    exclusion silently dropping such completions."""
    ql = """@info(name = 'query1')
    from e1=Stream1[price>20] and not Stream2[price>50] for 1 sec
    select e1.symbol as symbol1 insert into OutputStream;"""
    got = run_pb(ql, [
        (1500, "adv", None),              # deadline 1000 fires with no e1
        (900, S1, ("WSO2", 55.6, 100)),   # late event, ts before the deadline
        (1600, "adv", None),
    ])
    assert got == [("WSO2",)], got
