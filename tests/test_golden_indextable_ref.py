"""Golden corpus: reference query/table/IndexTableTestCase.java (data-level
translation: queries, event sequences, expected rows). @Index tables keep
duplicates (inserts never drop; updates/deletes hit every match), unlike
@PrimaryKey tables. Test 34 (perf race asserting indexed sends are faster)
is not a behavioral contract and is not translated."""

from __future__ import annotations

import pytest

from siddhi_tpu import SiddhiManager
from siddhi_tpu.core.errors import SiddhiAppCreationError

from tests.test_golden_pktable_ref import eq, eq_unsorted, run

S3 = (
    "define stream StockStream (symbol string, price float, volume long); "
    "define stream CheckStockStream (symbol string, volume long); "
    "define stream UpdateStockStream (symbol string, price float, volume long);"
)
S3D = (
    "define stream StockStream (symbol string, price float, volume long); "
    "define stream CheckStockStream (symbol string, volume long); "
    "define stream DeleteStockStream (symbol string, price float, volume long);"
)


class TestIndexTableGolden:
    def test1_index_join_equality(self):
        ql = S3 + """@Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, nrem = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq(ins, [("IBM", 100), ("WSO2", 100)])
        assert nrem == 0

    def test2_index_join_not_equal(self):
        ql = S3 + """@Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol!=StockTable.symbol
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("GOOG", 100)),
        ], "query2")
        eq_unsorted(ins, [("GOOG", "IBM", 100), ("GOOG", "WSO2", 100)])

    def test3_index_join_greater(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.volume > StockTable.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("FOO", 60)),
        ], "query2")
        eq_unsorted(ins[:2], [("IBM", "GOOG", 50), ("IBM", "ABC", 70)])
        eq_unsorted(ins[2:], [("FOO", "GOOG", 50)])

    def test4_index_join_less(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume < CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 200)),
        ], "query2")
        eq_unsorted(ins, [("IBM", "ABC", 70), ("IBM", "GOOG", 50)])

    def test5_index_join_less_equal(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume <= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 70)),
        ], "query2")
        eq_unsorted(ins, [("IBM", "ABC", 70), ("IBM", "GOOG", 50)])

    def test6_index_join_table_greater(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume > CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 50)),
        ], "query2")
        eq_unsorted(ins, [("IBM", "WSO2", 200), ("IBM", "ABC", 70)])

    def test7_index_join_table_greater_equal(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 70)),
        ], "query2")
        eq_unsorted(ins, [("IBM", "ABC", 70), ("IBM", "WSO2", 200)])

    def test8_index_insert_keeps_duplicates(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on StockTable.volume >= CheckStockStream.volume
        select CheckStockStream.symbol, StockTable.symbol as tableSymbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("FOO", 50.6, 200)),
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("GOOG", 50.6, 50)),
            ("StockStream", ("ABC", 5.6, 70)),
            ("CheckStockStream", ("IBM", 70)),
        ], "query2")
        eq_unsorted(
            ins,
            [("IBM", "ABC", 70), ("IBM", "WSO2", 200), ("IBM", "FOO", 200)],
        )

    def test9_index_update_equality(self):
        ql = S3 + """@Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        update StockTable on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("UpdateStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query3")
        eq(ins, [("IBM", 100), ("WSO2", 100), ("IBM", 200), ("WSO2", 100)])

    def test10_index_update_not_equal_rekeys(self):
        ql = S3 + """@Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        update StockTable on StockTable.symbol!=symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol!=StockTable.symbol
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("UpdateStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query3")
        # the WSO2 row is fully rewritten to (IBM, 77.6, 200) — no pk guard
        eq(ins[:2], [("WSO2", 100), ("IBM", 100)])
        eq_unsorted(ins[2:], [("IBM", 200), ("IBM", 100)])

    def test11_index_update_le_applies_to_all(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume <= volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume >= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 200)),
            ("UpdateStockStream", ("FOO", 77.6, 200)),
            ("CheckStockStream", ("BAR", 200)),
        ], "query3")
        eq_unsorted(ins[:2], [(55.6, 200), (55.6, 100)])
        eq_unsorted(ins[2:], [(77.6, 200), (77.6, 200)])

    def test12_index_update_lt(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume < volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume >= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 200)),
            ("UpdateStockStream", ("FOO", 77.6, 200)),
            ("CheckStockStream", ("BAR", 200)),
        ], "query3")
        eq_unsorted(ins[:2], [(55.6, 200), (55.6, 100)])
        eq_unsorted(ins[2:], [(77.6, 200), (55.6, 200)])

    def test13_index_update_ge(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume >= volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume <= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 200)),
            ("UpdateStockStream", ("FOO", 77.6, 200)),
            ("CheckStockStream", ("BAR", 200)),
        ], "query3")
        eq(ins, [(55.6, 200), (77.6, 200)])

    def test14_index_update_gt(self):
        ql = S3 + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from UpdateStockStream
        select price, volume
        update StockTable on StockTable.volume > volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        on CheckStockStream.volume <= StockTable.volume
        select StockTable.price, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 150)),
            ("UpdateStockStream", ("FOO", 77.6, 150)),
            ("CheckStockStream", ("BAR", 150)),
        ], "query3")
        eq(ins, [(55.6, 200), (77.6, 150)])

    def test15_index_delete_equality(self):
        ql = S3D + """@Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 100)])
        eq(ins[2:], [("WSO2", 100)])

    def test16_index_delete_not_equal(self):
        ql = S3D + """@Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.symbol!=symbol;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 100)])
        eq(ins[2:], [("IBM", 100)])

    def test17_index_delete_table_gt(self):
        ql = S3D + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume>volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 150)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 200)])
        eq(ins[2:], [("IBM", 100)])

    def test18_index_delete_table_ge(self):
        ql = S3D + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume>=volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 200)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 200)])
        eq(ins[2:], [("IBM", 100)])

    def test19_index_delete_table_lt(self):
        ql = S3D + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume < volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 150)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:2], [("IBM", 100), ("WSO2", 200)])
        eq(ins[2:], [("WSO2", 200)])

    def test20_index_delete_table_le(self):
        ql = S3D + """@Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2') from DeleteStockStream
        delete StockTable on StockTable.volume <= volume;
        @info(name = 'query3')
        from CheckStockStream join StockTable
        select StockTable.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("WSO2", 100)),
            ("DeleteStockStream", ("IBM", 77.6, 150)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query3")
        eq_unsorted(ins[:3], [("IBM", 100), ("BAR", 150), ("WSO2", 200)])
        eq(ins[3:], [("WSO2", 200)])

    def test21_index_in_condition_eq(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(symbol==StockTable.symbol) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq_unsorted(ins, [("WSO2", 100)])

    def test22_index_in_condition_ne(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(symbol!=StockTable.symbol) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 100), ("WSO2", 100)])

    def test23_index_in_condition_gt(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(volume > StockTable.volume) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 170)),
            ("CheckStockStream", ("FOO", 500)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 170), ("FOO", 500)])

    def test24_index_in_condition_lt(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(volume < StockTable.volume) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 170)),
            ("CheckStockStream", ("FOO", 500)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 170)])

    def test25_index_in_condition_le(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(volume <= StockTable.volume) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 170)),
            ("CheckStockStream", ("FOO", 200)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 170), ("FOO", 200)])

    def test26_index_in_condition_ge(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long);
        @Index('volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream[(volume >= StockTable.volume) in StockTable]
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 200)),
            ("StockStream", ("BAR", 55.6, 150)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("FOO", 170)),
            ("CheckStockStream", ("FOO", 100)),
        ], "query2")
        eq_unsorted(ins, [("FOO", 170), ("FOO", 100)])

    def test27_index_left_outer_join_upsert(self):
        ql = """define stream StockStream (symbol string, price float, volume long);
        define stream CheckStockStream (symbol string, volume long, price float);
        define stream UpdateStockStream (comp string, vol long);
        @Index('symbol')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from UpdateStockStream left outer join StockTable
        on UpdateStockStream.comp == StockTable.symbol
        select comp as symbol, ifThenElse(price is null,0f,price) as price, vol as volume
        update or insert into StockTable
        on StockTable.symbol==symbol;
        @info(name = 'query3')
        from CheckStockStream[(symbol==StockTable.symbol and volume==StockTable.volume
         and price==StockTable.price) in StockTable]
        insert into OutStream;"""
        ins, nrem = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("CheckStockStream", ("IBM", 100, 155.6)),
            ("CheckStockStream", ("WSO2", 100, 155.6)),
            ("UpdateStockStream", ("IBM", 200)),
            ("UpdateStockStream", ("WSO2", 300)),
            ("CheckStockStream", ("IBM", 200, 0.0)),
            ("CheckStockStream", ("WSO2", 300, 55.6)),
        ], "query3")
        eq(ins, [("IBM", 200, 0.0), ("WSO2", 300, 55.6)])
        assert nrem == 0

    def test28_multi_index_join(self):
        ql = S3 + """@Index('price','volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq(ins, [("IBM", 100), ("WSO2", 100)])

    def test29_multi_index_join_other_attr(self):
        ql = S3 + """@Index('symbol', 'volume')
        define table StockTable (symbol string, price float, volume long);
        @info(name = 'query1') from StockStream insert into StockTable ;
        @info(name = 'query2')
        from CheckStockStream join StockTable
        on CheckStockStream.symbol==StockTable.symbol
        select CheckStockStream.symbol, StockTable.volume
        insert into OutStream;"""
        ins, _ = run(ql, [
            ("StockStream", ("WSO2", 55.6, 100)),
            ("StockStream", ("IBM", 55.6, 100)),
            ("CheckStockStream", ("IBM", 100)),
            ("CheckStockStream", ("WSO2", 100)),
        ], "query2")
        eq(ins, [("IBM", 100), ("WSO2", 100)])

    def test30_index_empty_attr_rejected(self):
        with pytest.raises(SiddhiAppCreationError):
            SiddhiManager().create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @Index('')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable ;
            """)

    def test31_index_duplicate_attr_rejected(self):
        with pytest.raises(SiddhiAppCreationError):
            SiddhiManager().create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @Index('symbol', 'symbol')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable ;
            """)

    def test32_index_duplicate_annotation_rejected(self):
        with pytest.raises(SiddhiAppCreationError):
            SiddhiManager().create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @Index('symbol')
            @Index('volume')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable ;
            """)

    def test33_index_unknown_attr_rejected(self):
        with pytest.raises(SiddhiAppCreationError):
            SiddhiManager().create_siddhi_app_runtime("""
            define stream StockStream (symbol string, price float, volume long);
            @Index('foo')
            define table StockTable (symbol string, price float, volume long);
            @info(name = 'query1') from StockStream insert into StockTable ;
            """)
