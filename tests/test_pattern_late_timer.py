"""Regression: the resurrected-deadline hazard in `every (A and not B for t)`
(core/pattern.py absent-deadline timer branch).

A persistent (`every`) and-not-for generator that fires at its deadline must
re-arm with its window restarting AT THE DEADLINE. Before the fix it re-armed
at the firing row's raw timestamp; a LATE row (event time below the already
fired deadline, firing through the eff_now rescue) re-armed the generator in
the past, so its next deadline was already expired and every subsequent row
re-fired it — duplicate absent emissions from one logical window.

Playback clock throughout: event time is the only clock, no wall races.
"""

from __future__ import annotations

from siddhi_tpu import SiddhiManager

QL = """
define stream StockStream (symbol string, price float);
define stream TickStream (symbol string, price float);

@info(name='q')
from every e1=StockStream[price > 10] and not TickStream[price > 20]
     for 150 millisec
select e1.symbol as sym, e1.price as price
insert into Out;
"""


def _run(feeds):
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime("@app:playback\n" + QL)
    got = []
    rt.add_callback(
        "Out", lambda evs: got.extend((e.timestamp, tuple(e.data)) for e in evs)
    )
    rt.start()
    for sid, row, ts in feeds:
        rt.get_input_handler(sid).send(row, timestamp=ts)
    rt.shutdown()
    mgr.shutdown()
    return got


def test_deadline_fires_once_on_time():
    got = _run([
        ("StockStream", ("A", 15.0), 0),
        # inert clock advance past the 150 ms deadline (matches nothing)
        ("StockStream", ("Z", 1.0), 200),
    ])
    assert [r for _, r in got] == [("A", 15.0)]


def test_late_row_does_not_resurrect_fired_deadline():
    got = _run([
        ("StockStream", ("A", 15.0), 0),
        ("StockStream", ("Z", 1.0), 200),   # deadline 150 fired -> 1 emission
        # LATE row: event time 50 < the fired deadline. It matches the
        # present side, entering the re-armed generator's NEXT window —
        # which restarts at the deadline (150), so its own deadline is 300.
        ("StockStream", ("B", 30.0), 50),
        # rows at 210/250: before 300, nothing may fire (the buggy re-arm
        # at ts=50 put the next deadline at 200, already expired, so each
        # of these rows re-fired the generator)
        ("StockStream", ("Z", 1.0), 210),
        ("StockStream", ("Z", 1.0), 250),
    ])
    fired = [r for _, r in got]
    assert fired == [("A", 15.0)], f"resurrected deadline refired: {fired}"


def test_late_present_arrival_completes_exactly_once():
    # timer passed the deadline with the present side absent (no fire);
    # each LATE present-side arrival then completes its window instantly
    # through the eff_now rescue — exactly once per arrival, and the
    # trailing rows must not re-fire any resurrected deadline
    got = _run([
        ("StockStream", ("Z", 1.0), 400),   # deadline 150 passes, A absent
        ("StockStream", ("A", 15.0), 40),   # late arrival -> rescue fire
        ("StockStream", ("Z", 1.0), 45),
        ("StockStream", ("A2", 15.0), 48),  # next window, same rescue
        ("StockStream", ("Z", 1.0), 200),
        ("StockStream", ("Z", 1.0), 320),
    ])
    assert [r for _, r in got] == [("A", 15.0), ("A2", 15.0)]


def test_rearmed_window_still_completes_later():
    got = _run([
        ("StockStream", ("A", 15.0), 0),
        ("StockStream", ("Z", 1.0), 200),   # fire #1 at deadline 150
        ("StockStream", ("B", 30.0), 50),   # late capture into window @150
        ("StockStream", ("Z", 1.0), 400),   # past deadline 300: fire #2
    ])
    fired = [r for _, r in got]
    assert fired[0] == ("A", 15.0)
    # exactly one more completion for the re-armed window — not one per row
    assert len(fired) == 2, f"expected 2 firings, got {fired}"
