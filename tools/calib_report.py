"""Calibration regression sentinel: diff two runs' calibration blobs.

A calibration blob is the `/calibration.json` payload of one app (what
`bench.py --leg calibration` puts under detail `calibration`, and what
`runtime.calibration_report()` returns): per-(kind, component) prediction
pairs with live values and error ratios, plus cumulative mispricing
counters. This tool compares a CURRENT blob against a committed BASELINE
and fails (exit 1) when the plan's pricing got measurably worse:

  * prediction-error drift: a pair's |log(ratio)| grew by more than
    --drift (default 0.69 ~= 2x) over the baseline's — the static model
    now misprices something it used to price well;
  * new unexplained-recompile flags: `unpredicted_recompile_cause`
    mispricings that the baseline did not carry (any count regression on
    that reason code);
  * lost pairings: a prediction kind that paired live values in the
    baseline no longer does (the meter went dark, or the join key drifted);
  * p99 trajectory (optional): when both blobs carry `p99_detect_ms`
    (bench detail), the current p99 must stay within --p99-slack (default
    25%) of the baseline.

Usage:
    python tools/calib_report.py BASELINE.json CURRENT.json \
        [--drift 0.69] [--p99-slack 0.25] [--json]

Each input is either a bare calibration blob, a bench detail dict with a
`calibration` key, or a full bench snapshot line (detail nested under
`detail`). Exit 0 = calibrated as well as before.
"""

from __future__ import annotations

import argparse
import json
import math
import sys

REASON_RECOMPILE = "unpredicted_recompile_cause"


def _extract(doc: dict) -> tuple[dict, float | None]:
    """(calibration blob, p99_detect_ms or None) from any supported input
    shape."""
    d = doc
    if "detail" in d and isinstance(d["detail"], dict):
        d = d["detail"]
    p99 = d.get("p99_detect_ms")
    if "calibration" in d and isinstance(d["calibration"], dict):
        return d["calibration"], p99
    if "pairs" in d:  # bare blob
        return d, p99
    raise SystemExit(
        "input is not a calibration blob (no `pairs`/`calibration` key)"
    )


def _pair_index(blob: dict) -> dict:
    return {
        (p["kind"], p["component"]): p
        for p in blob.get("pairs", ())
    }


def _abs_log_ratio(p: dict) -> float | None:
    r = p.get("ratio_ewma")
    if r is None:
        r = p.get("ratio")
    if r is None or r <= 0:
        return None
    return abs(math.log(r))


def _recompile_count(blob: dict) -> int:
    return sum(
        m.get("count", 0)
        for m in blob.get("mispriced", ())
        if m.get("reason") == REASON_RECOMPILE
    )


def diff(baseline: dict, current: dict, drift: float,
         p99_base=None, p99_cur=None, p99_slack: float = 0.25) -> dict:
    base_pairs = _pair_index(baseline)
    cur_pairs = _pair_index(current)
    problems: list[str] = []
    drifted: list[dict] = []
    for key, bp in sorted(base_pairs.items()):
        cp = cur_pairs.get(key)
        kind, comp = key
        if cp is None:
            problems.append(f"pair vanished: {kind} {comp}")
            continue
        if bp.get("live") is not None and cp.get("live") is None:
            problems.append(f"live meter went dark: {kind} {comp}")
            continue
        b_err, c_err = _abs_log_ratio(bp), _abs_log_ratio(cp)
        if b_err is None or c_err is None:
            continue
        if c_err - b_err > drift:
            drifted.append({
                "kind": kind, "component": comp,
                "baseline_abs_log_ratio": round(b_err, 4),
                "current_abs_log_ratio": round(c_err, 4),
            })
            problems.append(
                f"prediction error drifted: {kind} {comp} "
                f"|log ratio| {b_err:.3f} -> {c_err:.3f}"
            )
    base_kinds = set(baseline.get("kinds_paired", ()))
    cur_kinds = set(current.get("kinds_paired", ()))
    for k in sorted(base_kinds - cur_kinds):
        problems.append(f"prediction kind no longer pairs live: {k}")
    rc_base, rc_cur = _recompile_count(baseline), _recompile_count(current)
    if rc_cur > rc_base:
        problems.append(
            f"unexplained-recompile mispricings grew: {rc_base} -> {rc_cur}"
        )
    p99 = None
    if p99_base is not None and p99_cur is not None and p99_base > 0:
        p99 = {"baseline_ms": p99_base, "current_ms": p99_cur}
        if p99_cur > p99_base * (1.0 + p99_slack):
            problems.append(
                f"p99 trajectory regressed: {p99_base:.2f} ms -> "
                f"{p99_cur:.2f} ms (> +{p99_slack:.0%})"
            )
    return {
        "ok": not problems,
        "problems": problems,
        "drifted": drifted,
        "kinds": {
            "baseline": sorted(base_kinds), "current": sorted(cur_kinds),
        },
        "recompile_mispricings": {"baseline": rc_base, "current": rc_cur},
        **({"p99": p99} if p99 else {}),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two calibration blobs; exit 1 on regression"
    )
    ap.add_argument("baseline")
    ap.add_argument("current")
    ap.add_argument("--drift", type=float, default=0.69,
                    help="max |log(ratio)| growth per pair (default ~2x)")
    ap.add_argument("--p99-slack", type=float, default=0.25,
                    help="allowed fractional p99 growth (default 0.25)")
    ap.add_argument("--json", action="store_true",
                    help="emit the full diff as JSON")
    args = ap.parse_args(argv)
    with open(args.baseline) as f:
        base_blob, p99_b = _extract(json.load(f))
    with open(args.current) as f:
        cur_blob, p99_c = _extract(json.load(f))
    res = diff(base_blob, cur_blob, args.drift, p99_b, p99_c,
               args.p99_slack)
    if args.json:
        print(json.dumps(res, indent=2))
    else:
        for p in res["problems"]:
            print(f"REGRESSION: {p}")
        print(
            f"{'OK' if res['ok'] else 'FAIL'}: "
            f"{len(base_blob.get('pairs', ()))} baseline pairs, "
            f"kinds {','.join(res['kinds']['current']) or '-'}"
        )
    return 0 if res["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
