"""CI smoke for the black-box incident recorder + deterministic replay
(tier1.yml "Incident replay parity").

Boots an app with `@app:blackbox` armed and `@OnError(action='LOG')` on
the input stream, drives a deterministic feed while collecting the live
emissions, then installs a one-shot `junction_dispatch` FaultPlan rule
and sends one poison event: the guarded dispatch failure fires the
`dispatch_error` trigger and the recorder freezes an incident bundle.
The bundle is replayed in a FRESH SUBPROCESS via tools/incident_replay.py
(no fault plan installed there — the replay regenerates the emissions
from the recorded rings alone), and the replayed per-stream rows must be
BYTE-IDENTICAL to the live run's collected emissions, checksums included.

The poison event is filtered by the query predicate, so the swallowed
dispatch changes no comparable output — live and replay agree exactly.
Runs under whatever SIDDHI_TPU_FUSE / SIDDHI_TPU_SHARD the environment
sets (tier1.yml repeats the step across legs); the replay subprocess
inherits the same env, so the parity holds per-leg AND the checksum is
stable across legs. Exit 0 = pass.

With SMOKE_OUT_DIR=<dir> the live + replayed emission JSONs (and the
bundle itself) land there for the `incident-replay` workflow artifact.
"""

from __future__ import annotations

import json
import os
import shutil
import subprocess
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main() -> int:
    from siddhi_tpu import SiddhiManager
    from siddhi_tpu.observability.blackbox import (
        attach_emission_collector, emissions_checksum,
    )
    from siddhi_tpu.testing import faults

    out_dir = os.environ.get("SMOKE_OUT_DIR")
    leg = os.environ.get("SIDDHI_TPU_FUSE", "d")
    if os.environ.get("SIDDHI_TPU_SHARD"):
        leg += "_shard" + os.environ["SIDDHI_TPU_SHARD"]
    bundle_dir = tempfile.mkdtemp(prefix="incident_smoke_")
    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(f"""
    @app:name('incidentsmoke')
    @app:blackbox(window='30 sec', triggers='dispatch_error,crash',
                  keep='4', dir='{bundle_dir}')
    @OnError(action='LOG')
    define stream S (symbol string, price float, volume int);
    @info(name='q')
    from S[price > 10.0]#window.length(8)
    select symbol, sum(volume) as v, avg(price) as ap insert into Out;
    """)
    live = attach_emission_collector(rt)
    rt.start()
    h = rt.get_input_handler("S")
    syms = ("AAA", "BBB", "CCC")
    rows = [
        (syms[i % 3], 5.0 + i * 1.5, i + 1)
        for i in range(48)
    ]
    ts = [1_700_000_000_000 + i * 25 for i in range(48)]
    h.send_many(rows, timestamps=ts)

    # one-shot dispatch fault on the NEXT junction dispatch for S: the
    # poison row is filtered (price <= 10) so the swallowed batch changes
    # no comparable output, and @OnError(action='LOG') makes the failure
    # guarded -> dispatch_error trigger -> frozen bundle
    faults.install(faults.parse_plan("seed=7;junction_dispatch@S:times=1"))
    try:
        h.send(("POISON", 1.0, 999), timestamp=ts[-1] + 25)
    finally:
        faults.uninstall()

    incidents = rt.incidents()
    assert incidents, "dispatch fault must freeze an incident bundle"
    inc = incidents[-1]
    assert inc["trigger"] == "dispatch_error", inc
    assert os.path.isfile(inc["path"]), inc
    live_payload = {
        "emissions": {
            sid: [[t, list(r)] for t, r in rws]
            for sid, rws in sorted(live.items())
        },
        "checksum": emissions_checksum(live),
    }
    mgr.shutdown()

    # replay in a FRESH subprocess (the time machine must not depend on
    # any state of the live process), fault-plan env scrubbed
    replay_out = os.path.join(bundle_dir, "replay.json")
    env = dict(os.environ)
    env.pop("SIDDHI_TPU_FAULTS", None)
    tool = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "incident_replay.py")
    proc = subprocess.run(
        [sys.executable, tool, inc["path"], "--json", replay_out, "--quiet"],
        env=env, timeout=300,
    )
    assert proc.returncode == 0, f"replay subprocess rc={proc.returncode}"
    with open(replay_out, encoding="utf-8") as f:
        replay = json.load(f)

    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        with open(os.path.join(out_dir, f"live_fuse{leg}.json"), "w",
                  encoding="utf-8") as f:
            json.dump(live_payload, f, indent=1)
        with open(os.path.join(out_dir, f"replay_fuse{leg}.json"), "w",
                  encoding="utf-8") as f:
            json.dump(replay, f, indent=1)
        shutil.copy2(inc["path"], out_dir)

    assert replay["trigger"] == "dispatch_error", replay
    assert replay["events_fed"] == 49, replay["events_fed"]
    # THE parity gate: every replayed stream's rows byte-identical to the
    # live run IN EMISSION ORDER (exact equality, no tolerance, no
    # re-sorting), checksums equal
    r_emis = {
        sid: [(int(t), tuple(r)) for t, r in rws]
        for sid, rws in replay["emissions"].items()
    }
    l_emis = {sid: list(rws) for sid, rws in live.items()}
    assert set(r_emis) == set(l_emis), (set(r_emis), set(l_emis))
    for sid in sorted(l_emis):
        assert r_emis[sid] == l_emis[sid], (
            f"stream {sid} diverged:\nlive   {l_emis[sid][:5]}...\n"
            f"replay {r_emis[sid][:5]}..."
        )
    assert replay["checksum"] == live_payload["checksum"], (
        replay["checksum"], live_payload["checksum"],
    )
    print(
        f"incident replay parity OK (leg fuse={leg}): "
        f"{replay['events_fed']} events re-fed, "
        f"{sum(len(v) for v in l_emis.values())} emissions byte-identical, "
        f"checksum {replay['checksum'][:12]}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
