"""CI smoke test for the metrics + introspection endpoint (tier1.yml).

Boots a small app with `@app:statistics(reporter='prometheus')` (which makes
the manager serve `/metrics`), drives a little traffic, scrapes the endpoint
with curl (urllib fallback), and asserts the exposition is non-empty and
well-formed: every sample line parses, every family is typed, and the
acceptance families (throughput, latency quantiles, buffered depth, device
budget) are present. Also scrapes `/status.json` (junction queue depth,
window fill, pipeline occupancy must be live), `/flight` (the flight ring
must hold the tail of the driven traffic), `/lineage.json` (+ `/lineage`:
a resolvable provenance chain from a known window emission back to decoded
input events, and live roofline gauges — wire bytes/event + h2d MB/s — in
the exposition and `/profile`), `/profile` (≥1 compile event with a cause
and wall time after ingest, plus chunk waterfalls), and `/explain` +
`/explain.json` (a non-empty live-annotated plan). A second app arms
`@app:blackbox` and a seeded dispatch fault freezes an incident: the
`/incidents(.json)` + `/incidents/<id>.json` routes must list it with
its trigger and bundle path, and the `siddhi_incidents_total` /
`siddhi_blackbox_ring_events` families must ride `/metrics`. Exit 0 =
pass.

With SMOKE_JSON_OUT=<path> the scraped payloads (profile, explain plan,
status) are written there as one JSON blob — tier1.yml uploads it as a
workflow artifact so a red run ships its evidence.
"""

from __future__ import annotations

import os
import re
import subprocess
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

_PROM_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? [-+]?[0-9.eE+-]+$"
)

REQUIRED_FAMILIES = (
    "siddhi_events_total",
    "siddhi_latency_ms",
    "siddhi_buffered_events",
    "siddhi_device_time_ms",
    "siddhi_pipeline_occupancy",
    "siddhi_pipeline_depth",
    "siddhi_traces_sampled_total",
)


def scrape(url: str) -> str:
    try:
        out = subprocess.run(
            ["curl", "-sf", url], capture_output=True, text=True, timeout=10
        )
        if out.returncode == 0 and out.stdout:
            return out.stdout
    except (FileNotFoundError, subprocess.TimeoutExpired):
        pass
    import urllib.request

    return urllib.request.urlopen(url, timeout=10).read().decode()


def main() -> int:
    """Run the smoke; ALWAYS flush whatever was scraped to SMOKE_JSON_OUT
    (a red run must still ship its evidence as a workflow artifact)."""
    blob: dict = {}
    try:
        return _run(blob)
    finally:
        out_path = os.environ.get("SMOKE_JSON_OUT")
        if out_path and blob:
            import json

            os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
            with open(out_path, "w", encoding="utf-8") as f:
                json.dump(blob, f, indent=1, default=str)


def _run(blob: dict) -> int:
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    # @app:wire: dictionary-encode the interned symbol column statically
    # (core/wire.py), so the encoded-vs-logical roofline split below is
    # exercised by an analyzer-chosen encoder, not just the sampled narrow
    rt = mgr.create_siddhi_app_runtime("""
    @app:statistics(reporter='prometheus', port='0', trace.sample='1.0')
    @app:lineage(capacity='512')
    @app:wire(dict.S.symbol='8')
    @flightRecorder(size='16')
    define stream S (symbol string, price float);
    @info(name='q')
    from S[price > 10]#window.length(8)
    select symbol, avg(price) as ap insert into Out;
    """)
    rt.start()
    h = rt.get_input_handler("S")
    for i in range(32):
        h.send(("A", float(i)))
    # columnar send big enough to engage the PIPELINED fused ingest, so the
    # pipeline stage histograms (op="pipeline.*") and occupancy gauge carry
    # real samples in the exposition below
    import numpy as np

    n = 256
    sym = np.full((n,), mgr.interner.intern("A"), dtype=np.int32)
    h.send_columns(
        np.arange(n, dtype=np.int64) + 1_700_000_000_000,
        {"symbol": sym, "price": np.linspace(0.0, 99.0, n, dtype=np.float32)},
    )
    port = mgr.metrics_port
    assert port, "reporter='prometheus' must start the metrics endpoint"
    text = scrape(f"http://127.0.0.1:{port}/metrics")
    blob["prometheus"] = text
    assert text.strip(), "empty exposition"

    typed: set = set()
    samples = 0
    for line in text.strip().splitlines():
        if line.startswith("# TYPE "):
            typed.add(line.split()[2])
            continue
        if line.startswith("#"):
            continue
        assert _PROM_LINE.match(line), f"malformed line: {line!r}"
        name = re.split(r"[{ ]", line, 1)[0]
        base = re.sub(r"_(sum|count)$", "", name)
        assert base in typed or name in typed, f"untyped family: {name}"
        samples += 1
    missing = [f for f in REQUIRED_FAMILIES if f not in typed]
    assert not missing, f"missing families: {missing}"
    for q in ('quantile="0.5"', 'quantile="0.95"', 'quantile="0.99"'):
        assert q in text, f"missing latency {q}"
    for op in ("pipeline.encode", "pipeline.h2d", "pipeline.dispatch"):
        assert f'op="{op}"' in text, f"missing pipeline stage metric {op}"
    assert rt.traces(), "trace.sample='1.0' must produce sampled traces"

    # introspection endpoints: /status.json must carry live per-component
    # state, /flight the recorded ring tail (see observability/introspect.py)
    import json

    status = json.loads(scrape(f"http://127.0.0.1:{port}/status.json"))
    blob["status"] = status
    app = status["apps"]["SiddhiApp"]
    s_state = app["streams"]["S"]
    assert "queue_depth" in s_state, f"no junction queue depth: {s_state}"
    assert "occupancy" in s_state.get("pipeline", {}), (
        f"no pipeline occupancy: {s_state}"
    )
    q_state = app["queries"]["q"]
    assert q_state.get("window", {}).get("fill") == 8, (
        f"window fill must be live (expected full length(8)): {q_state}"
    )
    assert s_state.get("flight", {}).get("recorded") == 16, (
        f"flight ring must be full: {s_state}"
    )
    flight = json.loads(scrape(f"http://127.0.0.1:{port}/flight"))
    ring = flight["SiddhiApp"]["S"]
    assert len(ring) == 16, f"/flight must serve the 16-event ring: {ring}"
    status_text = scrape(f"http://127.0.0.1:{port}/status")
    assert "app SiddhiApp" in status_text and "queue_depth" in status_text

    # continuous profiler: after ingest /profile must report at least one
    # compile event carrying a cause and a wall time, plus chunk waterfalls
    profile = json.loads(scrape(f"http://127.0.0.1:{port}/profile"))
    blob["profile"] = profile
    assert profile and profile[0]["app"] == "SiddhiApp", profile
    compile_rep = profile[0]["compile"]
    events = [ev for ent in compile_rep.values() for ev in ent["recent"]]
    assert events, f"/profile must carry compile events: {compile_rep}"
    assert all(ev["cause"] and ev["wall_ms"] > 0 for ev in events), events
    assert profile[0]["waterfalls"]["chunks"] >= 1, profile[0]["waterfalls"]
    assert profile[0]["waterfalls"]["slowest"], "no slowest-chunk ring"

    # live roofline gauges: the fused columnar send above shipped wire
    # bytes, so /metrics and /profile must carry bytes/event + MB/s
    assert "siddhi_wire_bytes_per_event" in text, "no roofline gauge"
    assert "siddhi_wire_logical_bytes_per_event" in text, (
        "no logical-bytes gauge (encoded-vs-logical split)"
    )
    assert "siddhi_h2d_mb_s" in text, "no h2d MB/s gauge"
    roof = profile[0].get("roofline", {})
    s_roof = roof.get("stream.S", {})
    assert s_roof.get("wire_bytes_per_event", 0) > 0, (
        f"/profile roofline must be live: {roof}"
    )
    # the compact wire encodings contract: on this statically dict-encoded
    # stream the encoded bytes/event must be strictly below logical
    assert 0 < s_roof["wire_bytes_per_event"] < s_roof[
        "wire_logical_bytes_per_event"
    ], f"encoded must undercut logical: {s_roof}"

    # event lineage & provenance: /lineage.json must resolve a known
    # match back to its exact contributing input events
    lineage = json.loads(scrape(f"http://127.0.0.1:{port}/lineage.json"))
    blob["lineage"] = lineage
    lrep = lineage["SiddhiApp"]
    assert lrep["streams"]["S"]["next_seq"] > 0, lrep["streams"]
    qlin = lrep["queries"]["q"]
    assert qlin["outputs"] > 0 and qlin["avg_inputs_per_output"] > 0, qlin
    chains = lrep.get("recent", {}).get("q")
    assert chains, f"/lineage.json must carry a resolved chain: {lrep}"
    chain = chains[-1]
    assert chain["inputs"] and not chain["approx"], chain
    inp = chain["inputs"][0]
    assert inp["stream"] == "S" and inp["n"] > 0, inp
    assert any(e.get("event") is not None for e in inp.get("events", ())), (
        f"chain must resolve to decoded input events: {inp}"
    )
    lineage_text = scrape(f"http://127.0.0.1:{port}/lineage")
    assert "query q" in lineage_text and "fan-in" in lineage_text

    # EXPLAIN ANALYZE: a non-empty live plan for the running app
    explain_text = scrape(f"http://127.0.0.1:{port}/explain")
    assert "EXPLAIN ANALYZE" in explain_text and "query q" in explain_text
    plan = json.loads(scrape(f"http://127.0.0.1:{port}/explain.json"))
    plan = plan["SiddhiApp"]
    blob["explain"] = plan
    blob["prom_samples"] = samples
    blob["prom_families"] = sorted(typed)
    assert plan["live"] and plan["nodes"] and plan["edges"], plan
    assert any(n["id"] == "query:q" for n in plan["nodes"]), plan["nodes"]

    # plan-vs-actual calibration ledger: statistics are armed, so the app
    # carries a ledger whose /calibration.json pairs static predictions
    # (selectivity, state bytes, compiles) against the live meters
    calib = json.loads(scrape(f"http://127.0.0.1:{port}/calibration.json"))
    crep = calib["SiddhiApp"]
    blob["calibration"] = crep
    assert crep["generation"] >= 1, crep
    assert crep["pairs"], "/calibration.json must carry prediction pairs"
    assert crep["kinds_paired"], crep
    calib_text = scrape(f"http://127.0.0.1:{port}/calibration")
    assert "generation=" in calib_text
    # /slo: this app declares no @app:slo, so the route reports the
    # fallback rather than 404ing (scrapers probe every route)
    slo_text = scrape(f"http://127.0.0.1:{port}/slo")
    assert "no slo-enabled apps" in slo_text

    # black-box incident recorder: a second app arms @app:blackbox, a
    # one-shot junction_dispatch fault seeds a dispatch_error incident,
    # and /incidents(.json) + /incidents/<id>.json must list it with its
    # trigger and bundle path (observability/blackbox.py)
    import tempfile

    from siddhi_tpu.testing import faults

    bb_dir = tempfile.mkdtemp(prefix="metrics_smoke_bb_")
    rt2 = mgr.create_siddhi_app_runtime(f"""
    @app:name('bbapp')
    @app:blackbox(window='30 sec', triggers='dispatch_error,crash',
                  keep='2', dir='{bb_dir}')
    @OnError(action='LOG')
    define stream B (symbol string, price float);
    @info(name='qb')
    from B[price > 10] select symbol, price insert into BOut;
    """)
    rt2.start()
    hb = rt2.get_input_handler("B")
    for i in range(8):
        hb.send(("X", 20.0 + i))
    faults.install(faults.parse_plan("seed=3;junction_dispatch@B:times=1"))
    try:
        hb.send(("POISON", 1.0))
    finally:
        faults.uninstall()
    inc_list = json.loads(scrape(f"http://127.0.0.1:{port}/incidents.json"))
    blob["incidents"] = inc_list
    bb = inc_list["bbapp"]
    assert bb["incidents"]["dispatch_error"] == 1, bb
    assert bb["bundles"], "/incidents.json must list the frozen bundle"
    entry = bb["bundles"][-1]
    assert entry["trigger"] == "dispatch_error", entry
    assert entry["path"] and os.path.isfile(entry["path"]), entry
    detail = json.loads(
        scrape(f"http://127.0.0.1:{port}/incidents/{entry['id']}.json")
    )
    blob["incident_detail"] = detail
    assert detail["id"] == entry["id"], detail
    assert detail["trigger"] == "dispatch_error", detail
    assert detail["rings"]["B"]["events"] == 9, detail["rings"]
    inc_text = scrape(f"http://127.0.0.1:{port}/incidents")
    assert entry["id"] in inc_text
    # the two blackbox Prometheus families ride the manager exposition
    text2 = scrape(f"http://127.0.0.1:{port}/metrics")
    assert (
        'siddhi_incidents_total{app="bbapp",trigger="dispatch_error"} 1'
        in text2
    ), "incident counter family missing"
    assert "siddhi_blackbox_ring_events" in text2
    assert 'stream="B"' in text2

    mgr.shutdown()
    print(
        f"metrics smoke OK: {samples} samples, {len(typed)} families, "
        f"status + flight + lineage + roofline + profile + explain + "
        f"calibration + incidents live"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
