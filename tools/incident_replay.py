"""Deterministic time-travel replay of a black-box incident bundle (CLI).

Loads a frozen incident bundle (observability/blackbox.py), rebuilds the
app from the bundle's retained AST under `@app:playback`, restores the
pinned checkpoint, re-feeds every source-stream ring in recorded seq
order on the event-time clock, and prints one JSON object:

    {"id": ..., "app": ..., "trigger": ..., "detail": ...,
     "events_fed": N, "emissions": {stream: [[ts, [row...]], ...]},
     "checksum": "<sha256 over the emission set>"}

The emissions are byte-identical to what the live run emitted over the
bundle's covered interval (the replay determinism contract — see README
"Black box & incident replay"), so CI diffs this output against the live
recorder's collected rows to prove the time machine works. Exit 0 = the
replay ran to completion; any divergence is the CALLER's diff to make
(tools/incident_smoke.py, tier1.yml "Incident replay parity").

Usage:
    python tools/incident_replay.py BUNDLE.pkl [--json OUT] [--quiet]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="path to an incident_*.pkl bundle")
    ap.add_argument(
        "--json", dest="out", default=None,
        help="also write the JSON payload to this path",
    )
    ap.add_argument(
        "--quiet", action="store_true",
        help="suppress stdout (use with --json)",
    )
    args = ap.parse_args(argv)

    from siddhi_tpu.observability.blackbox import (
        load_bundle, replay_incident,
    )

    bundle = load_bundle(args.bundle)
    replay = replay_incident(bundle)
    payload = {
        "id": bundle["id"],
        "app": bundle["app"],
        "trigger": bundle["trigger"],
        "detail": bundle["detail"],
        "events_fed": replay.events_fed,
        "emissions": {
            sid: [[ts, list(row)] for ts, row in rows]
            for sid, rows in sorted(replay.emissions.items())
        },
        "checksum": replay.checksum(),
    }
    text = json.dumps(payload, indent=1, default=str)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(text)
    if not args.quiet:
        print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
