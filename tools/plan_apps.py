"""Emit the static FusionPlan for every analysis-corpus app and bench
workload.

CI (tier1.yml lint job) runs this and uploads the output directory as a
workflow artifact, so every push carries the machine-readable plan the
fusion PR will consume — and a planner crash on ANY app (including the
intentionally-bad corpus) fails the job. Warnings-only and even
error-carrying apps must still plan: the planner is best-effort by
contract, like EXPLAIN.

Usage:
    python tools/plan_apps.py [--out plan-artifacts]

Exit codes: 0 every app planned; 1 a planner crash (the defect report is
printed per app).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="plan-artifacts")
    args = ap.parse_args()
    os.makedirs(args.out, exist_ok=True)

    from siddhi_tpu.analysis import build_fusion_plan

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    jobs: list[tuple[str, str]] = []  # (name, SiddhiQL source)
    for path in sorted(glob.glob(
        os.path.join(repo, "tests", "analysis_corpus", "*.siddhi")
    )):
        name = os.path.basename(path)[:-len(".siddhi")]
        jobs.append((f"corpus_{name}", open(path).read()))

    import bench

    for name, (ql, _stream, _mult, _batch) in sorted(bench.WORKLOADS.items()):
        jobs.append((f"bench_{name}", ql))
    # the timebudget leg's multi-query fused-group app: the one bench app
    # whose plan actually FORMS a group (the headline legs are single-query)
    jobs.append(("bench_fusedgroup", bench.FUSED_GROUP_QL))
    # the wire leg's A/B apps — their plans carry the inferred wire lanes
    # and value domains the `--leg wire` inference assertions rely on
    for name, (ql, _stream) in sorted(bench.WIRE_WORKLOADS.items()):
        jobs.append((f"bench_{name}", ql))

    failures = 0
    index = []
    for name, source in jobs:
        try:
            plan = build_fusion_plan(source).to_dict()
        except Exception as exc:
            print(f"PLAN CRASH on {name}: {exc!r}", file=sys.stderr)
            failures += 1
            continue
        out_path = os.path.join(args.out, f"{name}.plan.json")
        with open(out_path, "w") as f:
            json.dump(plan, f, indent=2)
        index.append({
            "app": name,
            "groups": len(plan["groups"]),
            "blockers": len(plan["blockers"]),
            "shared_state": len(plan["shared_state"]),
            "rewrites": len(plan["rewrites"]),
            "domains": len(plan["domains"]),
        })
        print(
            f"{name}: {len(plan['groups'])} group(s), "
            f"{len(plan['blockers'])} blocker(s), "
            f"{len(plan['shared_state'])} shared-state candidate(s), "
            f"{len(plan['rewrites'])} rewrite(s), "
            f"{len(plan['domains'])} stream(s) with domains"
        )
    with open(os.path.join(args.out, "index.json"), "w") as f:
        json.dump(index, f, indent=2)
    print(f"{len(index)}/{len(jobs)} apps planned -> {args.out}/")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
