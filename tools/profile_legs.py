"""Per-leg time budget profiler: measures the FUSED ingest program itself.

For each headline workload this stages real wire chunks on host, then times
(a) host wire encode, (b) h2d transfer of the wire, (c) the fused device
scan (states donated, one truth-sync read at the end), so the terms provably
bound the end-to-end leg number and name its binding wall.

Usage: python tools/profile_legs.py [leg ...]
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)) + "/..")

import bench as B  # noqa: E402


def profile_leg(name: str, batch=32768, reps=4):
    import jax

    ql, stream, mult, batch_override = B.WORKLOADS[name]
    bsz = batch_override or batch
    ql = f"@app:batch(size='{bsz}')\n" + ql
    from siddhi_tpu import SiddhiManager

    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    B._prime_interner(mgr, B._make_stock_data(8)["names"])
    rt.start()
    j = rt.junctions[stream]
    fi = j.fused_ingest
    if fi is None or not fi.eligible():
        print(f"{name}: fused path NOT eligible")
        return
    K = fi.K
    data = B._make_stock_data(bsz * K)  # sized from the engine's real K
    cols = {k: v for k, v in data.items() if k not in ("ts", "names")}
    encode, wire_bytes = fi.staged_codec(
        data["ts"][:bsz], {k: v[:bsz] for k, v in cols.items()})

    # ---- host encode of one K-batch chunk
    t0 = time.perf_counter()
    bufs, counts, bases = [], np.full((K,), bsz, np.int32), np.zeros((K,), np.int64)
    for k in range(K):
        lo = k * bsz
        buf, base = encode(data["ts"][lo:lo + bsz], {kk: v[lo:lo + bsz] for kk, v in cols.items()}, bsz)
        bufs.append(buf)
        bases[k] = base
    wire = np.stack(bufs)
    t_encode = time.perf_counter() - t0

    ev_per_chunk = K * bsz

    # warm up + flip relay to truth mode
    def run_once(w):
        states = []
        for ep in fi.endpoints:
            if ep.qr.state is None:
                ep.qr.state = ep.qr._fresh(ep.init_state(0))
            states.append(ep.qr.state)
        tstates = {}
        for ep in fi.endpoints:
            tstates.update(ep.qr._collect_table_states())
        ns, tst, _aux, _lin, _packs = fi._fused(tuple(states), tstates, w, counts, bases, np.int64(1_700_000_000_000))
        for ep, st in zip(fi.endpoints, ns):
            ep.qr.state = st
        return ns

    ns = run_once(wire)
    # truth sync
    leaf = jax.tree_util.tree_leaves(ns)[0]
    np.asarray(leaf.ravel()[:1])

    # ---- h2d: transfer the wire alone (median of 5)
    h2ds = []
    for _ in range(5):
        t0 = time.perf_counter()
        dev = jax.device_put(wire)
        np.asarray(dev.ravel()[:1])
        h2ds.append(time.perf_counter() - t0)
    h2ds.sort()
    t_h2d = h2ds[len(h2ds) // 2]

    # ---- fused device scan on a PRE-STAGED device wire: pure device cost
    dev_wire = jax.device_put(wire)
    np.asarray(dev_wire.ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(reps):
        ns = run_once(dev_wire)
    leaf = jax.tree_util.tree_leaves(ns)[0]
    np.asarray(leaf.ravel()[:1])
    t_dev = (time.perf_counter() - t0) / reps

    # ---- end-to-end chunk (host wire: h2d + scan as the engine runs it)
    t0 = time.perf_counter()
    for _ in range(reps):
        ns = run_once(wire)
    leaf = jax.tree_util.tree_leaves(ns)[0]
    np.asarray(leaf.ravel()[:1])
    t_scan = (time.perf_counter() - t0) / reps

    print(f"{name}: B={bsz} K={K} wire={wire.nbytes/1e6:.1f}MB "
          f"encode={t_encode*1e3:.1f}ms ({ev_per_chunk/t_encode/1e6:.2f}Mev/s) "
          f"h2d={t_h2d*1e3:.1f}ms ({wire.nbytes/t_h2d/1e6:.0f}MB/s) "
          f"device={t_dev*1e3:.1f}ms ({ev_per_chunk/t_dev/1e6:.2f}Mev/s) "
          f"e2e={t_scan*1e3:.1f}ms ({ev_per_chunk/t_scan/1e6:.2f}Mev/s)")
    rt.shutdown()
    mgr.shutdown()


if __name__ == "__main__":
    legs = sys.argv[1:] or list(B.WORKLOADS)
    for leg in legs:
        profile_leg(leg)
