"""Chaos smoke: subprocess crash -> auto-restore -> replay, diffed against a
clean control run (the CI half of ISSUE 9's chaos e2e proof; the in-process
half lives in tests/test_supervision.py).

Orchestration (parent, default mode):

 1. CONTROL   one child feeds seq 1..N cleanly; outputs land in JSONL files.
 2. CHAOS #1  a second child runs the same feed under SIDDHI_TPU_FAULTS
              (injected sink outages spill payloads to the restart-surviving
              FileErrorStore via on.error='STORE') and @app:persist
              auto-checkpoints; the parent SIGKILLs it mid-feed.
 3. CHAOS #2  the child restarts with --resume: restore_last_revision(),
              replay_errors(), then continues the feed from the last
              checkpointed sequence (read back from a checkpointed table).
 4. DIFF      query outputs and sink deliveries across both chaos runs are
              deduped by sequence number and compared against the control:
              every sequence 1..N must be present, every (seq -> total)
              must agree, and the error-store entries stored before the
              kill must have been replayed. Exit 0 = contract holds.

Duplicates are EXPECTED (events between the last checkpoint and the kill
re-run after restore — at-least-once), silent loss is not: dedup-by-seq
must recover exactly the control outputs.

Churn leg (`--churn`): the chaos child ALSO hot-deploys/undeploys queries
while the feed runs (core/churn.py `add_query`/`remove_query` at fixed
sequence points, printing `splicing K` markers), and the parent SIGKILLs
it on a mid-feed splice marker — so the kill lands around a live splice.
The resume child restores from the last auto-checkpoint (whose snapshot
may contain hot-query elements the rebuilt base app does not know —
restore must skip them, never tear) and re-runs the churn schedule for
the remaining sequences. The diff contract is unchanged and PROVES churn
consistency: the surviving base query's outputs are byte-identical to a
churn-free control (dedup by seq), and no STORE'd sink event is lost.

Usage:
    python tools/chaos_smoke.py [--events N] [--dir D] [--json] [--churn]
    python tools/chaos_smoke.py child --dir D --events N [--resume] [--churn]
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

APP = """
@app:name('Chaos')
@app:persist(interval='150 millisec', keep='3')
define stream S (seq long, v long);
define table M (k long, s long);
@sink(type='inMemory', topic='chaos-out', on.error='STORE',
      @map(type='json'))
define stream Out (seq long, total long);
@info(name='q')
from S#window.length(8) select seq, sum(v) as total insert into Out;
@info(name='m')
from S select 0 as k, seq as s update or insert into M on M.k == k;
"""


# churn schedule for the --churn child: seq -> (op, hot query id). Exact
# seq matches only, so a resumed child skips ops its predecessor already
# passed and re-runs the ones still ahead of its start_seq.
CHURN_OPS = {
    60: ("add", "hot1"),
    120: ("remove", "hot1"),
    180: ("add", "hot2"),
    240: ("remove", "hot2"),
}


def _churn_op(rt, op: str, qid: str, hot_f, splice_no: int) -> None:
    """One scheduled churn op with mid-splice markers the parent kills on."""
    print(f"splicing {splice_no} {op} {qid}", flush=True)
    if op == "add":
        rt.add_query(
            f"@info(name='{qid}') from S[seq % 2 == 0] "
            "select seq, v insert into HotOut;"
        )
        rt.add_callback(qid, lambda ts, ins, rem, _q=qid: [
            hot_f.write(json.dumps(
                {"q": _q, "seq": e.data[0], "v": e.data[1]}
            ) + "\n")
            for e in ins or []
        ])
    elif qid in rt.queries:  # a resumed child never deployed this one
        rt.remove_query(qid)
    print(f"spliced {splice_no} {op} {qid}", flush=True)


def _child(args) -> int:
    import logging

    logging.basicConfig(level=logging.ERROR)
    from siddhi_tpu import FileErrorStore, SiddhiManager
    from siddhi_tpu.core.io import InMemoryBroker, _BrokerSubscriber
    from siddhi_tpu.core.persistence import FileSystemPersistenceStore

    d = args.dir
    mgr = SiddhiManager()
    mgr.set_persistence_store(
        FileSystemPersistenceStore(os.path.join(d, "snap"))
    )
    mgr.set_error_store(FileErrorStore(os.path.join(d, "errors")))
    rt = mgr.create_siddhi_app_runtime(APP)

    # line-buffered appends: a SIGKILL loses at most one torn tail line,
    # which the parent's reader tolerates
    out_f = open(os.path.join(d, "out.jsonl"), "a", buffering=1)
    sink_f = open(os.path.join(d, "sink.jsonl"), "a", buffering=1)
    rt.add_callback("q", lambda ts, ins, rem: [
        out_f.write(json.dumps({"seq": e.data[0], "total": e.data[1]}) + "\n")
        for e in ins or []
    ])
    InMemoryBroker.subscribe(_BrokerSubscriber(
        "chaos-out", lambda payload: sink_f.write(str(payload) + "\n")
    ))

    start_seq = 1
    if args.resume:
        rt.restore_last_revision()
        rows = rt.query("from M select k, s")
        if rows:
            start_seq = int(rows[0].data[1]) + 1
    rt.start()
    if args.resume:
        # replay AFTER start — sinks connect at start(); same order as the
        # supervisor's restart sequence
        replayed = mgr.replay_errors(skip_unavailable=True)
        print(f"resumed from seq {start_seq}, replayed {replayed}",
              flush=True)
    hot_f = open(os.path.join(d, "hot.jsonl"), "a", buffering=1)
    splice_no = 0
    h = rt.get_input_handler("S")
    for seq in range(start_seq, args.events + 1):
        if args.churn and seq in CHURN_OPS:
            op, qid = CHURN_OPS[seq]
            splice_no += 1
            _churn_op(rt, op, qid, hot_f, splice_no)
        h.send((seq, seq % 10), timestamp=seq)
        print(f"fed {seq}", flush=True)  # the parent kills on this marker
        time.sleep(0.002)
    # a final explicit checkpoint so a clean exit retains everything
    rt.persist()
    mgr.shutdown()
    print("done", flush=True)
    return 0


def _read_jsonl(path):
    out = []
    if not os.path.exists(path):
        return out
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                continue  # torn tail line from the SIGKILL
    return out


def _spawn(d, events, resume=False, env_extra=None, churn=False):
    env = dict(os.environ)
    env.pop("SIDDHI_TPU_FAULTS", None)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if env_extra:
        env.update(env_extra)
    cmd = [
        sys.executable, os.path.abspath(__file__), "child",
        "--dir", d, "--events", str(events),
    ]
    if resume:
        cmd.append("--resume")
    if churn:
        cmd.append("--churn")
    return subprocess.Popen(
        cmd, env=env, cwd=os.path.dirname(os.path.dirname(__file__)) or ".",
        stdout=subprocess.PIPE, text=True,
    )


def run_chaos(
    events: int = 300, base_dir: str | None = None, churn: bool = False
) -> dict:
    """Run the full control/kill/resume/diff sequence; returns the result
    dict (raises AssertionError on contract violation). With `churn=True`
    the chaos children hot-deploy/undeploy queries while feeding and the
    SIGKILL lands on a mid-feed splice marker — the diff then proves the
    surviving query's outputs ride through live churn AND a crash around
    a splice byte-identically."""
    import tempfile

    base = base_dir or tempfile.mkdtemp(prefix="chaos_smoke_")
    ctl_dir = os.path.join(base, "control")
    chaos_dir = os.path.join(base, "chaos")
    os.makedirs(ctl_dir, exist_ok=True)
    os.makedirs(chaos_dir, exist_ok=True)

    # 1. control: churn-free — the base query's outputs must be identical
    # WHETHER OR NOT the chaos runs churned (the splice parity contract)
    p = _spawn(ctl_dir, events)
    out, _ = p.communicate(timeout=600)
    assert p.returncode == 0, f"control run failed:\n{out}"

    # 2. chaos run 1: injected sink outages + SIGKILL mid-feed (churn mode:
    # on the second splice marker, so the kill lands around a live splice
    # with one hot query's deploy already committed)
    p = _spawn(chaos_dir, events, churn=churn, env_extra={
        "SIDDHI_TPU_FAULTS": "seed=7;sink_publish@Chaos:after=25,times=5",
    })
    kill_at = events // 2
    killed = False
    # watchdog, not an in-loop deadline check: `for line in p.stdout` blocks
    # in readline, so a child that wedges SILENTLY (stops printing) would
    # never reach an in-loop check — the timer kills it, readline returns
    # EOF, and the assertion below reports the hang
    import threading

    hung = threading.Event()
    watchdog = threading.Timer(600, lambda: (hung.set(), p.kill()))
    watchdog.start()
    try:
        for line in p.stdout:
            if churn and line.startswith("splicing 2 "):
                p.send_signal(signal.SIGKILL)
                killed = True
                break
            if not churn and line.startswith("fed ") and int(
                line.split()[1]
            ) >= kill_at:
                p.send_signal(signal.SIGKILL)
                killed = True
                break
    finally:
        watchdog.cancel()
    p.wait(timeout=60)
    assert not hung.is_set(), "chaos run 1 hung before the kill point"
    assert killed, "chaos run 1 exited before the kill point"
    hot_rows_before_kill = 0
    if churn:
        # the first hot deploy committed before the kill: the hot query
        # must have produced rows while deployed (counted NOW — the
        # resume child appends to the same file)
        hot_rows_before_kill = len(
            _read_jsonl(os.path.join(chaos_dir, "hot.jsonl"))
        )
        assert hot_rows_before_kill, (
            "no hot-query output before the mid-splice kill"
        )

    # the kill must have left durable state behind: checkpoints + stored
    # sink payloads (FileErrorStore JSONL survives SIGKILL)
    snaps = os.listdir(os.path.join(chaos_dir, "snap", "Chaos"))
    assert snaps, "no checkpoint survived the kill"
    err_dir = os.path.join(chaos_dir, "errors")
    stored_before = sum(
        len(_read_jsonl(os.path.join(err_dir, f)))
        for f in os.listdir(err_dir)
    ) if os.path.isdir(err_dir) else 0
    assert stored_before > 0, (
        "the injected sink outages stored nothing before the kill"
    )

    # 3. chaos run 2: restore + replay + finish (no faults). In churn mode
    # the restore consumes a checkpoint that may carry hot-query elements
    # the rebuilt base app does not define — landing on a CONSISTENT (old)
    # runtime, never a torn one — and the remaining churn schedule re-runs.
    p = _spawn(chaos_dir, events, resume=True, churn=churn)
    out, _ = p.communicate(timeout=600)
    assert p.returncode == 0, f"resume run failed:\n{out}"
    resumed_line = next(
        (ln for ln in out.splitlines() if ln.startswith("resumed")), ""
    )
    resume_splices = sum(
        1 for ln in out.splitlines() if ln.startswith("spliced ")
    )

    # 4. diff against control, dedup by seq
    def collate(d):
        rows = {}
        for r in _read_jsonl(os.path.join(d, "out.jsonl")):
            prev = rows.setdefault(r["seq"], r["total"])
            assert prev == r["total"], (
                f"divergent replayed output at seq {r['seq']}: "
                f"{prev} != {r['total']}"
            )
        return rows

    control = collate(ctl_dir)
    chaos = collate(chaos_dir)
    assert set(control) == set(range(1, events + 1)), "control feed incomplete"
    missing = set(control) - set(chaos)
    assert not missing, f"chaos run LOST outputs for seqs {sorted(missing)[:10]}"
    diverged = [s for s in control if control[s] != chaos[s]]
    assert not diverged, (
        f"restored state diverged from control at seqs {diverged[:10]}"
    )

    # sink deliveries: every stored payload must have been replayed — the
    # union of both runs' sink lines covers every sequence
    def sink_seqs(d):
        seqs = set()
        for line in open(os.path.join(d, "sink.jsonl")):
            try:
                for ev in json.loads(line.replace("'", '"')):
                    seqs.add(ev["event"]["seq"])
            except (ValueError, KeyError, TypeError):
                continue
        return seqs

    ctl_sink = sink_seqs(ctl_dir)
    chaos_sink = sink_seqs(chaos_dir)
    lost_sink = ctl_sink - chaos_sink
    assert not lost_sink, (
        f"STORE'd sink events lost across the crash: {sorted(lost_sink)[:10]}"
    )

    result = {
        "events": events,
        "killed_at": "splicing 2" if churn else kill_at,
        "checkpoints_after_kill": len(snaps),
        "stored_entries_before_resume": stored_before,
        "resume": resumed_line,
        "outputs_control": len(control),
        "outputs_chaos_deduped": len(chaos),
        "sink_seqs_recovered": len(chaos_sink),
        "parity": "ok",
    }
    if churn:
        result["churn"] = {
            "hot_rows_before_kill": hot_rows_before_kill,
            "resume_splices": resume_splices,
        }
        assert resume_splices >= 1, (
            "the resumed child re-ran no churn ops — the schedule should "
            "still have splices ahead of the restore point"
        )
    return result


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", nargs="?", default="run")
    ap.add_argument("--dir")
    ap.add_argument("--events", type=int, default=300)
    ap.add_argument("--resume", action="store_true")
    ap.add_argument("--churn", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    if args.mode == "child":
        return _child(args)
    result = run_chaos(events=args.events, base_dir=args.dir, churn=args.churn)
    print(json.dumps(result) if args.json else
          "chaos smoke OK: " + json.dumps(result, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
