"""Experiment: count_sequence device rate vs (patternCapacity T, chunk C).

Times ONLY the fused device program (pre-staged wire) like profile_legs.
Usage: python tools/exp_count.py [T:C ...]   e.g. 4096:4096 1024:4096 512:2048
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, __import__("os").path.dirname(__import__("os").path.abspath(__file__)) + "/..")

import bench as B  # noqa: E402


def run(T: int, C: int, bsz=32768, reps=3):
    import jax

    from siddhi_tpu import SiddhiManager
    import siddhi_tpu.core.pattern_runtime as prtm

    ql = f"""@app:batch(size='{bsz}')
    @app:patternCapacity(size='{T}')
    define stream StockStream (symbol string, price float, volume long);
    @info(name='q')
    from every a1=StockStream[price > 90]<2:4> -> a2=StockStream[price < 10]
    select a2.symbol as s2
    insert into Out;
    """
    prtm.COUNT_CHUNK_OVERRIDE = C  # pin the chunk exactly as labeled

    mgr = SiddhiManager()
    rt = mgr.create_siddhi_app_runtime(ql)
    B._prime_interner(mgr, B._make_stock_data(8)["names"])
    rt.start()
    j = rt.junctions["StockStream"]
    fi = j.fused_ingest
    assert fi is not None and fi.eligible()
    Kf = fi.K
    data = B._make_stock_data(bsz * Kf)
    cols = {k: v for k, v in data.items() if k not in ("ts", "names")}
    encode, _nb = fi.staged_codec(
        data["ts"][:bsz], {k: v[:bsz] for k, v in cols.items()})
    bufs, counts, bases = [], np.full((Kf,), bsz, np.int32), np.zeros((Kf,), np.int64)
    for k in range(Kf):
        lo = k * bsz
        buf, base = encode(data["ts"][lo:lo + bsz], {kk: v[lo:lo + bsz] for kk, v in cols.items()}, bsz)
        bufs.append(buf)
        bases[k] = base
    wire = np.stack(bufs)
    ev = Kf * bsz

    def run_once(w):
        states = []
        for ep in fi.endpoints:
            if ep.qr.state is None:
                ep.qr.state = ep.qr._fresh(ep.init_state(0))
            states.append(ep.qr.state)
        tstates = {}
        for ep in fi.endpoints:
            tstates.update(ep.qr._collect_table_states())
        ns, _t, _a, _lin, _p = fi._fused(tuple(states), tstates, w, counts, bases, np.int64(1_700_000_000_000))
        for ep, st in zip(fi.endpoints, ns):
            ep.qr.state = st
        return ns

    ns = run_once(wire)
    np.asarray(jax.tree_util.tree_leaves(ns)[0].ravel()[:1])
    dw = jax.device_put(wire)
    np.asarray(dw.ravel()[:1])
    t0 = time.perf_counter()
    for _ in range(reps):
        ns = run_once(dw)
    np.asarray(jax.tree_util.tree_leaves(ns)[0].ravel()[:1])
    t_dev = (time.perf_counter() - t0) / reps
    print(f"T={T} C={C}: device={t_dev*1e3:.1f}ms ({ev/t_dev/1e6:.2f} Mev/s)")
    rt.shutdown()
    mgr.shutdown()
    prtm.COUNT_CHUNK_OVERRIDE = None


if __name__ == "__main__":
    specs = sys.argv[1:] or ["4096:4096"]
    for s in specs:
        t, c = map(int, s.split(":"))
        run(t, c)
