"""Quartz-style cron next-fire computation.

Reference: the engine's cron scheduling is delegated to Quartz
(modules/siddhi-core/pom.xml:68-69; CronWindowProcessor.java:75,
trigger/CronTrigger.java). This is a dependency-free re-implementation of the
subset of the Quartz cron syntax those call sites use:

    sec min hour day-of-month month day-of-week [year]

with `*`, `?`, numbers, names (JAN-DEC, SUN-SAT), lists `a,b`, ranges `a-b`,
and steps `*/n` / `a/n` / `a-b/n`. Day-of-week is Quartz-style 1=SUN..7=SAT.
"""

from __future__ import annotations

import calendar
import datetime as _dt

_MONTHS = {m: i + 1 for i, m in enumerate(
    "JAN FEB MAR APR MAY JUN JUL AUG SEP OCT NOV DEC".split()
)}
_DOWS = {d: i + 1 for i, d in enumerate("SUN MON TUE WED THU FRI SAT".split())}

_FIELD_RANGES = [  # (lo, hi) per field: sec min hour dom mon dow
    (0, 59), (0, 59), (0, 23), (1, 31), (1, 12), (1, 7),
]


def _parse_field(spec: str, lo: int, hi: int, names: dict[str, int]) -> frozenset[int]:
    out: set[int] = set()
    for part in spec.split(","):
        part = part.strip().upper()
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            step = int(step_s)
            if step <= 0:
                raise ValueError(f"bad cron step in {spec!r}")
        if part in ("*", "?", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start = names.get(a, None) if a in names else int(a)
            end = names.get(b, None) if b in names else int(b)
        else:
            v = names[part] if part in names else int(part)
            start = v
            end = hi if "/" in spec and part == spec.split("/", 1)[0] else v
            if step > 1:
                end = hi
        if start is None or end is None or start < lo or end > hi or start > end:
            raise ValueError(f"bad cron field {spec!r} (range {lo}-{hi})")
        out.update(range(start, end + 1, step))
    return frozenset(out)


class CronSchedule:
    def __init__(self, expr: str):
        fields = expr.split()
        if len(fields) == 7:
            fields = fields[:6]  # ignore the optional year field
        posix = len(fields) == 5
        if posix:
            fields = ["0"] + fields  # plain 5-field cron: seconds = 0
        if len(fields) != 6:
            raise ValueError(f"cron expression needs 5-7 fields: {expr!r}")
        self.expr = expr
        names = [{}, {}, {}, {}, _MONTHS]
        self.sec, self.min, self.hour, self.dom, self.mon = (
            _parse_field(f, lo, hi, nm)
            for f, (lo, hi), nm in zip(fields[:5], _FIELD_RANGES[:5], names)
        )
        if posix:
            # POSIX day-of-week numbering: 0 (or 7) = SUN, 1 = MON ... 6 = SAT;
            # names map to their POSIX numbers, then everything remaps onto the
            # Quartz 1=SUN..7=SAT encoding used internally
            posix_names = {d: (q - 1) for d, q in _DOWS.items()}
            self.dow = frozenset(
                (v % 7) + 1 for v in _parse_field(fields[5], 0, 7, posix_names)
            )
        else:
            self.dow = _parse_field(fields[5], *_FIELD_RANGES[5], _DOWS)
        self.dom_any = fields[3] in ("*", "?")
        self.dow_any = fields[5] in ("*", "?")

    def _day_matches(self, d: _dt.datetime) -> bool:
        dom_ok = d.day in self.dom
        dow_ok = ((d.weekday() + 1) % 7) + 1 in self.dow  # Mon=0 -> Quartz 2
        if self.dom_any and self.dow_any:
            return True
        if self.dom_any:
            return dow_ok
        if self.dow_any:
            return dom_ok
        return dom_ok or dow_ok  # Quartz: specified dom OR dow

    def next_fire_ms(self, after_ms: int) -> int:
        """Earliest fire time strictly after `after_ms` (epoch millis, local)."""
        d = _dt.datetime.fromtimestamp(after_ms / 1000.0).replace(microsecond=0)
        d += _dt.timedelta(seconds=1)
        for _ in range(4 * 366 * 24 * 60):  # bound the scan (~4 years of minutes)
            if d.month not in self.mon:
                d = _dt.datetime(d.year + (d.month == 12), d.month % 12 + 1, 1)
                continue
            if not self._day_matches(d):
                d = (d + _dt.timedelta(days=1)).replace(hour=0, minute=0, second=0)
                continue
            if d.hour not in self.hour:
                d = (d + _dt.timedelta(hours=1)).replace(minute=0, second=0)
                continue
            if d.minute not in self.min:
                d = (d + _dt.timedelta(minutes=1)).replace(second=0)
                continue
            if d.second not in self.sec:
                nxt = min((s for s in self.sec if s > d.second), default=None)
                if nxt is None:
                    d = (d + _dt.timedelta(minutes=1)).replace(second=0)
                else:
                    d = d.replace(second=nxt)
                continue
            return int(d.timestamp() * 1000)
        raise ValueError(f"cron {self.expr!r}: no fire time within 4 years")
