"""Console event printer for samples/tests.

Reference: core/util/EventPrinter.java — prints callback payloads.
"""

from __future__ import annotations


def print_event(timestamp, in_events, removed_events) -> None:
    """QueryCallback-shaped printer."""
    print(
        f"Events{{ @timestamp = {timestamp}, inEvents = "
        f"{[tuple(e.data) for e in in_events] if in_events else None}, "
        f"RemoveEvents = "
        f"{[tuple(e.data) for e in removed_events] if removed_events else None} }}",
        flush=True,
    )


def print_stream(events) -> None:
    """StreamCallback-shaped printer."""
    for e in events:
        print(f"Event{{ timestamp={e.timestamp}, data={tuple(e.data)} }}", flush=True)
