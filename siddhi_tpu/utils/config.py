"""Deployment config SPI.

Reference: util/config/ConfigManager.java + ConfigReader SPI resolving
per-extension system configs, with the in-memory impl
InMemoryConfigManager.java:27-60. Extensions receive a ConfigReader scoped to
their `namespace.name` prefix.
"""

from __future__ import annotations

from typing import Optional


class ConfigReader:
    def __init__(self, configs: dict[str, str], prefix: str):
        self._configs = configs
        self._prefix = prefix

    def read_config(self, name: str, default: Optional[str] = None) -> Optional[str]:
        return self._configs.get(f"{self._prefix}.{name}", default)

    def get_all_configs(self) -> dict[str, str]:
        p = self._prefix + "."
        return {
            k[len(p):]: v for k, v in self._configs.items() if k.startswith(p)
        }


class ConfigManager:
    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        raise NotImplementedError

    def extract_system_configs(self, name: str) -> dict:
        raise NotImplementedError


class InMemoryConfigManager(ConfigManager):
    def __init__(
        self,
        configs: Optional[dict[str, str]] = None,
        system_configs: Optional[dict[str, dict]] = None,
    ):
        self._configs = dict(configs or {})
        self._system = dict(system_configs or {})

    def generate_config_reader(self, namespace: str, name: str) -> ConfigReader:
        return ConfigReader(self._configs, f"{namespace}.{name}")

    def extract_system_configs(self, name: str) -> dict:
        return self._system.get(name, {})
