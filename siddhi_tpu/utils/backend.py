"""Backend capability probes.

Some PJRT plugins (e.g. tunneled accelerators) report a standard platform
name but reject host send/recv callbacks at execution time — a name check
cannot detect that, so capabilities are probed once by actually running a
trivial callback.
"""

from __future__ import annotations

from typing import Optional

_CB_SUPPORT: Optional[bool] = None
_TRANSFER_DEGRADES: Optional[bool] = None


def transfer_degrades_dispatch() -> bool:
    """True when a device->host transfer permanently degrades dispatch on the
    default backend (observed on tunneled/relayed PJRT plugins, where the
    relay speculatively acks async work until the first transfer forces it
    into a synchronous completion cycle of ~100 ms). Detected by platform
    name — probing behaviorally would itself trigger the degradation."""
    global _TRANSFER_DEGRADES
    if _TRANSFER_DEGRADES is None:
        try:
            import jax

            client = jax.devices()[0].client
            pv = getattr(client, "platform_version", "") or ""
            # under PJRT the version string is multi-line:
            # "PJRT C API\naxon 0.1.0; ..."; under IFRT it starts with "axon"
            _TRANSFER_DEGRADES = any(
                line.startswith("axon") for line in pv.splitlines()
            )
        except Exception:
            _TRANSFER_DEGRADES = False
    return _TRANSFER_DEGRADES


def host_callbacks_supported() -> bool:
    """True when jax io/debug callbacks execute on the default backend."""
    global _CB_SUPPORT
    if _CB_SUPPORT is None:
        if transfer_degrades_dispatch():
            # tunneled relays ack async work speculatively, so a
            # block_until_ready probe would "succeed" and the UNIMPLEMENTED
            # error only surfaces at first real completion — and forcing
            # completion here would flip the relay out of its fast mode.
            # These backends reject host send/recv callbacks anyway.
            _CB_SUPPORT = False
            return _CB_SUPPORT
        import numpy as _np

        import jax
        import jax.numpy as jnp
        from jax.experimental import io_callback

        def probe(x):
            return io_callback(
                lambda v: v, jax.ShapeDtypeStruct((), jnp.int32), x
            )

        try:
            # the readback (not just block) forces real completion, so a
            # backend that accepts the launch but fails the callback at
            # execution time is still detected
            _np.asarray(jax.jit(probe)(jnp.int32(0)))
            _CB_SUPPORT = True
        except Exception:
            _CB_SUPPORT = False
    return _CB_SUPPORT
