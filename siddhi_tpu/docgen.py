"""Extension documentation generator.

Reference: modules/siddhi-doc-gen — Maven mojos scanning @Extension metadata
into FreeMarker markdown templates (core/MarkdownDocumentationGenerationMojo).
Here: walks the built-in registries + extension registry and emits one
markdown document per extension kind.
"""

from __future__ import annotations

import inspect
import os

from siddhi_tpu.core.extension import _REGISTRY

_BUILTIN_SECTIONS = {
    "Windows": [
        ("length(N)", "Sliding window of the last N events."),
        ("lengthBatch(N)", "Tumbling window flushing every N events."),
        ("time(T)", "Sliding window over the last T of event time."),
        ("timeBatch(T [, start])", "Tumbling window flushing every T."),
        ("timeLength(T, N)", "Sliding window bounded by both T and N."),
        ("externalTime(tsAttr, T)", "Sliding time window over an attribute clock."),
        ("externalTimeBatch(tsAttr, T [, start])", "Tumbling window over an attribute clock."),
        ("sort(N, attr [asc|desc], ...)", "Keeps the N least events per the comparator."),
        ("frequent(N [, attrs...])", "Misra-Gries top-N key retention."),
        ("lossyFrequent(support, error [, attrs...])", "Lossy-counting frequent keys."),
        ("cron('expr')", "Tumbling window flushed on a cron schedule."),
    ],
    "Aggregators": [
        ("sum/avg/count/min/max(x)", "Streaming aggregates with expired-event removal."),
        ("stdDev(x)", "Streaming standard deviation."),
        ("distinctCount(x)", "Distinct values inside the window."),
        ("minForever/maxForever(x)", "All-time extremes (never removed)."),
    ],
    "Functions": [
        ("cast/convert(v, 'type')", "Type conversion."),
        ("coalesce(a, b, ...)", "First non-null argument."),
        ("ifThenElse(cond, a, b)", "Conditional projection."),
        ("instanceOf<Type>(v)", "Runtime type check."),
        ("maximum/minimum(a, b, ...)", "Elementwise extremes."),
        ("eventTimestamp()", "The event's timestamp."),
        ("currentTimeMillis()", "The engine clock."),
        ("default(v, d)", "Null replacement."),
        ("UUID()", "Random identifier (host side)."),
    ],
    "Stream functions": [
        ("#log([message])", "Pass-through event tracing."),
        ("#pol2Cart(theta, rho [, z])", "Appends cartesian x/y[/z]."),
    ],
    "Sources": [("inMemory(topic)", "In-memory broker ingestion.")],
    "Sinks": [
        ("inMemory(topic)", "In-memory broker egress."),
        ("log()", "Logging egress."),
    ],
    "Mappers": [
        ("passThrough", "Raw tuples/Events."),
        ("json", "JSON objects keyed by attribute (siddhi-map-json envelope)."),
        ("keyvalue", "Dicts keyed by attribute."),
        ("text", "attr:value line format."),
    ],
}


def generate_markdown() -> str:
    lines = ["# siddhi_tpu extensions", ""]
    for section, entries in _BUILTIN_SECTIONS.items():
        lines.append(f"## {section}")
        lines.append("")
        lines.append("| syntax | description |")
        lines.append("|---|---|")
        for syntax, desc in entries:
            lines.append(f"| `{syntax}` | {desc} |")
        lines.append("")
    # user-registered extensions
    for kind, reg in _REGISTRY.items():
        if not reg:
            continue
        lines.append(f"## Registered `{kind}` extensions")
        lines.append("")
        lines.append("| name | doc |")
        lines.append("|---|---|")
        for name, obj in sorted(reg.items()):
            doc = (inspect.getdoc(obj) or "").splitlines()
            lines.append(f"| `{name}` | {doc[0] if doc else ''} |")
        lines.append("")
    return "\n".join(lines)


def write_docs(out_dir: str) -> str:
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "extensions.md")
    with open(path, "w") as f:
        f.write(generate_markdown())
    return path
