"""siddhi_tpu — a TPU-native stream-processing / complex-event-processing framework.

A from-scratch JAX/XLA re-design of the capabilities of Siddhi (the reference CEP
engine, see SURVEY.md): SiddhiQL queries are *compiled* into fused XLA programs that
run over micro-batched columnar event tensors with device-resident carried state
(window ring buffers, dense NFA token matrices, keyed aggregate stores) — instead of
the reference's per-event interpreter over pooled object graphs
(reference: modules/siddhi-core/.../core/stream/StreamJunction.java,
query/processor/*).

Timestamps are int64 milliseconds (matching the reference's `long` timestamps), so
x64 is enabled at import. All other arrays use explicit 32-bit (or narrower) dtypes;
nothing in the framework materialises float64 (TPU has no f64 ALU).
"""

import jax as _jax

_jax.config.update("jax_enable_x64", True)

from siddhi_tpu.core.admission import (  # noqa: E402,F401
    AdmissionRejectedError,
)
from siddhi_tpu.core.error_store import (  # noqa: E402,F401
    FileErrorStore,
    InMemoryErrorStore,
    SqliteErrorStore,
)
from siddhi_tpu.core.manager import SiddhiManager  # noqa: E402,F401
from siddhi_tpu.core.types import AttrType  # noqa: E402,F401

# analysis exports resolve lazily (PEP 562): `import siddhi_tpu` must not pay
# for the analyzer subsystem unless analyze()/strict mode is actually used
_ANALYSIS_EXPORTS = {
    "analyze", "AnalysisResult", "Diagnostic", "SiddhiAnalysisError",
}


def __getattr__(name):
    if name in _ANALYSIS_EXPORTS:
        import siddhi_tpu.analysis as _analysis

        return getattr(_analysis, name)
    raise AttributeError(f"module 'siddhi_tpu' has no attribute '{name}'")


__version__ = "0.1.0"

__all__ = [
    "SiddhiManager",
    "AttrType",
    "InMemoryErrorStore",
    "FileErrorStore",
    "SqliteErrorStore",
    "AdmissionRejectedError",
    "analyze",
    "AnalysisResult",
    "Diagnostic",
    "SiddhiAnalysisError",
    "__version__",
]
