"""Top-level SiddhiApp AST container.

Reference: siddhi-query-api .../SiddhiApp.java — ordered definitions +
execution elements + app-level annotations.
"""

from __future__ import annotations

import dataclasses
from typing import Union

from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.definition import (
    AggregationDefinition,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TriggerDefinition,
    WindowDefinition,
)
from siddhi_tpu.query_api.execution import Partition, Query

ExecutionElement = Union[Query, Partition]


@dataclasses.dataclass
class SiddhiApp:
    stream_definitions: dict[str, StreamDefinition] = dataclasses.field(default_factory=dict)
    table_definitions: dict[str, TableDefinition] = dataclasses.field(default_factory=dict)
    window_definitions: dict[str, WindowDefinition] = dataclasses.field(default_factory=dict)
    trigger_definitions: dict[str, TriggerDefinition] = dataclasses.field(default_factory=dict)
    function_definitions: dict[str, FunctionDefinition] = dataclasses.field(default_factory=dict)
    aggregation_definitions: dict[str, AggregationDefinition] = dataclasses.field(
        default_factory=dict
    )
    execution_elements: list[ExecutionElement] = dataclasses.field(default_factory=list)
    annotations: list[Annotation] = dataclasses.field(default_factory=list)

    @staticmethod
    def siddhi_app(name: str | None = None) -> "SiddhiApp":
        app = SiddhiApp()
        if name:
            app.annotations.append(Annotation("name", [(None, name)]))
        return app

    def define_stream(self, d: StreamDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.stream_definitions[d.id] = d
        return self

    def define_table(self, d: TableDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.table_definitions[d.id] = d
        return self

    def define_window(self, d: WindowDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.window_definitions[d.id] = d
        return self

    def define_trigger(self, d: TriggerDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.trigger_definitions[d.id] = d
        return self

    def define_function(self, d: FunctionDefinition) -> "SiddhiApp":
        self.function_definitions[d.id] = d
        return self

    def define_aggregation(self, d: AggregationDefinition) -> "SiddhiApp":
        self._check_unique(d.id)
        self.aggregation_definitions[d.id] = d
        return self

    def add_query(self, q: Query) -> "SiddhiApp":
        self.execution_elements.append(q)
        return self

    def add_partition(self, p: Partition) -> "SiddhiApp":
        self.execution_elements.append(p)
        return self

    @property
    def name(self) -> str:
        for a in self.annotations:
            if a.name.lower() in ("app:name", "app", "name"):
                v = a.element("name") or a.element(None)
                if v:
                    return v
        return "SiddhiApp"

    def _check_unique(self, id_: str) -> None:
        for m in (
            self.stream_definitions,
            self.table_definitions,
            self.window_definitions,
            self.trigger_definitions,
            self.aggregation_definitions,
        ):
            if id_ in m:
                raise ValueError(f"duplicate definition id '{id_}'")
