"""Stream / table / window / trigger / function / aggregation definitions.

Reference: siddhi-query-api .../definition/*.java (StreamDefinition, TableDefinition,
WindowDefinition, TriggerDefinition, FunctionDefinition, AggregationDefinition,
Attribute) and aggregation/TimePeriod.java.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.expression import Expression, Variable
from siddhi_tpu.core.types import AttrType


class SourceLocated:
    """Mixin: 1-based source position of the node's first token, stamped by
    the SiddhiQL parser (None for programmatic ASTs). Plain class attributes
    on purpose — they are not dataclass fields, so constructor signatures of
    the dataclasses mixing this in are unchanged."""

    line = None
    col = None


@dataclasses.dataclass
class Attribute(SourceLocated):
    name: str
    type: AttrType


@dataclasses.dataclass
class AbstractDefinition(SourceLocated):
    id: str
    attributes: list[Attribute] = dataclasses.field(default_factory=list)
    annotations: list[Annotation] = dataclasses.field(default_factory=list)

    def attribute(self, name: str, type_: AttrType) -> "AbstractDefinition":
        self.attributes.append(Attribute(name, type_))
        return self

    def annotation(self, ann: Annotation) -> "AbstractDefinition":
        self.annotations.append(ann)
        return self

    @property
    def attribute_names(self) -> list[str]:
        return [a.name for a in self.attributes]


class StreamDefinition(AbstractDefinition):
    pass


class TableDefinition(AbstractDefinition):
    pass


@dataclasses.dataclass
class WindowDefinition(AbstractDefinition):
    """`define window W(...) length(10) output all events`
    (reference: definition/WindowDefinition.java)."""

    window: Optional["WindowSpec"] = None
    output_events: str = "all"  # current | expired | all


@dataclasses.dataclass
class WindowSpec(SourceLocated):
    """A window invocation `ns:name(params)` attached to a stream or window def."""

    namespace: Optional[str]
    name: str
    parameters: list[Expression] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class TriggerDefinition(SourceLocated):
    """`define trigger T at every 5 sec | 'cron' | 'start'`
    (reference: definition/TriggerDefinition.java)."""

    id: str
    at_every_ms: Optional[int] = None
    at_cron: Optional[str] = None
    at_start: bool = False
    annotations: list[Annotation] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class FunctionDefinition(SourceLocated):
    """`define function f[lang] return type { body }`
    (reference: definition/FunctionDefinition.java)."""

    id: str
    language: str
    return_type: AttrType
    body: str
    annotations: list[Annotation] = dataclasses.field(default_factory=list)


class Duration(enum.Enum):
    """reference: query-api aggregation/TimePeriod.java SEC..YEARS"""

    SECONDS = 1_000
    MINUTES = 60_000
    HOURS = 3_600_000
    DAYS = 86_400_000
    MONTHS = -2  # calendar-based; resolved by time conversion util
    YEARS = -1

    @property
    def millis(self) -> int:
        if self.value < 0:
            raise ValueError(f"{self.name} is calendar-based")
        return self.value


DURATION_ORDER = [
    Duration.SECONDS,
    Duration.MINUTES,
    Duration.HOURS,
    Duration.DAYS,
    Duration.MONTHS,
    Duration.YEARS,
]


@dataclasses.dataclass
class TimePeriod:
    """`every sec ... year` range or explicit list."""

    durations: list[Duration]

    @staticmethod
    def range(start: Duration, end: Duration) -> "TimePeriod":
        i, j = DURATION_ORDER.index(start), DURATION_ORDER.index(end)
        if i > j:
            raise ValueError(f"invalid time period {start}..{end}")
        return TimePeriod(DURATION_ORDER[i : j + 1])


@dataclasses.dataclass
class AggregationDefinition(SourceLocated):
    """`define aggregation A from S select ... group by ... aggregate by ts every ...`
    (reference: definition/AggregationDefinition.java)."""

    id: str
    basic_single_input_stream: "object" = None  # SingleInputStream (import cycle)
    selector: "object" = None  # Selector
    aggregate_attribute: Optional[Variable] = None
    time_period: Optional[TimePeriod] = None
    annotations: list[Annotation] = dataclasses.field(default_factory=list)
