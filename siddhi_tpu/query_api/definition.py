"""Stream / table / window / trigger / function / aggregation definitions.

Reference: siddhi-query-api .../definition/*.java (StreamDefinition, TableDefinition,
WindowDefinition, TriggerDefinition, FunctionDefinition, AggregationDefinition,
Attribute) and aggregation/TimePeriod.java.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.expression import Expression, Variable
from siddhi_tpu.core.types import AttrType


class SourceLocated:
    """Mixin: 1-based source position of the node's first token, stamped by
    the SiddhiQL parser (None for programmatic ASTs). Plain class attributes
    on purpose — they are not dataclass fields, so constructor signatures of
    the dataclasses mixing this in are unchanged."""

    line = None
    col = None


@dataclasses.dataclass
class Attribute(SourceLocated):
    name: str
    type: AttrType


@dataclasses.dataclass
class AbstractDefinition(SourceLocated):
    id: str
    attributes: list[Attribute] = dataclasses.field(default_factory=list)
    annotations: list[Annotation] = dataclasses.field(default_factory=list)

    def attribute(self, name: str, type_: AttrType) -> "AbstractDefinition":
        self.attributes.append(Attribute(name, type_))
        return self

    def annotation(self, ann: Annotation) -> "AbstractDefinition":
        self.annotations.append(ann)
        return self

    @property
    def attribute_names(self) -> list[str]:
        return [a.name for a in self.attributes]


class StreamDefinition(AbstractDefinition):
    pass


class TableDefinition(AbstractDefinition):
    pass


@dataclasses.dataclass
class WindowDefinition(AbstractDefinition):
    """`define window W(...) length(10) output all events`
    (reference: definition/WindowDefinition.java)."""

    window: Optional["WindowSpec"] = None
    output_events: str = "all"  # current | expired | all


@dataclasses.dataclass
class WindowSpec(SourceLocated):
    """A window invocation `ns:name(params)` attached to a stream or window
    def, plus static state-bound metadata: which builtin windows tumble
    (two device buckets instead of one ring), which arm host timers, and
    the constant row bound when one is declared — consumed by the static
    cost model (analysis/cost.py) and anyone else reasoning about device
    state without building a runtime stage. The sets mirror
    `core/windows.py make_window` dispatch."""

    namespace: Optional[str]
    name: str
    parameters: list[Expression] = dataclasses.field(default_factory=list)

    # tumbling family: state is cur + prev buckets (core/windows.py
    # BatchWindow / windows_special.py CronWindow)
    BATCH_WINDOWS = frozenset(
        {"lengthbatch", "timebatch", "externaltimebatch", "cron"}
    )
    # these arm the host scheduler unconditionally; externalTimeBatch joins
    # them only with its 4th (idle timeout) parameter — see arms_scheduler
    SCHEDULER_WINDOWS = frozenset({"time", "timelength", "timebatch", "cron"})
    # parameter position of the constant row bound, where one is declared
    _LENGTH_PARAM = {
        "length": 0, "lengthbatch": 0, "timelength": 1, "sort": 0,
        "frequent": 0,
    }

    @property
    def key(self) -> str:
        """Lowercased dispatch key (`ns:name` for extensions)."""
        return (
            self.name.lower()
            if self.namespace is None
            else f"{self.namespace}:{self.name}".lower()
        )

    @property
    def is_batch(self) -> bool:
        return self.key in self.BATCH_WINDOWS

    @property
    def arms_scheduler(self) -> bool:
        """True when this window needs host timer wake-ups between batches
        (mirrors the runtime stages' `needs_scheduler`)."""
        k = self.key
        if k in self.SCHEDULER_WINDOWS:
            return True
        return k == "externaltimebatch" and len(self.parameters) > 3

    def length_bound(self) -> Optional[int]:
        """The window's constant row bound, or None when its capacity is a
        runtime default (time-capacity family) / unknowable (extension,
        non-constant parameter)."""
        from siddhi_tpu.query_api.expression import Constant

        i = self._LENGTH_PARAM.get(self.key)
        if i is None or i >= len(self.parameters):
            return None
        p = self.parameters[i]
        if isinstance(p, Constant) and isinstance(p.value, (int, float)) \
                and not isinstance(p.value, bool):
            return int(p.value)
        return None


@dataclasses.dataclass
class TriggerDefinition(SourceLocated):
    """`define trigger T at every 5 sec | 'cron' | 'start'`
    (reference: definition/TriggerDefinition.java)."""

    id: str
    at_every_ms: Optional[int] = None
    at_cron: Optional[str] = None
    at_start: bool = False
    annotations: list[Annotation] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class FunctionDefinition(SourceLocated):
    """`define function f[lang] return type { body }`
    (reference: definition/FunctionDefinition.java)."""

    id: str
    language: str
    return_type: AttrType
    body: str
    annotations: list[Annotation] = dataclasses.field(default_factory=list)


class Duration(enum.Enum):
    """reference: query-api aggregation/TimePeriod.java SEC..YEARS"""

    SECONDS = 1_000
    MINUTES = 60_000
    HOURS = 3_600_000
    DAYS = 86_400_000
    MONTHS = -2  # calendar-based; resolved by time conversion util
    YEARS = -1

    @property
    def millis(self) -> int:
        if self.value < 0:
            raise ValueError(f"{self.name} is calendar-based")
        return self.value


DURATION_ORDER = [
    Duration.SECONDS,
    Duration.MINUTES,
    Duration.HOURS,
    Duration.DAYS,
    Duration.MONTHS,
    Duration.YEARS,
]


@dataclasses.dataclass
class TimePeriod:
    """`every sec ... year` range or explicit list."""

    durations: list[Duration]

    @staticmethod
    def range(start: Duration, end: Duration) -> "TimePeriod":
        i, j = DURATION_ORDER.index(start), DURATION_ORDER.index(end)
        if i > j:
            raise ValueError(f"invalid time period {start}..{end}")
        return TimePeriod(DURATION_ORDER[i : j + 1])


@dataclasses.dataclass
class AggregationDefinition(SourceLocated):
    """`define aggregation A from S select ... group by ... aggregate by ts every ...`
    (reference: definition/AggregationDefinition.java)."""

    id: str
    basic_single_input_stream: "object" = None  # SingleInputStream (import cycle)
    selector: "object" = None  # Selector
    aggregate_attribute: Optional[Variable] = None
    time_period: Optional[TimePeriod] = None
    annotations: list[Annotation] = dataclasses.field(default_factory=list)

    def bucket_durations(self) -> list[Duration]:
        """The declared per-duration bucket tables (state-bound metadata:
        one closed-bucket device table per entry — analysis/cost.py sizes
        them; []) when the definition is incomplete."""
        if self.time_period is None:
            return []
        return list(self.time_period.durations)
