"""Typed query object model — the IR between the SiddhiQL front-end and the compiler.

Mirrors the reference's siddhi-query-api POJO/builder AST (reference:
modules/siddhi-query-api, SURVEY.md §2.2) and doubles as the public programmatic
API for building apps without SiddhiQL text.
"""

from siddhi_tpu.query_api.annotation import Annotation
from siddhi_tpu.query_api.definition import (
    AggregationDefinition,
    Attribute,
    FunctionDefinition,
    StreamDefinition,
    TableDefinition,
    TimePeriod,
    TriggerDefinition,
    WindowDefinition,
)
from siddhi_tpu.query_api.execution import (
    AbsentStreamStateElement,
    CountStateElement,
    DeleteStream,
    EventOutputRate,
    EveryStateElement,
    Filter,
    InsertIntoStream,
    JoinInputStream,
    LogicalStateElement,
    NextStateElement,
    OutputAttribute,
    OrderByAttribute,
    Partition,
    Query,
    RangePartitionType,
    ReturnStream,
    Selector,
    SingleInputStream,
    SnapshotOutputRate,
    StateInputStream,
    StoreQuery,
    StreamFunctionHandler,
    StreamStateElement,
    TimeOutputRate,
    UpdateOrInsertStream,
    UpdateSetAttribute,
    UpdateStream,
    ValuePartitionType,
    WindowHandler,
)
from siddhi_tpu.query_api.expression import (
    Add,
    And,
    AttributeFunction,
    Compare,
    Constant,
    Divide,
    Expression,
    In,
    IsNull,
    Mod,
    Multiply,
    Not,
    Or,
    Subtract,
    TimeConstant,
    Variable,
)
from siddhi_tpu.query_api.siddhi_app import SiddhiApp

__all__ = [n for n in dir() if not n.startswith("_")]
